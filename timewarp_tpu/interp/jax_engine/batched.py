"""Multi-world batching: one compiled superstep, a fleet of worlds.

The production use of a cheap emulator is *fleets* of runs — seed
sweeps, link-model sweeps, Monte-Carlo fault studies (ROADMAP north
star; the replica-sweep workload of Revati-style time-warp emulation,
PAPERS.md). Per-superstep the general engine pays fixed N-width costs
(sender-compaction sort, rung gathers, the [K, N] mailbox base —
PERF_r05.md) that do not shrink with the instantaneous event count;
a leading **world axis B** amortizes them: one batched sort/gather/
scatter serves B independent worlds.

:class:`BatchSpec` declares the fleet: per-world engine seeds, plus an
optional pytree of per-world link-model parameters (dotted attribute
paths into the link dataclass, e.g. ``{"lo": [...], "hi": [...]}`` for
a ``UniformDelay`` sweep or ``{"inner.median_us": [...]}`` through a
``Quantize`` wrapper). Worlds share one scenario (topology, shapes,
step function); everything else that distinguishes a run — the RNG
stream and the link model — varies per world.

The exactness law that makes the batch trustworthy and cheap to
verify: **slicing world b out of any batched run is bit-identical to
the solo run with that world's seed and link** (tests/test_world_batch.py;
the in-bench gates in bench.py; the batched column of
tools/parity_tpu.py). It holds by construction: ``vmap`` of the
integer superstep is the same arithmetic per world, per-world
quiescence and step budgets are masked exactly like the solo drivers
mask a finished run, and the adaptive routing ladder is pinned to its
top rung under the batch (rungs are result-identical by design; under
``vmap`` a batched ``lax.switch`` lowers to select-over-all-branches,
so the ladder would cost every rung anyway).

Sweepable parameters are the ones ``LinkModel.sample`` uses
*arithmetically* (delay bounds, medians, sigmas, quanta). Parameters
burned into static Python control flow — ``WithDrop.drop_prob``
(integer-threshold compare built at trace time) or
``SeededHashUniform.salt`` (expanded host-side) — cannot vary per
world and fail at trace time; sweep those by constructing one engine
per value instead.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Mapping, NamedTuple, Optional, Sequence, Tuple

import numpy as np

__all__ = ["BatchSpec", "WorldIdentity", "rebind_link", "world_slice"]


class WorldIdentity(NamedTuple):
    """The fleet's per-world *identity* as ONE traced-operand pytree:
    seed words, link-parameter vectors, and (optional) fault tables,
    all with a leading world axis B. The batched drivers thread this
    through ``jit`` as ordinary traced operands — never compile-time
    constants — so the compiled executable is a pure function of the
    bucket's *shape* (scenario params, link structure, window, pad
    dims, B), and swapping identity (a new admission's seed, link
    values, or same-shape fault schedule) re-invokes the SAME
    executable with new device arrays: zero recompiles
    (``JaxEngine.rebind_identity``; docs/serving.md)."""
    s0v: Any          # uint32[B] — per-world seed word 0
    s1v: Any          # uint32[B] — per-world seed word 1
    lpv: Any          # dict dotted-path -> [B] link-parameter vectors
    ftv: Any          # FaultTables with leading [B] axis, or None


def _split_params(params: Mapping[str, Any]):
    """Group dotted paths by head attribute: {"inner.lo": v} ->
    ({}, {"inner": {"lo": v}})."""
    direct, nested = {}, {}
    for path, v in params.items():
        head, dot, rest = path.partition(".")
        if dot:
            nested.setdefault(head, {})[rest] = v
        else:
            direct[head] = v
    return direct, nested


def rebind_link(link, params: Mapping[str, Any]):
    """A copy of ``link`` (a frozen dataclass, possibly nested) with
    the dotted-path ``params`` substituted. Values may be Python
    scalars (host-side validation links) or traced per-world scalars
    (inside the vmapped superstep). Unknown paths fail with the field
    inventory — a typo'd sweep must not silently sweep nothing."""
    direct, nested = _split_params(params)
    fields = {f.name for f in dataclasses.fields(link)}
    for attr in list(direct) + list(nested):
        if attr not in fields:
            raise ValueError(
                f"link {type(link).__name__} has no parameter "
                f"{attr!r}; sweepable fields: {sorted(fields)}")
    for attr, sub in nested.items():
        direct[attr] = rebind_link(getattr(link, attr), sub)
    return dataclasses.replace(link, **direct)


def world_slice(state, b: int):
    """World ``b``'s slice of a batched state pytree — the left-hand
    side of the batch exactness law (compare against the solo run's
    state with :func:`~timewarp_tpu.trace.events.assert_states_equal`)."""
    import jax
    return jax.tree.map(lambda x: x[b], state)


@dataclass(frozen=True)
class BatchSpec:
    """A fleet declaration for the world axis (module docstring).

    ``seeds`` — one engine seed per world (world count B = len(seeds);
    replaces the engine's ``seed`` argument). ``link_params`` — optional
    mapping of dotted link-model attribute paths to length-B vectors of
    per-world values (``None``: all worlds share the engine's link).
    """
    seeds: Tuple[int, ...]
    link_params: Optional[Mapping[str, Any]] = None

    def __post_init__(self) -> None:
        seeds = tuple(int(s) for s in self.seeds)
        if not seeds:
            raise ValueError("a batch needs at least one world "
                             "(BatchSpec.seeds is empty)")
        object.__setattr__(self, "seeds", seeds)
        if self.link_params is not None:
            lp = {}
            for path, v in dict(self.link_params).items():
                arr = np.asarray(v)
                if arr.ndim != 1 or arr.shape[0] != len(seeds):
                    raise ValueError(
                        f"link_params[{path!r}] must be one value per "
                        f"world, shape [{len(seeds)}]; got {arr.shape}")
                lp[path] = arr
            object.__setattr__(self, "link_params", lp)

    @property
    def B(self) -> int:
        return len(self.seeds)

    @classmethod
    def of(cls, batch: Optional[int] = None,
           seeds: Optional[Sequence[int]] = None, *,
           base_seed: int = 0,
           link_params: Optional[Mapping[str, Any]] = None
           ) -> "BatchSpec":
        """The CLI constructor: ``--batch B`` -> seeds
        ``base_seed .. base_seed+B-1``; ``--seeds a:b`` -> the explicit
        half-open range. Both given must agree on B."""
        if seeds is not None:
            seeds = tuple(int(s) for s in seeds)
            if batch is not None and batch != len(seeds):
                raise ValueError(
                    f"--batch {batch} disagrees with --seeds "
                    f"({len(seeds)} worlds)")
        elif batch is not None:
            seeds = tuple(base_seed + i for i in range(batch))
        else:
            raise ValueError("BatchSpec.of needs batch= or seeds=")
        return cls(seeds=seeds, link_params=link_params)

    # -- per-world views --------------------------------------------------

    def world_link(self, link, b: int):
        """World ``b``'s concrete (host-level) link model: the engine's
        link with this world's parameters substituted as Python
        scalars. This is the link a solo run must use to reproduce
        world b bit-for-bit, and the object whose ``min_delay_us``
        gates windowed execution for the whole batch (the batched
        engine validates its window against the min over worlds)."""
        if not self.link_params:
            return link
        return rebind_link(link, {
            path: v[b].item() for path, v in self.link_params.items()})
