"""Sharded engines: the same superstep semantics over a device mesh.

The mesh/collective layer itself (MeshComm, ShardedDriver, make_mesh)
lives in :mod:`timewarp_tpu.parallel`; this module binds it to the two
engines:

- :class:`ShardedEdgeEngine` — the edge engine (edge_engine.py) under
  ``shard_map`` with the node axis sharded; ring delivery is a
  boundary-slice ``ppermute`` (one ICI neighbor hop per superstep),
  requiring a pure-shift topology.
- :class:`ShardedEngine` — the general engine (engine.py) with its
  exchange stage replaced by destination-shard bucketing + one
  ``lax.all_to_all`` per superstep.

The acceptance law is unchanged: an 8-device run must reproduce the
1-device trace **bit-for-bit** (tests/test_sharded.py runs both
engines on a virtual 8-device CPU mesh against the 1-device engines
and the host oracle).
"""

from __future__ import annotations

from typing import Optional

from ...utils import jaxconfig  # noqa: F401

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...core.scenario import Scenario
from ...net.delays import LinkModel
from ...parallel.mesh import (AxisName, Mesh, MeshComm,
                              ShardedDriver, axis_size, make_mesh)
from .batched import BatchSpec
from .common import group_rank
from .edge_engine import EdgeEngine, EdgeState
from .engine import EngineState, JaxEngine

__all__ = ["MeshComm", "ShardedBatchedEngine", "ShardedEdgeEngine",
           "ShardedEngine", "ShardedFusedSparseEngine", "make_mesh"]


def _refuse_record(record: str, who: str) -> str:
    """The node-sharded engines distribute each superstep's events
    across the mesh; the flight recorder's per-superstep event plane
    is a single-host debug artifact (like the device event ring).
    Refused loudly — a 1-device run of the same config records the
    identical events by the sharding exactness law (docs/engines.md).
    The WORLD-sharded engine records fine (each world's nodes are
    device-local) and does not route through this guard."""
    if record != "off":
        raise ValueError(
            f"{who}: record={record!r} is unsupported on the "
            "node-sharded engines (events would be scattered across "
            "shards); run the config on 1 device — bit-identical by "
            "the sharding exactness law — or use ShardedBatchedEngine "
            "for recorded fleets (docs/observability.md)")
    return record


class ShardedEdgeEngine(ShardedDriver, EdgeEngine):
    """Edge engine over a mesh: node axis sharded, ring delivery on
    ``ppermute``. Same ``run`` / ``run_quiet`` API as the local engine."""

    def __init__(self, scenario: Scenario, link: LinkModel,
                 mesh: Mesh, *, axis: AxisName = "nodes", seed: int = 0,
                 cap: int = 2, lint: str = "warn",
                 telemetry: str = "off", verify: str = "off",
                 record: str = "off") -> None:
        _refuse_record(record, type(self).__name__)
        super().__init__(scenario, link, seed=seed, cap=cap, lint=lint,
                         telemetry=telemetry, verify=verify)
        bad = [e for e, s in enumerate(self.topo.shift) if s is None]
        if bad:
            raise ValueError(
                f"edges {bad} are not pure shifts; the sharded edge "
                "engine delivers by ppermute only — irregular "
                "topologies need the all_to_all general sharded engine")
        self.mesh = mesh
        self.axis = axis
        D = axis_size(mesh, axis)
        self.comm = MeshComm(axis, scenario.n_nodes, D)

    # -- sharding specs --------------------------------------------------

    def _state_specs(self, st: EdgeState) -> EdgeState:
        leaf = self._leaf_spec
        return EdgeState(
            states=jax.tree.map(lambda x: leaf(x, False), st.states),
            wake=P(self.axis),
            q_rel=leaf(st.q_rel, True),
            q_step=leaf(st.q_step, True),
            q_pay=leaf(st.q_pay, True),
            overflow=P(), unrouted=P(), misrouted=P(), bad_delay=P(),
            delivered=P(), steps=P(), time=P(),
            fault_dropped=P(), restart_done=P(),
        )


class ShardedEngine(ShardedDriver, JaxEngine):
    """General (dynamic-destination) engine over a mesh: node axis
    sharded, message exchange via destination-shard bucketing + one
    ``lax.all_to_all`` per superstep (SURVEY.md §5.8's general-topology
    delivery — the TPU-native replacement for the reference's per-peer
    TCP sockets, `Transfer.hs:473,577`).

    Each device buckets its outgoing messages by destination shard
    (keyed on shard only, so in-bucket order is slot-major and
    *irrelevant*), the buckets swap in one collective, and contract
    #3's arrival order is restored downstream by the insertion sort on
    the global sender-major rank (``smrank``) that rides along with
    every message — exchange order never matters. Bucket
    capacity ``bucket_cap`` defaults to this device's total outbox
    width (``n_local * max_out``), which cannot overflow — bit-for-bit
    parity by construction; tune it down to shrink the exchange volume
    (≤ the true per-shard fan-in) and any overflow is counted in
    ``EngineState.overflow``, never silent.
    """

    def __init__(self, scenario: Scenario, link: LinkModel,
                 mesh: Mesh, *, axis: AxisName = "nodes", seed: int = 0,
                 bucket_cap: Optional[int] = None,
                 window: int = 1,
                 route_cap: Optional[int] = None,
                 lint: str = "warn", telemetry: str = "off",
                 verify: str = "off", record: str = "off") -> None:
        _refuse_record(record, type(self).__name__)
        super().__init__(scenario, link, seed=seed, window=window,
                         route_cap=route_cap, lint=lint,
                         telemetry=telemetry, verify=verify)
        self.mesh = mesh
        self.axis = axis
        D = axis_size(mesh, axis)
        self.comm = MeshComm(axis, scenario.n_nodes, D)
        full = self.comm.n_local * scenario.max_out
        self.bucket_cap = full if bucket_cap is None else min(
            bucket_cap, full)

    # -- the all_to_all exchange -----------------------------------------

    def _exchange(self, ok, drel, src_f, dst_f, smrank, woff, pay_cols):
        comm = self.comm
        D, nl, B = comm.n_shards, comm.n_local, self.bucket_cap
        # destination shard of each message; invalid -> sentinel D.
        # One variadic sort groups messages by shard with all values
        # riding along (no argsort + gather chain); in-bucket order is
        # irrelevant — insertion downstream sorts on (woff, smrank).
        dshard = jnp.where(ok, dst_f // jnp.int32(nl), jnp.int32(D))
        ops = jax.lax.sort(
            (dshard, drel, src_f, dst_f, smrank, woff) + pay_cols,
            dimension=0, num_keys=1)
        sk = ops[0]
        rank = group_rank(sk)
        fits = (sk < D) & (rank < B)
        brow = jnp.where(fits, sk, D)             # -> dropped scatter
        bcol = jnp.clip(rank, 0, B - 1)
        bucket_ovf = comm.all_sum(
            jnp.sum((sk < D) & (rank >= B), dtype=jnp.int32))

        def scat(x):
            buf = jnp.zeros((D, B), x.dtype)
            return buf.at[brow, bcol].set(x, mode="drop")

        # only fitting entries scatter (brow==D drops the rest), so the
        # occupancy mask is just "slot was written"
        b_ok = jnp.zeros((D, B), jnp.int8).at[brow, bcol].set(
            jnp.int8(1), mode="drop")
        bufs = [b_ok] + [scat(x) for x in ops[1:]]

        def a2a(x):
            return jax.lax.all_to_all(
                x, self.axis, split_axis=0, concat_axis=0).reshape(D * B)

        r_ok = a2a(b_ok).astype(bool)
        r_drel, r_src, r_dst, r_smrank, r_woff = (
            a2a(b) for b in bufs[1:6])
        r_pay = tuple(a2a(b) for b in bufs[6:])
        # received rows are local: subtract this shard's node offset
        off = jax.lax.axis_index(self.axis).astype(jnp.int32) \
            * jnp.int32(nl)
        return (r_ok, r_drel, r_src, r_dst - off, r_smrank, r_woff,
                r_pay, bucket_ovf)

    # -- sharding specs --------------------------------------------------

    def _state_specs(self, st: EngineState) -> EngineState:
        leaf = self._leaf_spec
        return EngineState(
            states=jax.tree.map(lambda x: leaf(x, False), st.states),
            wake=P(self.axis),
            mb_rel=leaf(st.mb_rel, True),
            mb_src=leaf(st.mb_src, True),
            mb_payload=leaf(st.mb_payload, True),
            overflow=P(), bad_dst=P(), bad_delay=P(), short_delay=P(),
            route_drop=P(),
            delivered=P(), steps=P(), time=P(),
            # the event ring is a single-chip debug artifact
            # (record_events=0 sharded: zero-size, replicated)
            ev_time=P(), ev_meta=P(), ev_count=P(),
            # faults are the local/world-sharded engines' lever; the
            # node-sharded engine carries the (empty) leaves replicated
            fault_dropped=P(), restart_done=P(),
        )


class ShardedBatchedEngine(ShardedDriver, JaxEngine):
    """The fleet over a mesh: the **world axis** sharded, nodes
    device-local. Each device runs ``B / D`` complete worlds — the
    embarrassingly-parallel layout the replica-sweep workload wants
    (worlds are independent, so the superstep needs NO collectives;
    the only mesh-wide reduction is run_quiet's "any world still
    active" liveness check). Contrast :class:`ShardedEngine`, which
    shards the *node* axis of one world and pays an ``all_to_all``
    per superstep — compose them by passing this engine a mesh axis
    of a multi-axis mesh when single-world capacity AND fleet width
    are both needed.

    The batch exactness law is unchanged: world b sliced out of the
    gathered state is bit-identical to the solo run with that world's
    seed/link (tests/test_world_batch.py runs this on the virtual
    8-device CPU mesh)."""

    def __init__(self, scenario: Scenario, link: LinkModel,
                 mesh: Mesh, *, batch: BatchSpec,
                 axis: AxisName = "worlds", seed: int = 0,
                 window=1, route_cap: Optional[int] = None,
                 lint: str = "warn", faults=None,
                 telemetry: str = "off", controller=None,
                 verify: str = "off", record: str = "off",
                 record_cap=None, speculate: str = "off") -> None:
        # the flight recorder works here: worlds are whole per device
        # (comm stays LocalComm), and the per-world [T, B_local, R]
        # event planes gather over the world axis like any trace leaf
        # — and so does the speculation plane (speculate/): worlds
        # are device-local, so the violation decode sees the gathered
        # [T, B] columns exactly like the single-chip fleet's
        super().__init__(scenario, link, seed=seed, window=window,
                         route_cap=route_cap, lint=lint, batch=batch,
                         faults=faults, telemetry=telemetry,
                         controller=controller, verify=verify,
                         record=record, record_cap=record_cap,
                         speculate=speculate)
        if batch is None:
            raise ValueError(
                "ShardedBatchedEngine shards the world axis; it needs "
                "a BatchSpec (for a single sharded world use "
                "ShardedEngine)")
        self.mesh = mesh
        self.axis = axis
        D = axis_size(mesh, axis)
        if batch.B % D:
            raise ValueError(
                f"batch of {batch.B} worlds not divisible over "
                f"{D} devices (worlds are whole — pad the seed list "
                "or shrink the mesh)")
        #: worlds resident per device
        self.worlds_local = batch.B // D
        # comm stays LocalComm: every world's nodes live on one device

    # -- world-axis sharding ---------------------------------------------

    def _state_specs(self, st: EngineState) -> EngineState:
        # uniform rule: every leaf's LEADING axis is the world axis
        ax = self.axis
        return jax.tree.map(
            lambda x: P(ax, *([None] * (x.ndim - 1))), st)

    def _trace_spec(self) -> P:
        # scan-trace leaves are [T, B_local] per device: gather the
        # world axis, not the (nonexistent) replication
        return P(None, self.axis)

    def _step_all(self, st, with_trace: bool):
        # this device's slice of the world context (seed words + link
        # parameter vectors + fault tables): the identity arrives as
        # the driver-bound replicated operand (engine.py WorldIdentity
        # — traced, never a closure constant, so an identity swap is
        # zero-recompile here too), sliced by mesh position — the
        # same pattern as MeshComm.local_rows
        ident = self._ident_in
        if ident is None:
            ident = self._identity()
        Bl = self.worlds_local
        off = jax.lax.axis_index(self.axis).astype(jnp.int32) \
            * jnp.int32(Bl)
        def sl(v):
            return jax.lax.dynamic_slice_in_dim(v, off, Bl, axis=0)
        ftv = None if ident.ftv is None else \
            jax.tree.map(sl, ident.ftv)
        return self._vstep(st, sl(ident.s0v), sl(ident.s1v),
                           {k: sl(v) for k, v in ident.lpv.items()},
                           ftv, with_trace)

    def _any_world(self, x):
        # liveness must be mesh-wide: one device's worlds finishing
        # must not stop the others' (int32 psum — bool all-reduce
        # does not lower on the TPU path, see MeshComm.all_min)
        return jax.lax.psum(x.astype(jnp.int32), self.axis) > 0


class ShardedFusedSparseEngine(ShardedEngine):
    """The multi-chip windowed path's share of the fused-sparse lever
    (fused_sparse.py): sampling, destination-shard bucketing, and the
    ``all_to_all`` exchange are :class:`ShardedEngine`'s — message
    placement is a collective, not a kernel concern — but each shard's
    post-exchange *mailbox insertion* runs the fused Pallas kernel in
    its pre-sampled mode: deliver-times arrive with the batch, holes
    are ranked in-VMEM per block, and the local [K, n_local] mailbox
    planes stream through the kernel exactly once (no free-rows sort,
    no per-plane scatters — ``JaxEngine._fused_holes``). Semantics,
    counters, and trace digests are bit-identical to
    :class:`ShardedEngine` (tests/test_fused_sparse.py sharded leg)."""

    def __init__(self, scenario: Scenario, link: LinkModel,
                 mesh: Mesh, *, axis: AxisName = "nodes", seed: int = 0,
                 bucket_cap: Optional[int] = None,
                 window: int = 1, lint: str = "warn",
                 telemetry: str = "off", verify: str = "off",
                 record: str = "off") -> None:
        _refuse_record(record, type(self).__name__)
        super().__init__(scenario, link, mesh, axis=axis, seed=seed,
                         bucket_cap=bucket_cap, window=window,
                         route_cap=None, lint=lint, telemetry=telemetry,
                         verify=verify)
        # the kernel machinery's home since round 12 (pallas_insert.py;
        # fused_sparse re-exports for older callers)
        from .pallas_insert import _build_kernel, _insertion_plan
        sc = scenario
        nl = self.comm.n_local
        # post-exchange batch width: one bucket per peer shard
        self._S2, R, G = _insertion_plan(
            sc, nl, self.comm.n_shards * self.bucket_cap,
            who="ShardedFusedSparseEngine",
            what_n="n_nodes per shard")
        self._fused_holes = True
        self._ins_kernel = _build_kernel(
            K=sc.mailbox_cap, P=sc.payload_width, R=R, G=G,
            SR=self._S2 // 128, n=nl, M=sc.max_out, W=self.window,
            inbox_src=sc.inbox_src, mode="drel", needs_key=False,
            s0=0, s1=0, delay_fn=None)

    def _insert_sorted(self, mb_rel, mb_src, mb_payload, sd, ok_s,
                       drel_s, src_s, pay_s, free_rows, counts):
        from .pallas_insert import _fused_insert_call
        sc = self.scenario
        mrel, msrc, mpay, cnts = _fused_insert_call(
            self._ins_kernel, self._S2, self.comm.n_local,
            sc.mailbox_cap, sc.payload_width, sc.inbox_src,
            jnp.zeros(4, jnp.int32), sd, drel_s, src_s, pay_s,
            mb_rel, mb_src, mb_payload)
        return mrel, msrc, mpay, jnp.sum(cnts[0], dtype=jnp.int32)
