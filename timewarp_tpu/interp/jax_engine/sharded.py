"""Sharded engines: the same superstep semantics over a device mesh.

SURVEY.md §2.5/§5.8: simulated-node message passing maps onto XLA
collectives over the mesh's ICI — ``ppermute`` for fixed shift
topologies (the token ring's neighbor exchange), ``all_to_all`` for
dynamic destinations — instead of the reference's TCP sockets
(`/root/reference/src/Control/TimeWarp/Rpc/Transfer.hs:473,577`).

:class:`ShardedEdgeEngine` is the edge engine (edge_engine.py) run
under ``shard_map`` with the node axis sharded. All communication goes
through :class:`MeshComm`: the global clock min is an ``all_gather`` +
local reduce, counters and trace digests are ``psum`` (the digests are
*wrapping uint32 sums*, so the cross-device reduction is exact, not
approximate), and the ring delivery roll becomes a boundary-slice
``ppermute`` — one neighbor hop over ICI per superstep, never an
all-gather of the payload arrays. Requires a pure-shift topology
(every edge a constant ring offset); anything else needs cross-shard exchange bucketed by
destination shard (``lax.all_to_all``) — the general sharded engine.

The acceptance law is unchanged: an 8-device run must reproduce the
1-device trace **bit-for-bit** (tests/test_sharded.py runs the engine
on a virtual 8-device CPU mesh against both the 1-device engine and
the host oracle).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

from ...utils import jaxconfig  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.scenario import Scenario
from ...net.delays import LinkModel
from .common import LocalComm, group_rank
from .edge_engine import EdgeEngine, EdgeState
from .engine import EngineState, JaxEngine

__all__ = ["MeshComm", "ShardedEdgeEngine", "ShardedEngine", "make_mesh"]


def make_mesh(n_devices: Optional[int] = None,
              axis: str = "nodes") -> Mesh:
    """A 1-D mesh over the first ``n_devices`` available devices."""
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    return Mesh(np.asarray(devs[:n_devices]), (axis,))


class MeshComm(LocalComm):
    """Mesh collectives behind the LocalComm interface; valid only
    inside a ``shard_map`` body with ``axis`` bound."""

    def __init__(self, axis: str, n_global: int, n_shards: int) -> None:
        if n_global % n_shards:
            raise ValueError(
                f"n_nodes {n_global} not divisible by {n_shards} shards")
        self.axis = axis
        self.n_global = n_global
        self.n_shards = n_shards
        self.n_local = n_global // n_shards

    def node_ids(self) -> jax.Array:
        off = jax.lax.axis_index(self.axis).astype(jnp.int32) \
            * jnp.int32(self.n_local)
        return off + jnp.arange(self.n_local, dtype=jnp.int32)

    def all_min(self, x: jax.Array) -> jax.Array:
        # Not ``pmin``: the int64 min-all-reduce fails to lower on the
        # TPU compiler path ("Supported lowering only of Sum all
        # reduce"); gathering one scalar per device and reducing
        # locally lowers everywhere and costs D words on ICI.
        return jax.lax.all_gather(x, self.axis).min()

    def all_sum(self, x: jax.Array) -> jax.Array:
        return jax.lax.psum(x, self.axis)

    def roll(self, x: jax.Array, s: int) -> jax.Array:
        """Global roll by ``s`` along the last (node) axis: local roll +
        boundary-slice ``ppermute`` to the next shard (and a whole-shard
        ``ppermute`` when ``s`` spans shards). One ICI neighbor hop for
        the ring's s=1."""
        s = s % self.n_global
        if s == 0:
            return x
        D, nl = self.n_shards, self.n_local
        whole, rem = divmod(s, nl)
        if whole:
            perm = [(i, (i + whole) % D) for i in range(D)]
            x = jax.lax.ppermute(x, self.axis, perm)
        if rem:
            tail = x[..., nl - rem:]
            perm = [(i, (i + 1) % D) for i in range(D)]
            recv = jax.lax.ppermute(tail, self.axis, perm)
            x = jnp.concatenate([recv, x[..., :nl - rem]], axis=-1)
        return x

    def local_rows(self, table: np.ndarray) -> jax.Array:
        off = jax.lax.axis_index(self.axis).astype(jnp.int32) \
            * jnp.int32(self.n_local)
        return jax.lax.dynamic_slice_in_dim(
            jnp.asarray(table), off, self.n_local, axis=-1)


class _ShardedDriver:
    """Shared ``shard_map`` driver for the sharded engines: state
    placement with ``NamedSharding`` (so XLA keeps every per-node array
    resident on its owning device across the whole loop), and the
    jitted scan / while_loop wrappers. The concrete engine supplies
    ``_state_specs`` (its state's PartitionSpecs), ``_superstep``, and
    ``_next_event`` (the quiescence expression, inherited from its
    local base class)."""

    def init_state(self):
        st = super().init_state()
        specs = self._state_specs(st)
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            st, specs)

    @partial(jax.jit, static_argnums=(0, 2))
    def _run_scan(self, st, max_steps: int):
        specs = self._state_specs(st)

        def body(s):
            def step(carry, _):
                return self._superstep(carry, True)
            return jax.lax.scan(step, s, None, length=max_steps)

        return jax.shard_map(
            body, mesh=self.mesh, in_specs=(specs,),
            out_specs=(specs, P()), check_vma=False)(st)

    @partial(jax.jit, static_argnums=(0,))
    def _run_while(self, st, max_steps):
        from ...core.scenario import NEVER

        specs = self._state_specs(st)
        max_steps = jnp.asarray(max_steps, jnp.int64)

        def body_fn(s, ms):
            start_steps = s.steps

            def cond(carry):
                nxt = self.comm.all_min(self._next_event(carry))
                return (nxt < NEVER) & (carry.steps - start_steps < ms)

            def body(carry):
                return self._superstep(carry, False)[0]

            return jax.lax.while_loop(cond, body, s)

        return jax.shard_map(
            body_fn, mesh=self.mesh, in_specs=(specs, P()),
            out_specs=specs, check_vma=False)(st, max_steps)


class ShardedEdgeEngine(_ShardedDriver, EdgeEngine):
    """Edge engine over a mesh: node axis sharded, ring delivery on
    ``ppermute``. Same ``run`` / ``run_quiet`` API as the local engine."""

    def __init__(self, scenario: Scenario, link: LinkModel,
                 mesh: Mesh, *, axis: str = "nodes", seed: int = 0,
                 cap: int = 2) -> None:
        super().__init__(scenario, link, seed=seed, cap=cap)
        bad = [e for e, s in enumerate(self.topo.shift) if s is None]
        if bad:
            raise ValueError(
                f"edges {bad} are not pure shifts; the sharded edge "
                "engine delivers by ppermute only — irregular "
                "topologies need the all_to_all general sharded engine")
        self.mesh = mesh
        self.axis = axis
        D = mesh.shape[axis]
        self.comm = MeshComm(axis, scenario.n_nodes, D)

    # -- sharding specs --------------------------------------------------

    def _state_specs(self, st: EdgeState) -> EdgeState:
        ax = self.axis

        def leaf(x, last_axis: bool):
            nd = getattr(x, "ndim", 0)
            if nd == 0:
                return P()
            if last_axis:
                return P(*([None] * (nd - 1) + [ax]))
            return P(ax, *([None] * (nd - 1)))

        return EdgeState(
            states=jax.tree.map(lambda x: leaf(x, False), st.states),
            wake=P(ax),
            q_rel=leaf(st.q_rel, True),
            q_step=leaf(st.q_step, True),
            q_pay=leaf(st.q_pay, True),
            q_valid=leaf(st.q_valid, True),
            overflow=P(), unrouted=P(), misrouted=P(), bad_delay=P(),
            delivered=P(), steps=P(), time=P(),
        )


class ShardedEngine(_ShardedDriver, JaxEngine):
    """General (dynamic-destination) engine over a mesh: node axis
    sharded, message exchange via destination-shard bucketing + one
    ``lax.all_to_all`` per superstep (SURVEY.md §5.8's general-topology
    delivery — the TPU-native replacement for the reference's per-peer
    TCP sockets, `Transfer.hs:473,577`).

    Each device buckets its outgoing messages by destination shard
    (stable, so sender-major order survives), the buckets swap in one
    collective, and the received (src-shard-major, in-bucket) order
    *is* global sender-major order — contract #3 for free. Bucket
    capacity ``bucket_cap`` defaults to this device's total outbox
    width (``n_local * max_out``), which cannot overflow — bit-for-bit
    parity by construction; tune it down to shrink the exchange volume
    (≤ the true per-shard fan-in) and any overflow is counted in
    ``EngineState.overflow``, never silent.
    """

    def __init__(self, scenario: Scenario, link: LinkModel,
                 mesh: Mesh, *, axis: str = "nodes", seed: int = 0,
                 bucket_cap: Optional[int] = None) -> None:
        super().__init__(scenario, link, seed=seed)
        self.mesh = mesh
        self.axis = axis
        D = mesh.shape[axis]
        self.comm = MeshComm(axis, scenario.n_nodes, D)
        full = self.comm.n_local * scenario.max_out
        self.bucket_cap = full if bucket_cap is None else min(
            bucket_cap, full)

    # -- the all_to_all exchange -----------------------------------------

    def _exchange(self, ok, drel, src_f, dst_f, smrank, pay_cols):
        comm = self.comm
        D, nl, B = comm.n_shards, comm.n_local, self.bucket_cap
        # destination shard of each message; invalid -> sentinel D.
        # One variadic sort groups messages by shard with all values
        # riding along (no argsort + gather chain); in-bucket order is
        # irrelevant — insertion downstream sorts on smrank.
        dshard = jnp.where(ok, dst_f // jnp.int32(nl), jnp.int32(D))
        ops = jax.lax.sort(
            (dshard, drel, src_f, dst_f, smrank) + pay_cols,
            dimension=0, num_keys=1)
        sk = ops[0]
        rank = group_rank(sk)
        fits = (sk < D) & (rank < B)
        brow = jnp.where(fits, sk, D)             # -> dropped scatter
        bcol = jnp.clip(rank, 0, B - 1)
        bucket_ovf = comm.all_sum(
            jnp.sum((sk < D) & (rank >= B), dtype=jnp.int32))

        def scat(x):
            buf = jnp.zeros((D, B), x.dtype)
            return buf.at[brow, bcol].set(x, mode="drop")

        # only fitting entries scatter (brow==D drops the rest), so the
        # occupancy mask is just "slot was written"
        b_ok = jnp.zeros((D, B), jnp.int8).at[brow, bcol].set(
            jnp.int8(1), mode="drop")
        bufs = [b_ok] + [scat(x) for x in ops[1:]]

        def a2a(x):
            return jax.lax.all_to_all(
                x, self.axis, split_axis=0, concat_axis=0).reshape(D * B)

        r_ok = a2a(b_ok).astype(bool)
        r_drel, r_src, r_dst, r_smrank = (a2a(b) for b in bufs[1:5])
        r_pay = tuple(a2a(b) for b in bufs[5:])
        # received rows are local: subtract this shard's node offset
        off = jax.lax.axis_index(self.axis).astype(jnp.int32) \
            * jnp.int32(nl)
        return (r_ok, r_drel, r_src, r_dst - off, r_smrank, r_pay,
                bucket_ovf)

    # -- sharding specs --------------------------------------------------

    def _state_specs(self, st: EngineState) -> EngineState:
        ax = self.axis

        def leaf(x, last_axis: bool):
            nd = getattr(x, "ndim", 0)
            if nd == 0:
                return P()
            if last_axis:
                return P(*([None] * (nd - 1) + [ax]))
            return P(ax, *([None] * (nd - 1)))

        return EngineState(
            states=jax.tree.map(lambda x: leaf(x, False), st.states),
            wake=P(ax),
            mb_rel=leaf(st.mb_rel, True),
            mb_src=leaf(st.mb_src, True),
            mb_payload=leaf(st.mb_payload, True),
            mb_valid=leaf(st.mb_valid, True),
            overflow=P(), bad_dst=P(), bad_delay=P(),
            delivered=P(), steps=P(), time=P(),
        )
