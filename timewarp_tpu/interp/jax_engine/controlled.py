"""The controller-driven chunked driver (dispatch/; docs/dispatch.md).

``run_controlled`` is ``run_stream``'s adaptive sibling: the fleet (or
solo run) executes one jitted chunk at a time, and **between** chunks
the bound :class:`~timewarp_tpu.dispatch.DispatchController` reads the
chunk's telemetry (``engine.last_run_telemetry``) and picks the next
chunk's dispatch knobs — window width and rung pin as *traced scalars*
(``DynDispatch``, common.py: new values re-invoke the same executable;
nothing retraces), chunk length through the pow2-padded scan cache
(a revisited length is a cache hit; ``last_run_stats``'s per-chunk
compile attribution proves it).

Laws (tests/test_zzzdispatch.py):

- **replay law** — re-running with ``mode="replay"`` over the emitted
  decision trace is bit-identical on states, traces, digests, and
  checkpoints (solo, batched, under faults);
- **per-chunk static equivalence** — each chunk is bit-identical to a
  static engine constructed with that chunk's window, run for that
  chunk's budget from the same state (degradation-free runs; under a
  degradation window the device clamp varies the effective window
  *within* a chunk, which no single static construction can express —
  there the replay law and the ``short_delay == 0`` evidence carry
  the guarantee).

The mixin serves every chunk-capable engine; engines whose window or
rung is a compile-time constant (EdgeEngine — classic supersteps;
FusedSparseEngine and ``insert="pallas"`` — kernels bake the width)
set ``_dyn_ok = False`` and adapt chunk length only, with the pinned
knob values recorded in the trace.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .common import DynDispatch

__all__ = ["ControlledRunMixin"]


class ControlledRunMixin:
    """``controller=`` wiring + the adaptive chunked driver (module
    docstring). Host state only — an engine without a controller is
    byte-identical to the pre-dispatch engine (``_dyn`` stays None, so
    every traced driver lowers its original program)."""

    #: the bound DispatchController (None = static dispatch)
    controller = None
    #: the traced DynDispatch operand while a chunk traces (None =
    #: static values — the compile-time constants the engine was
    #: constructed with)
    _dyn = None
    #: whether this engine threads dynamic window/rung scalars
    #: (JaxEngine and its window-dynamic subclasses); False = the
    #: controller adapts chunk length only
    _dyn_ok = False
    #: the emitted decision list of the last run_controlled call
    last_run_decisions = None

    def _bind_controller(self, controller) -> None:
        """Engine-construction half of the wiring: validate the
        controller against this engine's observability mode. The
        engine binding is *loud*: an auto controller without
        telemetry would silently decide from nothing every chunk."""
        if controller is None:
            return
        if not hasattr(controller, "decide") \
                or not hasattr(controller, "begin"):
            raise ValueError(
                f"controller must be a dispatch.DispatchController "
                f"(or duck-type decide/begin), got {controller!r}")
        if getattr(controller, "mode", "auto") == "auto" \
                and self.telemetry == "off":
            raise ValueError(
                "an auto dispatch controller consumes "
                "last_run_telemetry between chunks; build the engine "
                "with telemetry='counters' (or 'full') — replay mode "
                "alone runs with telemetry off (docs/dispatch.md)")
        self.controller = controller

    def dyn_values(self, decision) -> Optional[DynDispatch]:
        """The traced knob operand for one decision — None when this
        engine's knobs are compile-time constants (chunk-length-only
        adaptation)."""
        if not self._dyn_ok:
            return None
        return DynDispatch(window=jnp.int64(decision.window_us),
                           rung_pin=jnp.int32(decision.rung_pin))

    def _controlled_progress(self, state, budgets, start):
        """(steps_done, remaining, active) — ``fleet_progress``'s law
        generalized to solo states (0-d arrays reduce identically)."""
        steps_done = (np.asarray(jax.device_get(state.steps), np.int64)
                      - np.asarray(start, np.int64))
        remaining = np.maximum(np.asarray(budgets, np.int64)
                               - steps_done, 0)
        active = (np.asarray(jax.device_get(self.world_active(state)))
                  & (remaining > 0))
        return steps_done, remaining, active

    def run_controlled(self, budgets, state=None):
        """Run to quiescence/budget under the bound controller,
        deciding the dispatch knobs chunk by chunk. Accepts the same
        budget forms as :meth:`run` (int; batched engines also a
        per-world vector). Returns ``(final_state, trace)`` —
        batched engines a per-world trace list — exactly like
        :meth:`run`; the decision trace lands on
        ``last_run_decisions`` (and streams to an attached metrics
        registry as ``decision`` lines)."""
        from ...trace.events import SuperstepTrace
        ctrl = self.controller
        if ctrl is None:
            raise ValueError(
                "run_controlled needs a dispatch controller; build "
                "the engine with controller=DispatchController(...) "
                "(docs/dispatch.md) — static runs use run()/run_quiet")
        ctrl.begin(self)
        batch = getattr(self, "batch", None)
        if batch is not None:
            budgets = np.broadcast_to(
                np.asarray(budgets, np.int64), (batch.B,)).copy()
        else:
            budgets = int(budgets)
        if np.min(budgets) < 0:
            raise ValueError("step budgets must be >= 0")
        st = state if state is not None else self.init_state()
        start = np.asarray(jax.device_get(st.steps), np.int64)
        rows = [[] for _ in range(batch.B)] if batch is not None \
            else []
        chunk_stats = []
        frame_chunks = []
        flight_chunks = []
        self.last_run_telemetry = None
        ci = 0
        while True:
            _, remaining, active = self._controlled_progress(
                st, budgets, start)
            if not np.any(active):
                break
            t_now = int(np.min(np.asarray(
                jax.device_get(st.time), np.int64)))
            dec, fresh = ctrl.decide(ci, self.last_run_telemetry,
                                     t_now)
            if self._dyn_ok and dec.window_us > self.window:
                from ...dispatch.trace import DispatchTraceError
                raise DispatchTraceError(
                    f"chunk {ci} decision requests window "
                    f"{dec.window_us} µs beyond the engine bound "
                    f"{self.window} µs")
            if fresh and self.metrics is not None:
                self.metrics.emit("decision", label=self.metrics_label,
                                  chunk=dec.chunk,
                                  window_us=dec.window_us,
                                  rung_pin=dec.rung_pin,
                                  chunk_len=dec.chunk_len)
            dyn = self.dyn_values(dec)
            kw = {} if dyn is None else {"_dyn": dyn}
            if batch is not None:
                vec = np.where(active,
                               np.minimum(remaining, dec.chunk_len), 0)
                st, traces = self.run(vec, state=st, **kw)
                for b in range(batch.B):
                    rows[b].extend(traces[b].row(i)
                                   for i in range(len(traces[b])))
            else:
                step_n = int(min(int(remaining), dec.chunk_len))
                st, tr = self.run(step_n, state=st, **kw)
                rows.extend(tr.row(i) for i in range(len(tr)))
            chunk_stats.append(self.last_run_stats)
            frame_chunks.append(self.last_run_telemetry)
            flight_chunks.append(self.last_run_flight)
            ci += 1
        if chunk_stats:
            self._stats_merge(chunk_stats)
        if self.telemetry != "off":
            # post-run consumers (the CLI's --metrics-out/--trace-out
            # exporters) must see the WHOLE run's telemetry, not the
            # final chunk's — the controller consumed the per-chunk
            # views already
            from ...obs.telemetry import concat_frames
            self.last_run_telemetry = concat_frames(frame_chunks)
        if getattr(self, "record", "off") != "off":
            # same whole-run contract for the flight log (indices are
            # run-global already — each chunk drained as it committed)
            from ...obs.flight import concat_flight
            self.last_run_flight = concat_flight(flight_chunks)
        self.last_run_decisions = ctrl.decisions
        if batch is not None:
            return st, [SuperstepTrace.from_rows(r) for r in rows]
        return st, SuperstepTrace.from_rows(rows)
