"""Counter-based RNG key derivation shared by oracle and engine.

The reference threads one ``StdGen`` through the emulated network
(seeded ``mkStdGen 0``, examples/token-ring/Main.hs:60, 82-85) — a
*sequential* RNG that cannot be evaluated in parallel. The TPU build
replaces it with counter-based derivation (SURVEY.md §5.3): every
random draw is keyed by *what* it is for — ``(node, time)`` for a
firing, ``(src, dst, time, slot)`` for a link sample — so any engine,
batched or sequential, sharded or not, derives bit-identical streams.

Threefry (JAX's default) is integer-based and backend-deterministic, so
fold-in chains agree between the CPU oracle and the TPU engine.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["fold_time", "fire_key", "msg_key"]

_MASK32 = (1 << 32) - 1


def fold_time(key: jax.Array, t) -> jax.Array:
    """Fold a µs timestamp (int64 range) into a key as two 32-bit words."""
    t = jnp.asarray(t, jnp.int64)
    lo = jnp.asarray(t & _MASK32, jnp.uint32)
    hi = jnp.asarray((t >> 32) & _MASK32, jnp.uint32)
    return jax.random.fold_in(jax.random.fold_in(key, lo), hi)


def fire_key(key: jax.Array, node, t) -> jax.Array:
    """Key for one node's firing at virtual time ``t``."""
    return fold_time(jax.random.fold_in(key, jnp.asarray(node, jnp.uint32)), t)


def msg_key(key: jax.Array, src, dst, t, slot) -> jax.Array:
    """Key for the link sample of one message: sender ``src`` -> ``dst``
    emitted at time ``t`` from outbox slot ``slot``.

    ≙ the role of the seeded ``Delays`` function in the removed API
    (examples/token-ring/Main.hs:73-77), made order-independent.
    """
    k = jax.random.fold_in(key, jnp.asarray(src, jnp.uint32))
    k = jax.random.fold_in(k, jnp.asarray(dst, jnp.uint32))
    k = fold_time(k, t)
    return jax.random.fold_in(k, jnp.asarray(slot, jnp.uint32))
