"""Edge engine: sort/scatter-free batched execution for static topologies.

The general engine (engine.py) routes messages with one variadic sort
plus 2+P mailbox scatters per superstep; on TPU scatters are the
dominant cost (profiling/superstep_breakdown.md: random scatter
≈ 1 ms/131k updates, int64 scatter ≈ 15 ms, while elementwise/sort
work is ~free). When the communication graph is
*static* — every outbox slot always targets the same destination
(``Scenario.static_dst``) — routing needs none of that:

- the graph is inverted **on the host** into per-node in-edge tables;
- per-edge bounded queues hold in-flight messages in ``[E, C, N]``
  layout (minor dim = node axis: no lane padding, perfect VPU tiling);
- delivery moves each sender's outbox slot to its receiver's edge
  queue by a *static* index map — a gather, and for pure-shift
  topologies (the ring: ``dst = (i+1) mod N``) ``jnp.roll``, which XLA
  fuses into the surrounding elementwise work;
- queue insert/remove are one-hot elementwise updates over the static
  capacity axis ``C`` — no scatter anywhere.

This is the reference's event loop (TimedT.hs:234-286) specialized the
TPU way: the priority queue becomes per-edge arrival buffers whose
minimum is a masked reduction.

Semantics match core/scenario.py's superstep contract with one scoped
difference: capacity is **per edge** (``cap`` messages in flight per
(src,slot)→dst edge) rather than per-node ``mailbox_cap``. Overflow is
still counted and dropped, never silent; trace parity with the oracle
is bit-for-bit in all no-overflow regimes (the parity tests assert
overflow == 0), which is the regime the capacity declarations are for.

Inbox ordering: for ``commutative_inbox`` scenarios the inbox is
presented unsorted (the step result and the order-independent digests
are invariant to slot order, so parity holds bit-for-bit); otherwise
one variadic ``lax.sort`` along the slot axis — cheap in this layout —
restores contract #2's ``(deliver_time, insert_step, sender-major)``
order.

Delays must fit int32 µs (< ~35 min): queue times are stored relative
to the engine's rebased epoch so no int64 ever needs scattering (or
storing per-slot).
"""

from __future__ import annotations

from functools import partial
from typing import Any, List, NamedTuple, Optional, Tuple

from ...utils import jaxconfig  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np

from ...core.rng import fire_bits, msg_bits, seed_words
from ...core.scenario import NEVER, Inbox, Outbox, Scenario
from ...net.delays import LinkModel
from ...trace.events import SuperstepTrace
from ...trace.hashing import FIRED, RECV, SENT, mix32_jnp
from .common import I32MAX as _I32MAX
from .common import LocalComm, RunStatsMixin, StepOut as _StepOut
from .common import padded_scan, scan_pad
from .controlled import ControlledRunMixin
from .common import thi as _thi, tlo as _tlo, u32sum as _u32sum
from ...integrity.runner import VerifiedRunMixin
from ...obs.flight import FlightRecorderMixin

__all__ = ["EdgeEngine", "EdgeState", "EdgeTopology"]


class EdgeTopology(NamedTuple):
    """Host-side inversion of ``Scenario.static_dst`` (int32 [N, M],
    -1 = unused slot) into receiver-centric in-edge tables.

    Edge order per node is *arbitrary* (the slot-major fast path orders
    edges by outbox column, the inversion path by (src, slot) rank) —
    contract #2/#3 ordering is enforced downstream by the explicit
    ``(deliver_time, insert_step, src, slot)`` inbox sort keys, never
    by edge index.
    """
    n_edges: int               # E = max in-degree
    in_valid: np.ndarray       # bool [E, N] — edge exists
    in_src: np.ndarray         # int32 [E, N] — sender (0 where invalid)
    in_slot: np.ndarray        # int32 [E, N] — sender's outbox slot
    in_flat: np.ndarray        # int32 [E, N] — slot*N + src, for 1D gather
    shift: List[Optional[Tuple[int, int]]]  # per edge: (roll, slot) or None

    @staticmethod
    def build(static_dst: np.ndarray, n: int) -> "EdgeTopology":
        sd = np.asarray(static_dst, np.int32)
        if sd.shape[0] != n:
            raise ValueError(f"static_dst rows {sd.shape[0]} != n_nodes {n}")
        if n * sd.shape[1] >= 2**31:
            # in_flat = slot*N + src must fit int32 (mirrors the
            # JaxEngine smrank guard)
            raise ValueError(
                "n_nodes * max_out must fit int32 (in_flat gather index)")
        used = sd >= 0
        if np.any(sd[used] >= n):
            raise ValueError("static_dst contains out-of-range destination")
        M = sd.shape[1]
        # slot-major fast path: when every declared outbox column is a
        # uniform ring shift, make each column one shift edge directly.
        # The receiver-centric lexsort below ranks in-edges by (src,
        # slot) per receiver, and that ranking is NOT a uniform shift at
        # the ring wrap (receiver 1's smallest src may come from a
        # different column than receiver 20's) — which would wrongly
        # disqualify multi-shift topologies from the ppermute engine.
        # Inbox-order semantics don't depend on edge rank: the contract
        # #2 sort keys on actual (src, slot).
        ids64 = np.arange(n, dtype=np.int64)
        col_shift: List[Optional[int]] = []
        for k in range(M):
            col = sd[:, k]
            if (col < 0).all():
                col_shift.append(-1)        # unused column: skip
            elif (col >= 0).all():
                d = (col.astype(np.int64) - ids64) % n
                col_shift.append(int(d[0]) if (d == d[0]).all() else None)
            else:
                col_shift.append(None)      # partially declared
        if all(s is not None for s in col_shift) \
                and any(s != -1 for s in col_shift):
            cols = [k for k in range(M) if col_shift[k] != -1]
            E = len(cols)
            in_valid = np.ones((E, n), bool)
            in_src = np.stack([
                ((ids64 - col_shift[k]) % n).astype(np.int32)
                for k in cols])
            in_slot = np.stack([np.full(n, k, np.int32) for k in cols])
            in_flat = in_slot * np.int32(n) + in_src
            shift = [(int(col_shift[k]), k) for k in cols]
            return EdgeTopology(E, in_valid, in_src, in_slot, in_flat,
                                shift)
        # vectorized graph inversion: flatten (src, slot) pairs, order by
        # (dst, src, slot) — sender-major within each receiver
        flat = sd.ravel()
        srcs = np.repeat(np.arange(n, dtype=np.int32), M)
        slots = np.tile(np.arange(M, dtype=np.int32), n)
        mask = flat >= 0
        d, s, sl = flat[mask], srcs[mask], slots[mask]
        if d.size == 0:
            raise ValueError("static_dst declares no edges")
        o = np.lexsort((sl, s, d))
        d, s, sl = d[o], s[o], sl[o]
        starts = np.searchsorted(d, np.arange(n, dtype=np.int32))
        e_idx = np.arange(d.size, dtype=np.int64) - starts[d]
        E = int(e_idx.max()) + 1
        in_valid = np.zeros((E, n), bool)
        in_src = np.zeros((E, n), np.int32)
        in_slot = np.zeros((E, n), np.int32)
        in_valid[e_idx, d] = True
        in_src[e_idx, d] = s
        in_slot[e_idx, d] = sl
        in_flat = in_slot * np.int32(n) + in_src
        # pure-shift detection: edge e is src = (i - s) mod N for all i
        shift: List[Optional[Tuple[int, int]]] = []
        ids = np.arange(n, dtype=np.int64)
        for e in range(E):
            if in_valid[e].all() and (in_slot[e] == in_slot[e, 0]).all():
                d = (ids - in_src[e]) % n
                if (d == d[0]).all():
                    shift.append((int(d[0]), int(in_slot[e, 0])))
                    continue
            shift.append(None)
        return EdgeTopology(E, in_valid, in_src, in_slot, in_flat, shift)


class EdgeState(NamedTuple):
    """Complete simulation state — one pytree, checkpointable and
    shardable. Queue axes: [E edges, C capacity, N nodes]."""
    states: Any            # scenario pytree, leading dim N
    wake: jax.Array        # int64[N]
    #: int32[E, C, N] deliver time minus `time`; I32MAX = empty slot
    #: (real delays clamp to I32MAX-1), so validity is derived
    q_rel: jax.Array
    q_step: jax.Array      # int32[E, C, N] — insertion superstep
    #                        (C is 0 for commutative_inbox scenarios:
    #                        the table only feeds the contract-#2 sort)
    q_pay: jax.Array       # int32[E, C, P, N]
    overflow: jax.Array    # int32[]
    unrouted: jax.Array    # int32[] — valid sends on undeclared slots
    misrouted: jax.Array   # int32[] — out.dst disagreeing with static_dst
    bad_delay: jax.Array   # int32[] — delays >= 2^31 µs, clamped
    delivered: jax.Array   # int64[]
    steps: jax.Array       # int64[]
    time: jax.Array        # int64[] — current virtual time == queue epoch
    #: int32[] — messages the fault schedule killed (cuts, down-window
    #: deliveries, reset purges) — mirrors EngineState.fault_dropped
    fault_dropped: jax.Array
    #: bool[C] — consumed restart injections (engine.py EngineState)
    restart_done: jax.Array


class EdgeEngine(RunStatsMixin, ControlledRunMixin, VerifiedRunMixin,
                 FlightRecorderMixin):
    """Batched engine for static-topology scenarios. Same driver API as
    :class:`~timewarp_tpu.interp.jax_engine.engine.JaxEngine`: ``run``
    (traced, per-superstep rows) and ``run_quiet`` (while_loop, no
    trace work compiled in), including the ``telemetry`` knob and its
    zero-overhead/bit-exactness contract (obs/; the edge engine has no
    routing ladder, so the rung field is pinned -1 and ``route_drop``
    0 — per-edge capacity losses are the ``overflow`` counter)."""

    def __init__(self, scenario: Scenario, link: LinkModel, *,
                 seed: int = 0, cap: int = 2,
                 lint: str = "warn", faults=None,
                 telemetry: str = "off", controller=None,
                 verify: str = "off", record: str = "off",
                 record_cap: Optional[int] = None) -> None:
        # static scenario sanitizer — same knob contract as JaxEngine
        from ...analysis import check_scenario
        from ...obs.telemetry import validate_mode
        self.telemetry = validate_mode(telemetry, type(self).__name__)
        # state-integrity checking — same knob contract as JaxEngine
        # (integrity/, docs/integrity.md)
        self._bind_verify(verify)
        # causal flight recorder — same knob contract as JaxEngine
        # (obs/flight.py, docs/observability.md)
        self._bind_record(record, record_cap)
        self.metrics = None
        self.metrics_label = type(self).__name__
        self.last_run_telemetry = None
        self.lint = lint
        self.lint_report = check_scenario(scenario, lint,
                                          who=type(self).__name__)
        if scenario.static_dst is None:
            raise ValueError(
                f"scenario {scenario.name!r} declares no static_dst; "
                "use the general JaxEngine")
        self.scenario = scenario
        self.link = link
        self.s0, self.s1 = seed_words(seed)
        self.cap = cap
        self.topo = EdgeTopology.build(scenario.static_dst,
                                       scenario.n_nodes)
        self.comm = LocalComm(scenario.n_nodes)
        self._setup_faults(faults, scenario, lint)
        # online dispatch (dispatch/): the edge engine runs classic
        # W=1 supersteps and has no routing ladder, so the controller
        # adapts CHUNK LENGTH only — window/rung ride the decision
        # trace pinned (1 / -1). `window` exists for the controller's
        # bound query; `_dyn_ok` stays False (ControlledRunMixin).
        self.window = 1
        self._bind_controller(controller)

    # -- faults (same semantics/masks as JaxEngine, classic W=1) ---------

    def _setup_faults(self, faults, scenario, lint) -> None:
        self.faults = faults
        self._faulted = faults is not None
        self._ft = None
        self.fault_lint_report = None
        self._has_skew = self._has_reset = False
        self._n_restarts = 0
        if faults is None:
            return
        from ...faults.schedule import FaultSchedule
        if not isinstance(faults, FaultSchedule):
            raise ValueError(
                f"the edge engine runs one world; faults must be a "
                f"FaultSchedule, got {faults!r}")
        from ...analysis import check_faults
        self.fault_lint_report = check_faults(
            faults, scenario, lint, who=type(self).__name__)
        self._has_skew = faults.has_skew
        self._has_reset = faults.has_reset
        self._n_restarts = faults.n_restarts
        tables = faults.tables(scenario.n_nodes)
        self._ft = type(tables)(*(jnp.asarray(x) for x in tables))
        if self._has_reset:
            self._reset_states, _ = self._init_states_wake()

    # -- initial state ---------------------------------------------------

    def _init_states_wake(self):
        from .common import init_states_wake
        return init_states_wake(self.scenario)

    def init_state(self) -> EdgeState:
        sc = self.scenario
        n, E, C, P = sc.n_nodes, self.topo.n_edges, self.cap, \
            sc.payload_width
        states, wake = self._init_states_wake()
        # q_step orders same-deliver-time messages for the contract-#2
        # sort; a commutative inbox never sorts, so carrying the table
        # through the loop would be pure dead HBM traffic (~2 reads +
        # writes of [E,C,N] int32 per superstep) — elide it to width 0
        C_step = 0 if sc.commutative_inbox else C
        return EdgeState(
            states=states,
            wake=wake,
            q_rel=jnp.full((E, C, n), _I32MAX, jnp.int32),
            q_step=jnp.zeros((E, C_step, n), jnp.int32),
            q_pay=jnp.zeros((E, C, P, n), jnp.int32),
            overflow=jnp.int32(0),
            unrouted=jnp.int32(0),
            misrouted=jnp.int32(0),
            bad_delay=jnp.int32(0),
            delivered=jnp.int64(0),
            steps=jnp.int64(0),
            time=jnp.int64(0),
            fault_dropped=jnp.int32(0),
            restart_done=jnp.zeros((self._n_restarts,), bool),
        )

    # -- one superstep ---------------------------------------------------

    def _superstep(self, st: EdgeState, with_trace: bool
                   ) -> Tuple[EdgeState, Optional[_StepOut]]:
        sc, topo, comm = self.scenario, self.topo, self.comm
        E, C, P = topo.n_edges, self.cap, sc.payload_width
        n = comm.n_local            # array width on this device
        n_glob = comm.n_global
        W = E * C
        node_ids = comm.node_ids()  # global identities, int32[n]
        base = st.time
        #: flight-recorder side channels (obs/flight.py; the JaxEngine
        #: twin): per-trace compacted event buffers, merged into the
        #: StepOut event plane below
        self._rec_extra = []
        rec_full = with_trace and self.record == "full"

        # validity is the rel sentinel (I32MAX = empty slot)
        q_live = st.q_rel < _I32MAX                          # [E,C,N]

        # 1. global next event time (the batched "pop min")
        nnr = st.q_rel.min(axis=(0, 1))                          # int32[N]
        node_next = jnp.minimum(
            st.wake,
            jnp.where(nnr == _I32MAX, jnp.int64(NEVER),
                      base + nnr.astype(jnp.int64)))
        if self._faulted:
            # crash suppression + injected restarts (faults/apply.py;
            # same masks as JaxEngine)
            from ...faults.apply import defer_next
            node_next_pre = node_next
            node_next = defer_next(self._ft, node_ids, node_next,
                                   st.restart_done)
            if rec_full:
                # fault action: crash window slid a pending event
                # later (engine.py's defer capture, identically)
                from ...obs import flight as _flight
                dm = (node_next > node_next_pre) \
                    & (node_next_pre < NEVER)
                self._rec_extra.append(_flight.compact(
                    self.record_cap, _flight.EV_FAULT, dm, node_ids,
                    node_ids, node_next_pre, node_next,
                    _flight.TAG_DEFER))
        t = comm.all_min(node_next.min())
        live = t < NEVER
        fire = (node_next == t) & live

        # 1.5. restart bookkeeping (engine.py twin): consume restart
        # rows firing now; reset their nodes' state; purge pre-crash
        # queue entries (counted — memory the reboot lost)
        restart_done = st.restart_done
        fault_step = jnp.int32(0)
        purge = None
        states_in = st.states
        if self._faulted and self._has_reset:
            from ...faults.apply import consume_restarts, restart_fire
            now_vec = jnp.broadcast_to(t, (n,))  # classic W=1: now == t
            reset_now, purge_before = restart_fire(
                self._ft, fire, now_vec, node_ids, st.restart_done)
            restart_done = consume_restarts(
                self._ft, fire, now_vec, node_ids, st.restart_done)
            purge = q_live & (
                (base + st.q_rel.astype(jnp.int64))
                < purge_before[None, None, :])
            fault_step = fault_step + comm.all_sum(
                jnp.sum(purge, dtype=jnp.int32))
            states_in = jax.tree.map(
                lambda cur, init: jnp.where(
                    reset_now.reshape((n,) + (1,) * (cur.ndim - 1)),
                    init, cur),
                st.states, self._reset_states)
            if rec_full:
                # the injected reboot firing (purged entries are
                # captured below, once per-edge sender ids exist)
                from ...obs import flight as _flight
                self._rec_extra.append(_flight.compact(
                    self.record_cap, _flight.EV_FAULT, reset_now,
                    node_ids, node_ids, jnp.int64(-1), now_vec,
                    _flight.TAG_RESTART))

        # 2. deliverable messages (all per-edge slots due at fired nodes)
        shift32 = jnp.minimum(t - base,
                              jnp.int64(_I32MAX - 1)).astype(jnp.int32)
        deliver = q_live & (st.q_rel <= shift32) & fire[None, None, :]
        if purge is not None:
            deliver = deliver & ~purge

        # 3. inbox [W, N] — slot-axis views of the queues (leading-axis
        #    reshape: no relayout)
        iv = deliver.reshape(W, n)
        rel = jnp.where(iv, st.q_rel.reshape(W, n), _I32MAX)
        istep = None if sc.commutative_inbox \
            else st.q_step.reshape(W, n)
        # per-edge sender ids: computable elementwise for shift edges
        # (works sharded); table lookup otherwise (local only)
        src_rows = jnp.stack([
            (node_ids - jnp.int32(topo.shift[e][0])) % jnp.int32(n_glob)
            if topo.shift[e] is not None
            else comm.local_rows(topo.in_src[e])
            for e in range(E)], axis=0)                      # int32[E, n]
        isrc = jnp.broadcast_to(
            src_rows[:, None, :], (E, C, n)).reshape(W, n)
        ipay = st.q_pay.reshape(W, P, n)
        if rec_full and purge is not None:
            # purged queue entries (reboot memory loss), now that the
            # per-edge sender ids exist — src/deliver-time identify
            # the lost message
            from ...obs import flight as _flight
            self._rec_extra.append(_flight.compact(
                self.record_cap, _flight.EV_FAULT,
                purge.transpose(2, 0, 1),
                jnp.broadcast_to(src_rows[:, None, :],
                                 (E, C, n)).transpose(2, 0, 1)
                if sc.inbox_src else jnp.int32(0),
                jnp.broadcast_to(node_ids[None, None, :],
                                 (E, C, n)).transpose(2, 0, 1),
                jnp.int64(-1),
                st.q_rel.transpose(2, 0, 1),
                _flight.TAG_PURGE, t_off=base))
        if not sc.commutative_inbox:
            # contract #2 order: (deliver_time, insert_step, src, slot)
            # — the oracle's arrival order is chronological routing
            # order, i.e. step-major then sender-major then slot; one
            # variadic sort along the inbox-slot axis restores it
            slot_rows = jnp.stack([
                jnp.full((n,), topo.shift[e][1], jnp.int32)
                if topo.shift[e] is not None
                else comm.local_rows(topo.in_slot[e])
                for e in range(E)], axis=0)                  # int32[E, n]
            islot = jnp.broadcast_to(
                slot_rows[:, None, :], (E, C, n)).reshape(W, n)
            ops = jax.lax.sort(
                (~iv, rel, istep, isrc, islot) + tuple(
                    ipay[:, p, :] for p in range(P)),
                dimension=0, num_keys=5)
            iv, rel, isrc = ~ops[0], ops[1], ops[3]
            ipay = jnp.stack(ops[5:5 + P], axis=1)
        itime = jnp.where(iv, base + rel.astype(jnp.int64),
                          jnp.int64(NEVER))
        inbox = Inbox(
            valid=iv,
            # inbox_src=False scenarios never read src: all
            # interpreters present 0 (core/scenario.py)
            src=jnp.where(iv, isrc, 0) if sc.inbox_src
            else jnp.zeros_like(isrc),
            time=itime,
            payload=jnp.where(iv[:, None, :], ipay, 0),
        )

        # 4. fire every node; batch axis is the *minor* dim for inbox and
        #    outbox leaves (no [N, small] padding anywhere)
        bits = fire_bits(self.s0, self.s1, node_ids, t) \
            if sc.needs_key else None
        stepf = sc.step
        if self._faulted and self._has_skew:
            from ...faults.apply import skewed_step
            stepf = skewed_step(sc.step, self._ft.skew)
        new_states, out, new_wake = jax.vmap(
            stepf,
            in_axes=(0, Inbox(valid=-1, src=-1, time=-1, payload=-1),
                     None, 0, None if bits is None else 0),
            out_axes=(0, Outbox(valid=-1, dst=-1, payload=-1), 0))(
                states_in, inbox, t, node_ids, bits)
        states = jax.tree.map(
            lambda a, b: jnp.where(
                fire.reshape((n,) + (1,) * (b.ndim - 1)), b, a),
            st.states, new_states)
        new_wake = jnp.where(new_wake >= NEVER, NEVER,
                             jnp.maximum(new_wake, t + 1))  # contract #5
        wake = jnp.where(fire, new_wake, st.wake)
        out_valid = out.valid & fire[None, :]               # [M, N]
        out_pay = out.payload                                # [M, P, N]
        # never-silent contract: a valid send on a slot whose static_dst
        # is -1 has nowhere to go — counted (≙ JaxEngine's bad_dst);
        # and routing goes by the *declared* table, so a step emitting a
        # dst that disagrees with its declaration is counted too rather
        # than silently diverging from the oracle (which routes by dst)
        sd_local = comm.local_rows(
            np.asarray(sc.static_dst, np.int32).T)           # [M, N]
        declared = sd_local >= 0
        unrouted_step = jnp.sum(out_valid & ~declared, dtype=jnp.int32)
        misrouted_step = jnp.sum(
            out_valid & declared & (out.dst != sd_local), dtype=jnp.int32)

        # 5. rebase surviving queue entries to the new epoch t
        keep = q_live & ~deliver
        if purge is not None:
            keep = keep & ~purge
        q_rel = jnp.where(keep, st.q_rel - shift32, _I32MAX)
        q_step = st.q_step
        q_pay = st.q_pay

        # 6-7. route + enqueue, one static in-edge at a time — gathers
        # only on non-shift edges, never a scatter
        step32 = st.steps.astype(jnp.int32)
        overflow_step = jnp.int32(0)
        bad_delay_total = jnp.int32(0)
        sent_count = jnp.int32(0)
        sent_hash = jnp.uint32(0)
        for e in range(E):
            sh = topo.shift[e]
            if sh is not None:
                s, slot = sh
                arr_v = comm.roll(out_valid[slot], s)
                arr_p = comm.roll(out_pay[slot], s)          # [P, N]
                slot_e = jnp.int32(slot)
            else:
                flat_idx = jnp.asarray(topo.in_flat[e])
                arr_v = out_valid.reshape(-1)[flat_idx] \
                    & jnp.asarray(topo.in_valid[e])
                arr_p = out_pay.transpose(1, 0, 2).reshape(P, -1)[
                    :, flat_idx]
                slot_e = comm.local_rows(topo.in_slot[e])
            src_e = src_rows[e]
            mb = msg_bits(self.s0, self.s1, src_e, node_ids, t, slot_e) \
                if self.link.needs_key else None
            delay, drop = self.link.sample(src_e, node_ids, t, mb)
            ok = arr_v & ~drop
            if self._faulted:
                # same drop order as JaxEngine/oracle: partition cut
                # at the send instant, degradation on the sampled
                # delay, down-window check on the deliver time
                from ...faults.apply import (cut_mask, degrade,
                                             down_mask)
                cutm = ok & cut_mask(self._ft, src_e, node_ids, t)
                delay = degrade(self._ft, delay, src_e, node_ids, t)
                downm = (ok & ~cutm) & down_mask(
                    self._ft, node_ids,
                    t + jnp.maximum(delay, jnp.int64(1)))
                fault_step = fault_step + comm.all_sum(
                    jnp.sum(cutm | downm, dtype=jnp.int32))
                if rec_full:
                    # per-edge flight capture (obs/flight.py): the
                    # cut, then the sends with down-dropped ones
                    # re-tagged — the shared mixin helpers, at edge
                    # width
                    self._rec_cut(rec_full, cutm, src_e, node_ids, t)
                    self._rec_extra.append(self._rec_sends(
                        ok & ~cutm, downm, src_e, node_ids, t,
                        t + jnp.maximum(delay, jnp.int64(1))))
                ok = ok & ~cutm & ~downm
            elif rec_full:
                self._rec_extra.append(self._rec_sends(
                    ok, None, src_e, node_ids, t,
                    t + jnp.maximum(delay, jnp.int64(1))))
            drel64 = jnp.maximum(delay, jnp.int64(1))       # contract #4
            # queue times are int32-relative; a >= 2^31 µs delay cannot
            # be represented — clamp and count, never wrap silently
            bad_delay_step = jnp.sum(
                ok & (drel64 > jnp.int64(_I32MAX - 1)), dtype=jnp.int32)
            bad_delay_total = bad_delay_total + bad_delay_step
            drel = jnp.minimum(
                drel64, jnp.int64(_I32MAX - 1)).astype(jnp.int32)
            if with_trace:
                dt_abs = t + jnp.maximum(delay, jnp.int64(1))
                smix = mix32_jnp(SENT, src_e, node_ids, _tlo(dt_abs),
                                 _thi(dt_abs), arr_p[0])
                sent_hash = sent_hash + _u32sum(jnp.where(ok, smix, 0))
                sent_count = sent_count + jnp.sum(ok, dtype=jnp.int32)
            # first-free-slot one-hot insert over the static C axis
            free = q_rel[e] == _I32MAX                       # [C, N]
            cids = jnp.arange(C, dtype=jnp.int32)[:, None]
            ff = jnp.where(free, cids, C).min(axis=0)        # int32[N]
            ins = ok[None, :] & (cids == ff)                 # [C, N]
            q_rel = q_rel.at[e].set(
                jnp.where(ins, drel, q_rel[e]))
            if not sc.commutative_inbox:
                q_step = q_step.at[e].set(
                    jnp.where(ins, step32, q_step[e]))
            q_pay = q_pay.at[e].set(
                jnp.where(ins[:, None, :], arr_p[None, :, :], q_pay[e]))
            overflow_step = overflow_step + jnp.sum(
                ok & (ff == C), dtype=jnp.int32)

        recv_count = comm.all_sum(jnp.sum(deliver, dtype=jnp.int32))
        overflow_step = comm.all_sum(overflow_step)
        new_st = EdgeState(
            states=states, wake=wake,
            q_rel=q_rel, q_step=q_step, q_pay=q_pay,
            overflow=st.overflow + overflow_step,
            unrouted=st.unrouted + comm.all_sum(unrouted_step),
            misrouted=st.misrouted + comm.all_sum(misrouted_step),
            bad_delay=st.bad_delay + comm.all_sum(bad_delay_total),
            delivered=st.delivered + recv_count.astype(jnp.int64),
            steps=st.steps + 1,
            time=t,
            fault_dropped=st.fault_dropped + fault_step,
            restart_done=restart_done,
        )
        final = jax.tree.map(lambda a, b: jnp.where(live, b, a), st, new_st)
        if not with_trace:
            return final, None

        # 8. trace digests (order-independent; computed pre-sort from the
        # deliver mask — identical to the sorted-inbox digest by
        # commutativity of the (wrapping) uint32 sum, which also makes
        # the cross-device psum exact)
        fired_hash = comm.all_sum(
            _u32sum(jnp.where(fire, mix32_jnp(FIRED, node_ids), 0)))
        d_abs = base + jnp.where(deliver, st.q_rel, 0).astype(jnp.int64)
        rsrc = (jnp.broadcast_to(src_rows[:, None, :], (E, C, n))
                if sc.inbox_src else jnp.zeros((E, C, n), jnp.int32))
        rmix = mix32_jnp(
            RECV, jnp.broadcast_to(node_ids, (E, C, n)),
            rsrc, _tlo(d_abs), _thi(d_abs), st.q_pay[:, :, 0, :])
        recv_hash = comm.all_sum(_u32sum(jnp.where(deliver, rmix, 0)))
        rec = None
        if self.record != "off" and with_trace:
            # the flight-recorder event plane (engine.py's twin):
            # deliveries node-major over the [E, C] queue axes, then
            # the capture buffers in superstep order
            from ...obs import flight as _flight
            d_src = (jnp.broadcast_to(src_rows[:, None, :],
                                      (E, C, n)).transpose(2, 0, 1)
                     if sc.inbox_src else jnp.int32(0))
            d_dst = jnp.broadcast_to(node_ids[None, None, :],
                                     (E, C, n)).transpose(2, 0, 1)
            if self.record == "deliveries":
                # slim fast path (engine.py's twin): one compaction,
                # constant planes elided
                rec = _flight.record_deliveries(
                    self.record_cap, deliver.transpose(2, 0, 1),
                    d_src, d_dst, st.q_rel.transpose(2, 0, 1),
                    t_off=base)
            else:
                row = _flight.record_masked(
                    _flight.empty_row(self.record_cap),
                    _flight.EV_DELIVER, deliver.transpose(2, 0, 1),
                    d_src, d_dst, jnp.int64(-1),
                    st.q_rel.transpose(2, 0, 1), 0, t_off=base)
                for comp in self._rec_extra:
                    row = _flight.record_compacted(row, comp)
                rec = row
        telem = None
        if self.telemetry != "off":
            telem = self._telemetry_row(wake, q_rel, t, out_valid,
                                        fault_step)
        integ = None
        if self.verify != "off":
            # the guard invariant plane — the JaxEngine twin
            # (integrity/checks.py; one shared implementation)
            from ...integrity.checks import make_guard_row
            integ = make_guard_row(
                comm, t, st.time,
                (new_st.overflow, new_st.unrouted, new_st.misrouted,
                 new_st.bad_delay, new_st.fault_dropped,
                 new_st.delivered, new_st.steps, new_st.time),
                wake, jnp.int64(NEVER), (q_rel,),
                st.restart_done, restart_done, self._faulted)
        yrow = _StepOut(
            valid=live, t=t,
            fired_count=comm.all_sum(jnp.sum(fire, dtype=jnp.int32)),
            fired_hash=fired_hash,
            recv_count=recv_count, recv_hash=recv_hash,
            sent_count=comm.all_sum(sent_count),
            sent_hash=comm.all_sum(sent_hash),
            overflow=overflow_step,
            telem=telem,
            integ=integ,
            rec=rec,
        )
        yrow = jax.tree.map(
            lambda x: jnp.where(live, x, jnp.zeros_like(x)), yrow)
        return final, yrow

    def _telemetry_row(self, wake, q_rel, t, out_valid, fault_step):
        """The edge engine's telemetry plane (obs/telemetry.py) —
        derived from the post-step wake, post-insert queues, and the
        step's outbox/fault values, so digests are bit-identical with
        telemetry on or off. No routing ladder here: rung is -1 and
        route_drop 0 by construction (per-edge losses are
        ``overflow``)."""
        from ...obs.telemetry import TelemetryRow
        comm = self.comm
        qmin = q_rel.min()
        nxt = comm.all_min(jnp.minimum(
            wake.min(),
            jnp.where(qmin < _I32MAX, t + qmin.astype(jnp.int64),
                      jnp.int64(NEVER))))
        row = TelemetryRow(
            active_senders=comm.all_sum(jnp.sum(
                jnp.any(out_valid, axis=0), dtype=jnp.int32)),
            rung=jnp.int32(-1),
            route_drop=jnp.int32(0),
            fault_dropped=fault_step,
            qslack_us=jnp.where(nxt >= NEVER, jnp.int64(-1), nxt - t),
        )
        if self.telemetry == "full":
            # queue occupancy: per-node fill over the [E, C] axes
            fill_node = jnp.sum(q_rel < _I32MAX, axis=(0, 1),
                                dtype=jnp.int32)                # [N]
            row = row._replace(
                mb_fill=comm.all_sum(jnp.sum(fill_node,
                                             dtype=jnp.int32)),
                mb_peak=comm.all_max(fill_node.max()))
        return row

    # -- drivers ---------------------------------------------------------

    def _next_event(self, carry: EdgeState) -> jax.Array:
        """This device's next event time (NEVER = quiesced) — the
        while-loop condition shared by the local and sharded drivers."""
        qmin = carry.q_rel.min()
        return jnp.minimum(
            carry.wake.min(),
            jnp.where(qmin < _I32MAX,
                      carry.time + qmin.astype(jnp.int64),
                      jnp.int64(NEVER)))

    #: the edge engine carries no world axis (batch=BatchSpec is the
    #: general engine's lever); the shared drivers key off this
    batch = None

    def world_active(self, state) -> jax.Array:
        """Liveness probe (JaxEngine.world_active's solo twin): True
        while an event is pending — the controller drivers
        (controlled.py) test it between chunks."""
        return self._next_event(state) < NEVER

    def _step_all(self, st, with_trace: bool):
        """One driver step (the ShardedDriver/scan hook — the edge
        engine has no world axis, so this is always the solo step)."""
        return self._superstep(st, with_trace)

    def _while_cond_fn(self, start_steps, max_steps):
        def cond(carry):
            nxt = self.comm.all_min(self._next_event(carry))
            return (nxt < NEVER) & \
                (carry.steps - start_steps < max_steps)
        return cond

    def _while_body_fn(self, start_steps, max_steps):
        def body(carry):
            return self._step_all(carry, False)[0]
        return body

    @partial(jax.jit, static_argnums=(0, 2))
    def _run_scan(self, st: EdgeState, n_pad: int, max_steps):
        # pow2-padded scan length + masked tail, the shared
        # compile-reuse contract (common.py scan_pad/padded_scan)
        return padded_scan(self._step_all, st, n_pad, max_steps)

    def _warn_on_overflow(self, final: EdgeState) -> None:
        """Per-edge capacity (``cap``) is NOT the oracle's per-node
        ``mailbox_cap``: once anything overflows, which message is
        dropped legitimately differs, so a run with overflow > 0 is not
        trace-comparable to the oracle — said out loud, not silently
        (VERDICT r2 weak #5). Use :class:`JaxEngine` when
        overflow-exact parity matters."""
        import warnings
        if int(final.overflow) > 0:
            warnings.warn(
                f"edge engine counted {int(final.overflow)} overflowed "
                "messages; per-edge capacity semantics diverge from the "
                "per-node-capacity oracle under overflow — raise cap=, "
                "or use the general JaxEngine for overflow-exact parity",
                RuntimeWarning, stacklevel=3)

    def run(self, max_steps: int,
            state: Optional[EdgeState] = None
            ) -> Tuple[EdgeState, SuperstepTrace]:
        st = state if state is not None else self.init_state()
        begin = self._stats_begin()
        # _pad_mult = 2 is the shadow verify mode's pow2-cache twin
        # (integrity/runner.py) — a distinct executable, same results
        final, ys = self._run_scan(st,
                                   scan_pad(max_steps) * self._pad_mult,
                                   jnp.asarray(max_steps, jnp.int64))
        ys = jax.device_get(ys)
        self._stats_end(begin, st.steps, final.steps)
        self._capture_flight(ys, st)
        self._capture_integrity(ys)
        self.last_run_telemetry = None
        if self.telemetry != "off" and ys.telem is not None:
            from ...obs.telemetry import decode_frames
            self.last_run_telemetry = decode_frames(
                ys.telem, np.asarray(ys.valid), np.asarray(ys.t))
            if self.metrics is not None:
                self.metrics.superstep_chunk(self.metrics_label,
                                             self.last_run_telemetry)
        self._warn_on_overflow(final)
        m = np.asarray(ys.valid)
        rows = list(zip(
            np.asarray(ys.t)[m], np.asarray(ys.fired_count)[m],
            np.asarray(ys.fired_hash)[m], np.asarray(ys.recv_count)[m],
            np.asarray(ys.recv_hash)[m], np.asarray(ys.sent_count)[m],
            np.asarray(ys.sent_hash)[m], np.asarray(ys.overflow)[m]))
        return final, SuperstepTrace.from_rows(rows)

    @partial(jax.jit, static_argnums=(0,))
    def _run_while(self, st: EdgeState, max_steps) -> EdgeState:
        start_steps = st.steps
        max_steps = jnp.asarray(max_steps, jnp.int64)
        return jax.lax.while_loop(
            self._while_cond_fn(start_steps, max_steps),
            self._while_body_fn(start_steps, max_steps), st)

    def run_quiet(self, max_steps: int,
                  state: Optional[EdgeState] = None) -> EdgeState:
        """Traceless driver: one ``while_loop``, digests, counts, and
        telemetry planes not even compiled in."""
        st = state if state is not None else self.init_state()
        begin = self._stats_begin()
        final = self._run_while(st, max_steps)
        self._stats_end(begin, st.steps, final.steps)
        if self.verify != "off":
            # never silently unverified (JaxEngine.run_quiet twin)
            from ...integrity.checks import final_state_guard
            final_state_guard(final, type(self).__name__)
        return final
