"""Shared machinery of the batched engines: trace-row container,
digest helpers, int32 sentinels, and the device-communication
abstraction that lets one superstep implementation run single-chip or
sharded over a mesh (sharded.py)."""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["LocalComm", "StepOut", "I32MAX", "group_rank", "u32sum",
           "tlo", "thi"]

I32MAX = np.int32(2**31 - 1)


def group_rank(sorted_keys: jax.Array) -> jax.Array:
    """Rank of each element within its run of equal keys (keys must be
    sorted ascending): ``iota - cummax(run-start indices)``.

    Replaces ``searchsorted(keys, keys, 'left')`` in the routing path —
    on TPU searchsorted lowers to ~log2(S) chained gather rounds
    (~1 ms each at 131k elements, profiling/superstep_breakdown.md)
    while the associative cummax scan is elementwise-cheap."""
    S = sorted_keys.shape[0]
    iota = jnp.arange(S, dtype=jnp.int32)
    boundary = jnp.concatenate([
        jnp.ones((1,), bool), sorted_keys[1:] != sorted_keys[:-1]])
    first = jax.lax.associative_scan(
        jnp.maximum, jnp.where(boundary, iota, 0))
    return iota - first


class StepOut(NamedTuple):
    """Per-superstep trace row (valid=False once the scenario quiesced)."""
    valid: jax.Array
    t: jax.Array
    fired_count: jax.Array
    fired_hash: jax.Array
    recv_count: jax.Array
    recv_hash: jax.Array
    sent_count: jax.Array
    sent_hash: jax.Array
    overflow: jax.Array


def u32sum(x: jax.Array) -> jax.Array:
    return jnp.sum(x.astype(jnp.uint32), dtype=jnp.uint32)


def tlo(t: jax.Array) -> jax.Array:
    return (t & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)


def thi(t: jax.Array) -> jax.Array:
    return ((t >> jnp.int64(32)) & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)


class LocalComm:
    """Single-device communication: every "collective" is local. The
    sharded engines (sharded.py) substitute mesh collectives (pmin /
    psum / ppermute / all_to_all) behind the same operations, so one
    superstep implementation serves both."""

    def __init__(self, n_global: int) -> None:
        self.n_global = n_global
        self.n_local = n_global
        self.n_shards = 1

    def node_ids(self) -> jax.Array:
        """Global ids of the nodes this device owns."""
        return jnp.arange(self.n_local, dtype=jnp.int32)

    def all_min(self, x: jax.Array) -> jax.Array:
        return x

    def all_sum(self, x: jax.Array) -> jax.Array:
        return x

    def roll(self, x: jax.Array, s: int) -> jax.Array:
        """Global roll by ``s`` along the (last) node axis."""
        return jnp.roll(x, s, axis=-1)

    def local_rows(self, table: np.ndarray) -> jax.Array:
        """This device's column block of a host table [..., N]."""
        return jnp.asarray(table)
