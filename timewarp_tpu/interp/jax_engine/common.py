"""Shared machinery of the batched engines: trace-row container and
the device-communication abstraction that lets one superstep
implementation run single-chip or sharded over a mesh
(parallel/mesh.py). The integer primitives live in
:mod:`timewarp_tpu.ops` and are re-exported here for the engines."""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.numeric import I32MAX, group_rank, thi, tlo, u32sum

__all__ = ["LocalComm", "StepOut", "I32MAX", "group_rank", "u32sum",
           "tlo", "thi"]


class StepOut(NamedTuple):
    """Per-superstep trace row (valid=False once the scenario quiesced)."""
    valid: jax.Array
    t: jax.Array
    fired_count: jax.Array
    fired_hash: jax.Array
    recv_count: jax.Array
    recv_hash: jax.Array
    sent_count: jax.Array
    sent_hash: jax.Array
    overflow: jax.Array


class LocalComm:
    """Single-device communication: every "collective" is local. The
    sharded engines (sharded.py) substitute mesh collectives (pmin /
    psum / ppermute / all_to_all) behind the same operations, so one
    superstep implementation serves both."""

    def __init__(self, n_global: int) -> None:
        self.n_global = n_global
        self.n_local = n_global
        self.n_shards = 1

    def node_ids(self) -> jax.Array:
        """Global ids of the nodes this device owns."""
        return jnp.arange(self.n_local, dtype=jnp.int32)

    def all_min(self, x: jax.Array) -> jax.Array:
        return x

    def all_sum(self, x: jax.Array) -> jax.Array:
        return x

    def roll(self, x: jax.Array, s: int) -> jax.Array:
        """Global roll by ``s`` along the (last) node axis."""
        return jnp.roll(x, s, axis=-1)

    def local_rows(self, table: np.ndarray) -> jax.Array:
        """This device's column block of a host table [..., N]."""
        return jnp.asarray(table)
