"""Shared machinery of the batched engines: trace-row container and
the device-communication abstraction that lets one superstep
implementation run single-chip or sharded over a mesh
(parallel/mesh.py). The integer primitives live in
:mod:`timewarp_tpu.ops` and are re-exported here for the engines."""

from __future__ import annotations

import time
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.numeric import I32MAX, group_rank, thi, tlo, u32sum

__all__ = ["LocalComm", "StepOut", "I32MAX", "group_rank", "u32sum",
           "tlo", "thi", "padded_scan", "scan_pad",
           "init_states_wake", "RunStatsMixin", "DynDispatch"]


class DynDispatch(NamedTuple):
    """The online-dispatch controller's per-chunk knob values
    (dispatch/), threaded into the traced scan drivers as ORDINARY
    TRACED OPERANDS — never compile-time constants — so a controller
    adapting them between chunks re-invokes the same executable with
    new scalars (zero recompiles by construction; the pow2 scan pad
    stays the drivers' only static input).

    ``window`` — requested superstep window width, int64 µs (clamped
    on-device to ``[1, engine.window]`` and, under a fault schedule,
    to the per-superstep degraded link floor — faults/apply.py
    ``window_floor``). ``rung_pin`` — a *floor* on the adaptive
    routing ladder's selected rung index, int32 (-1 = unpinned; the
    effective index is ``max(computed, pin)``, so a pin can only
    select a wider — always result-identical — rung, never drop a
    message)."""
    window: Any     # int64[] requested window µs
    rung_pin: Any   # int32[] ladder index floor, -1 = unpinned


def init_states_wake(scenario):
    """The scenario's stacked initial ``(states, wake)`` — ONE
    implementation shared by every engine's ``init_state`` and the
    fault subsystem's restart-reset template (a divergence here would
    silently split "fresh boot" from "reboot" semantics)."""
    n = scenario.n_nodes
    if scenario.init_batched is not None:
        states, wake = scenario.init_batched(n)
        wake = jnp.asarray(wake, jnp.int64)
    else:
        per = [scenario.init(i) for i in range(n)]
        states = jax.tree.map(
            lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
            *[p[0] for p in per])
        wake = jnp.asarray([p[1] for p in per], jnp.int64)
    return states, wake


def scan_pad(max_steps: int) -> int:
    """Scan length for a ``max_steps`` budget: the next power of two.
    The scan length is the ONLY static compile input of the traced
    drivers, so rounding it up to a pow2 bucket (and masking the tail
    supersteps out — :func:`padded_scan`) collapses every budget in a
    bucket onto one executable — ``run(100)`` then ``run(120)`` reuse
    the 128-step program instead of recompiling
    (tests/test_world_batch.py pins the compile count). The masked
    tail still *executes* (its results are discarded), bounding the
    waste at <2x supersteps — cheap next to a 20-40 s TPU compile per
    distinct budget."""
    if max_steps <= 0:
        return 0
    return 1 << (max_steps - 1).bit_length()


def padded_scan(step_all, st, n_pad: int, max_steps):
    """The ONE pow2-padded masked-tail scan body every traced driver
    shares (local, edge, sharded — a single implementation so the
    run/freeze/zero contract cannot drift per driver): iterations at
    index >= ``max_steps`` (traced) compute and discard their
    superstep, freezing the carry and zeroing the trace row
    (valid=False, filtered host-side). ``step_all`` is the engine's
    one-driver-step hook ``(carry, with_trace) -> (carry', yrow)``.

    ``max_steps`` may also be an int64[B] vector of per-world budgets
    (batched engines only — the sweep service's heterogeneous-budget
    buckets, sweep/): world b freezes leaf-wise after its own budget,
    exactly as the quiescence mask freezes it, so a short-budget world
    stays bit-identical to its solo run while sibling worlds keep
    stepping. Trace rows are [B]-leading under the batch, so the same
    mask zeroes only the frozen worlds' rows."""
    per_world = getattr(max_steps, "ndim", 0) == 1

    def body(carry, i):
        new, y = step_all(carry, True)
        run = i < max_steps          # bool[] — or bool[B] per world

        def mask(a, b):
            r = run.reshape(run.shape + (1,) * (b.ndim - 1)) \
                if per_world else run
            return jnp.where(r, b, a)
        carry = jax.tree.map(mask, carry, new)
        # the same per-world trailing-dim broadcast for the trace row:
        # [B]-leading y leaves may carry plane dims beyond B (the
        # telemetry full mode's per-node columns, the flight
        # recorder's [B, R] event plane)
        y = jax.tree.map(
            lambda x: jnp.where(
                run.reshape(run.shape + (1,) * (x.ndim - 1))
                if per_world else run, x, jnp.zeros_like(x)), y)
        return carry, y
    return jax.lax.scan(body, st, jnp.arange(n_pad, dtype=jnp.int64))


class StepOut(NamedTuple):
    """Per-superstep trace row (valid=False once the scenario quiesced).

    ``telem`` is the opt-in telemetry counter plane
    (obs/telemetry.py ``TelemetryRow``) — ``None`` unless the engine
    was built with ``telemetry != "off"``. None is an empty pytree
    node, so the default adds zero scan outputs and zero jaxpr
    equations: the zero-overhead-when-off law holds at the type level.

    ``integ`` is the state-integrity guard plane (integrity/checks.py
    ``IntegrityRow``) — ``None`` unless ``verify != "off"``; the same
    None-default contract, so the verify-off jaxpr is byte-identical
    to the pre-knob engine (tests/test_zzzzintegrity.py).

    ``rec`` is the causal flight recorder's bounded event plane
    (obs/flight.py ``RecordRow``) — ``None`` unless ``record !=
    "off"``; the same None-default contract again
    (tests/test_zzzzzflight.py).

    ``spec`` is the optimistic-execution causality-violation plane
    (speculate/plane.py ``SpecRow``) — ``None`` unless ``speculate !=
    "off"``; the same None-default contract, so the speculate-off
    jaxpr is byte-identical to the pre-knob engine
    (tests/test_zzzzzzspec.py)."""
    valid: jax.Array
    t: jax.Array
    fired_count: jax.Array
    fired_hash: jax.Array
    recv_count: jax.Array
    recv_hash: jax.Array
    sent_count: jax.Array
    sent_hash: jax.Array
    overflow: jax.Array
    telem: Any = None
    integ: Any = None
    rec: Any = None
    spec: Any = None


class LocalComm:
    """Single-device communication: every "collective" is local. The
    sharded engines (sharded.py) substitute mesh collectives (pmin /
    psum / ppermute / all_to_all) behind the same operations, so one
    superstep implementation serves both."""

    def __init__(self, n_global: int) -> None:
        self.n_global = n_global
        self.n_local = n_global
        self.n_shards = 1

    def node_ids(self) -> jax.Array:
        """Global ids of the nodes this device owns."""
        return jnp.arange(self.n_local, dtype=jnp.int32)

    def all_min(self, x: jax.Array) -> jax.Array:
        return x

    def all_sum(self, x: jax.Array) -> jax.Array:
        return x

    def all_max(self, x: jax.Array) -> jax.Array:
        return x

    def roll(self, x: jax.Array, s: int) -> jax.Array:
        """Global roll by ``s`` along the (last) node axis."""
        return jnp.roll(x, s, axis=-1)

    def local_rows(self, table: np.ndarray) -> jax.Array:
        """This device's column block of a host table [..., N]."""
        return jnp.asarray(table)


class RunStatsMixin:
    """Uniform host-side driver accounting for every engine: after any
    ``run``/``run_quiet``, ``engine.last_run_stats`` holds::

        {"supersteps": int,    # executed this call (fleet total)
         "wall_seconds": float,
         "compiles": int}      # driver executables compiled this call

    Compile counting reads the jitted drivers' ``_cache_size`` (the
    same probe tests/test_world_batch.py pins the pow2 bucketing
    with), so a run that silently retraced is visible in its stats.
    Host-side timing only — nothing here is compiled in, so the
    telemetry zero-overhead law is untouched and the stats exist in
    every telemetry mode including "off".
    """

    #: the jitted driver attributes whose compile caches count
    _DRIVER_FNS = ("_run_scan", "_run_while")

    last_run_stats = None

    def _driver_compiles(self) -> int:
        n = 0
        for name in self._DRIVER_FNS:
            fn = getattr(type(self), name, None)
            cs = getattr(fn, "_cache_size", None)
            if cs is not None:
                n += cs()
        return n

    def _stats_begin(self):
        return time.perf_counter(), self._driver_compiles()

    def _stats_end(self, begin, steps_before, steps_after) -> dict:
        t0, c0 = begin
        d = (np.asarray(jax.device_get(steps_after), np.int64)
             - np.asarray(jax.device_get(steps_before), np.int64))
        self.last_run_stats = {
            "supersteps": int(d.sum()),
            "wall_seconds": time.perf_counter() - t0,
            "compiles": self._driver_compiles() - c0,
        }
        return self.last_run_stats

    def _stats_merge(self, chunks) -> dict:
        """Fold per-chunk ``last_run_stats`` dicts into one run-level
        record for the chunked drivers (``run_stream``,
        ``run_controlled``). Before this existed, each chunk's
        ``run()`` overwrote ``last_run_stats``, so a chunked run
        reported only its FINAL chunk — every earlier chunk's compile
        (where the real compiles happen: the first use of each pow2
        scan pad) was silently lost. ``per_chunk_compiles`` keeps the
        attribution: entry i is the number of driver executables chunk
        i compiled, so "zero recompiles across controller adaptations"
        is testable per chunk, not just in aggregate."""
        self.last_run_stats = {
            "supersteps": sum(c["supersteps"] for c in chunks),
            "wall_seconds": sum(c["wall_seconds"] for c in chunks),
            "compiles": sum(c["compiles"] for c in chunks),
            "chunks": len(chunks),
            "per_chunk_compiles": [c["compiles"] for c in chunks],
        }
        return self.last_run_stats
