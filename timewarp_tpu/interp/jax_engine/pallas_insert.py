"""Pallas mailbox-insertion kernels for the general engine's sparse
path — fire-compaction + in-tile hole-ranked insertion (round 12; the
ROADMAP's first open item and PERF_r05.md's "unexplored lever").

PERF_r05.md names the remaining praos fat precisely: ~15 ms/superstep
at 2²⁰×8 dominated by the rung-width outbox gathers (~1.4 ms per
65k-lane rung access, 3 arrays), the sender-compaction N-sort
(1.0–1.6 ms), the free-rows short-axis sort, and the `[K, N]`
elementwise base — and records that fire-compaction via XLA gathers is
*pathological* on the mailbox side (minor-axis `[K, A]` column
gathers blew praos superstep 30 up to 301 ms). This module is the
structural exit: both halves become grid-free Pallas kernels that
stream their operands exactly once, so no minor-axis XLA gather (and
no N-wide sender sort) is owed at all.

Two kernels, one opt-in engine knob (``JaxEngine(insert=...)``):

- **fire-compaction** (:func:`_build_compact_kernel`): streams the raw
  ``[M, N]`` outbox planes through VMEM in double-buffered blocks and
  emits the *compact* fired batch ``(dst, woff, smrank, payload…)``
  directly — in-block exclusive ranks via log-step masked roll-adds
  (``jnp.roll`` is the one lane-crossing op the probed Mosaic
  inventory admits, fused_ring.py), a running write base carried
  through the sequential block loop, and capacity drops counted as
  lane partials into ``EngineState.route_drop`` (never silent). This
  replaces the sender-compaction sort + per-rung gathers of
  ``JaxEngine._route_adaptive``: the ordering sort still runs in XLA,
  but at *compacted* width (a 131k-element sort is < 0.1 ms on this
  chip — PERF_r05.md cost table), not at N.
- **insertion** (:func:`_build_kernel` — shared with fused_sparse.py,
  which this module is now the home of): the double-buffered, grid-free
  kernel that streams the ``[K, N]`` mailbox planes through VMEM once
  and merges the destination-sorted batch in-tile — hole-ranked rows
  for commutative inboxes (an unrolled K-cumsum while the block is
  resident, so the free-rows ``[K, N]`` sort is not owed), or
  append-after-kept rows for ordered inboxes (``counts`` rides as one
  extra input plane). Overflow is counted in-kernel, bit-identical to
  ``JaxEngine._insert_sorted``'s accounting.

**The exactness law extends unconditionally**: ``insert="pallas"``
(or ``"interpret"``) produces bit-identical ``EngineState``, traces,
and digests to ``insert="xla"`` — under faults (sampling, partition
cuts, and down-window drops stay in XLA around the kernels, so every
mask point is preserved), under telemetry, and under the world axis
(the kernels ``vmap``; tests/test_pallas_insert.py pins a faulted
batched config). ``JaxEngine`` is itself pinned to the host oracle
(tests/test_parity.py), so the chain pallas ≡ xla ≡ oracle covers the
kernels.

Knob resolution (:func:`resolve_insert`): ``insert=None`` reads the
``TW_INSERT`` env hatch (the promotion of PERF_r05.md §3's
``TW_FLAT_SCATTER``, which is still honored as a legacy alias) and
defaults to ``"xla"``; ``"pallas"`` auto-falls back to ``"xla"`` off
TPU (recorded in ``engine.insert_fallback``, never silent) while
``"interpret"`` forces the Pallas interpreter — the CPU test surface.
``"xla2d"`` selects the 2D ``[col, row]`` scatter form of the XLA
insertion stage (no flat-reshape relayout copy of the tiled mailbox —
the escape hatch PERF_r05.md §3 kept for future hardware).

Hardware status: on non-TPU backends the kernels run under the pallas
interpreter (identical DMA/loop semantics — the exactness tests run
there). Both kernels are written inside the probed remote-Mosaic
constraint inventory (grid-free, int32-only, no scalar reductions,
``pl.when``-unrolled DMA slots, roll-based lane crossings — the full
list is consolidated in docs/pallas_kernels.md), plus the two
constructs the inventory does not cover — the insertion kernel's
per-slot gather from the resident batch (carried over from
fused_sparse.py) and the compaction kernel's per-row scatter into the
VMEM-resident output — which need a hardware probe before the chip
numbers can be recorded (PERF_r06.md; the in-bench exactness gate
fails loudly rather than recording a wrong number).

≙ the reference's event dispatch this batches:
`/root/reference/src/Control/TimeWarp/Timed/TimedT.hs:234-286`.
"""

from __future__ import annotations

import os
from typing import Optional

from ...utils import jaxconfig  # noqa: F401

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...core.rng import _MSG_TAG, threefry2x32
from ...core.scenario import Scenario
from .common import I32MAX as _I32MAX
from .common import group_rank

__all__ = ["INSERT_MODES", "PallasInsertStage", "resolve_insert"]

_LANES = 1024
_ROWS = 8          # rows per pipelined mailbox block (when NR % 8 == 0)
#: VMEM budget the constructors guard against (resident batch + the
#: double-buffered block buffers), leaving headroom of a ~16 MB VMEM
#: for the compiler's own temporaries
_VMEM_BUDGET = 12 * 2**20

#: the engine knob's legal values: "xla" = flat-index 1D scatters (the
#: r5-measured default on this chip), "xla2d" = the 2D [col, row]
#: scatter form (no tiled-relayout copy — the TW_FLAT_SCATTER escape
#: hatch, promoted), "pallas" = the kernels on TPU (auto-fallback to
#: "xla" elsewhere), "interpret" = the kernels under the Pallas
#: interpreter on any backend (the test/CI surface)
INSERT_MODES = ("xla", "xla2d", "pallas", "interpret")
_ENV_KNOB = "TW_INSERT"
_LEGACY_ENV = "TW_FLAT_SCATTER"


def resolve_insert(requested: Optional[str], *, honor_env: bool,
                   who: str = "engine"):
    """Resolve the ``insert=`` knob to the strategy that will actually
    run: ``(requested, resolved, fallback_reason, from_env)``.
    ``None`` reads the documented ``TW_INSERT`` env hatch (legacy
    ``TW_FLAT_SCATTER=1`` maps to ``"xla"``, ``=0`` to ``"xla2d"`` —
    PERF_r05.md §3, promoted) and defaults to ``"xla"``; ``"pallas"``
    off-TPU auto-falls back to ``"xla"`` with the reason recorded (use
    ``"interpret"`` to force the kernels under the Pallas
    interpreter). ``from_env`` marks env-sourced modes: an env hatch
    must stay behavior-neutral, so kernel-scope violations fall back
    (recorded) instead of crashing runs that worked before the var was
    exported — explicit constructor/CLI requests still refuse loudly.
    Engine subclasses that replace the insertion stage themselves pass
    ``honor_env=False`` so the hatch cannot leak into a path whose
    kernels it does not describe."""
    mode, from_env = requested, False
    if mode is None and honor_env:
        mode = os.environ.get(_ENV_KNOB)
        if mode is None:
            legacy = os.environ.get(_LEGACY_ENV)
            if legacy is not None:
                mode = "xla" if legacy not in ("0", "") else "xla2d"
        from_env = mode is not None
    if mode is None:
        mode = "xla"
    if mode not in INSERT_MODES:
        raise ValueError(
            f"{who}: insert must be one of {INSERT_MODES}, got "
            f"{mode!r} ('xla' = flat scatters, 'xla2d' = 2D scatter "
            "form, 'pallas' = the Pallas insertion kernels, "
            "'interpret' = the kernels under the Pallas interpreter)")
    resolved, reason = mode, None
    if mode == "pallas" and jax.default_backend() != "tpu":
        resolved = "xla"
        reason = (f"no TPU backend ({jax.default_backend()}) — "
                  "insert='pallas' auto-falls back to 'xla'; use "
                  "insert='interpret' to force the kernels under the "
                  "Pallas interpreter")
    return mode, resolved, reason, from_env


# ----------------------------------------------------------------------
# kernel helpers: reductions as lane partials (no scalar reductions
# lower in-kernel — the probed constraint inventory, fused_ring.py /
# docs/pallas_kernels.md)
# ----------------------------------------------------------------------

def _fold_lanes(x):
    """[R, 1024] int32 -> [R, 128] partial sums (unrolled adds)."""
    R = x.shape[0]
    x = x.reshape(R, _LANES // 128, 128)
    acc = x[:, 0]
    for j in range(1, _LANES // 128):
        acc = acc + x[:, j]
    return acc


def _fold_rows8(x):
    """[rows, 128] int32 -> [8, 128] partial sums. rows must be a
    multiple of 8, or < 8 (zero-padded — axis-0 concat lowers, lane
    axis does not)."""
    rows = x.shape[0]
    if rows < 8:
        return jnp.concatenate(
            [x, jnp.zeros((8 - rows, 128), jnp.int32)], axis=0)
    acc = x[0:8]
    for i in range(1, rows // 8):
        acc = acc + x[8 * i:8 * i + 8]
    return acc


def _lane_excl_prefix(v, lane):
    """Exclusive per-row prefix sum of int32 ``v`` along the 1024-lane
    axis via log-step masked roll-adds — ``jnp.roll`` is the one
    lane-crossing op the probed Mosaic inventory admits (fused_ring.py;
    wrapped lanes are masked out with the lane iota)."""
    x = v
    s = 1
    while s < _LANES:
        x = x + jnp.where(lane >= s, jnp.roll(x, s, axis=-1), 0)
        s *= 2
    return x - v


def _row_total(incl):
    """Per-row total of an inclusive lane prefix, [R, 1024] -> [R, 1]:
    the last lane read through ``roll`` + lane 0 (last-lane slices
    crash the remote Mosaic service; lane-0 reads of a rolled array
    are the fused_ring.py boundary idiom)."""
    return jnp.roll(incl, 1, axis=-1)[:, 0:1]


# ----------------------------------------------------------------------
# shared scope guards + static shape plan (the fused engines AND the
# insert= knob — one copy, so the kernels' constraint inventory and
# the VMEM budget cannot desynchronize between them)
# ----------------------------------------------------------------------

def _insertion_plan(sc: Scenario, n: int, S_raw: int, *, who: str,
                    what_n: str = "n_nodes",
                    require_commutative: bool = True):
    """Check ``sc`` against the fused insertion kernel's constraint
    inventory (K <= 128 unrolled hole/append cumsum, 1024-lane mailbox
    planes; ``require_commutative`` for the fused engines, whose
    sample-mode kernel has no append path), round the resident batch
    width up to 8-row tiling, and size the VMEM footprint against the
    budget. Returns ``(S, R, G)`` — batch width, rows per block, block
    count. Raises ``ValueError`` (never silently narrows scope)."""
    if require_commutative and not sc.commutative_inbox:
        raise ValueError(
            f"{who} requires a commutative_inbox scenario (insertion "
            "targets mailbox holes; an ordered inbox owes the "
            "contract-#2 compaction sort — run the XLA engine)")
    if sc.payload_width < 1:
        raise ValueError("payload_width must be >= 1")
    if sc.mailbox_cap > 128:
        raise ValueError("mailbox_cap must be <= 128 (the kernel "
                         "unrolls the hole-rank cumsum over K)")
    if n % _LANES:
        raise ValueError(
            f"{what_n} must be a multiple of {_LANES} (mailbox "
            "block lane shape)")
    NR = n // _LANES
    R = _ROWS if NR % _ROWS == 0 else 1
    S = -(-S_raw // 1024) * 1024            # SR must be 8-row tiled
    K, P = sc.mailbox_cap, sc.payload_width
    NP = 2 + K + K * P + (K if sc.inbox_src else 0)
    if not sc.commutative_inbox:
        NP += 1                             # the counts plane (append)
    NPO = K + K * P + (K if sc.inbox_src else 0)
    footprint = (3 + P) * S * 4 + 2 * (NP + NPO) * R * _LANES * 4
    if footprint > _VMEM_BUDGET:
        raise ValueError(
            f"fused-insertion VMEM footprint {footprint} B exceeds the "
            f"{_VMEM_BUDGET} B budget — lower the batch bound "
            "(max_batch / bucket_cap / insert_cap) or mailbox_cap")
    return S, R, NR // R


# ----------------------------------------------------------------------
# the insertion kernel (the home of fused_sparse.py's kernel builder;
# that module re-exports these names for its engines)
# ----------------------------------------------------------------------

def _build_kernel(*, K, P, R, G, SR, n, M, W, inbox_src, mode,
                  needs_key, s0, s1, delay_fn, ordered=False):
    """Build the grid-free fused insertion kernel for one static shape.

    Refs: ``scal`` SMEM int32[4] = [t_lo, t_hi, 0, 0]; ``msgs`` VMEM
    int32[3+P, SR, 128] — the resident sorted batch, planes
    (dst | woff | smrank | payload_0..P-1) in ``mode="sample"`` or
    (dst | drel | src | payload…) in ``mode="drel"`` (pre-sampled:
    the sharded insertion path and the ``insert="pallas"`` knob);
    ``st_ref`` ANY int32[NP, N/1024, 1024] — stacked (start | cnt |
    counts? | mb_rel[K] | mb_payload[K*P] | mb_src[K]?) planes, where
    the ``counts`` plane exists only for ``ordered=True`` (the
    append-after-kept target of ordered inboxes — drel mode only);
    outputs: the post-insertion mailbox planes (same layout minus the
    batch-boundary planes) and int32[3, 8, 128] lane-partial counters
    (overflow, bad_delay, short_delay)."""
    if ordered and mode != "drel":
        raise ValueError("ordered insertion is a drel-mode construct "
                         "(the fused engines' sample mode is hole-only)")
    KP = K * P
    OFS = 3 if ordered else 2
    NP = OFS + K + KP + (K if inbox_src else 0)
    NPO = K + KP + (K if inbox_src else 0)

    def kernel(scal, msgs_ref, st_ref, out_ref, cnt_ref):
        MAXI = jnp.int32(_I32MAX)
        m = msgs_ref[:]                                 # [3+P, SR, 128]
        dstp = m[0]
        valid = dstp < jnp.int32(n)
        zero_part = jnp.zeros((SR, 128), jnp.int32)
        if mode == "sample":
            woffp, smrank = m[1], m[2]
            srcp = smrank // jnp.int32(M)
            slot = smrank - srcp * jnp.int32(M)
            # send instant = t + woff as two uint32 words with an
            # explicit carry (int64 does not lower in-kernel)
            tl = scal[0].astype(jnp.uint32)
            th = scal[1].astype(jnp.uint32)
            woff_u = woffp.astype(jnp.uint32)
            lo = tl + woff_u
            carry = (lo < tl).astype(jnp.uint32)
            hi = th + carry
            key = None
            if needs_key:
                # msg_bits (core/rng.py) inlined: same chain, same bits
                a0, a1 = threefry2x32(
                    jnp.uint32(s0) ^ jnp.uint32(_MSG_TAG),
                    jnp.uint32(s1), srcp, dstp)
                b0, b1 = threefry2x32(a0, a1, lo, hi)
                key = threefry2x32(b0, b1, slot, jnp.uint32(0))
            delay = delay_fn(srcp, dstp, lo, hi, key)
            flight = jnp.maximum(delay, jnp.uint32(1))  # contract #4
            dsum = woff_u + flight
            badm = valid & (dsum > jnp.uint32(_I32MAX - 1))
            shortm = (valid & (flight < jnp.uint32(W))) if W > 1 \
                else jnp.zeros((SR, 128), bool)
            drelp = jnp.minimum(
                dsum, jnp.uint32(_I32MAX - 1)).astype(jnp.int32)
            bad8 = _fold_rows8(badm.astype(jnp.int32))
            short8 = _fold_rows8(shortm.astype(jnp.int32))
            srcp = srcp if inbox_src else None
        else:
            drelp, srcp = m[1], (m[2] if inbox_src else None)
            bad8 = short8 = _fold_rows8(zero_part)
        payps = [m[3 + p] for p in range(P)]

        def block_compute(blk):
            """Insert the resident batch into one [NP, R, L] mailbox
            block: meet the r-th message to each destination at its
            r-th hole (hole-ranked, commutative inboxes — an unrolled
            K-cumsum while the block is resident) or at row
            ``counts + r`` (append-after-kept, ordered inboxes) via a
            gather from the resident planes. Returns the output block
            and the per-node overflow partial."""
            start_b, cnt_b = blk[0], blk[1]
            rel = blk[OFS:OFS + K]
            pay = blk[OFS + K:OFS + K + KP]
            smb = blk[OFS + K + KP:] if inbox_src else None
            o_rel, o_pay, o_src = [], [None] * KP, []

            def take(want, j, k):
                jr = j // jnp.int32(128)
                jc = j - jr * jnp.int32(128)
                o_rel.append(jnp.where(want, drelp[jr, jc], rel[k]))
                for p in range(P):
                    o_pay[k * P + p] = jnp.where(
                        want, payps[p][jr, jc], pay[k * P + p])
                if inbox_src:
                    o_src.append(jnp.where(want, srcp[jr, jc], smb[k]))

            if ordered:
                # append mode: row k receives the (k - counts)-th new
                # message of its node — the kernel half of
                # _insert_sorted's `pos = counts + rank` law
                base_b = blk[2]
                for k in range(K):
                    j = jnp.int32(k) - base_b
                    want = (j >= 0) & (j < cnt_b)
                    take(want, jnp.where(want, start_b + j,
                                         jnp.int32(0)), k)
                ovf = jnp.maximum(
                    cnt_b - (jnp.int32(K) - base_b), jnp.int32(0))
            else:
                acc = jnp.zeros(rel[0].shape, jnp.int32)
                for k in range(K):
                    free_k = rel[k] >= MAXI
                    h_k = acc
                    acc = acc + free_k.astype(jnp.int32)
                    want = free_k & (h_k < cnt_b)
                    take(want, jnp.where(want, start_b + h_k,
                                         jnp.int32(0)), k)
                # messages beyond a destination's hole count are
                # dropped and counted — identical to _insert_sorted's
                # ok & ~fits
                ovf = jnp.maximum(cnt_b - acc, jnp.int32(0))
            out = jnp.stack(o_rel + o_pay + o_src)
            return out, _fold_lanes(ovf)

        def body(in_buf0, in_buf1, out_buf0, out_buf1,
                 in_sem0, in_sem1, out_sem0, out_sem1):
            RW = jnp.int32(R)
            in_bufs = (in_buf0, in_buf1)
            out_bufs = (out_buf0, out_buf1)
            in_sems = (in_sem0, in_sem1)
            out_sems = (out_sem0, out_sem1)

            def in_dma(slot, b):
                return pltpu.make_async_copy(
                    st_ref.at[:, pl.ds(b * RW, R), :],
                    in_bufs[slot], in_sems[slot])

            def out_dma(slot, b):
                return pltpu.make_async_copy(
                    out_bufs[slot],
                    out_ref.at[:, pl.ds(b * RW, R), :],
                    out_sems[slot])

            in_dma(0, 0).start()
            ONE = jnp.int32(1)
            TWO = jnp.int32(2)
            GG = jnp.int32(G)

            def when_slot(slot, fn):
                # dynamic buffer-slot indices emit 64-bit memref
                # slices Mosaic rejects — unroll the two slots
                @pl.when(slot == jnp.int32(0))
                def _():
                    fn(0)

                @pl.when(slot == ONE)
                def _():
                    fn(1)

            def loop(carry):
                b, slot, ovf = carry

                @pl.when(b + ONE < GG)
                def _():
                    when_slot(slot,
                              lambda sl: in_dma(1 - sl, b + ONE).start())

                when_slot(slot, lambda sl: in_dma(sl, b).wait())
                blk = jnp.where(slot == ONE, in_buf1[:], in_buf0[:])
                out, o = block_compute(blk)

                @pl.when(b >= TWO)
                def _():
                    when_slot(slot, lambda sl: out_dma(sl, b - TWO).wait())

                def put(sl):
                    out_bufs[sl][:] = out
                    out_dma(sl, b).start()
                when_slot(slot, put)
                return (b + ONE, ONE - slot, ovf + o)

            carry = jax.lax.while_loop(
                lambda c: c[0] < GG, loop,
                (jnp.int32(0), jnp.int32(0),
                 jnp.zeros((R, 128), jnp.int32)))

            if G >= 2:
                out_dma(G % 2, jnp.int32(G - 2)).wait()
            out_dma((G - 1) % 2, jnp.int32(G - 1)).wait()
            cnt_ref[:] = jnp.stack(
                [_fold_rows8(carry[2]), bad8, short8])

        pl.run_scoped(
            body,
            in_buf0=pltpu.VMEM((NP, R, _LANES), jnp.int32),
            in_buf1=pltpu.VMEM((NP, R, _LANES), jnp.int32),
            out_buf0=pltpu.VMEM((NPO, R, _LANES), jnp.int32),
            out_buf1=pltpu.VMEM((NPO, R, _LANES), jnp.int32),
            in_sem0=pltpu.SemaphoreType.DMA(()),
            in_sem1=pltpu.SemaphoreType.DMA(()),
            out_sem0=pltpu.SemaphoreType.DMA(()),
            out_sem1=pltpu.SemaphoreType.DMA(()),
        )

    return kernel


# ----------------------------------------------------------------------
# the insertion-kernel invocation shared by the fused engines, the
# sharded insertion path, and the insert= knob
# ----------------------------------------------------------------------

def _fused_insert_call(kernel, S, n, K, P, inbox_src, scal, sd, a1, a2,
                       pay_s, mb_rel, mb_src, mb_payload, *,
                       ordered=False, counts=None, interpret=None):
    """Stack the sorted batch + per-node bucket planes and run the
    fused kernel once. ``sd`` is the sorted destination row (sentinel
    ``n`` = invalid); ``(a1, a2)`` are the mode's second/third resident
    planes — (woff, smrank) for in-kernel sampling, (drel, src) for
    pre-sampled insertion. ``ordered=True`` threads the per-node kept
    ``counts`` as one extra input plane (the append-mode target);
    ``interpret`` overrides the backend-derived Pallas-interpreter
    choice (the insert="interpret" knob). Returns the post-insertion
    mailbox arrays plus the [3, 8, 128] counter partials."""
    SA = sd.shape[0]
    L = _LANES
    NR = n // L

    # per-destination bucket boundaries: two S-sized scatters into [N]
    # planes (S = the compacted batch width — the sparse regime's
    # cheap side); the kernel meets rank r at hole r via start + r
    rank = group_rank(sd)
    validm = sd < n
    iota = jnp.arange(SA, dtype=jnp.int32)
    start = jnp.zeros(n, jnp.int32).at[
        jnp.where(validm & (rank == 0), sd, n)].set(iota, mode="drop")
    nxt = jnp.concatenate([sd[1:], jnp.full((1,), n, sd.dtype)])
    cnt = jnp.zeros(n, jnp.int32).at[
        jnp.where(validm & (sd != nxt), sd, n)].set(
            rank + 1, mode="drop")

    pad = S - SA

    def padded(x, fill):
        if not pad:
            return x
        return jnp.concatenate([x, jnp.full((pad,), fill, x.dtype)])

    SR = S // 128
    msgs = jnp.stack(
        [padded(sd, n).reshape(SR, 128),
         padded(a1, 0).reshape(SR, 128),
         padded(a2, 0).reshape(SR, 128)]
        + [padded(p, 0).reshape(SR, 128) for p in pay_s])
    st_planes = jnp.concatenate(
        [start.reshape(1, NR, L), cnt.reshape(1, NR, L)]
        + ([counts.reshape(1, NR, L)] if ordered else [])
        + [mb_rel.reshape(K, NR, L),
           mb_payload.reshape(K * P, NR, L)]
        + ([mb_src.reshape(K, NR, L)] if inbox_src else []),
        axis=0)

    NPO = K + K * P + (K if inbox_src else 0)
    out_planes, cnts = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_shape=[
            jax.ShapeDtypeStruct((NPO, NR, L), jnp.int32),
            jax.ShapeDtypeStruct((3, 8, 128), jnp.int32)],
        # non-TPU backends run the pallas interpreter — identical
        # DMA/loop semantics, which is what the exactness tests pin
        interpret=(jax.default_backend() != "tpu"
                   if interpret is None else interpret),
    )(scal, msgs, st_planes)
    mrel = out_planes[:K].reshape(K, n)
    mpay = out_planes[K:K + K * P].reshape(K, P, n)
    msrc = out_planes[K + K * P:].reshape(K, n) if inbox_src \
        else mb_src
    return mrel, msrc, mpay, cnts


# ----------------------------------------------------------------------
# the fire-compaction kernel
# ----------------------------------------------------------------------

def _build_compact_kernel(*, M, P, RW, G, SR, n, W):
    """Build the grid-free fire-compaction kernel for one static
    shape: stream the raw outbox planes (woff? | dst[M] | pay[M*P],
    int32[NPI, N/1024, 1024], destination -1 = no message) through
    double-buffered VMEM blocks and emit the compact fired batch
    (dst | woff | smrank | payload…, int32[3+P, SR, 128], sentinel
    dst = n beyond the fired width) plus [8, 128] lane-partial
    capacity-drop counters. Ranks within a block are exclusive lane
    prefixes via log-step masked roll-adds; the running write base is
    a [1, 1] carry of the sequential block loop (scalar *reductions*
    do not lower — scalar carries do, fused_ring.py)."""
    NPI = (1 if W > 1 else 0) + M + M * P
    DOF = 1 if W > 1 else 0
    L = _LANES
    S = SR * 128

    def kernel(src_ref, msgs_ref, cnt_ref):
        lane = jax.lax.broadcasted_iota(jnp.int32, (RW, L), 1)

        def block_compute(b, blk, wbase, msgs, drops):
            woff_b = blk[0] if W > 1 else None
            for mm in range(M):
                d_m = blk[DOF + mm]                     # [RW, L]
                v_m = d_m >= 0
                vi = v_m.astype(jnp.int32)
                excl = _lane_excl_prefix(vi, lane)      # [RW, L]
                tot = _row_total(excl + vi)             # [RW, 1]
                for r in range(RW):
                    pos = wbase[0] + excl[r]            # [L]
                    okw = v_m[r] & (pos < jnp.int32(S))
                    tgt = jnp.where(okw, pos, jnp.int32(S))
                    jr = tgt // jnp.int32(128)
                    jc = tgt - jr * jnp.int32(128)
                    msgs = msgs.at[0, jr, jc].set(d_m[r], mode="drop")
                    if W > 1:
                        msgs = msgs.at[1, jr, jc].set(woff_b[r],
                                                      mode="drop")
                    node0 = (b * jnp.int32(RW) + jnp.int32(r)) \
                        * jnp.int32(L)
                    smr = (node0 + lane[r]) * jnp.int32(M) \
                        + jnp.int32(mm)
                    msgs = msgs.at[2, jr, jc].set(smr, mode="drop")
                    for p in range(P):
                        msgs = msgs.at[3 + p, jr, jc].set(
                            blk[DOF + M + mm * P + p][r], mode="drop")
                    drops = drops + (
                        v_m[r] & (pos >= jnp.int32(S))
                    ).astype(jnp.int32)[None, :]
                    wbase = wbase + tot[r:r + 1]
            return msgs, drops, wbase

        def body(in_buf0, in_buf1, in_sem0, in_sem1):
            RWI = jnp.int32(RW)
            in_bufs = (in_buf0, in_buf1)
            in_sems = (in_sem0, in_sem1)

            def in_dma(slot, b):
                return pltpu.make_async_copy(
                    src_ref.at[:, pl.ds(b * RWI, RW), :],
                    in_bufs[slot], in_sems[slot])

            in_dma(0, 0).start()
            ONE = jnp.int32(1)
            GG = jnp.int32(G)

            def when_slot(slot, fn):
                @pl.when(slot == jnp.int32(0))
                def _():
                    fn(0)

                @pl.when(slot == ONE)
                def _():
                    fn(1)

            # the output batch stays VMEM-resident across the whole
            # stream as a loop-carried value; sentinel dst = n marks
            # the unfired tail (axis-0 concat lowers)
            init_msgs = jnp.concatenate(
                [jnp.full((1, SR, 128), n, jnp.int32),
                 jnp.zeros((2 + P, SR, 128), jnp.int32)], axis=0)

            def loop(carry):
                b, slot, wbase, drops, msgs = carry

                @pl.when(b + ONE < GG)
                def _():
                    when_slot(slot,
                              lambda sl: in_dma(1 - sl, b + ONE).start())

                when_slot(slot, lambda sl: in_dma(sl, b).wait())
                blk = jnp.where(slot == ONE, in_buf1[:], in_buf0[:])
                msgs, drops, wbase = block_compute(
                    b, blk, wbase, msgs, drops)
                return (b + ONE, ONE - slot, wbase, drops, msgs)

            carry = jax.lax.while_loop(
                lambda c: c[0] < GG, loop,
                (jnp.int32(0), jnp.int32(0),
                 jnp.zeros((1, 1), jnp.int32),
                 jnp.zeros((1, L), jnp.int32), init_msgs))
            msgs_ref[:] = carry[4]
            cnt_ref[:] = _fold_rows8(_fold_lanes(carry[3]))

        pl.run_scoped(
            body,
            in_buf0=pltpu.VMEM((NPI, RW, L), jnp.int32),
            in_buf1=pltpu.VMEM((NPI, RW, L), jnp.int32),
            in_sem0=pltpu.SemaphoreType.DMA(()),
            in_sem1=pltpu.SemaphoreType.DMA(()),
        )

    return kernel


def _fire_compact_call(kernel, S, n, M, P, W, pdst, woff_n, payload,
                       interpret):
    """Stack the raw outbox planes and run the fire-compaction kernel
    once: ``pdst`` int32[M, N] (-1 = no message), ``woff_n`` int32[N]
    in-window send offsets, ``payload`` int32[M, P, N]. Returns the
    compact batch columns ``(dst, woff, smrank, pay_tuple)`` at static
    width S (sentinel dst = n beyond the fired width) plus the
    capacity-drop count."""
    L = _LANES
    NR = n // L
    planes = ([woff_n.reshape(1, NR, L)] if W > 1 else []) \
        + [pdst.reshape(M, NR, L),
           payload.reshape(M * P, NR, L)]
    src_planes = jnp.concatenate(planes, axis=0)
    SR = S // 128
    msgs, cnts = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_shape=[
            jax.ShapeDtypeStruct((3 + P, SR, 128), jnp.int32),
            jax.ShapeDtypeStruct((8, 128), jnp.int32)],
        interpret=interpret,
    )(src_planes)
    dst_c = msgs[0].reshape(S)
    woff_c = msgs[1].reshape(S)
    smrank_c = msgs[2].reshape(S)
    pay_c = tuple(msgs[3 + p].reshape(S) for p in range(P))
    drop_step = jnp.sum(cnts, dtype=jnp.int32)
    return dst_c, woff_c, smrank_c, pay_c, drop_step


# ----------------------------------------------------------------------
# the engine-facing stage (JaxEngine insert="pallas"|"interpret")
# ----------------------------------------------------------------------

class PallasInsertStage:
    """The ``insert="pallas"`` knob's kernel bundle, owned by one
    :class:`~timewarp_tpu.interp.jax_engine.engine.JaxEngine`: the
    fire-compaction kernel (adaptive regimes — it replaces the
    sender-compaction sort + rung gathers of ``_route_adaptive``) and
    per-width drel-mode insertion kernels (every ``_insert_sorted``
    call site: the compacted adaptive batch, the eager S = N·max_out
    width, the lazy ``route_cap`` width). Construction validates the
    full kernel scope loudly (1024-lane node multiple, K <= 128, VMEM
    budget at the widths this engine's regime will actually run) —
    never a silent narrowing.

    ``insert_cap`` bounds the compacted adaptive batch in *messages*
    (like the fused engine's ``max_batch``); the default is
    ``n_nodes * max_out`` — no superstep can ever drop, so the
    exactness law holds unconditionally. A smaller cap drops the
    excess into ``EngineState.route_drop``, counted, never silent.
    The cap is rounded UP to the next 1024 multiple (the resident
    batch's lane tiling), so the effective floor is 1024 messages —
    caps below that behave identically (``self.S`` is the width that
    actually runs, and the VMEM budget is checked on it)."""

    def __init__(self, scenario: Scenario, n: int, *, window: int,
                 interpret: bool, adaptive: bool,
                 insert_cap: Optional[int],
                 route_cap: Optional[int]) -> None:
        sc = scenario
        self.sc, self.n = sc, n
        self.K, self.M, self.P = (sc.mailbox_cap, sc.max_out,
                                  sc.payload_width)
        self.W = int(window)
        self.interpret = bool(interpret)
        self.ordered = not sc.commutative_inbox
        self.adaptive = bool(adaptive)
        full = n * sc.max_out
        if insert_cap is not None:
            if int(insert_cap) < sc.max_out:
                raise ValueError(
                    f"insert_cap must be >= max_out={sc.max_out} "
                    "(one whole sender), got "f"{insert_cap}")
            if not adaptive:
                raise ValueError(
                    "insert_cap bounds the fire-compacted adaptive "
                    "batch; this engine's regime (route_cap / droppy "
                    "link / classic narrow outbox) never compacts — "
                    "drop the knob or use route_cap")
        cap = full if insert_cap is None else min(int(insert_cap), full)
        self._kernels = {}
        who = "insert='pallas'"
        if adaptive:
            self.S, _, _ = _insertion_plan(
                sc, n, cap, who=who, require_commutative=False)
            NR = n // _LANES
            RWc = _ROWS if NR % _ROWS == 0 else 1
            NPI = (1 if self.W > 1 else 0) \
                + sc.max_out * (1 + sc.payload_width)
            extra = 2 * NPI * RWc * _LANES * 4 \
                + (3 + sc.payload_width) * self.S * 4
            if extra > _VMEM_BUDGET:
                raise ValueError(
                    f"fire-compaction VMEM footprint {extra} B exceeds "
                    f"the {_VMEM_BUDGET} B budget — lower insert_cap "
                    "or max_out")
            self._compact_kernel = _build_compact_kernel(
                M=sc.max_out, P=sc.payload_width, RW=RWc,
                G=NR // RWc, SR=self.S // 128, n=n, W=self.W)
        else:
            # the eager width (route_cap slices it when set and
            # smaller — slice_cap in engine.py)
            width = full if route_cap is None \
                else min(int(route_cap), full)
            self.S, _, _ = _insertion_plan(
                sc, n, width, who=who, require_commutative=False)
            self._compact_kernel = None
        #: sender-denominated static width — what telemetry records as
        #: the pallas path's "rung" (the ladder analog of the fused
        #: engine's VMEM batch slice)
        self.A = self.S // sc.max_out
        self._insert_kernel_for(self.S)   # pre-build + budget-check

    def _insert_kernel_for(self, SA: int):
        """The drel-mode insertion kernel for a call-site batch width
        ``SA`` (cached per padded width — the eager, lazy, and
        compacted-adaptive call sites each see exactly one)."""
        S = -(-SA // 1024) * 1024
        hit = self._kernels.get(S)
        if hit is None:
            sc = self.sc
            _, R, G = _insertion_plan(
                sc, self.n, S, who="insert='pallas'",
                require_commutative=False)
            hit = _build_kernel(
                K=self.K, P=self.P, R=R, G=G, SR=S // 128, n=self.n,
                M=self.M, W=self.W, inbox_src=sc.inbox_src,
                mode="drel", needs_key=False, s0=0, s1=0,
                delay_fn=None, ordered=self.ordered)
            self._kernels[S] = hit
        return hit, S

    def insert(self, sd, drel_s, src_s, pay_s, mb_rel, mb_src,
               mb_payload, counts):
        """One destination-sorted batch through the insertion kernel —
        the pallas form of ``JaxEngine._insert_sorted`` (same
        arguments' semantics, same overflow accounting, bit-for-bit).
        ``counts`` is the ordered-inbox kept-rows plane (None for
        commutative scenarios — holes are ranked in-tile)."""
        kernel, S = self._insert_kernel_for(sd.shape[0])
        mrel, msrc, mpay, cnts = _fused_insert_call(
            kernel, S, self.n, self.K, self.P, self.sc.inbox_src,
            jnp.zeros(4, jnp.int32), sd, drel_s, src_s, pay_s,
            mb_rel, mb_src, mb_payload, ordered=self.ordered,
            counts=counts, interpret=self.interpret)
        return mrel, msrc, mpay, jnp.sum(cnts[0], dtype=jnp.int32)

    def compact(self, pdst, woff_n, payload):
        """The fire-compaction front end (adaptive regimes only):
        raw pre-masked outbox planes in, compact fired batch out."""
        return _fire_compact_call(
            self._compact_kernel, self.S, self.n, self.M, self.P,
            self.W, pdst, woff_n, payload, self.interpret)
