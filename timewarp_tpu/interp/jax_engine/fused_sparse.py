"""Pallas fused routing superstep for the *sparse* general engine —
the gossip / praos path (round 6; VERDICT r5 item 1).

Round 5 proved the Pallas lever on the dense ring (fused_ring.py:
6.5e9 msg/s/chip) but every dynamic-destination config still runs the
XLA `JaxEngine` at 0.05-0.08x the north star, and the profiler says
where the fat is (PERF_r05.md "Where the remaining praos fat is"): the
free-rows [K, N] short-axis sort, the (1 + P) flat mailbox scatters
with their tiled-layout relayout copies, and the per-stage HBM
round-trips between them. This module fuses the post-compaction
pipeline — delay sampling → destination bucketing → hole-ranked
mailbox insertion — into ONE grid-free, double-buffered Pallas kernel
that streams the [K, N] mailbox planes exactly once while the
sender-compacted message batch stays resident in VMEM:

- the **compaction insight is reused, not replaced**: active senders
  are still compacted by ONE single-operand N-sort and the batch is
  still ordered by ``(destination, window offset, sender-major rank)``
  in XLA (sorts are the one thing XLA does near-bandwidth;
  PERF_r05.md cost table) — but the sorted batch is then handed to
  the kernel ONCE and never re-materialized per stage;
- link delays are sampled **in-kernel** with the counter-based
  threefry of core/rng.py inlined as uint32 VPU ops (the same bits
  the XLA engine derives — entropy is keyed by (src, dst, send
  instant, slot), so execution venue cannot change the stream); int64
  never lowers on this chip's Mosaic (fused_ring.py), so send
  instants enter as two uint32 words and the in-window offset is
  added with an explicit carry;
- mailbox **holes are ranked in-VMEM per block** (an unrolled
  K-cumsum while the block is already resident), so the free-rows
  [K, N] sort is not owed at all (`JaxEngine._fused_holes`), and the
  r-th message to a destination meets its r-th hole by a per-slot
  gather from the resident batch — no [K, N] scatter, no relayout
  copy, every mailbox byte read and written exactly once;
- counters (``overflow`` / ``bad_delay`` / ``short_delay``)
  accumulate as lane partials (scalar reductions do not lower —
  fused_ring.py constraint inventory) and are summed outside; they
  land in the same never-silent ``EngineState`` fields.

The per-destination bucket boundaries (``start``/``cnt``) are two
S-sized scatters into [N] planes computed in XLA from the sorted
batch — S is the *compacted* batch width, so this is the sparse
regime's cheap side.

**State layout is `EngineState`, bit-for-bit.** The engine subclasses
:class:`JaxEngine` and overrides only the adaptive routing stage, so
drivers, trace digests, the device event ring, checkpoints
(utils/checkpoint.py — a `.npz` saved by either engine resumes under
the other), and the CLI/bench plumbing are inherited unchanged, and
the exactness law is *state + trace equality against JaxEngine at
every superstep* (tests/test_fused_sparse.py; chained to the host
oracle by tests/test_parity.py).

Capacity: the resident batch is VMEM-bounded, so the engine carries a
static ``max_batch`` (messages per superstep). Supersteps whose
active-sender count exceeds ``max_batch // max_out`` drop the excess
messages and count them in ``EngineState.route_drop`` — the same
loudly-accounted capacity contract as ``route_cap`` (a parity run
must keep the counter 0; the in-bench gate asserts it). Scope guards
(constructor, never silent): ``commutative_inbox`` scenarios (hole
insertion), drop-free links that lower to the in-kernel uint32/f32
registry (`_lower_link`), windowed or wide-outbox workloads, and
``n_nodes`` divisible by the 1024-lane block shape.

Hardware status: on non-TPU backends the kernel runs under the pallas
interpreter (identical DMA/loop semantics — the exactness tests run
there); the kernel is written inside fused_ring.py's probed remote-
Mosaic constraint inventory (grid-free, int32-only, no scalar
reductions, slot-unrolled DMA buffers), plus one construct that
inventory does not cover — the per-slot gather from the resident
batch — which needs a hardware probe before the ≥10x r5 target can
be recorded (no chip is attached to this session; the in-bench gate
will fail loudly rather than record a wrong number).

≙ the reference's event dispatch this batches:
`/root/reference/src/Control/TimeWarp/Timed/TimedT.hs:234-286`.
"""

from __future__ import annotations

from ...utils import jaxconfig  # noqa: F401

import jax
import jax.numpy as jnp

from ...core.rng import normal_f32, threefry2x32
from ...core.scenario import Scenario
from ...net.delays import (FixedDelay, LinkModel, LogNormalDelay,
                           Quantize, SeededHashUniform, UniformDelay)
from ...trace.hashing import SENT, mix32_jnp
from .common import thi as _thi, tlo as _tlo, u32sum as _u32sum
from .engine import JaxEngine
# the kernel machinery now lives in pallas_insert.py (the insert=
# knob's home, round 12) — these modules share ONE copy so the probed
# Mosaic constraint inventory and the VMEM budget cannot drift apart.
# Re-exported here because the sharded engines (and r6-era callers)
# import them from this module.
from .pallas_insert import (_LANES, _ROWS, _VMEM_BUDGET,  # noqa: F401
                            _build_kernel, _fold_lanes, _fold_rows8,
                            _fused_insert_call, _insertion_plan)

__all__ = ["FusedSparseEngine"]


# ----------------------------------------------------------------------
# link-model lowering: the kernel's uint32/float32 delay samplers
# ----------------------------------------------------------------------

def _lower_link(link: LinkModel):
    """Compile ``link.sample`` into kernel-lowerable ops: returns
    ``(needs_key, max_delay_us, fn)`` where ``fn(src, dst, tlo, thi,
    key) -> uint32 delay`` uses only uint32/int32/float32 arithmetic
    (int64 never lowers in-kernel — fused_ring.py) and reproduces the
    XLA sampler's values bit-for-bit for integer models (float models
    carry delays.py's documented transcendental-lowering caveat).
    Unsupported models raise — a model the kernel cannot express must
    fail construction loudly, not sample differently."""
    if isinstance(link, Quantize):
        nk, mx, inner = _lower_link(link.inner)
        q = int(link.quantum_us)
        if q < 1:
            raise ValueError("Quantize quantum_us must be >= 1")

        def fn(src, dst, tl, th, key):
            d = jnp.maximum(inner(src, dst, tl, th, key), jnp.uint32(1))
            qq = jnp.uint32(q)
            return ((d + qq - jnp.uint32(1)) // qq) * qq
        return nk, ((max(mx, 1) + q - 1) // q) * q, fn
    if isinstance(link, FixedDelay):
        d = int(link.delay)
        if not 0 <= d < 2**31:
            raise ValueError("FixedDelay delay must fit int32 for the "
                             "fused kernel's uint32 deliver arithmetic")

        def fn(src, dst, tl, th, key):
            return jnp.full(jnp.shape(dst), d, jnp.uint32)
        return False, d, fn
    if isinstance(link, UniformDelay):
        lo, hi = int(link.lo), int(link.hi)
        if not (0 <= lo <= hi < 2**31):
            raise ValueError("UniformDelay bounds must satisfy "
                             "0 <= lo <= hi < 2**31 for the fused kernel")

        def fn(src, dst, tl, th, key):
            b0, _ = key
            return jnp.uint32(lo) + b0 % jnp.uint32(hi - lo + 1)
        return True, hi, fn
    if isinstance(link, SeededHashUniform):
        lo, hi = int(link.lo_us), int(link.hi_us)
        if not (0 <= lo <= hi < 2**31):
            raise ValueError("SeededHashUniform bounds must satisfy "
                             "0 <= lo <= hi < 2**31 for the fused kernel")
        s0, s1 = link._s0, link._s1

        def fn(src, dst, tl, th, key):
            # the model's own (dst, t)-keyed self-contained draw —
            # same chain as SeededHashUniform.sample, uint32-only
            bits, _ = threefry2x32(
                jnp.uint32(s0) ^ dst.astype(jnp.uint32),
                jnp.uint32(s1), tl, th)
            return jnp.uint32(lo) + bits % jnp.uint32(hi - lo + 1)
        return False, hi, fn
    if isinstance(link, LogNormalDelay):
        med, sig = int(link.median_us), float(link.sigma)
        cap, floor = int(link.cap_us), int(link.floor_us)
        if not 0 <= cap < 2**31:
            raise ValueError("LogNormalDelay cap_us must fit int32 for "
                             "the fused kernel")

        def fn(src, dst, tl, th, key):
            b0, b1 = key
            z = normal_f32(b0, b1)
            d = jnp.float32(med) * jnp.exp(jnp.float32(sig) * z)
            d = jnp.clip(d, jnp.float32(floor), jnp.float32(cap))
            return jnp.round(d).astype(jnp.uint32)
        return True, cap, fn
    raise ValueError(
        f"FusedSparseEngine cannot lower link model {link!r} into the "
        "kernel (supported: FixedDelay / UniformDelay / "
        "SeededHashUniform / LogNormalDelay, optionally Quantize-"
        "wrapped); run the XLA JaxEngine instead")


# ----------------------------------------------------------------------
# the engine
# ----------------------------------------------------------------------

class FusedSparseEngine(JaxEngine):
    """:class:`JaxEngine` with the adaptive routing stage replaced by
    the fused Pallas kernel (module docstring). Same state, drivers,
    trace, event ring, and checkpoint format — construction-time scope
    guards are the only API difference.

    ``max_batch`` bounds the VMEM-resident message batch per
    superstep; excess messages are dropped into
    ``EngineState.route_drop`` (never silent — the parity regime and
    the in-bench gate require the counter to stay 0). With
    ``max_batch >= n_nodes * max_out`` no superstep can ever drop."""

    def __init__(self, scenario: Scenario, link: LinkModel, *,
                 seed: int = 0, window=1, record_events: int = 0,
                 max_batch: int = 1 << 16,
                 lint: str = "warn", telemetry: str = "off",
                 controller=None, verify: str = "off",
                 record: str = "off", record_cap=None) -> None:
        super().__init__(scenario, link, seed=seed, window=window,
                         route_cap=None, record_events=record_events,
                         lint=lint, telemetry=telemetry,
                         controller=controller, verify=verify,
                         record=record, record_cap=record_cap)
        # the fused kernel bakes the window into its uint32 deliver
        # arithmetic and in-kernel short-delay counter, so a dispatch
        # controller adapts CHUNK LENGTH only here — window/rung ride
        # the decision trace pinned (dispatch/, controlled.py)
        self._dyn_ok = False
        sc = scenario
        if link.can_drop:
            raise ValueError(
                "FusedSparseEngine requires a drop-free link (message "
                "validity must not depend on the sample — the lazy-"
                "sampling precondition, engine.py)")
        if not (self.window > 1 or sc.max_out > 1):
            raise ValueError(
                "FusedSparseEngine serves the windowed / wide-outbox "
                "sparse regime (window > 1 or max_out > 1); the "
                "classic regime routes nothing the kernel can batch")
        n = sc.n_nodes
        nk, mx, fn = _lower_link(link)
        if mx + self.window >= 2**32:
            raise ValueError("max link delay + window must fit the "
                             "kernel's uint32 deliver arithmetic")
        self._delay_fn, self._link_needs_key = fn, nk
        A = min(n, max(1, int(max_batch) // sc.max_out))
        self._A = A
        self._S, self._R, G = _insertion_plan(
            sc, n, A * sc.max_out, who="FusedSparseEngine")
        self._fused_holes = True
        self._kernel = _build_kernel(
            K=sc.mailbox_cap, P=sc.payload_width, R=self._R, G=G,
            SR=self._S // 128, n=n, M=sc.max_out, W=self.window,
            inbox_src=sc.inbox_src, mode="sample", needs_key=nk,
            s0=self.s0, s1=self.s1, delay_fn=fn)

    # -- the fused routing stage -----------------------------------------

    def _route_adaptive(self, out, out_valid, now_vec, t, mb_rel,
                        mb_src, mb_payload, free_rows, counts,
                        node_ids, with_trace):
        """Sender-compact in XLA (one N-sort — the compaction insight
        of the base engine, unchanged), sort the batch by
        (destination, window offset, sender-major rank), then hand it
        to the fused kernel ONCE: sampling, bucketing, and hole-ranked
        insertion all happen against the resident batch while the
        mailbox planes stream through VMEM (module docstring)."""
        sc = self.scenario
        K, M, P = sc.mailbox_cap, sc.max_out, sc.payload_width
        n = self.comm.n_local
        n_glob = self.comm.n_global
        W = self.window
        if self.telemetry != "off":
            # the fused engine's "rung" is its static VMEM batch slice
            self._t_rung = jnp.int32(self._A)

        dst32 = out.dst.astype(jnp.int32)                       # [M, N]
        dst_okf = (dst32 >= 0) & (dst32 < n_glob)
        bad_dst_step = jnp.sum(out_valid & ~dst_okf, dtype=jnp.int32)
        pdst = jnp.where(out_valid & dst_okf, dst32, -1)        # [M, N]
        sender_live = jnp.any(pdst >= 0, axis=0)                # [N]
        sid_sorted = jax.lax.sort(
            jnp.where(sender_live, node_ids, jnp.int32(n)))
        woff_n = (now_vec - t).astype(jnp.int32)                # [N]

        # static batch slice: active senders sort first, so slicing A
        # keeps every active sender while n_active <= A; the excess is
        # counted into route_drop below, never silent
        A = self._A
        sids = jax.lax.slice_in_dim(sid_sorted, 0, A)
        real = sids < n
        sidc = jnp.where(real, sids, 0)
        woff_a = woff_n[sidc]                                   # [A]
        dst_a = jnp.take(pdst, sidc, axis=1)                    # [M, A]
        pay_a = tuple(jnp.take(out.payload[:, p, :], sidc, axis=1)
                      for p in range(P))
        SA = A * M
        dst_f = dst_a.reshape(SA)
        ok = (dst_f >= 0) & jnp.broadcast_to(
            real[None, :], (M, A)).reshape(SA)
        smrank = (jnp.broadcast_to(sidc[None, :] * jnp.int32(M),
                                   (M, A))
                  + jnp.arange(M, dtype=jnp.int32)[:, None]
                  ).reshape(SA)
        total_msgs = jnp.sum(pdst >= 0, dtype=jnp.int32)
        kept = jnp.sum(ok, dtype=jnp.int32)
        route_drop_step = total_msgs - kept

        sort_dst = jnp.where(ok, dst_f, n)
        pay_f = tuple(p.reshape(SA) for p in pay_a)
        if W > 1:
            woff_f = jnp.broadcast_to(woff_a[None, :], (M, A)
                                      ).reshape(SA)
            ops = jax.lax.sort((sort_dst, woff_f, smrank) + pay_f,
                               dimension=0, num_keys=3)
            sd, woff_s, smrank_s = ops[0], ops[1], ops[2]
            pay_s = ops[3:]
        else:
            ops = jax.lax.sort((sort_dst, smrank) + pay_f,
                               dimension=0, num_keys=2)
            sd, smrank_s = ops[0], ops[1]
            woff_s = jnp.zeros_like(sd)
            pay_s = ops[2:]

        scal = jnp.stack([_tlo(t).astype(jnp.int32),
                          _thi(t).astype(jnp.int32),
                          jnp.int32(0), jnp.int32(0)])
        mrel, msrc, mpay, cnts = _fused_insert_call(
            self._kernel, self._S, n, K, P, sc.inbox_src, scal,
            sd, woff_s, smrank_s, pay_s, mb_rel, mb_src, mb_payload)
        overflow_step = jnp.sum(cnts[0], dtype=jnp.int32)
        bad_delay_step = jnp.sum(cnts[1], dtype=jnp.int32)
        short_step = jnp.sum(cnts[2], dtype=jnp.int32)

        sent_count = kept
        rec_full = with_trace and self.record == "full"
        sent_hash = jnp.uint32(0)
        if with_trace:
            # the SENT digest needs per-message flight times; re-derive
            # them in XLA from the same counters (bit-identical stream
            # — entropy is keyed by message identity, not venue). Only
            # the traced `run` driver compiles this; `run_quiet`
            # benchmarks never do. The flight recorder's send capture
            # (obs/flight.py) rides the same re-derivation.
            ok_s = sd < n
            src_s = smrank_s // jnp.int32(M)
            tmsg_s = t + woff_s.astype(jnp.int64)
            flight_s, _, _, _, _ = self._sample_nodrop(
                src_s, sd, tmsg_s, smrank_s % jnp.int32(M), woff_s,
                ok_s)
            dt_abs = tmsg_s + flight_s
            sent_mix = mix32_jnp(SENT, src_s, sd, _tlo(dt_abs),
                                 _thi(dt_abs), pay_s[0])
            sent_hash = _u32sum(jnp.where(ok_s, sent_mix, 0))
        ret = (mrel, msrc, mpay, overflow_step, bad_dst_step,
               bad_delay_step, short_step, route_drop_step,
               sent_count, sent_hash)
        if rec_full:
            ret += (self._rec_sends(ok_s, None, src_s, sd, tmsg_s,
                                    dt_abs),)
        return ret
