"""Pallas fused routing superstep for the *sparse* general engine —
the gossip / praos path (round 6; VERDICT r5 item 1).

Round 5 proved the Pallas lever on the dense ring (fused_ring.py:
6.5e9 msg/s/chip) but every dynamic-destination config still runs the
XLA `JaxEngine` at 0.05-0.08x the north star, and the profiler says
where the fat is (PERF_r05.md "Where the remaining praos fat is"): the
free-rows [K, N] short-axis sort, the (1 + P) flat mailbox scatters
with their tiled-layout relayout copies, and the per-stage HBM
round-trips between them. This module fuses the post-compaction
pipeline — delay sampling → destination bucketing → hole-ranked
mailbox insertion — into ONE grid-free, double-buffered Pallas kernel
that streams the [K, N] mailbox planes exactly once while the
sender-compacted message batch stays resident in VMEM:

- the **compaction insight is reused, not replaced**: active senders
  are still compacted by ONE single-operand N-sort and the batch is
  still ordered by ``(destination, window offset, sender-major rank)``
  in XLA (sorts are the one thing XLA does near-bandwidth;
  PERF_r05.md cost table) — but the sorted batch is then handed to
  the kernel ONCE and never re-materialized per stage;
- link delays are sampled **in-kernel** with the counter-based
  threefry of core/rng.py inlined as uint32 VPU ops (the same bits
  the XLA engine derives — entropy is keyed by (src, dst, send
  instant, slot), so execution venue cannot change the stream); int64
  never lowers on this chip's Mosaic (fused_ring.py), so send
  instants enter as two uint32 words and the in-window offset is
  added with an explicit carry;
- mailbox **holes are ranked in-VMEM per block** (an unrolled
  K-cumsum while the block is already resident), so the free-rows
  [K, N] sort is not owed at all (`JaxEngine._fused_holes`), and the
  r-th message to a destination meets its r-th hole by a per-slot
  gather from the resident batch — no [K, N] scatter, no relayout
  copy, every mailbox byte read and written exactly once;
- counters (``overflow`` / ``bad_delay`` / ``short_delay``)
  accumulate as lane partials (scalar reductions do not lower —
  fused_ring.py constraint inventory) and are summed outside; they
  land in the same never-silent ``EngineState`` fields.

The per-destination bucket boundaries (``start``/``cnt``) are two
S-sized scatters into [N] planes computed in XLA from the sorted
batch — S is the *compacted* batch width, so this is the sparse
regime's cheap side.

**State layout is `EngineState`, bit-for-bit.** The engine subclasses
:class:`JaxEngine` and overrides only the adaptive routing stage, so
drivers, trace digests, the device event ring, checkpoints
(utils/checkpoint.py — a `.npz` saved by either engine resumes under
the other), and the CLI/bench plumbing are inherited unchanged, and
the exactness law is *state + trace equality against JaxEngine at
every superstep* (tests/test_fused_sparse.py; chained to the host
oracle by tests/test_parity.py).

Capacity: the resident batch is VMEM-bounded, so the engine carries a
static ``max_batch`` (messages per superstep). Supersteps whose
active-sender count exceeds ``max_batch // max_out`` drop the excess
messages and count them in ``EngineState.route_drop`` — the same
loudly-accounted capacity contract as ``route_cap`` (a parity run
must keep the counter 0; the in-bench gate asserts it). Scope guards
(constructor, never silent): ``commutative_inbox`` scenarios (hole
insertion), drop-free links that lower to the in-kernel uint32/f32
registry (`_lower_link`), windowed or wide-outbox workloads, and
``n_nodes`` divisible by the 1024-lane block shape.

Hardware status: on non-TPU backends the kernel runs under the pallas
interpreter (identical DMA/loop semantics — the exactness tests run
there); the kernel is written inside fused_ring.py's probed remote-
Mosaic constraint inventory (grid-free, int32-only, no scalar
reductions, slot-unrolled DMA buffers), plus one construct that
inventory does not cover — the per-slot gather from the resident
batch — which needs a hardware probe before the ≥10x r5 target can
be recorded (no chip is attached to this session; the in-bench gate
will fail loudly rather than record a wrong number).

≙ the reference's event dispatch this batches:
`/root/reference/src/Control/TimeWarp/Timed/TimedT.hs:234-286`.
"""

from __future__ import annotations

from ...utils import jaxconfig  # noqa: F401

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...core.rng import _MSG_TAG, normal_f32, threefry2x32
from ...core.scenario import Scenario
from ...net.delays import (FixedDelay, LinkModel, LogNormalDelay,
                           Quantize, SeededHashUniform, UniformDelay)
from ...trace.hashing import SENT, mix32_jnp
from .common import I32MAX as _I32MAX
from .common import group_rank, thi as _thi, tlo as _tlo, u32sum as _u32sum
from .engine import JaxEngine

__all__ = ["FusedSparseEngine"]

_LANES = 1024
_ROWS = 8          # rows per pipelined mailbox block (when NR % 8 == 0)
#: VMEM budget the constructor guards against (resident batch + the
#: four double-buffered block buffers), leaving headroom of a ~16 MB
#: VMEM for the compiler's own temporaries
_VMEM_BUDGET = 12 * 2**20


# ----------------------------------------------------------------------
# link-model lowering: the kernel's uint32/float32 delay samplers
# ----------------------------------------------------------------------

def _lower_link(link: LinkModel):
    """Compile ``link.sample`` into kernel-lowerable ops: returns
    ``(needs_key, max_delay_us, fn)`` where ``fn(src, dst, tlo, thi,
    key) -> uint32 delay`` uses only uint32/int32/float32 arithmetic
    (int64 never lowers in-kernel — fused_ring.py) and reproduces the
    XLA sampler's values bit-for-bit for integer models (float models
    carry delays.py's documented transcendental-lowering caveat).
    Unsupported models raise — a model the kernel cannot express must
    fail construction loudly, not sample differently."""
    if isinstance(link, Quantize):
        nk, mx, inner = _lower_link(link.inner)
        q = int(link.quantum_us)
        if q < 1:
            raise ValueError("Quantize quantum_us must be >= 1")

        def fn(src, dst, tl, th, key):
            d = jnp.maximum(inner(src, dst, tl, th, key), jnp.uint32(1))
            qq = jnp.uint32(q)
            return ((d + qq - jnp.uint32(1)) // qq) * qq
        return nk, ((max(mx, 1) + q - 1) // q) * q, fn
    if isinstance(link, FixedDelay):
        d = int(link.delay)
        if not 0 <= d < 2**31:
            raise ValueError("FixedDelay delay must fit int32 for the "
                             "fused kernel's uint32 deliver arithmetic")

        def fn(src, dst, tl, th, key):
            return jnp.full(jnp.shape(dst), d, jnp.uint32)
        return False, d, fn
    if isinstance(link, UniformDelay):
        lo, hi = int(link.lo), int(link.hi)
        if not (0 <= lo <= hi < 2**31):
            raise ValueError("UniformDelay bounds must satisfy "
                             "0 <= lo <= hi < 2**31 for the fused kernel")

        def fn(src, dst, tl, th, key):
            b0, _ = key
            return jnp.uint32(lo) + b0 % jnp.uint32(hi - lo + 1)
        return True, hi, fn
    if isinstance(link, SeededHashUniform):
        lo, hi = int(link.lo_us), int(link.hi_us)
        if not (0 <= lo <= hi < 2**31):
            raise ValueError("SeededHashUniform bounds must satisfy "
                             "0 <= lo <= hi < 2**31 for the fused kernel")
        s0, s1 = link._s0, link._s1

        def fn(src, dst, tl, th, key):
            # the model's own (dst, t)-keyed self-contained draw —
            # same chain as SeededHashUniform.sample, uint32-only
            bits, _ = threefry2x32(
                jnp.uint32(s0) ^ dst.astype(jnp.uint32),
                jnp.uint32(s1), tl, th)
            return jnp.uint32(lo) + bits % jnp.uint32(hi - lo + 1)
        return False, hi, fn
    if isinstance(link, LogNormalDelay):
        med, sig = int(link.median_us), float(link.sigma)
        cap, floor = int(link.cap_us), int(link.floor_us)
        if not 0 <= cap < 2**31:
            raise ValueError("LogNormalDelay cap_us must fit int32 for "
                             "the fused kernel")

        def fn(src, dst, tl, th, key):
            b0, b1 = key
            z = normal_f32(b0, b1)
            d = jnp.float32(med) * jnp.exp(jnp.float32(sig) * z)
            d = jnp.clip(d, jnp.float32(floor), jnp.float32(cap))
            return jnp.round(d).astype(jnp.uint32)
        return True, cap, fn
    raise ValueError(
        f"FusedSparseEngine cannot lower link model {link!r} into the "
        "kernel (supported: FixedDelay / UniformDelay / "
        "SeededHashUniform / LogNormalDelay, optionally Quantize-"
        "wrapped); run the XLA JaxEngine instead")


# ----------------------------------------------------------------------
# kernel helpers: reductions as lane partials (no scalar reductions
# lower in-kernel — fused_ring.py constraint inventory)
# ----------------------------------------------------------------------

def _fold_lanes(x):
    """[R, 1024] int32 -> [R, 128] partial sums (unrolled adds)."""
    R = x.shape[0]
    x = x.reshape(R, _LANES // 128, 128)
    acc = x[:, 0]
    for j in range(1, _LANES // 128):
        acc = acc + x[:, j]
    return acc


def _fold_rows8(x):
    """[rows, 128] int32 -> [8, 128] partial sums. rows must be a
    multiple of 8, or < 8 (zero-padded — axis-0 concat lowers, lane
    axis does not)."""
    rows = x.shape[0]
    if rows < 8:
        return jnp.concatenate(
            [x, jnp.zeros((8 - rows, 128), jnp.int32)], axis=0)
    acc = x[0:8]
    for i in range(1, rows // 8):
        acc = acc + x[8 * i:8 * i + 8]
    return acc


# ----------------------------------------------------------------------
# shared scope guards + static shape plan (single-chip engine AND the
# sharded insertion path — one copy, so the kernel's constraint
# inventory and the VMEM budget cannot desynchronize between them)
# ----------------------------------------------------------------------

def _insertion_plan(sc: Scenario, n: int, S_raw: int, *, who: str,
                    what_n: str = "n_nodes"):
    """Check ``sc`` against the fused insertion kernel's constraint
    inventory (commutative inbox, K <= 128 unrolled hole cumsum,
    1024-lane mailbox planes), round the resident batch width up to
    8-row tiling, and size the VMEM footprint against the budget.
    Returns ``(S, R, G)`` — batch width, rows per block, block count.
    Raises ``ValueError`` (never silently narrows scope)."""
    if not sc.commutative_inbox:
        raise ValueError(
            f"{who} requires a commutative_inbox scenario (insertion "
            "targets mailbox holes; an ordered inbox owes the "
            "contract-#2 compaction sort — run the XLA engine)")
    if sc.payload_width < 1:
        raise ValueError("payload_width must be >= 1")
    if sc.mailbox_cap > 128:
        raise ValueError("mailbox_cap must be <= 128 (the kernel "
                         "unrolls the hole-rank cumsum over K)")
    if n % _LANES:
        raise ValueError(
            f"{what_n} must be a multiple of {_LANES} (mailbox "
            "block lane shape)")
    NR = n // _LANES
    R = _ROWS if NR % _ROWS == 0 else 1
    S = -(-S_raw // 1024) * 1024            # SR must be 8-row tiled
    K, P = sc.mailbox_cap, sc.payload_width
    NP = 2 + K + K * P + (K if sc.inbox_src else 0)
    NPO = NP - 2
    footprint = (3 + P) * S * 4 + 2 * (NP + NPO) * R * _LANES * 4
    if footprint > _VMEM_BUDGET:
        raise ValueError(
            f"fused-sparse VMEM footprint {footprint} B exceeds the "
            f"{_VMEM_BUDGET} B budget — lower the batch bound "
            "(max_batch / bucket_cap) or mailbox_cap")
    return S, R, NR // R


# ----------------------------------------------------------------------
# the kernel
# ----------------------------------------------------------------------

def _build_kernel(*, K, P, R, G, SR, n, M, W, inbox_src, mode,
                  needs_key, s0, s1, delay_fn):
    """Build the grid-free fused routing kernel for one static shape.

    Refs: ``scal`` SMEM int32[4] = [t_lo, t_hi, 0, 0]; ``msgs`` VMEM
    int32[3+P, SR, 128] — the resident sorted batch, planes
    (dst | woff | smrank | payload_0..P-1) in ``mode="sample"`` or
    (dst | drel | src | payload…) in ``mode="drel"`` (pre-sampled,
    the sharded insertion path); ``st_ref`` ANY
    int32[NP, N/1024, 1024] — stacked (start | cnt | mb_rel[K] |
    mb_payload[K*P] | mb_src[K]?) planes; outputs: the post-insertion
    mailbox planes (same layout minus start/cnt) and int32[3, 8, 128]
    lane-partial counters (overflow, bad_delay, short_delay)."""
    KP = K * P
    NP = 2 + K + KP + (K if inbox_src else 0)
    NPO = K + KP + (K if inbox_src else 0)

    def kernel(scal, msgs_ref, st_ref, out_ref, cnt_ref):
        MAXI = jnp.int32(_I32MAX)
        m = msgs_ref[:]                                 # [3+P, SR, 128]
        dstp = m[0]
        valid = dstp < jnp.int32(n)
        zero_part = jnp.zeros((SR, 128), jnp.int32)
        if mode == "sample":
            woffp, smrank = m[1], m[2]
            srcp = smrank // jnp.int32(M)
            slot = smrank - srcp * jnp.int32(M)
            # send instant = t + woff as two uint32 words with an
            # explicit carry (int64 does not lower in-kernel)
            tl = scal[0].astype(jnp.uint32)
            th = scal[1].astype(jnp.uint32)
            woff_u = woffp.astype(jnp.uint32)
            lo = tl + woff_u
            carry = (lo < tl).astype(jnp.uint32)
            hi = th + carry
            key = None
            if needs_key:
                # msg_bits (core/rng.py) inlined: same chain, same bits
                a0, a1 = threefry2x32(
                    jnp.uint32(s0) ^ jnp.uint32(_MSG_TAG),
                    jnp.uint32(s1), srcp, dstp)
                b0, b1 = threefry2x32(a0, a1, lo, hi)
                key = threefry2x32(b0, b1, slot, jnp.uint32(0))
            delay = delay_fn(srcp, dstp, lo, hi, key)
            flight = jnp.maximum(delay, jnp.uint32(1))  # contract #4
            dsum = woff_u + flight
            badm = valid & (dsum > jnp.uint32(_I32MAX - 1))
            shortm = (valid & (flight < jnp.uint32(W))) if W > 1 \
                else jnp.zeros((SR, 128), bool)
            drelp = jnp.minimum(
                dsum, jnp.uint32(_I32MAX - 1)).astype(jnp.int32)
            bad8 = _fold_rows8(badm.astype(jnp.int32))
            short8 = _fold_rows8(shortm.astype(jnp.int32))
            srcp = srcp if inbox_src else None
        else:
            drelp, srcp = m[1], (m[2] if inbox_src else None)
            bad8 = short8 = _fold_rows8(zero_part)
        payps = [m[3 + p] for p in range(P)]

        def block_compute(blk):
            """Insert the resident batch into one [NP, R, L] mailbox
            block: rank holes (unrolled K-cumsum), meet the r-th
            message to each destination at its r-th hole via a gather
            from the resident planes. Returns the output block and
            the per-node overflow partial."""
            start_b, cnt_b = blk[0], blk[1]
            rel = blk[2:2 + K]
            pay = blk[2 + K:2 + K + KP]
            smb = blk[2 + K + KP:] if inbox_src else None
            acc = jnp.zeros(rel[0].shape, jnp.int32)
            o_rel, o_pay, o_src = [], [None] * KP, []
            for k in range(K):
                free_k = rel[k] >= MAXI
                h_k = acc
                acc = acc + free_k.astype(jnp.int32)
                want = free_k & (h_k < cnt_b)
                j = jnp.where(want, start_b + h_k, jnp.int32(0))
                jr = j // jnp.int32(128)
                jc = j - jr * jnp.int32(128)
                o_rel.append(jnp.where(want, drelp[jr, jc], rel[k]))
                for p in range(P):
                    o_pay[k * P + p] = jnp.where(
                        want, payps[p][jr, jc], pay[k * P + p])
                if inbox_src:
                    o_src.append(jnp.where(want, srcp[jr, jc], smb[k]))
            # messages beyond a destination's hole count are dropped
            # and counted — identical to _insert_sorted's ok & ~fits
            ovf = jnp.maximum(cnt_b - acc, jnp.int32(0))
            out = jnp.stack(o_rel + o_pay + o_src)
            return out, _fold_lanes(ovf)

        def body(in_buf0, in_buf1, out_buf0, out_buf1,
                 in_sem0, in_sem1, out_sem0, out_sem1):
            RW = jnp.int32(R)
            in_bufs = (in_buf0, in_buf1)
            out_bufs = (out_buf0, out_buf1)
            in_sems = (in_sem0, in_sem1)
            out_sems = (out_sem0, out_sem1)

            def in_dma(slot, b):
                return pltpu.make_async_copy(
                    st_ref.at[:, pl.ds(b * RW, R), :],
                    in_bufs[slot], in_sems[slot])

            def out_dma(slot, b):
                return pltpu.make_async_copy(
                    out_bufs[slot],
                    out_ref.at[:, pl.ds(b * RW, R), :],
                    out_sems[slot])

            in_dma(0, 0).start()
            ONE = jnp.int32(1)
            TWO = jnp.int32(2)
            GG = jnp.int32(G)

            def when_slot(slot, fn):
                # dynamic buffer-slot indices emit 64-bit memref
                # slices Mosaic rejects — unroll the two slots
                @pl.when(slot == jnp.int32(0))
                def _():
                    fn(0)

                @pl.when(slot == ONE)
                def _():
                    fn(1)

            def loop(carry):
                b, slot, ovf = carry

                @pl.when(b + ONE < GG)
                def _():
                    when_slot(slot,
                              lambda sl: in_dma(1 - sl, b + ONE).start())

                when_slot(slot, lambda sl: in_dma(sl, b).wait())
                blk = jnp.where(slot == ONE, in_buf1[:], in_buf0[:])
                out, o = block_compute(blk)

                @pl.when(b >= TWO)
                def _():
                    when_slot(slot, lambda sl: out_dma(sl, b - TWO).wait())

                def put(sl):
                    out_bufs[sl][:] = out
                    out_dma(sl, b).start()
                when_slot(slot, put)
                return (b + ONE, ONE - slot, ovf + o)

            carry = jax.lax.while_loop(
                lambda c: c[0] < GG, loop,
                (jnp.int32(0), jnp.int32(0),
                 jnp.zeros((R, 128), jnp.int32)))

            if G >= 2:
                out_dma(G % 2, jnp.int32(G - 2)).wait()
            out_dma((G - 1) % 2, jnp.int32(G - 1)).wait()
            cnt_ref[:] = jnp.stack(
                [_fold_rows8(carry[2]), bad8, short8])

        pl.run_scoped(
            body,
            in_buf0=pltpu.VMEM((NP, R, _LANES), jnp.int32),
            in_buf1=pltpu.VMEM((NP, R, _LANES), jnp.int32),
            out_buf0=pltpu.VMEM((NPO, R, _LANES), jnp.int32),
            out_buf1=pltpu.VMEM((NPO, R, _LANES), jnp.int32),
            in_sem0=pltpu.SemaphoreType.DMA(()),
            in_sem1=pltpu.SemaphoreType.DMA(()),
            out_sem0=pltpu.SemaphoreType.DMA(()),
            out_sem1=pltpu.SemaphoreType.DMA(()),
        )

    return kernel


# ----------------------------------------------------------------------
# the kernel invocation shared by the single-chip engine and the
# sharded insertion path (sharded.py ShardedFusedSparseEngine)
# ----------------------------------------------------------------------

def _fused_insert_call(kernel, S, n, K, P, inbox_src, scal, sd, a1, a2,
                       pay_s, mb_rel, mb_src, mb_payload):
    """Stack the sorted batch + per-node bucket planes and run the
    fused kernel once. ``sd`` is the sorted destination row (sentinel
    ``n`` = invalid); ``(a1, a2)`` are the mode's second/third resident
    planes — (woff, smrank) for in-kernel sampling, (drel, src) for
    pre-sampled insertion. Returns the post-insertion mailbox arrays
    plus the [3, 8, 128] counter partials."""
    SA = sd.shape[0]
    L = _LANES
    NR = n // L

    # per-destination bucket boundaries: two S-sized scatters into [N]
    # planes (S = the compacted batch width — the sparse regime's
    # cheap side); the kernel meets rank r at hole r via start + r
    rank = group_rank(sd)
    validm = sd < n
    iota = jnp.arange(SA, dtype=jnp.int32)
    start = jnp.zeros(n, jnp.int32).at[
        jnp.where(validm & (rank == 0), sd, n)].set(iota, mode="drop")
    nxt = jnp.concatenate([sd[1:], jnp.full((1,), n, sd.dtype)])
    cnt = jnp.zeros(n, jnp.int32).at[
        jnp.where(validm & (sd != nxt), sd, n)].set(
            rank + 1, mode="drop")

    pad = S - SA

    def padded(x, fill):
        if not pad:
            return x
        return jnp.concatenate([x, jnp.full((pad,), fill, x.dtype)])

    SR = S // 128
    msgs = jnp.stack(
        [padded(sd, n).reshape(SR, 128),
         padded(a1, 0).reshape(SR, 128),
         padded(a2, 0).reshape(SR, 128)]
        + [padded(p, 0).reshape(SR, 128) for p in pay_s])
    st_planes = jnp.concatenate(
        [start.reshape(1, NR, L), cnt.reshape(1, NR, L),
         mb_rel.reshape(K, NR, L),
         mb_payload.reshape(K * P, NR, L)]
        + ([mb_src.reshape(K, NR, L)] if inbox_src else []),
        axis=0)

    NPO = K + K * P + (K if inbox_src else 0)
    out_planes, cnts = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_shape=[
            jax.ShapeDtypeStruct((NPO, NR, L), jnp.int32),
            jax.ShapeDtypeStruct((3, 8, 128), jnp.int32)],
        # non-TPU backends run the pallas interpreter — identical
        # DMA/loop semantics, which is what the exactness tests pin
        interpret=jax.default_backend() != "tpu",
    )(scal, msgs, st_planes)
    mrel = out_planes[:K].reshape(K, n)
    mpay = out_planes[K:K + K * P].reshape(K, P, n)
    msrc = out_planes[K + K * P:].reshape(K, n) if inbox_src \
        else mb_src
    return mrel, msrc, mpay, cnts


# ----------------------------------------------------------------------
# the engine
# ----------------------------------------------------------------------

class FusedSparseEngine(JaxEngine):
    """:class:`JaxEngine` with the adaptive routing stage replaced by
    the fused Pallas kernel (module docstring). Same state, drivers,
    trace, event ring, and checkpoint format — construction-time scope
    guards are the only API difference.

    ``max_batch`` bounds the VMEM-resident message batch per
    superstep; excess messages are dropped into
    ``EngineState.route_drop`` (never silent — the parity regime and
    the in-bench gate require the counter to stay 0). With
    ``max_batch >= n_nodes * max_out`` no superstep can ever drop."""

    def __init__(self, scenario: Scenario, link: LinkModel, *,
                 seed: int = 0, window=1, record_events: int = 0,
                 max_batch: int = 1 << 16,
                 lint: str = "warn", telemetry: str = "off") -> None:
        super().__init__(scenario, link, seed=seed, window=window,
                         route_cap=None, record_events=record_events,
                         lint=lint, telemetry=telemetry)
        sc = scenario
        if link.can_drop:
            raise ValueError(
                "FusedSparseEngine requires a drop-free link (message "
                "validity must not depend on the sample — the lazy-"
                "sampling precondition, engine.py)")
        if not (self.window > 1 or sc.max_out > 1):
            raise ValueError(
                "FusedSparseEngine serves the windowed / wide-outbox "
                "sparse regime (window > 1 or max_out > 1); the "
                "classic regime routes nothing the kernel can batch")
        n = sc.n_nodes
        nk, mx, fn = _lower_link(link)
        if mx + self.window >= 2**32:
            raise ValueError("max link delay + window must fit the "
                             "kernel's uint32 deliver arithmetic")
        self._delay_fn, self._link_needs_key = fn, nk
        A = min(n, max(1, int(max_batch) // sc.max_out))
        self._A = A
        self._S, self._R, G = _insertion_plan(
            sc, n, A * sc.max_out, who="FusedSparseEngine")
        self._fused_holes = True
        self._kernel = _build_kernel(
            K=sc.mailbox_cap, P=sc.payload_width, R=self._R, G=G,
            SR=self._S // 128, n=n, M=sc.max_out, W=self.window,
            inbox_src=sc.inbox_src, mode="sample", needs_key=nk,
            s0=self.s0, s1=self.s1, delay_fn=fn)

    # -- the fused routing stage -----------------------------------------

    def _route_adaptive(self, out, out_valid, now_vec, t, mb_rel,
                        mb_src, mb_payload, free_rows, counts,
                        node_ids, with_trace):
        """Sender-compact in XLA (one N-sort — the compaction insight
        of the base engine, unchanged), sort the batch by
        (destination, window offset, sender-major rank), then hand it
        to the fused kernel ONCE: sampling, bucketing, and hole-ranked
        insertion all happen against the resident batch while the
        mailbox planes stream through VMEM (module docstring)."""
        sc = self.scenario
        K, M, P = sc.mailbox_cap, sc.max_out, sc.payload_width
        n = self.comm.n_local
        n_glob = self.comm.n_global
        W = self.window
        if self.telemetry != "off":
            # the fused engine's "rung" is its static VMEM batch slice
            self._t_rung = jnp.int32(self._A)

        dst32 = out.dst.astype(jnp.int32)                       # [M, N]
        dst_okf = (dst32 >= 0) & (dst32 < n_glob)
        bad_dst_step = jnp.sum(out_valid & ~dst_okf, dtype=jnp.int32)
        pdst = jnp.where(out_valid & dst_okf, dst32, -1)        # [M, N]
        sender_live = jnp.any(pdst >= 0, axis=0)                # [N]
        sid_sorted = jax.lax.sort(
            jnp.where(sender_live, node_ids, jnp.int32(n)))
        woff_n = (now_vec - t).astype(jnp.int32)                # [N]

        # static batch slice: active senders sort first, so slicing A
        # keeps every active sender while n_active <= A; the excess is
        # counted into route_drop below, never silent
        A = self._A
        sids = jax.lax.slice_in_dim(sid_sorted, 0, A)
        real = sids < n
        sidc = jnp.where(real, sids, 0)
        woff_a = woff_n[sidc]                                   # [A]
        dst_a = jnp.take(pdst, sidc, axis=1)                    # [M, A]
        pay_a = tuple(jnp.take(out.payload[:, p, :], sidc, axis=1)
                      for p in range(P))
        SA = A * M
        dst_f = dst_a.reshape(SA)
        ok = (dst_f >= 0) & jnp.broadcast_to(
            real[None, :], (M, A)).reshape(SA)
        smrank = (jnp.broadcast_to(sidc[None, :] * jnp.int32(M),
                                   (M, A))
                  + jnp.arange(M, dtype=jnp.int32)[:, None]
                  ).reshape(SA)
        total_msgs = jnp.sum(pdst >= 0, dtype=jnp.int32)
        kept = jnp.sum(ok, dtype=jnp.int32)
        route_drop_step = total_msgs - kept

        sort_dst = jnp.where(ok, dst_f, n)
        pay_f = tuple(p.reshape(SA) for p in pay_a)
        if W > 1:
            woff_f = jnp.broadcast_to(woff_a[None, :], (M, A)
                                      ).reshape(SA)
            ops = jax.lax.sort((sort_dst, woff_f, smrank) + pay_f,
                               dimension=0, num_keys=3)
            sd, woff_s, smrank_s = ops[0], ops[1], ops[2]
            pay_s = ops[3:]
        else:
            ops = jax.lax.sort((sort_dst, smrank) + pay_f,
                               dimension=0, num_keys=2)
            sd, smrank_s = ops[0], ops[1]
            woff_s = jnp.zeros_like(sd)
            pay_s = ops[2:]

        scal = jnp.stack([_tlo(t).astype(jnp.int32),
                          _thi(t).astype(jnp.int32),
                          jnp.int32(0), jnp.int32(0)])
        mrel, msrc, mpay, cnts = _fused_insert_call(
            self._kernel, self._S, n, K, P, sc.inbox_src, scal,
            sd, woff_s, smrank_s, pay_s, mb_rel, mb_src, mb_payload)
        overflow_step = jnp.sum(cnts[0], dtype=jnp.int32)
        bad_delay_step = jnp.sum(cnts[1], dtype=jnp.int32)
        short_step = jnp.sum(cnts[2], dtype=jnp.int32)

        sent_count = kept
        if with_trace:
            # the SENT digest needs per-message flight times; re-derive
            # them in XLA from the same counters (bit-identical stream
            # — entropy is keyed by message identity, not venue). Only
            # the traced `run` driver compiles this; `run_quiet`
            # benchmarks never do.
            ok_s = sd < n
            src_s = smrank_s // jnp.int32(M)
            tmsg_s = t + woff_s.astype(jnp.int64)
            flight_s, _, _, _ = self._sample_nodrop(
                src_s, sd, tmsg_s, smrank_s % jnp.int32(M), woff_s,
                ok_s)
            dt_abs = tmsg_s + flight_s
            sent_mix = mix32_jnp(SENT, src_s, sd, _tlo(dt_abs),
                                 _thi(dt_abs), pay_s[0])
            sent_hash = _u32sum(jnp.where(ok_s, sent_mix, 0))
        else:
            sent_hash = jnp.uint32(0)
        return (mrel, msrc, mpay, overflow_step, bad_dst_step,
                bad_delay_step, short_step, route_drop_step,
                sent_count, sent_hash)
