"""Real wall-clock interpreter over asyncio — the framework's "IO mode".

TPU-native re-design of the reference's ``TimedIO``
(`/root/reference/src/Control/TimeWarp/Timed/TimedIO.hs`): the *same*
generator programs that run under the pure emulator
(:class:`timewarp_tpu.interp.ref.des.PureEmulation`) run here against
real time — ``virtualTime = now − origin`` (TimedIO.hs:60), ``wait`` is
a real sleep (:64-66), ``fork`` a real concurrent task (:68),
``throwTo`` delivers a real async exception (:72).

Where the reference maps onto GHC's runtime threads, we map onto
asyncio: one task per timed thread, with the interpreter driving the
program generator and translating effects. The reference's semantics
are kept:

- **Interruption only at suspension points.** GHC delivers async
  exceptions at safe points; our unit of uninterruptible execution is
  the straight-line code between two ``yield``\\ s, exactly as in the
  pure emulator (TimedT.hs:324-325) — so programs are interrupt-safe in
  the same places under both interpreters.
- **First thrower wins** when exceptions race to one thread
  (TimedT.hs:359).
- **Forked failures don't kill the scenario**: uncaught exceptions in
  child threads are logged — ``ThreadKilled`` at DEBUG, others at
  WARNING (TimedT.hs:153-158, 306-316) — never propagated to main.
- **Main return ends the run**: like ``runTimedIO`` returning while
  daemon threads still run, ``run`` cancels all surviving threads once
  the main program finishes (GHC kills daemons at process exit; we do
  it at scenario exit so runs compose inside one process).

Beyond the reference, this interpreter honors the :class:`AwaitIO`
effect — awaiting an arbitrary asyncio awaitable with throw-to
cancellation — which is what the real TCP transport layer is built on.
"""

from __future__ import annotations

import asyncio
import logging
import time as _time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ...core.effects import (AwaitIO, Fork, ForkSlave, GetLogName, GetTime,
                             MyTid, Park, ProgramFn, SetLogName, ThrowTo,
                             Unpark, Wait)
from ...core.errors import ThreadKilled
from ...core.time import Microsecond, resolve
from ..common import NO_TOKEN as _NO_TOKEN
from ..common import log_thread_death

__all__ = ["RealTime", "AioThreadId", "run_real_time"]

_log = logging.getLogger("timewarp.realtime")


@dataclass(frozen=True)
class AioThreadId:
    """Thread id under the real-IO interpreter (≙ ``ThreadId TimedIO`` =
    a GHC ThreadId, TimedIO.hs:50)."""
    n: int

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"AioThreadId({self.n})"


@dataclass
class _Thread:
    tid: AioThreadId
    log_name: str
    task: Optional["asyncio.Task"] = None
    #: set while the thread sits at an interruptible suspension
    wake: Optional["asyncio.Future"] = None
    pending_exc: Optional[BaseException] = None
    park_token: Any = _NO_TOKEN
    parked: bool = False
    done: "asyncio.Event" = field(default_factory=asyncio.Event)
    #: linked-lifetime bookkeeping (ForkSlave): slaves killed when this
    #: thread terminates; master receives forwarded uncaught exceptions
    slaves: Optional[List["AioThreadId"]] = None
    master: Optional["AioThreadId"] = None


class RealTime:
    """Real wall-clock interpreter (≙ ``runTimedIO``, TimedIO.hs:81-85).

    ``run(program_fn)`` blocks until the main program returns, then
    cancels surviving forked threads. ``run_async`` is the same as a
    coroutine, for embedding in an existing event loop.
    """

    def __init__(self, *, default_log_name: str = "real") -> None:
        self._default_log_name = default_log_name
        self._origin: float = 0.0
        self._threads: Dict[AioThreadId, _Thread] = {}
        self._tid_counter = 0

    # -- clock -----------------------------------------------------------

    @property
    def virtual_time(self) -> Microsecond:
        """µs since ``run`` started (≙ TimedIO.hs:60, 84-85)."""
        return int((_time.monotonic() - self._origin) * 1_000_000)

    # -- public ----------------------------------------------------------

    def run(self, program_fn: ProgramFn) -> Any:
        return asyncio.run(self.run_async(program_fn))

    async def run_async(self, program_fn: ProgramFn) -> Any:
        # stamp the origin (≙ curTime in runTimedIO, TimedIO.hs:84-85)
        self._origin = _time.monotonic()
        self._threads = {}
        self._tid_counter = 0
        main = self._spawn(program_fn, self._default_log_name)
        try:
            return await main.task
        finally:
            await self._cancel_survivors(except_tid=main.tid)

    async def _cancel_survivors(self, except_tid: AioThreadId) -> None:
        live = [t for t in self._threads.values()
                if t.tid != except_tid and t.task is not None
                and not t.task.done()]
        for t in live:
            t.task.cancel()
        for t in live:
            try:
                await t.task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    # -- thread machinery ------------------------------------------------

    def _spawn(self, program_fn: ProgramFn, log_name: str) -> _Thread:
        tid = AioThreadId(self._tid_counter)
        self._tid_counter += 1
        th = _Thread(tid=tid, log_name=log_name)
        self._threads[tid] = th
        th.task = asyncio.ensure_future(self._drive(th, program_fn))
        return th

    def _pop_exc(self, th: _Thread) -> Optional[BaseException]:
        exc, th.pending_exc = th.pending_exc, None
        return exc

    async def _drive(self, th: _Thread, program_fn: ProgramFn) -> Any:
        is_main = th.tid.n == 0
        try:
            result = await self._run_program(th, program_fn)
            return result
        except asyncio.CancelledError:
            raise
        except BaseException as e:  # noqa: BLE001 — interpreter boundary
            if is_main:
                raise
            # ForkSlave contract: forward a slave's uncaught exception
            # (other than ThreadKilled) to its master (core/effects.py)
            if (th.master is not None
                    and not isinstance(e, ThreadKilled)
                    and th.master in self._threads):
                self._throw_to(th.master, e)
            else:
                log_thread_death(_log, th.log_name, e)
            return None
        finally:
            th.done.set()
            self._threads.pop(th.tid, None)
            # ForkSlave contract: a terminating slave prunes itself from
            # its master's list (keeps the list O(live slaves)); a
            # terminating master kills its live slaves, cascading
            # through slave subtrees via their own _drive finallys
            if th.master is not None:
                master = self._threads.get(th.master)
                if master is not None and master.slaves:
                    try:
                        master.slaves.remove(th.tid)
                    except ValueError:
                        pass
            if th.slaves:
                for stid in th.slaves:
                    self._throw_to(stid, ThreadKilled())

    async def _run_program(self, th: _Thread, program_fn: ProgramFn) -> Any:
        # Pre-start throw_to parity with the emulator (des.py _step): an
        # exception stored before the body first runs kills the thread
        # without creating the frame — no user handler exists yet.
        if th.pending_exc is not None:
            raise self._pop_exc(th)
        gen = program_fn()
        if not hasattr(gen, "send"):
            return gen  # yield-free program: already ran at call time
        try:
            return await self._drive_gen(th, gen)
        finally:
            # Runs the program's finally blocks even when the *task* is
            # cancelled out of a suspension point (e.g. scenario-exit
            # survivor cleanup) — GeneratorExit at the yield, exactly
            # like GHC killing a thread blocked in threadDelay. Cleanup
            # code may still yield *instantaneous* effects (Unpark to
            # release waiters, ThrowTo, time/tid reads); a suspension
            # during cleanup aborts it.
            self._close_gen(th, gen)

    _INSTANT = (GetTime, MyTid, GetLogName, SetLogName, Unpark, ThrowTo)

    def _close_gen(self, th: _Thread, gen: Any) -> None:
        try:
            eff = gen.throw(GeneratorExit)
        except (StopIteration, GeneratorExit):
            return
        while True:
            if type(eff) in self._INSTANT:
                value: Any = None
                if type(eff) is GetTime:
                    value = self.virtual_time
                elif type(eff) is MyTid:
                    value = th.tid
                elif type(eff) is GetLogName:
                    value = th.log_name
                elif type(eff) is SetLogName:
                    th.log_name = eff.name
                elif type(eff) is Unpark:
                    self._unpark(eff.tid, eff.value)
                elif type(eff) is ThrowTo:
                    self._throw_to(eff.tid, eff.exc)
                try:
                    eff = gen.send(value)
                except (StopIteration, GeneratorExit):
                    return
            else:
                # tried to suspend during cleanup: hard stop. Close an
                # abandoned AwaitIO coroutine so it neither warns nor
                # holds resources.
                if type(eff) is AwaitIO and hasattr(eff.awaitable,
                                                    "close"):
                    eff.awaitable.close()
                gen.close()
                return

    async def _drive_gen(self, th: _Thread, gen: Any) -> Any:
        value: Any = None
        exc: Optional[BaseException] = None
        while True:
            try:
                if exc is not None:
                    e, exc, value = exc, None, None
                    eff = gen.throw(e)
                else:
                    eff, value = gen.send(value), None
            except StopIteration as stop:
                return stop.value

            if type(eff) is Wait:
                target = resolve(eff.spec, self.virtual_time)
                exc = await self._sleep_until(th, target)
            elif type(eff) is GetTime:
                value = self.virtual_time
            elif type(eff) is MyTid:
                value = th.tid
            elif type(eff) is Fork or type(eff) is ForkSlave:
                child = self._spawn(eff.program, th.log_name)
                if type(eff) is ForkSlave:
                    child.master = th.tid
                    if th.slaves is None:
                        th.slaves = []
                    th.slaves.append(child.tid)
                # forkIO-handoff parity with the emulator (des.py Fork:
                # child enqueued at `now`, parent resumes at now+1, so
                # the child reaches its first suspension first): yield
                # the loop once so the child task runs to its first
                # await. Fork is thereby a suspension point, and a
                # stored async exception is deliverable here — exactly
                # where the emulator's parent-resume event delivers it.
                await asyncio.sleep(0)
                exc = self._pop_exc(th)
                value = child.tid
            elif type(eff) is ThrowTo:
                # self-throw parity with the emulator: the exception is
                # *stored* and delivered at the next suspension point
                # (core/effects.py ThrowTo docstring)
                self._throw_to(eff.tid, eff.exc)
            elif type(eff) is GetLogName:
                value = th.log_name
            elif type(eff) is SetLogName:
                th.log_name = eff.name
            elif type(eff) is Park:
                if th.park_token is not _NO_TOKEN:
                    value, th.park_token = th.park_token, _NO_TOKEN
                else:
                    value, exc = await self._park(th)
            elif type(eff) is Unpark:
                self._unpark(eff.tid, eff.value)
            elif type(eff) is AwaitIO:
                value, exc = await self._await_io(th, eff.awaitable)
            else:
                raise TypeError(f"unknown effect: {eff!r}")

    # -- suspension points -----------------------------------------------

    def _make_wake(self, th: _Thread) -> "asyncio.Future":
        assert th.wake is None, "thread suspended twice"
        th.wake = asyncio.get_running_loop().create_future()
        return th.wake

    async def _sleep_until(self, th: _Thread,
                           target: Microsecond) -> Optional[BaseException]:
        """Interruptible sleep (≙ ``wait``→``threadDelay``, TimedIO.hs:64-66;
        interruption ≙ GHC async exception delivery)."""
        if th.pending_exc:  # stored self-throw: deliver at this point
            return self._pop_exc(th)
        wake = self._make_wake(th)
        try:
            delay = max(target - self.virtual_time, 0) / 1_000_000
            await asyncio.wait_for(asyncio.shield(wake), timeout=delay)
        except asyncio.TimeoutError:
            pass  # timer fired normally
        finally:
            th.wake = None
        return self._pop_exc(th)

    async def _park(self, th: _Thread):
        if th.pending_exc:
            return None, self._pop_exc(th)
        wake = self._make_wake(th)
        th.parked = True
        try:
            value = await wake
        finally:
            th.parked = False
            th.wake = None
        return value, self._pop_exc(th)

    async def _await_io(self, th: _Thread, awaitable: Any):
        """Await real IO; a throw_to cancels the awaitable and delivers
        the exception here (the AwaitIO cancellation contract)."""
        if th.pending_exc:
            return None, self._pop_exc(th)
        fut = asyncio.ensure_future(awaitable)
        wake = self._make_wake(th)
        try:
            await asyncio.wait({fut, wake},
                               return_when=asyncio.FIRST_COMPLETED)
        except BaseException:
            # Outer cancellation (task killed mid-await): don't leak the
            # inner future — cancel it, reap it, then re-raise so
            # _run_program's finally closes the program.
            fut.cancel()
            try:
                await fut
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            raise
        finally:
            th.wake = None
            if not wake.done():
                wake.cancel()
        if th.pending_exc is not None:
            fut.cancel()
            try:
                await fut
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            return None, self._pop_exc(th)
        try:
            return fut.result(), None
        except BaseException as e:  # noqa: BLE001 — surface in program
            return None, e

    # -- cross-thread signals --------------------------------------------

    def _throw_to(self, tid: AioThreadId, exc: BaseException) -> None:
        """≙ throwTo → Control.Exception.throwTo (TimedIO.hs:72), with
        the emulator's first-thrower-wins contract (TimedT.hs:359)."""
        th = self._threads.get(tid)
        if th is None:
            return
        if th.pending_exc is None:
            th.pending_exc = exc
        if th.wake is not None and not th.wake.done():
            th.wake.set_result(None)

    def _unpark(self, tid: AioThreadId, value: Any) -> None:
        th = self._threads.get(tid)
        if th is None:
            return
        if th.parked and th.wake is not None and not th.wake.done():
            th.wake.set_result(value)
        else:
            th.park_token = value


def run_real_time(program_fn: ProgramFn, **kw: Any) -> Any:
    """One-shot convenience ≙ ``runTimedIO`` (TimedIO.hs:81-82)."""
    return RealTime(**kw).run(program_fn)
