"""Real wall-clock interpreter over asyncio (≙ ``TimedIO`` + the real
``Transfer`` network, SURVEY.md §1 L1a/L3)."""

from .timed import AioThreadId, RealTime, run_real_time

__all__ = ["AioThreadId", "RealTime", "run_real_time"]
