"""Pure deterministic discrete-event emulation — the framework's oracle.

TPU-native re-design of the reference's ``TimedT``
(`/root/reference/src/Control/TimeWarp/Timed/TimedT.hs`). The whole
multi-thread scenario executes on one host thread; ``wait`` costs zero
wall-clock; every action between waits is 0-cost in virtual time
(TimedT.hs:139-145). This interpreter is the *semantic reference* that
the batched JAX engine must match trace-for-trace (SURVEY.md §7).

Where the reference captures continuations with ``ContT`` (TimedT.hs:
146-151, 343-355), we use Python generators: a suspended thread *is* its
generator frame, and the event queue holds resume thunks. Exception
handler stacks with re-arming after each wait (the reference's
``catchesSeq``/``ContException`` machinery, TimedT.hs:178-204, 259-284)
are subsumed by the language: throwing into a generator at its
suspension point runs the program's own ``try/except`` blocks with
exactly the scoping the reference had to build by hand.

Determinism contract (explicit where the reference leaned on heap
internals, TimedT.hs:100-104; SURVEY.md §5.2): events are totally
ordered by ``(virtual_time, seq)`` where ``seq`` is a monotone insertion
counter. Equal-time events therefore run in the order they were
scheduled, and a ``throw_to`` wake-up reschedules the target with a
fresh ``seq`` (it runs after events already queued at `now`).
"""

from __future__ import annotations

import heapq
import logging
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ...core.effects import (AwaitIO, Fork, ForkSlave, GetLogName,
                             GetTime, MyTid, Park, Program, ProgramFn,
                             SetLogName, ThrowTo, Unpark, Wait)
from ...core.errors import DeadlockError, ThreadKilled, TimedError
from ..common import NO_TOKEN as _NO_TOKEN
from ..common import log_thread_death
from ...core.time import Microsecond, resolve

__all__ = ["PureEmulation", "PureThreadId", "run_emulation"]

_log = logging.getLogger("timewarp.emulation")


@dataclass(frozen=True)
class PureThreadId:
    """≙ ``PureThreadId`` (TimedT.hs:72-76)."""
    n: int

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"PureThreadId({self.n})"


@dataclass
class _Thread:
    tid: PureThreadId
    gen: Optional[Program]       # None until the start event fires
    program: Optional[ProgramFn]
    is_main: bool
    log_name: str
    alive: bool = True
    started: bool = False
    resume_entry: Optional[list] = None  # live queue entry, for wake-ups
    parked: bool = False
    park_token: Any = _NO_TOKEN           # pending unpark value
    #: linked-lifetime bookkeeping (ForkSlave): tids of this thread's
    #: slaves (killed when it finishes) and the master to forward
    #: uncaught exceptions to (None for plain forks)
    slaves: Optional[List["PureThreadId"]] = None
    master: Optional["PureThreadId"] = None


# Queue entry layout: [time, seq, tid, send_value, cancelled]
_TIME, _SEQ, _TID, _VALUE, _CANCELLED = range(5)


class PureEmulation:
    """Deterministic emulation interpreter (≙ ``runTimedT``, TimedT.hs:293-304).

    ``run(program_fn)`` executes the scenario to quiescence (event queue
    empty, TimedT.hs:266-267) and returns the main program's result; an
    exception escaping the *main* thread propagates to the caller, while
    uncaught exceptions in forked threads are logged — ``ThreadKilled``
    at DEBUG, others at WARNING (TimedT.hs:153-158, 306-316).
    """

    def __init__(self, *, default_log_name: str = "emulation") -> None:
        # ≙ defaultLoggerName (TimedT.hs:380-381)
        self._default_log_name = default_log_name
        self._queue: List[list] = []
        self._threads: Dict[PureThreadId, _Thread] = {}
        self._pending_exc: Dict[PureThreadId, BaseException] = {}
        self._time: Microsecond = 0
        self._seq = 0
        self._tid_counter = 0  # ≙ threadsCounter (TimedT.hs:114-115)

    # -- public ----------------------------------------------------------

    @property
    def virtual_time(self) -> Microsecond:
        return self._time

    def run(self, program_fn: ProgramFn) -> Any:
        # fresh scenario per run (≙ evalStateT emptyScenario, TimedT.hs:227)
        self._queue = []
        self._threads = {}
        self._pending_exc = {}
        self._time = 0
        self._seq = 0
        self._tid_counter = 0
        main = self._spawn(program_fn, self._default_log_name, is_main=True)
        self._push(main, self._time, None)
        main_result: List[Any] = []
        main_error: List[BaseException] = []
        deadlock_served: set = set()

        # Event loop ≙ launchTimedT (TimedT.hs:234-286).
        while True:
            while self._queue:
                entry = heapq.heappop(self._queue)
                if entry[_CANCELLED]:
                    continue
                th = self._threads[entry[_TID]]
                th.resume_entry = None
                if not th.alive:
                    continue
                # Rewind the clock to the event's instant (TimedT.hs:247).
                self._time = entry[_TIME]
                # Deliver a pending async exception (TimedT.hs:252-257).
                exc = self._pending_exc.pop(th.tid, None)
                self._step(th, entry[_VALUE], exc, main_result, main_error)
            # Queue drained. Parked survivors can never be woken again —
            # deliver DeadlockError into each (≙ GHC's
            # BlockedIndefinitelyOnMVar; handlers/finally still run) and
            # keep looping until true quiescence. At most one delivery
            # per thread: a handler that catches the error and parks
            # again would otherwise be re-woken forever at frozen
            # virtual time (GHC spins the same way, once per GC; we
            # terminate instead).
            parked = [th for th in self._threads.values()
                      if th.alive and th.parked
                      and th.tid not in deadlock_served]
            if not parked:
                break
            for th in parked:
                deadlock_served.add(th.tid)
                th.parked = False
                self._push(th, self._time, None)
                self._pending_exc.setdefault(th.tid, DeadlockError(
                    f"thread {th.tid} parked with no runnable events "
                    "left — blocked indefinitely"))

        if main_error:
            raise main_error[0]
        return main_result[0] if main_result else None

    # -- scheduling ------------------------------------------------------

    def _next_tid(self) -> PureThreadId:
        tid = PureThreadId(self._tid_counter)
        self._tid_counter += 1
        return tid

    def _spawn(self, program_fn: ProgramFn, log_name: str, *,
               is_main: bool) -> _Thread:
        th = _Thread(tid=self._next_tid(), gen=None, program=program_fn,
                     is_main=is_main, log_name=log_name)
        self._threads[th.tid] = th
        return th

    def _push(self, th: _Thread, time: Microsecond, value: Any) -> None:
        entry = [time, self._seq, th.tid, value, False]
        self._seq += 1
        th.resume_entry = entry
        heapq.heappush(self._queue, entry)

    # -- effect handling -------------------------------------------------

    def _step(self, th: _Thread, value: Any, exc: Optional[BaseException],
              main_result: list, main_error: list) -> None:
        """Drive one thread from its resume point to its next suspension."""
        if not th.started:
            th.started = True
            prog_fn, th.program = th.program, None
            assert prog_fn is not None
            if exc is not None:
                # Exception delivered before the body ran: no user handler
                # can be installed yet, so the thread dies immediately
                # (matches the top-level-catch placement, TimedT.hs:332-338).
                self._finish(th, exc, main_result, main_error)
                return
            try:
                g = prog_fn()  # create the frame lazily
            except BaseException as e:  # noqa: BLE001
                self._finish(th, e, main_result, main_error)
                return
            if not hasattr(g, "send"):
                # A yield-free program is a plain function: it already ran
                # to completion at frame-creation time.
                self._finish(th, None, main_result, main_error, result=g)
                return
            th.gen = g
        gen = th.gen
        assert gen is not None
        try:
            while True:
                if exc is not None:
                    e, exc, value = exc, None, None
                    eff = gen.throw(e)
                else:
                    eff, value = gen.send(value), None

                if type(eff) is Wait:
                    # ≙ wait: capture continuation, enqueue at
                    # max(now, spec(now)) (TimedT.hs:343-355).
                    self._push(th, resolve(eff.spec, self._time), None)
                    return
                elif type(eff) is GetTime:
                    value = self._time  # ≙ virtualTime (TimedT.hs:322)
                elif type(eff) is MyTid:
                    value = th.tid
                elif type(eff) is Fork or type(eff) is ForkSlave:
                    # ≙ fork (TimedT.hs:326-342): child enqueued at `now`
                    # (inheriting the logger name), parent yields 1 µs and
                    # then receives the child tid. ForkSlave additionally
                    # links the lifetimes (core/effects.py ForkSlave).
                    child = self._spawn(eff.program, th.log_name,
                                        is_main=False)
                    if type(eff) is ForkSlave:
                        child.master = th.tid
                        if th.slaves is None:
                            th.slaves = []
                        th.slaves.append(child.tid)
                    self._push(child, self._time, None)
                    self._push(th, self._time + 1, child.tid)
                    return
                elif type(eff) is ThrowTo:
                    self._throw_to(eff.tid, eff.exc)
                elif type(eff) is GetLogName:
                    value = th.log_name
                elif type(eff) is SetLogName:
                    th.log_name = eff.name
                elif type(eff) is Park:
                    if th.park_token is not _NO_TOKEN:
                        # pending token: consume, continue instantly
                        value, th.park_token = th.park_token, _NO_TOKEN
                    else:
                        th.parked = True
                        return  # no queue entry until unparked/thrown-to
                elif type(eff) is Unpark:
                    self._unpark(eff.tid, eff.value)
                elif type(eff) is AwaitIO:
                    # thrown *into* the program (catchable), not out of
                    # the interpreter
                    exc = TimedError(
                        "AwaitIO (real host IO) has no meaning under pure "
                        "emulation; use the real-IO interpreter or the "
                        "emulated transport")
                else:
                    raise TypeError(f"unknown effect: {eff!r}")
        except StopIteration as stop:
            self._finish(th, None, main_result, main_error,
                         result=stop.value)
        except BaseException as e:  # noqa: BLE001 — interpreter boundary
            self._finish(th, e, main_result, main_error)

    def _unpark(self, tid: PureThreadId, value: Any) -> None:
        th = self._threads.get(tid)
        if th is None or not th.alive:
            return
        if th.parked:
            th.parked = False
            self._push(th, self._time, value)
        else:
            th.park_token = value  # consumed by the next Park

    def _throw_to(self, tid: PureThreadId, exc: BaseException) -> None:
        """≙ throwTo (TimedT.hs:357-368): wake the target to `now`, then
        store the exception — first thrower wins (TimedT.hs:359)."""
        th = self._threads.get(tid)
        if th is None or not th.alive:
            return
        if th.parked:
            th.parked = False
            self._push(th, self._time, None)
        elif (th.resume_entry is not None
              and th.resume_entry[_TIME] > self._time):
            th.resume_entry[_CANCELLED] = True
            self._push(th, self._time, th.resume_entry[_VALUE])
        self._pending_exc.setdefault(tid, exc)

    def _finish(self, th: _Thread, exc: Optional[BaseException],
                main_result: list, main_error: list, *,
                result: Any = None) -> None:
        th.alive = False
        th.gen = None
        self._pending_exc.pop(th.tid, None)
        # evict: memory stays O(live threads), not O(total forks);
        # _throw_to treats a missing tid exactly like a dead one
        self._threads.pop(th.tid, None)
        # ForkSlave contract: a terminating master kills its live slaves
        # (in creation order — deterministic event seq); their own
        # _finish cascades through slave subtrees. A finishing slave
        # prunes itself from its master's list first, keeping the list
        # O(live slaves) — the O(live threads) memory invariant above.
        if th.master is not None:
            master = self._threads.get(th.master)
            if master is not None and master.slaves:
                try:
                    master.slaves.remove(th.tid)
                except ValueError:
                    pass
        if th.slaves:
            for stid in th.slaves:
                self._throw_to(stid, ThreadKilled())
        if th.is_main:
            if exc is not None:
                main_error.append(exc)
            else:
                main_result.append(result)
        elif exc is not None:
            # ForkSlave contract: a slave's uncaught exception (other
            # than ThreadKilled) is forwarded to its master instead of
            # logged-and-dropped (≙ slave-thread's exception redirect).
            if (th.master is not None
                    and not isinstance(exc, ThreadKilled)
                    and th.master in self._threads):
                self._throw_to(th.master, exc)
            else:
                log_thread_death(_log, th.log_name, exc)


def run_emulation(program_fn: ProgramFn, **kw: Any) -> Any:
    """One-shot convenience ≙ ``runTimedT`` (TimedT.hs:293-304)."""
    return PureEmulation(**kw).run(program_fn)
