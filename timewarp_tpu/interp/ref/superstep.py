"""Host reference executor for state-machine scenarios — the oracle.

Runs a :class:`~timewarp_tpu.core.scenario.Scenario` sequentially on the
host, implementing the shared superstep semantics (core/scenario.py
docstring) with plain Python data structures: per-node mailbox *lists*,
a Python min-scan for the clock, Python loops for routing and overflow.
This is the direct descendant of the reference's event loop
(`/root/reference/src/Control/TimeWarp/Timed/TimedT.hs:234-286`): a
global clock advanced to the minimum pending event time, with per-node
mailboxes instead of a single continuation queue. The batched XLA
engine (interp/jax_engine) must reproduce this executor's trace
bit-for-bit — that law is the framework's acceptance gate (SURVEY.md §6).

The scenario's ``step`` and the link model's ``sample`` are the *same
jax functions* the engine uses — evaluated here through one ``vmap``
per superstep (vmap of a pure function is just map; batching cannot
change values) so the oracle stays fast enough to check thousand-node
runs. All *scheduling* decisions — who fires, what each inbox
contains, message ordering, capacity — are made by independent host
code, which is what makes this an oracle rather than a second copy of
the engine.
"""

from __future__ import annotations

from typing import List, Optional

from ...utils import jaxconfig  # noqa: F401  (must precede jax use)

import jax
import jax.numpy as jnp
import numpy as np

from ...core.rng import fire_bits, msg_bits, seed_words
from ...core.scenario import NEVER, Inbox, Scenario
from ...core.time import Microsecond
from ...net.delays import LinkModel
from ...trace.events import SuperstepTrace
from ...trace.hashing import FIRED, RECV, SENT, combine_py, mix32_py

__all__ = ["SuperstepOracle"]

_MASK32 = (1 << 32) - 1


class SuperstepOracle:
    """Sequential host executor; oracle for trace parity."""

    def __init__(self, scenario: Scenario, link: LinkModel, *,
                 seed: int = 0, record_events: bool = False) -> None:
        self.scenario = scenario
        self.link = link
        self.s0, self.s1 = seed_words(seed)
        #: optional per-event debug log (SURVEY.md §5.1): tuples
        #: ("fire", t, node) / ("recv", t, node, src, deliver_t, pay0)
        #: / ("sent", t, src, dst, deliver_t, pay0) in execution order —
        #: the detail stream behind the aggregate digests, for
        #: pinpointing a divergence the parity checker reports.
        self.events: Optional[List[tuple]] = [] if record_events else None
        n = scenario.n_nodes
        per = [scenario.init(i) for i in range(n)]
        #: stacked numpy state pytree (row i = node i)
        self.states = jax.tree.map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]),
            *[p[0] for p in per])
        self.wake: List[int] = [int(p[1]) for p in per]
        #: per-node arrival-ordered pending (deliver_time, src, payload)
        self.mailbox: List[List[tuple]] = [[] for _ in range(n)]
        self.overflow_total = 0
        self.bad_dst_total = 0
        self.time: Microsecond = 0

        ids = jnp.arange(n, dtype=jnp.int32)
        M = scenario.max_out
        src_f = jnp.repeat(ids, M)
        slot_f = jnp.tile(jnp.arange(M, dtype=jnp.int32), n)

        # one vmapped step per superstep — same fn the engine vmaps;
        # entropy derived elementwise (core/rng.py), no key arrays
        def _vstep(states, inbox, t):
            if scenario.needs_key:
                bits = fire_bits(self.s0, self.s1, ids, t)
            else:
                bits = None
            return jax.vmap(
                scenario.step,
                in_axes=(0, 0, None, 0, None if bits is None else 0))(
                    states, inbox, t, ids, bits)

        self._vstep = jax.jit(_vstep)

        # one batched link sample per superstep, keyed per (src,dst,t,slot);
        # link models broadcast — no vmap needed
        def _vsample(dst, t):
            if link.needs_key:
                bits = msg_bits(self.s0, self.s1, src_f, dst, t, slot_f)
            else:
                bits = None
            return link.sample(src_f, dst, t, bits)

        self._vsample = jax.jit(_vsample)

    # ------------------------------------------------------------------

    def _node_next(self, i: int) -> int:
        m = min((mm[0] for mm in self.mailbox[i]), default=NEVER)
        return min(self.wake[i], m)

    # ------------------------------------------------------------------

    def run(self, max_steps: int = 1 << 30,
            until: Optional[Microsecond] = None) -> SuperstepTrace:
        sc = self.scenario
        n, M, K, P = sc.n_nodes, sc.max_out, sc.mailbox_cap, sc.payload_width
        rows = []
        for _ in range(max_steps):
            nexts = [self._node_next(i) for i in range(n)]
            t = min(nexts)
            if t >= NEVER or (until is not None and t > until):
                break
            self.time = t
            fired = [i for i in range(n) if nexts[i] == t]
            fired_hash = combine_py(mix32_py(FIRED, i) for i in fired)
            if self.events is not None:
                self.events.extend(("fire", t, i) for i in fired)

            # build inboxes (host decision: contract #2 ordering)
            ib_valid = np.zeros((n, K), bool)
            ib_src = np.zeros((n, K), np.int32)
            ib_time = np.full((n, K), NEVER, np.int64)
            ib_pay = np.zeros((n, K, P), np.int32)
            recv_hashes: List[int] = []
            recv_count = 0
            for i in fired:
                pend = self.mailbox[i]
                picked = sorted(
                    ((m, idx) for idx, m in enumerate(pend) if m[0] <= t),
                    key=lambda mi: (mi[0][0], mi[1]))
                self.mailbox[i] = [m for m in pend if m[0] > t]
                for j, (m, _) in enumerate(picked):
                    ib_valid[i, j] = True
                    ib_time[i, j] = m[0]
                    ib_src[i, j] = m[1]
                    ib_pay[i, j] = m[2]
                    recv_hashes.append(mix32_py(
                        RECV, i, m[1], m[0] & _MASK32, m[0] >> 32,
                        int(m[2][0]) if P else 0))
                    if self.events is not None:
                        self.events.append(
                            ("recv", t, i, int(m[1]), int(m[0]),
                             int(m[2][0]) if P else 0))
                recv_count += len(picked)

            inbox = Inbox(valid=ib_valid, src=ib_src, time=ib_time,
                          payload=ib_pay)
            new_states, out, new_wake = self._vstep(
                self.states, inbox, jnp.int64(t))
            new_states = jax.tree.map(np.asarray, new_states)
            out_valid = np.asarray(out.valid)
            out_dst = np.asarray(out.dst, dtype=np.int32)
            out_pay = np.asarray(out.payload)
            new_wake = np.asarray(new_wake)

            # apply results for fired nodes only (host decision)
            fired_arr = np.asarray(fired, dtype=np.int64)
            def _apply(cur, new):
                cur[fired_arr] = new[fired_arr]
                return cur
            self.states = jax.tree.map(_apply, self.states, new_states)
            for i in fired:
                w = int(new_wake[i])
                # contract #5: clamp re-arm strictly past now
                self.wake[i] = NEVER if w >= NEVER else max(w, t + 1)

            # route in sender-major order (contract #3)
            delay, drop = self._vsample(jnp.asarray(out_dst.reshape(-1)),
                                        jnp.int64(t))
            delay = np.asarray(delay).reshape(n, M)
            drop = np.asarray(drop).reshape(n, M)
            sent_hashes: List[int] = []
            sent_count = 0
            overflow_step = 0
            for i in fired:
                for slot in range(M):
                    if not out_valid[i, slot]:
                        continue
                    dst = int(out_dst[i, slot])
                    if not (0 <= dst < n):
                        self.bad_dst_total += 1  # surfaced, never silent
                        continue
                    if drop[i, slot]:
                        continue
                    dt = t + max(int(delay[i, slot]), 1)  # contract #4
                    p0 = int(out_pay[i, slot, 0]) if P else 0
                    sent_count += 1
                    sent_hashes.append(mix32_py(
                        SENT, i, dst, dt & _MASK32, dt >> 32, p0))
                    if self.events is not None:
                        self.events.append(("sent", t, i, dst, dt, p0))
                    if len(self.mailbox[dst]) >= K:
                        overflow_step += 1  # contract #6: counted, dropped
                    else:
                        self.mailbox[dst].append(
                            (dt, i, np.asarray(out_pay[i, slot], np.int32)))
            self.overflow_total += overflow_step

            rows.append((t, len(fired), fired_hash,
                         recv_count, combine_py(recv_hashes),
                         sent_count, combine_py(sent_hashes),
                         overflow_step))
        return SuperstepTrace.from_rows(rows)
