"""Host reference executor for state-machine scenarios — the oracle.

Runs a :class:`~timewarp_tpu.core.scenario.Scenario` sequentially on the
host, implementing the shared superstep semantics (core/scenario.py
docstring) with plain Python data structures: per-node mailbox *lists*,
a Python min-scan for the clock, Python loops for routing and overflow.
This is the direct descendant of the reference's event loop
(`/root/reference/src/Control/TimeWarp/Timed/TimedT.hs:234-286`): a
global clock advanced to the minimum pending event time, with per-node
mailboxes instead of a single continuation queue. The batched XLA
engine (interp/jax_engine) must reproduce this executor's trace
bit-for-bit — that law is the framework's acceptance gate (SURVEY.md §6).

The scenario's ``step`` and the link model's ``sample`` are the *same
jax functions* the engine uses — evaluated here through one ``vmap``
per superstep (vmap of a pure function is just map; batching cannot
change values) so the oracle stays fast enough to check thousand-node
runs. All *scheduling* decisions — who fires, what each inbox
contains, message ordering, capacity — are made by independent host
code, which is what makes this an oracle rather than a second copy of
the engine.
"""

from __future__ import annotations

from typing import List, Optional

from ...utils import jaxconfig  # noqa: F401  (must precede jax use)

import jax
import jax.numpy as jnp
import numpy as np

from ...core.rng import fire_bits, msg_bits, seed_words
from ...core.scenario import NEVER, Inbox, Scenario
from ...core.time import Microsecond
from ...net.delays import LinkModel
from ...trace.events import SuperstepTrace
from ...trace.hashing import FIRED, RECV, SENT, combine_py, mix32_py

__all__ = ["SuperstepOracle"]

_MASK32 = (1 << 32) - 1


class SuperstepOracle:
    """Sequential host executor; oracle for trace parity.

    ``window`` mirrors the engine's multi-instant windowed supersteps
    (interp/jax_engine/engine.py ``JaxEngine.window``): one superstep
    fires every node with an event in ``[t, t+window)``, each at its
    own instant, routing in chronological ``(instant, sender, slot)``
    order. Exact when link delays are ≥ window (validated here too;
    dynamic violations counted in ``short_delay_total``).
    """

    #: the uniform driver-accounting surface (populated by run())
    last_run_stats = None

    def __init__(self, scenario: Scenario, link: LinkModel, *,
                 seed: int = 0, record_events: bool = False,
                 window=1, lint: str = "warn", faults=None) -> None:
        # static scenario sanitizer — same knob contract as the
        # engines (analysis/check_scenario); the oracle is the
        # referee, so catching a contract violation here names it
        # before a digest mismatch would
        from ...analysis import check_scenario
        self.lint = lint
        self.lint_report = check_scenario(scenario, lint,
                                          who=type(self).__name__)
        link_floor = link.min_delay_us
        self._setup_faults(faults, scenario, lint)
        if self._faulted:
            # shrink-degradation windows lower the exact-window floor
            # (mirrors JaxEngine)
            link_floor = self.faults.min_delay_floor(link_floor)
        if isinstance(window, str) and window != "auto":
            # mirror JaxEngine: a typo'd "Auto"/"8ms" from a library
            # caller must fail clearly, not as `window < 1`'s opaque
            # str-vs-int TypeError (ADVICE r5)
            raise ValueError(
                f"window must be an int µs count or the string "
                f"'auto', got {window!r}")
        if window == "auto":    # mirror JaxEngine: link floor = widest
            # exact window, int32-clamped exactly like the engine (a
            # FOREVER-delay link must resolve the same width in both
            # interpreters or windowed parity would silently split)
            from ..jax_engine.common import I32MAX
            window = max(1, min(int(link_floor), I32MAX - 1))
        if window < 1:
            raise ValueError(f"window must be >= 1 µs, got {window}")
        if window > 1 and window > link_floor:
            raise ValueError(
                f"window={window} µs exceeds the link model's declared "
                f"min_delay_us={link_floor}"
                f"{' (degradation-adjusted)' if self._faulted else ''}")
        self.scenario = scenario
        self.link = link
        self.window = int(window)
        self.s0, self.s1 = seed_words(seed)
        #: optional per-event debug log (SURVEY.md §5.1): tuples
        #: ("fire", t, node) / ("recv", t, node, src, deliver_t, pay0)
        #: / ("sent", t, src, dst, deliver_t, pay0) in execution order —
        #: the detail stream behind the aggregate digests, for
        #: pinpointing a divergence the parity checker reports.
        self.events: Optional[List[tuple]] = [] if record_events else None
        n = scenario.n_nodes
        per = [scenario.init(i) for i in range(n)]
        #: stacked numpy state pytree (row i = node i)
        self.states = jax.tree.map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]),
            *[p[0] for p in per])
        self.wake: List[int] = [int(p[1]) for p in per]
        #: per-node arrival-ordered pending (deliver_time, src, payload)
        self.mailbox: List[List[tuple]] = [[] for _ in range(n)]
        self.overflow_total = 0
        self.bad_dst_total = 0
        self.short_delay_total = 0
        #: messages the fault schedule killed (cuts + down-window
        #: deliveries + reset purges) — mirrors
        #: ``EngineState.fault_dropped``
        self.fault_dropped_total = 0
        self.time: Microsecond = 0
        if self._faulted and self.faults.has_reset:
            # pristine reboot template (self.states is mutated in
            # place as the run progresses)
            self._reset_states = jax.tree.map(np.copy, self.states)

        ids = jnp.arange(n, dtype=jnp.int32)
        M = scenario.max_out
        src_f = jnp.repeat(ids, M)
        slot_f = jnp.tile(jnp.arange(M, dtype=jnp.int32), n)

        # one vmapped step per superstep — same fn the engine vmaps;
        # entropy derived elementwise (core/rng.py), no key arrays.
        # `now` is per-node (each fires at its own in-window instant;
        # all equal to t when window == 1). Clock skew wraps the SAME
        # step function the engine wraps (faults/apply.py), so skewed
        # behavior cannot diverge between interpreters.
        stepf = scenario.step
        if self._faulted and self.faults.has_skew:
            from ...faults.apply import skewed_step
            stepf = skewed_step(scenario.step,
                                jnp.asarray(self._ft.skew))

        def _vstep(states, inbox, now):
            if scenario.needs_key:
                bits = fire_bits(self.s0, self.s1, ids, now)
            else:
                bits = None
            return jax.vmap(
                stepf,
                in_axes=(0, 0, 0, 0, None if bits is None else 0))(
                    states, inbox, now, ids, bits)

        self._vstep = jax.jit(_vstep)

        # one batched link sample per superstep, keyed per
        # (src,dst,send-instant,slot); link models broadcast — no vmap.
        # Degradation windows transform the sampled delay here, with
        # the same integer helper the engines trace — identical bits.
        def _vsample(dst, tmsg):
            if link.needs_key:
                bits = msg_bits(self.s0, self.s1, src_f, dst, tmsg, slot_f)
            else:
                bits = None
            delay, drop = link.sample(src_f, dst, tmsg, bits)
            if self._faulted:
                from ...faults.apply import degrade
                ftj = jax.tree.map(jnp.asarray, self._ft)
                delay = degrade(ftj, delay, src_f, dst, tmsg)
            return delay, drop

        self._vsample = jax.jit(_vsample)

    # -- faults (host-side mirror of faults/apply.py) -------------------

    def _setup_faults(self, faults, scenario, lint) -> None:
        """Validate the ``faults`` argument and precompute the plain-
        Python crash/partition row lists the run loop's *independent*
        scheduling decisions use (the oracle shares only the jitted
        value functions — step, sample, degrade — with the engines;
        every who-fires/what-drops decision is re-made here in host
        code, which is what makes it an oracle)."""
        self.faults = faults
        self._faulted = faults is not None
        self._ft = None
        self.fault_lint_report = None
        if faults is None:
            return
        from ...faults.schedule import FaultFleet, FaultSchedule
        if isinstance(faults, FaultFleet):
            raise ValueError(
                "the oracle runs one world; pass one FaultSchedule "
                "(fleet.world_schedule(b) for a batched world's twin)")
        if not isinstance(faults, FaultSchedule):
            raise ValueError(
                f"faults must be a FaultSchedule, got {faults!r}")
        from ...analysis import check_faults
        self.fault_lint_report = check_faults(
            faults, scenario, lint, who=type(self).__name__)
        self._ft = faults.tables(scenario.n_nodes)
        #: (node, down, up, reset) for ACTIVE crash rows, with their
        #: table row index (the restart ledger key)
        self._crash_rows = [
            (int(self._ft.crash_node[c]), int(self._ft.crash_down[c]),
             int(self._ft.crash_up[c]), bool(self._ft.crash_reset[c]), c)
            for c in range(self._ft.crash_node.shape[0])
            if self._ft.crash_up[c] > self._ft.crash_down[c]]
        self._restart_done = [False] * self._ft.crash_node.shape[0]
        self._parts = [
            (self._ft.part_group[p], int(self._ft.part_start[p]),
             int(self._ft.part_end[p]))
            for p in range(self._ft.part_group.shape[0])
            if self._ft.part_end[p] > self._ft.part_start[p]]

    def _fault_next(self, i: int, x: int) -> int:
        """Crash-adjusted next-event time for node ``i`` (engine twin:
        ``defer_next``): defer an in-window event to its t_up, then
        min in any unconsumed restart injection."""
        ups = [u for (k, d, u, _r, _c) in self._crash_rows
               if k == i and d <= x < u]
        if ups:
            x = max(ups)
        inj = min((u for (k, _d, u, r, c) in self._crash_rows
                   if k == i and r and not self._restart_done[c]),
                  default=NEVER)
        return min(x, inj)

    def _cut(self, src: int, dst: int, t: int) -> bool:
        """Does a (src -> dst) message sent at ``t`` cross a live
        partition cut?"""
        for group, start, end in self._parts:
            if start <= t < end:
                gs, gd = int(group[src]), int(group[dst])
                if gs >= 0 and gd >= 0 and gs != gd:
                    return True
        return False

    def _down(self, node: int, t: int) -> bool:
        """Is ``node`` inside a crash window at time ``t``?"""
        return any(k == node and d <= t < u
                   for (k, d, u, _r, _c) in self._crash_rows)

    def _restart(self, i: int, ti: int) -> None:
        """Consume restart rows for node ``i`` firing at ``ti``; on a
        reset restart, reboot the state from the pristine template and
        purge mailbox entries older than the crash (memory loss,
        counted in ``fault_dropped_total``)."""
        purge_before, rebooted = 0, False
        for (k, d, u, r, c) in self._crash_rows:
            if r and not self._restart_done[c] and k == i and ti == u:
                self._restart_done[c] = True
                rebooted = True
                purge_before = max(purge_before, d)
        if rebooted:
            def _reset(cur, init):
                cur[i] = init[i]
                return cur
            self.states = jax.tree.map(_reset, self.states,
                                       self._reset_states)
            kept = [m for m in self.mailbox[i] if m[0] >= purge_before]
            self.fault_dropped_total += len(self.mailbox[i]) - len(kept)
            self.mailbox[i] = kept

    # ------------------------------------------------------------------

    def _node_next(self, i: int) -> int:
        m = min((mm[0] for mm in self.mailbox[i]), default=NEVER)
        nxt = min(self.wake[i], m)
        if self._faulted:
            nxt = self._fault_next(i, nxt)
        return nxt

    # ------------------------------------------------------------------

    def run(self, max_steps: int = 1 << 30,
            until: Optional[Microsecond] = None) -> SuperstepTrace:
        import time as _time
        _wall0 = _time.perf_counter()
        sc = self.scenario
        n, M, K, P = sc.n_nodes, sc.max_out, sc.mailbox_cap, sc.payload_width
        W = self.window
        rows = []
        for _ in range(max_steps):
            nexts = [self._node_next(i) for i in range(n)]
            t = min(nexts)
            if t >= NEVER or (until is not None and t > until):
                break
            self.time = t
            # windowed firing: every node with an event in [t, t+W),
            # each at its own instant nexts[i] (== t for W == 1);
            # an `until` horizon bounds the *instants*, not just the
            # window start — a W > 1 window straddling `until` fires
            # only the nodes at or before it (matching the window=1
            # semantics of the same horizon)
            fired = [i for i in range(n)
                     if nexts[i] < NEVER and nexts[i] - t < W
                     and (until is None or nexts[i] <= until)]
            fired_hash = combine_py(mix32_py(FIRED, i) for i in fired)
            if self.events is not None:
                self.events.extend(("fire", nexts[i], i) for i in fired)
            if self._faulted:
                # restart firings: consume the injected reboot, reset
                # state from the template, purge pre-crash mailbox
                # memory — BEFORE inboxes are built (engine: the purge
                # mask is excluded from `deliver`)
                for i in fired:
                    self._restart(i, nexts[i])

            # build inboxes (host decision: contract #2 ordering);
            # deliverable = due at the node's own firing instant
            ib_valid = np.zeros((n, K), bool)
            ib_src = np.zeros((n, K), np.int32)
            ib_time = np.full((n, K), NEVER, np.int64)
            ib_pay = np.zeros((n, K, P), np.int32)
            recv_hashes: List[int] = []
            recv_count = 0
            for i in fired:
                ti = nexts[i]
                pend = self.mailbox[i]
                picked = sorted(
                    ((m, idx) for idx, m in enumerate(pend) if m[0] <= ti),
                    key=lambda mi: (mi[0][0], mi[1]))
                self.mailbox[i] = [m for m in pend if m[0] > ti]
                for j, (m, _) in enumerate(picked):
                    ib_valid[i, j] = True
                    ib_time[i, j] = m[0]
                    # inbox_src=False: sender identity is not part of
                    # the scenario semantics — all interpreters present
                    # (and hash) 0 (core/scenario.py)
                    src_word = m[1] if sc.inbox_src else 0
                    ib_src[i, j] = src_word
                    ib_pay[i, j] = m[2]
                    recv_hashes.append(mix32_py(
                        RECV, i, src_word, m[0] & _MASK32, m[0] >> 32,
                        int(m[2][0]) if P else 0))
                    if self.events is not None:
                        self.events.append(
                            ("recv", ti, i, int(m[1]), int(m[0]),
                             int(m[2][0]) if P else 0))
                recv_count += len(picked)

            # per-node firing instants (t for unfired — masked anyway)
            now_arr = np.full(n, t, np.int64)
            for i in fired:
                now_arr[i] = nexts[i]

            inbox = Inbox(valid=ib_valid, src=ib_src, time=ib_time,
                          payload=ib_pay)
            new_states, out, new_wake = self._vstep(
                self.states, inbox, jnp.asarray(now_arr))
            new_states = jax.tree.map(np.asarray, new_states)
            out_valid = np.asarray(out.valid)
            out_dst = np.asarray(out.dst, dtype=np.int32)
            out_pay = np.asarray(out.payload)
            new_wake = np.asarray(new_wake)

            # apply results for fired nodes only (host decision)
            fired_arr = np.asarray(fired, dtype=np.int64)
            def _apply(cur, new):
                cur[fired_arr] = new[fired_arr]
                return cur
            self.states = jax.tree.map(_apply, self.states, new_states)
            for i in fired:
                w = int(new_wake[i])
                # contract #5: clamp re-arm strictly past the node's now
                self.wake[i] = NEVER if w >= NEVER else max(w, nexts[i] + 1)

            # route in chronological (send instant, sender, slot) order
            # — contract #3; pure sender-major for W == 1. Link entropy
            # is keyed by each message's own send instant.
            delay, drop = self._vsample(
                jnp.asarray(out_dst.reshape(-1)),
                jnp.asarray(np.repeat(now_arr, M)))
            delay = np.asarray(delay).reshape(n, M)
            drop = np.asarray(drop).reshape(n, M)
            sent_hashes: List[int] = []
            sent_count = 0
            overflow_step = 0
            for i in sorted(fired, key=lambda i: (nexts[i], i)):
                ti = nexts[i]
                for slot in range(M):
                    if not out_valid[i, slot]:
                        continue
                    dst = int(out_dst[i, slot])
                    if not (0 <= dst < n):
                        self.bad_dst_total += 1  # surfaced, never silent
                        continue
                    if drop[i, slot]:
                        continue
                    if self._faulted and self._cut(i, dst, ti):
                        # sent across a live partition cut: lost in
                        # transit — counted, never hashed (the engine
                        # kills the same set pre-insertion)
                        self.fault_dropped_total += 1
                        continue
                    flight = max(int(delay[i, slot]), 1)  # contract #4
                    if W > 1 and flight < W:
                        # windowed-causality violation — counted loudly,
                        # mirroring EngineState.short_delay
                        self.short_delay_total += 1
                    dt = ti + flight
                    if self._faulted and self._down(dst, dt):
                        # would land inside the destination's down
                        # window: its NIC is off — counted, dropped
                        self.fault_dropped_total += 1
                        continue
                    p0 = int(out_pay[i, slot, 0]) if P else 0
                    sent_count += 1
                    sent_hashes.append(mix32_py(
                        SENT, i, dst, dt & _MASK32, dt >> 32, p0))
                    if self.events is not None:
                        self.events.append(("sent", ti, i, dst, dt, p0))
                    if len(self.mailbox[dst]) >= K:
                        overflow_step += 1  # contract #6: counted, dropped
                    else:
                        self.mailbox[dst].append(
                            (dt, i, np.asarray(out_pay[i, slot], np.int32)))
            self.overflow_total += overflow_step

            rows.append((t, len(fired), fired_hash,
                         recv_count, combine_py(recv_hashes),
                         sent_count, combine_py(sent_hashes),
                         overflow_step))
        # the uniform driver-accounting surface every engine carries
        # (interp/jax_engine/common.py RunStatsMixin); the oracle is
        # host Python, so compiles is 0 by definition
        self.last_run_stats = {
            "supersteps": len(rows),
            "wall_seconds": _time.perf_counter() - _wall0,
            "compiles": 0,
        }
        return SuperstepTrace.from_rows(rows)
