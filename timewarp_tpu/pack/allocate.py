"""The packing planner: best-fit-decreasing by predicted supersteps.

Shape-bucketing (sweep/bucket.py) fixes WHICH worlds may share an
executable; this module decides the ORDER they fill buckets in. Under
``first-fit`` (the historical default) an oversize shape group chunks
in pack order, so a 100-superstep world routinely lands beside a
10000-superstep one — the short world quiesces almost immediately and
its slot idles (budget-masked) until the whole bucket drains, while
every chunk still pays the pow2 scan pad of the longest runner.

``predicted`` sorts each shape group by forecast supersteps,
descending (:func:`predicted_order`) before chunking. With bins of
equal capacity filled from a decreasing sequence, best-fit-decreasing
degenerates to exactly this sort-then-chunk: each bucket holds
neighbors of similar horizon, which simultaneously

- **equalizes per-bucket quiescence horizons** (worlds in a bucket
  finish together, so no slot idles budget-masked for long), and
- **minimizes pad waste** (the pow2 scan pad is paid per bucket at
  its longest member; grouping like with like keeps the pad
  proportional to the work actually done).

Ties sort stably by pack order, so the plan is a pure function of
``(pack, artifact)`` — the journaled ``pack_decision`` records
(sweep/service.py) carry it across resume bit-identically.

The same shape drives serve-side placement
(:func:`best_horizon_bucket`): an admitted config joins the open
bucket whose predicted remaining horizon best matches its own
forecast — continuous-batching slot allocation, inference-server
style.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

from ..sweep.spec import RunConfig, SweepConfigError

__all__ = ["PACK_MODES", "PACK_MODE_GRAMMAR", "validate_pack_mode",
           "predicted_order", "best_horizon_bucket"]

#: accepted ``--pack`` knob values
PACK_MODES = ("first-fit", "predicted")

#: the loud-refusal grammar (LINK_GRAMMAR discipline): malformed
#: values name this, never a raw traceback
PACK_MODE_GRAMMAR = "first-fit | predicted"


def validate_pack_mode(mode: str, who: str = "--pack") -> str:
    """Loud knob validation: anything outside :data:`PACK_MODES` is
    refused naming the grammar (tests/test_zgrammar.py
    BAD_PACK_MODES)."""
    if mode not in PACK_MODES:
        raise SweepConfigError(
            f"malformed pack mode {mode!r} for {who}; grammar: "
            f"{PACK_MODE_GRAMMAR}")
    return mode


def predicted_order(cfgs: Sequence[RunConfig],
                    predict: Callable[[RunConfig], int]
                    ) -> List[RunConfig]:
    """Best-fit-decreasing item order for one shape group: sort by
    forecast supersteps, descending, ties kept in pack order (stable
    sort). Chunking the result at ``max_bucket`` IS the bin packing —
    equal-capacity bins filled from a decreasing sequence (module
    docstring)."""
    return sorted(cfgs, key=lambda c: -int(predict(c)))


def best_horizon_bucket(pred: int,
                        candidates: Sequence[Tuple[str, int]]
                        ) -> Optional[str]:
    """Serve-side placement: among open buckets with free slots
    (``(bucket_id, predicted_remaining_horizon)`` pairs, in the
    frontend's stable discovery order), pick the one whose horizon is
    CLOSEST to the admitted config's forecast ``pred`` — a short
    world joins a bucket about to drain, a long one joins a bucket
    that will run anyway. Ties resolve to the earliest candidate, so
    the choice is deterministic in the candidate order."""
    best: Optional[str] = None
    best_d = None
    for bid, horizon in candidates:
        d = abs(int(horizon) - int(pred))
        if best_d is None or d < best_d:
            best, best_d = bid, d
    return best
