"""Predictive bucket packing: superstep forecasting + slot allocation.

The serving core admits worlds with zero recompiles (r20), but
placement was first-fit by arrival order — so heterogeneous packs
waste throughput two ways the ``bucket_util`` journal already
measures: pow2 scan-pad waste when a short world shares a bucket with
a long one, and budget-mask inefficiency when quiesced slots idle
until the whole bucket drains. This package closes that gap:

- :mod:`predict` — a deterministic superstep forecaster fit from
  run-ledger history (``RunLedger`` ``pack_stats`` rows, assembled at
  ingest from each run's ``world_done`` results + configs). Fitted
  coefficients save as a **sha-stamped artifact**, so a prediction is
  a pure function of ``(features, artifact)`` — the TempoNet
  decision-source discipline. With no artifact (or no matching
  history) the forecast falls back to the config's **budget**,
  honestly: never a fabricated number, always the documented upper
  bound.
- :mod:`allocate` — the packing planner: best-fit-decreasing by
  predicted supersteps behind ``--pack first-fit|predicted``
  (``sweep/bucket.plan_buckets``), plus the serve-side placement
  scorer (``ServeFrontend`` picks the open bucket whose predicted
  remaining horizon best matches an admitted config).

Every packing *choice* that is not a pure function of the pack alone
journals as a ``pack_decision`` event **before** its effect, so
resume/steal replay it bit-identically (sweep/journal.py). The
extended survival law (results independent of bucketing) makes
correctness free — packing is pure throughput.
"""

from .allocate import (PACK_MODE_GRAMMAR, PACK_MODES, predicted_order,
                       validate_pack_mode)
from .predict import (PackFitError, feature_key, fit_rows,
                      load_artifact, pack_features, predict_supersteps,
                      save_artifact, training_rows)

__all__ = ["PACK_MODES", "PACK_MODE_GRAMMAR", "validate_pack_mode",
           "predicted_order", "pack_features", "feature_key",
           "predict_supersteps", "fit_rows", "training_rows",
           "save_artifact", "load_artifact", "PackFitError"]
