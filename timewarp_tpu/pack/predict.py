"""Deterministic superstep forecasting from run-ledger history.

A world's *budget* is its superstep upper bound; how many supersteps
it actually runs before quiescing is what packing cares about — a
gossip burst quiesces in a fraction of its budget, a token ring runs
to the wire. The forecaster learns the **realized-fraction** of the
budget per feature key from history the ledger already holds:

- **features** (:func:`pack_features`): scenario family, node count,
  link signature + sweepable link values, fault-schedule summary
  (crash/partition/link-window row counts), resolved window. Exactly
  the facts that determine a world's quiescence behavior and are
  statically known at admission time.
- **labels**: the ``supersteps`` field of journaled ``world_done``
  results. ``timewarp-tpu ledger add <journal>`` assembles
  ``(features, budget, supersteps)`` rows (``pack_stats``) at ingest,
  so every sweep/serve run already archived is training data.
- **model** (:func:`fit_rows`): mean realized-fraction per exact
  feature key, backed off to per-family, backed off to global — three
  nested means, no iterative fitting, bit-deterministic from the row
  multiset.

The fitted coefficients save as a **sha-stamped artifact**
(:func:`save_artifact` / :func:`load_artifact` — the sha covers the
coefficient payload, so a tampered or torn artifact is refused
loudly). :func:`predict_supersteps` is then a *pure function* of
``(config, artifact)``: same config + same artifact = same forecast,
on every host, across resume — which is what lets the packing planner
stay deterministic (allocate.py) and the journaled ``pack_decision``
records replay bit-identically.

**The honest fallback:** with ``artifact=None``, or a key/family the
artifact never saw, the forecast is the config's **budget** — the
provable upper bound, never an invented number. First-fit behavior
degrades gracefully into budget-ordered packing, which is still the
right relative order for budget-dominated packs.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Iterable, List, Mapping, Optional

from ..sweep.spec import (RunConfig, SweepConfigError, link_signature,
                          link_sweep_params, resolve_window)

__all__ = ["pack_features", "feature_key", "training_rows",
           "fit_rows", "fit_from_ledger", "predict_supersteps",
           "save_artifact", "load_artifact", "PackFitError",
           "ARTIFACT_KIND"]

#: artifact self-identification (the loader refuses anything else)
ARTIFACT_KIND = "timewarp-pack-predictor"

#: coefficient schema version — bumped when the model form changes
ARTIFACT_VERSION = 1


class PackFitError(ValueError):
    """Fitting was asked for but the history cannot support it (no
    ledger, no ingested runs, no per-world rows) — always actionable,
    never a silent empty artifact."""


def pack_features(cfg: RunConfig) -> Dict[str, Any]:
    """The statically-known facts that determine a world's quiescence
    behavior — the forecaster's feature vector. Pure function of the
    config (window resolution included); raises
    :class:`SweepConfigError` for a config that does not parse."""
    link = cfg.parse_link()
    sched = cfg.parse_faults()
    return {
        "family": cfg.family,
        "nodes": int(dict(cfg.params).get("nodes", 0) or 0),
        "link": repr(link_signature(link)),
        "link_params": {k: float(v) for k, v in
                        sorted(link_sweep_params(link).items())},
        "faults": ([0, 0, 0] if sched is None else
                   [len(sched.crashes), len(sched.partitions),
                    len(sched.link_windows)]),
        "window": int(resolve_window(cfg)),
    }


def feature_key(cfg: RunConfig) -> str:
    """Canonical (sorted-key JSON) string of :func:`pack_features` —
    the exact-match grouping key for fitting and prediction."""
    return json.dumps(pack_features(cfg), sort_keys=True)


def training_rows(configs: Iterable[RunConfig],
                  done: Mapping[str, Mapping[str, Any]]) -> List[dict]:
    """Assemble ``(key, family, budget, supersteps)`` rows from a
    run's configs and its journaled ``world_done`` results — what the
    ledger stores as ``pack_stats`` at ingest. Configs without a
    result (unfinished, failed) and configs that no longer parse are
    skipped: ingest is best-effort archival, never a refusal."""
    rows: List[dict] = []
    for cfg in configs:
        res = done.get(cfg.run_id)
        if not isinstance(res, Mapping) or "supersteps" not in res:
            continue
        try:
            key = feature_key(cfg)
        except SweepConfigError:
            continue
        rows.append({"key": key, "family": cfg.family,
                     "budget": int(cfg.budget),
                     "supersteps": int(res["supersteps"])})
    return rows


def _mean_fraction(rows: List[dict]) -> Dict[str, Any]:
    fracs = [min(1.0, r["supersteps"] / r["budget"])
             for r in rows if r["budget"] > 0]
    if not fracs:
        return {"fraction": 1.0, "n": 0}
    return {"fraction": round(sum(fracs) / len(fracs), 6),
            "n": len(fracs)}


def fit_rows(rows: List[dict]) -> Dict[str, Any]:
    """Fit the three nested realized-fraction means (module
    docstring) from training rows. Deterministic: the coefficients
    depend only on the row multiset, never on iteration order.
    Raises :class:`PackFitError` on an empty row set — an artifact
    that predicts from nothing would silently shadow the honest
    budget fallback."""
    rows = [r for r in rows
            if isinstance(r, Mapping) and r.get("budget")
            and r.get("supersteps") is not None and r.get("key")]
    if not rows:
        raise PackFitError(
            "no per-world training rows — ingest finished runs first "
            "(`timewarp-tpu ledger add <journal-dir> --ledger DIR`), "
            "then re-run `pack fit`")
    by_key: Dict[str, List[dict]] = {}
    by_family: Dict[str, List[dict]] = {}
    for r in rows:
        by_key.setdefault(r["key"], []).append(r)
        by_family.setdefault(str(r.get("family", "?")), []).append(r)
    coeffs = {
        "version": ARTIFACT_VERSION,
        "keys": {k: _mean_fraction(v)
                 for k, v in sorted(by_key.items())},
        "families": {f: _mean_fraction(v)
                     for f, v in sorted(by_family.items())},
        "global": _mean_fraction(rows),
    }
    return {"artifact": ARTIFACT_KIND, "rows": len(rows),
            **coeffs, "sha": _coeff_sha(coeffs)}


def fit_from_ledger(ledger_root: str) -> Dict[str, Any]:
    """Fit an artifact from every ``pack_stats`` row the ledger
    holds (sweep and serve ingests alike). Loud, actionable refusals
    for an absent/empty ledger — the `pack fit` CLI surfaces them
    verbatim as its one-line error."""
    from ..obs.ledger import RunLedger
    index_path = os.path.join(ledger_root, "index.jsonl")
    if not os.path.exists(index_path):
        raise PackFitError(
            f"{ledger_root!r} is not a run ledger (no index.jsonl) — "
            "create one by ingesting a finished run: `timewarp-tpu "
            f"ledger add <journal-dir> --ledger {ledger_root}`")
    rows: List[dict] = []
    for rec in RunLedger(ledger_root).index():
        for kind in ("sweep", "serve"):
            block = rec.get(kind)
            if isinstance(block, Mapping):
                rows.extend(r for r in block.get("pack_stats", ())
                            if isinstance(r, Mapping))
    if not rows:
        raise PackFitError(
            f"ledger {ledger_root!r} holds no pack_stats rows (no "
            "ingested sweep/serve runs with per-world results) — run "
            "a sweep, `timewarp-tpu ledger add <journal-dir> "
            f"--ledger {ledger_root}`, then re-run `pack fit`")
    return fit_rows(rows)


def _coeff_sha(coeffs: Mapping[str, Any]) -> str:
    payload = {k: coeffs[k] for k in ("version", "keys", "families",
                                      "global")}
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()).hexdigest()


def save_artifact(artifact: Mapping[str, Any], path: str) -> str:
    """Atomically write the sha-stamped artifact; returns its sha."""
    from ..utils.checkpoint import atomic_write

    def write(f):
        json.dump(dict(artifact), f, indent=1, sort_keys=True)
        f.write("\n")
    atomic_write(path, write, mode="w")
    return str(artifact["sha"])


def load_artifact(path: str) -> Dict[str, Any]:
    """Load and VERIFY an artifact: wrong kind, missing coefficients,
    or a sha that does not match the payload is refused loudly — a
    silently-corrupt predictor would skew every packing decision
    downstream of it."""
    try:
        with open(path) as f:
            art = json.load(f)
    except OSError as e:
        raise ValueError(
            f"pack artifact {path!r} is unreadable: {e}") from None
    except json.JSONDecodeError as e:
        raise ValueError(
            f"pack artifact {path!r} is not JSON ({e}) — refit with "
            "`timewarp-tpu pack fit`") from None
    if not isinstance(art, dict) \
            or art.get("artifact") != ARTIFACT_KIND:
        raise ValueError(
            f"{path!r} is not a {ARTIFACT_KIND} artifact — fit one "
            "with `timewarp-tpu pack fit --ledger DIR --out PATH`")
    try:
        want = _coeff_sha(art)
    except KeyError as e:
        raise ValueError(
            f"pack artifact {path!r} is missing coefficient block "
            f"{e} — refit with `timewarp-tpu pack fit`") from None
    if art.get("sha") != want:
        raise ValueError(
            f"pack artifact {path!r} FAILED its sha check (stamped "
            f"{str(art.get('sha'))[:12]}.., payload hashes to "
            f"{want[:12]}..) — the file was modified after fitting; "
            "refit with `timewarp-tpu pack fit`")
    return art


def predict_supersteps(cfg: RunConfig,
                       artifact: Optional[Mapping[str, Any]] = None
                       ) -> int:
    """The forecast: a PURE function of ``(config, artifact)``.
    Exact-key mean fraction, else the family mean, else the global
    mean, else — and always with ``artifact=None`` — the config's
    budget (the honest fallback, module docstring). Clamped to
    ``[1, budget]``: a forecast must never promise more work than the
    budget allows, nor less than one superstep."""
    budget = int(cfg.budget)
    if artifact is None:
        return max(1, budget)
    ent = artifact.get("keys", {}).get(feature_key(cfg)) \
        or artifact.get("families", {}).get(cfg.family) \
        or artifact.get("global")
    if not ent or not ent.get("n"):
        return max(1, budget)
    return max(1, min(budget,
                      int(round(float(ent["fraction"]) * budget))))
