"""``timewarp-tpu pack fit`` — fit the superstep forecaster.

::

    timewarp-tpu pack fit --ledger DIR [--out PATH]

Reads every ``pack_stats`` row the run ledger holds (assembled at
``ledger add`` ingest from each run's configs + ``world_done``
results), fits the realized-fraction coefficients (predict.py), and
writes the sha-stamped artifact (default
``<ledger>/pack-predictor.json``). The artifact then feeds ``sweep
run --pack predicted --pack-artifact PATH`` and ``serve --pack
predicted --pack-artifact PATH``.

An absent or empty ledger is refused with ONE actionable line
(exit 1) — never a silent empty artifact, which would shadow the
honest budget fallback with fabricated coefficients.
"""

from __future__ import annotations

import argparse
import json
import os

from .predict import PackFitError, fit_from_ledger, save_artifact

__all__ = ["pack_main"]


def _fit(argv) -> int:
    p = argparse.ArgumentParser(
        prog="timewarp-tpu pack fit",
        description="Fit the packing predictor from run-ledger "
                    "history (timewarp_tpu/pack/, docs/sweeps.md "
                    "'Predictive packing').")
    p.add_argument("--ledger", required=True,
                   help="run-ledger directory (obs/ledger.py) holding "
                        "ingested sweep/serve runs")
    p.add_argument("--out", default=None,
                   help="artifact path (default "
                        "<ledger>/pack-predictor.json)")
    args = p.parse_args(argv)
    try:
        art = fit_from_ledger(args.ledger)
    except PackFitError as e:
        raise SystemExit(f"pack fit: {e}") from None
    out = args.out or os.path.join(args.ledger, "pack-predictor.json")
    sha = save_artifact(art, out)
    print(json.dumps({"artifact": out, "sha": sha,
                      "rows": art["rows"],
                      "keys": len(art["keys"]),
                      "families": sorted(art["families"])}))
    return 0


def pack_main(argv) -> int:
    if not argv or argv[0] != "fit":
        raise SystemExit(
            "usage: timewarp-tpu pack fit --ledger DIR [--out PATH]")
    return _fit(argv[1:])
