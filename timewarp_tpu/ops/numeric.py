"""Engine-generic integer primitives shared by every batched engine.

These are the "hot ops" of the TPU build in their XLA-native form —
profiled and shaped for the VPU (profiling/superstep_breakdown.md):
pure elementwise/scan/sort building blocks, no gathers or scatters.
SURVEY.md §2 records the design stance: XLA-compiled JAX *is* this
framework's native layer; Pallas would only enter if a fused op beat
the compiler, and at 10x the performance target none currently does.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["I32MAX", "group_rank", "u32sum", "tlo", "thi"]

I32MAX = np.int32(2**31 - 1)


def group_rank(sorted_keys: jax.Array) -> jax.Array:
    """Rank of each element within its run of equal keys (keys must be
    sorted ascending): ``iota - cummax(run-start indices)``.

    Replaces ``searchsorted(keys, keys, 'left')`` in the routing path —
    on TPU searchsorted lowers to ~log2(S) chained gather rounds
    (~1 ms each at 131k elements, profiling/superstep_breakdown.md)
    while the cummax scan is elementwise-cheap. Uses the ``lax.cummax``
    primitive: the hand-rolled ``associative_scan(maximum, …)`` tree it
    replaces wedged the TPU compile service for minutes-to-forever at
    S ≥ ~4M (slice/concat-heavy recursive lowering), while the
    primitive compiles in seconds and runs ~0.1 s at 16M."""
    S = sorted_keys.shape[0]
    iota = jnp.arange(S, dtype=jnp.int32)
    boundary = jnp.concatenate([
        jnp.ones((1,), bool), sorted_keys[1:] != sorted_keys[:-1]])
    first = jax.lax.cummax(jnp.where(boundary, iota, 0))
    return iota - first


def u32sum(x: jax.Array) -> jax.Array:
    """Wrapping uint32 sum — the order-independent digest reduction
    (commutative, so cross-device ``psum`` is exact)."""
    return jnp.sum(x.astype(jnp.uint32), dtype=jnp.uint32)


def tlo(t: jax.Array) -> jax.Array:
    """Low 32 bits of an int64 µs timestamp (digest word)."""
    return (t & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)


def thi(t: jax.Array) -> jax.Array:
    """High 32 bits of an int64 µs timestamp (digest word)."""
    return ((t >> jnp.int64(32)) & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
