"""Numeric building blocks of the batched engines (see numeric.py for
the native-layer design stance)."""

from .numeric import I32MAX, group_rank, thi, tlo, u32sum

__all__ = ["I32MAX", "group_rank", "u32sum", "tlo", "thi"]
