"""Blocking synchronization primitives over the timed effect API.

The reference gets blocking coordination from STM — ``TVar`` retries in
the job manager (`/root/reference/src/Control/TimeWarp/Manager/Job.hs:48-49,
158-161`), bounded ``TBMChan`` queues in the transport
(`/root/reference/src/Control/TimeWarp/Rpc/Transfer.hs:236-242`). The
TPU build has no STM; it has the :class:`~timewarp_tpu.core.effects.Park`
/ :class:`~timewarp_tpu.core.effects.Unpark` effect pair, from which the
same vocabulary is built here — and because these are *effects*, every
primitive works identically under the pure emulator (deterministically)
and the real asyncio interpreter.

Robustness model: wake-ups are advisory ("state changed, re-check") and
waiters re-check conditions in a loop, so spurious unparks — e.g. a
token left by a wake that raced with an async exception — are harmless,
and there are no lost wake-ups. State mutation between yields is atomic
under both interpreters (single host thread / single event loop).

Vocabulary:

- :class:`Flag` — one-shot broadcast event (≙ the closed ``TVar`` in
  JobCurator, Job.hs:69-71).
- :class:`MVar` — one-slot synchronized cell (≙
  ``Control.Concurrent.MVar`` used by the reference examples, e.g.
  ping-pong's implicit coordination).
- :class:`Channel` — bounded, closeable FIFO (≙ ``TBMChan``,
  Transfer.hs:236-242): ``get`` on a closed+drained channel returns
  :data:`CLOSED`; ``put`` on a closed channel returns ``False``
  (the reference warns and drops, Transfer.hs:281-288).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Deque, List

from ..core.effects import MyTid, Park, Program, Unpark

__all__ = ["Flag", "MVar", "Channel", "CLOSED", "wait_until"]


class _Waitable:
    """Shared waiter-set machinery: park in ``_await_change``, wake all
    in ``_notify`` (advisory; waiters re-check)."""

    def __init__(self) -> None:
        self._waiters: Deque[Any] = deque()

    def _await_change(self) -> Program:
        tid = yield MyTid()
        self._waiters.append(tid)
        try:
            yield Park()
        finally:
            try:
                self._waiters.remove(tid)
            except ValueError:
                pass

    def _notify(self) -> Program:
        woken: List[Any] = list(self._waiters)
        for tid in woken:
            yield Unpark(tid, None)


class Flag(_Waitable):
    """One-shot broadcast event."""

    def __init__(self) -> None:
        super().__init__()
        self._set = False

    @property
    def is_set(self) -> bool:
        return self._set

    def set(self) -> Program:
        self._set = True
        yield from self._notify()

    def wait(self) -> Program:
        while not self._set:
            yield from self._await_change()


class MVar(_Waitable):
    """One-slot cell: ``take`` blocks while empty, ``put`` while full."""

    _EMPTY = object()

    def __init__(self) -> None:
        super().__init__()
        self._value: Any = MVar._EMPTY

    @property
    def is_empty(self) -> bool:
        return self._value is MVar._EMPTY

    def put(self, value: Any) -> Program:
        while self._value is not MVar._EMPTY:
            yield from self._await_change()
        self._value = value
        yield from self._notify()

    def take(self) -> Program:
        while self._value is MVar._EMPTY:
            yield from self._await_change()
        value, self._value = self._value, MVar._EMPTY
        yield from self._notify()
        return value

    def read(self) -> Program:
        """Blocking read without emptying."""
        while self._value is MVar._EMPTY:
            yield from self._await_change()
        return self._value


#: Returned by :meth:`Channel.get` once the channel is closed and drained
#: (≙ ``readTBMChan`` yielding ``Nothing``).
CLOSED = object()


class Channel(_Waitable):
    """Bounded, closeable FIFO (≙ ``TBMChan``, Transfer.hs:236-242)."""

    def __init__(self, capacity: int) -> None:
        super().__init__()
        assert capacity >= 1
        self._cap = capacity
        self._items: Deque[Any] = deque()
        self._closed = False

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def full(self) -> bool:
        return len(self._items) >= self._cap

    def __len__(self) -> int:
        return len(self._items)

    def put(self, item: Any) -> Program:
        """Blocking put. Returns True if enqueued, False if the channel
        is (or becomes, while blocked) closed."""
        while True:
            if self._closed:
                return False
            if len(self._items) < self._cap:
                self._items.append(item)
                yield from self._notify()
                return True
            yield from self._await_change()

    def try_put(self, item: Any) -> Program:
        """Non-blocking put: 'ok' | 'full' | 'closed' (≙ the
        ``tryWriteTBMChan`` three-way used at Transfer.hs:281-288)."""
        if self._closed:
            return "closed"
        if len(self._items) >= self._cap:
            return "full"
        self._items.append(item)
        yield from self._notify()
        return "ok"

    def get(self) -> Program:
        """Blocking get; :data:`CLOSED` once closed and drained."""
        while True:
            if self._items:
                item = self._items.popleft()
                yield from self._notify()
                return item
            if self._closed:
                return CLOSED
            yield from self._await_change()

    def unget(self, item: Any) -> Program:
        """Prepend ``item``, ignoring capacity (≙ ``unGetTBMChan`` — the
        transport's send worker pushes a chunk back on socket error,
        Transfer.hs:387-388)."""
        self._items.appendleft(item)
        yield from self._notify()

    def close(self) -> Program:
        """Close: pending items remain readable; blocked ops re-check
        (≙ ``closeTBMChan``)."""
        self._closed = True
        yield from self._notify()

    def drain(self) -> None:
        """Discard all pending items (≙ the ``clearInChan`` loop in
        ``sfClose``, Transfer.hs:328-330)."""
        self._items.clear()


def wait_until(pred: Callable[[], bool], *waitables: _Waitable) -> Program:
    """Block until ``pred()`` holds, re-checking whenever any of the
    ``waitables`` notifies — the analogue of an STM transaction retrying
    over several ``TVar``\\ s (e.g. ``sfSend`` blocks on "sent-notifier
    fired ∨ socket closed", Transfer.hs:266-271)."""
    while not pred():
        tid = yield MyTid()
        for w in waitables:
            w._waiters.append(tid)
        try:
            yield Park()
        finally:
            for w in waitables:
                try:
                    w._waiters.remove(tid)
                except ValueError:
                    pass
