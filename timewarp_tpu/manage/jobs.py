"""Structured-concurrency job lifecycle — the L2 layer.

TPU-native re-design of the reference's ``JobCurator``
(`/root/reference/src/Control/TimeWarp/Manager/Job.hs`): track a set of
jobs, interrupt them all at once (politely, forcibly, or politely with
a forced deadline), and await their completion. The transport layer
hangs every socket's worker threads and every server's accept loop off
a curator (Transfer.hs:124-129), so graceful teardown is one
``stop_all_jobs``.

Where the reference blocks on STM ``TVar`` retries (Job.hs:48-49,
158-161), this build blocks on the Park/Unpark effect pair — so the
same curator works identically under the pure emulator and the real
asyncio interpreter, and state mutation between yields is atomic under
both (single host thread / single event loop).

Semantics map (file:line = reference):

- ``InterruptType`` Plain / Force / WithTimeout — Job.hs:84-91.
- ``add_job`` on a closed curator: the job is not registered and its
  interrupter runs immediately — Job.hs:111-134.
- ``interrupt_all_jobs`` is idempotent; ``WithTimeout`` forks a
  watchdog that Force-clears stragglers at the deadline (running the
  user callback first) — Job.hs:138-154.
- ``await_all_jobs`` blocks until closed ∧ no jobs — Job.hs:158-161.
- ``stop_all_jobs`` = interrupt + await — Job.hs:164-165.
- ``add_manager_as_job`` nests curators — Job.hs:168-173.
- ``add_thread_job`` forks a thread whose interrupter is
  ``kill_thread``; the thread finally-marks its job done —
  Job.hs:176-184.
- ``add_safe_thread_job`` forks a thread with a no-op interrupter: the
  job self-terminates, checking :attr:`JobCurator.is_interrupted` /
  :meth:`JobCurator.unless_interrupted` — Job.hs:189-199.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

from ..core.effects import Fork, MyTid, Program, ProgramFn, ThrowTo, Wait
from ..core.errors import ThreadKilled
from ..core.time import Microsecond
from .sync import _Waitable

__all__ = ["JobCurator", "InterruptType", "Plain", "Force", "WithTimeout"]


class InterruptType:
    """How to interrupt (≙ ``InterruptType``, Job.hs:84-91)."""
    __slots__ = ()


@dataclass(frozen=True)
class _Plain(InterruptType):
    """Run every job's interrupter; completion still awaited."""


@dataclass(frozen=True)
class _Force(InterruptType):
    """Interrupt and *consider every job done* immediately."""


@dataclass(frozen=True)
class WithTimeout(InterruptType):
    """Plain now; at ``timeout_us``, run ``on_timeout`` (if any) and
    Force-clear whatever is still registered."""
    timeout_us: Microsecond
    on_timeout: Optional[ProgramFn] = None


Plain = _Plain()
Force = _Force()


class JobCurator(_Waitable):
    """≙ ``JobCurator`` (Job.hs:65-81). All methods are programs
    (generators) — run them with ``yield from`` inside any timed
    program, under either interpreter."""

    def __init__(self) -> None:
        super().__init__()
        self._closed = False
        self._jobs: Dict[int, ProgramFn] = {}
        self._counter = 0

    # -- state -----------------------------------------------------------

    @property
    def is_closed(self) -> bool:
        return self._closed

    @property
    def is_interrupted(self) -> bool:
        """≙ ``isInterrupted`` (Job.hs:195-196): closed ⇒ interrupted."""
        return self._closed

    @property
    def job_count(self) -> int:
        return len(self._jobs)

    def unless_interrupted(self, program: ProgramFn) -> Program:
        """Run ``program`` only when not interrupted (≙ Job.hs:198-199)."""
        if not self._closed:
            return (yield from program())
        return None

    # -- registration ----------------------------------------------------

    def add_job(self, interrupter: ProgramFn) -> Program:
        """Register a job; returns its id, or ``None`` after running the
        interrupter immediately when the curator is already closed
        (≙ Job.hs:111-134)."""
        if self._closed:
            yield from interrupter()
            return None
        jid = self._counter
        self._counter += 1
        self._jobs[jid] = interrupter
        return jid

    def mark_done(self, jid: Optional[int]) -> Program:
        if jid is not None:
            self._jobs.pop(jid, None)
        yield from self._notify()

    def _thread_job(self, program: ProgramFn, *, safe: bool) -> Program:
        holder: Dict[str, Any] = {}

        def interrupter() -> Program:
            tid = holder.get("tid")
            if tid is not None and not safe:
                yield ThrowTo(tid, ThreadKilled())

        def wrapped() -> Program:
            holder["tid"] = yield MyTid()
            jid = yield from self.add_job(interrupter)
            if jid is None:
                # ≙ addJob on a closed curator (Job.hs:111-134): the
                # interrupter ran; the action never starts.
                return
            try:
                yield from program()
            finally:
                yield from self.mark_done(jid)

        return (yield Fork(wrapped))

    def add_thread_job(self, program: ProgramFn) -> Program:
        """Fork ``program`` as a tracked thread whose interrupter is
        ``kill_thread`` (≙ ``addThreadJob``, Job.hs:176-184). Returns
        the thread id."""
        return (yield from self._thread_job(program, safe=False))

    def add_safe_thread_job(self, program: ProgramFn) -> Program:
        """Fork ``program`` as a tracked thread that interruption does
        *not* kill — it self-terminates, typically polling
        :attr:`is_interrupted` (≙ ``addSafeThreadJob``, Job.hs:189-193)."""
        return (yield from self._thread_job(program, safe=True))

    def add_manager_as_job(self, child: "JobCurator",
                           itype: InterruptType = Plain) -> Program:
        """Nest ``child``: interrupting this curator interrupts it (with
        ``itype`` — the transport uses ``WithTimeout`` so a stuck
        listener is Force-cleared at the deadline, Transfer.hs:301-305),
        and it counts as one job until all its own jobs finish
        (≙ ``addManagerAsJob``, Job.hs:168-173)."""
        def interrupter() -> Program:
            yield from child.interrupt_all_jobs(itype)

        jid = yield from self.add_job(interrupter)
        if jid is None:
            return

        def waiter() -> Program:
            yield from child.await_all_jobs()
            yield from self.mark_done(jid)

        yield Fork(waiter)

    # -- interruption ----------------------------------------------------

    def interrupt_all_jobs(self, itype: InterruptType = Plain) -> Program:
        """≙ ``interruptAllJobs`` (Job.hs:136-152). The Plain pass runs
        interrupters once (second call is a no-op); Force additionally
        clears the job table; WithTimeout arms its Force watchdog even
        when the Plain pass was a no-op (the reference forks it
        unconditionally, Job.hs:147-152 — so a supervisor can impose a
        forced deadline on an already-interrupted curator)."""
        if not self._closed:
            self._closed = True
            jobs = dict(self._jobs)
            yield from self._notify()
            for fn in jobs.values():
                yield from fn()
        if isinstance(itype, _Force):
            # ≙ Force: consider every remaining job done (Job.hs:144-146)
            self._jobs.clear()
            yield from self._notify()
        elif isinstance(itype, WithTimeout):
            deadline, callback = itype.timeout_us, itype.on_timeout

            def watchdog() -> Program:
                yield Wait(int(deadline))
                if self._jobs:
                    if callback is not None:
                        yield from callback()
                    yield from self.interrupt_all_jobs(Force)

            yield Fork(watchdog)

    def await_all_jobs(self) -> Program:
        """Block until closed ∧ all jobs done (≙ Job.hs:158-161)."""
        while not (self._closed and not self._jobs):
            yield from self._await_change()

    def stop_all_jobs(self, itype: InterruptType = Plain) -> Program:
        """≙ ``stopAllJobs`` (Job.hs:164-165)."""
        yield from self.interrupt_all_jobs(itype)
        yield from self.await_all_jobs()
