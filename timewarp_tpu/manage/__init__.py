"""Lifecycle & coordination: sync primitives over the effect API and the
job manager (≙ ``Control.TimeWarp.Manager``, SURVEY.md §1 L2)."""

from .jobs import Force, InterruptType, JobCurator, Plain, WithTimeout
from .sync import CLOSED, Channel, Flag, MVar

__all__ = ["CLOSED", "Channel", "Flag", "MVar", "JobCurator",
           "InterruptType", "Plain", "Force", "WithTimeout"]
