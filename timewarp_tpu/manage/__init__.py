"""Lifecycle & coordination: sync primitives over the effect API and the
job manager (≙ ``Control.TimeWarp.Manager``, SURVEY.md §1 L2)."""

from .sync import CLOSED, Channel, Flag, MVar

__all__ = ["CLOSED", "Channel", "Flag", "MVar"]
