"""Bench message types and the measure-event stream.

≙ `/root/reference/bench/Network/Common/Bench/Network/Commons.hs`:
``Ping``/``Pong`` carry a message id and a filler payload (the payload
serializes as N bytes of 0x2A — Commons.hs:68-70); ``logMeasure``
writes one line per event through the logger with a µs timestamp
(Commons.hs:80-83), using the reference's exact glyphs
(Commons.hs:128-132), recovered later by :func:`parse_measure_line`
(≙ the attoparsec parsers, Commons.hs:134-186).
"""

from __future__ import annotations

import enum
import logging
import re
from typing import Optional, Tuple

from ..core.effects import GetTime, Program
from ..net.message import message

__all__ = ["Ping", "Pong", "MeasureEvent", "log_measure",
           "parse_measure_line", "payload_of"]


def payload_of(n: int) -> bytes:
    """``Payload l`` serializes as l filler bytes (Commons.hs:68-70)."""
    return b"\x2a" * n


@message(name="BenchPing")
class Ping:
    """≙ ``Ping MsgId Payload`` (Commons.hs:56-63). Wire name is
    namespaced: the ping-pong example already owns ``"Ping"``."""
    mid: int
    payload: bytes


@message(name="BenchPong")
class Pong:
    """≙ ``Pong MsgId Payload`` (Commons.hs:56-63)."""
    mid: int
    payload: bytes


class MeasureEvent(enum.Enum):
    """≙ ``MeasureEvent`` with the reference's glyph forms
    (Commons.hs:121-132)."""
    PING_SENT = "• → "      # "• → "
    PING_RECEIVED = " → •"  # " → •"
    PONG_SENT = " ← •"      # " ← •"
    PONG_RECEIVED = "• ← "  # "• ← "


#: measure line: ``#<mid> <glyph> (<payload-len>) <µs>``
_LINE = re.compile(
    r"#(?P<mid>\d+)\s+(?P<glyph>• → | → •"
    r"| ← •|• ← )\s+\((?P<plen>\d+)\)\s+(?P<t>\d+)")

_BY_GLYPH = {e.value: e for e in MeasureEvent}


def log_measure(logger: logging.Logger, event: MeasureEvent, mid: int,
                payload_len: int) -> Program:
    """Emit one measure line with the current virtual µs timestamp
    (≙ ``logMeasure``, Commons.hs:80-83)."""
    t = yield GetTime()
    logger.info("#%d %s (%d) %d", mid, event.value, payload_len, t)


def parse_measure_line(line: str
                       ) -> Optional[Tuple[MeasureEvent, int, int, int]]:
    """Recover ``(event, mid, payload_len, µs)`` from a log line, or
    None for non-measure lines (the parsers skip unrelated logging —
    Commons.hs:173-186)."""
    m = _LINE.search(line)
    if not m:
        return None
    return (_BY_GLYPH[m.group("glyph")], int(m.group("mid")),
            int(m.group("plen")), int(m.group("t")))
