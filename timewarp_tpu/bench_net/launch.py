"""Bench launcher + CLI — ≙ `/root/reference/bench/launch.sh` plus the
option surface of SenderOptions.hs / ReceiverOptions.hs /
LogReaderOptions.hs: run receiver + sender for a duration (emulated
fabric by default — deterministic; ``--real`` for kernel TCP loopback),
capture each node's measure log, join the 4-point timelines, write
``measures.csv``.

Usage::

    python -m timewarp_tpu.bench_net.launch --msgs 1000 --threads 5 \
        --duration 10 --payload-bound 64 --out measures.csv
"""

from __future__ import annotations

import argparse
import logging
import os
from typing import List

from ..core.effects import Program, fork_, modify_log_name
from ..utils.logconfig import configure_logging
from .log_reader import join_measures, summarize, write_csv
from .receiver import receiver
from .sender import sender

__all__ = ["launch", "main"]


class _ListHandler(logging.Handler):
    def __init__(self) -> None:
        super().__init__()
        self.lines: List[str] = []

    def emit(self, record: logging.LogRecord) -> None:
        self.lines.append(record.getMessage())


def launch(*, msgs: int = 1000, threads: int = 5, rate: int = 0,
           duration_s: int = 10, payload_bound: int = 0,
           port: int = 3456, no_pong: bool = False, real: bool = False,
           delay_us: int = 1000, seed: int = 0,
           logs_dir: str = None) -> dict:
    """Run the two-node bench once; returns the joined measure table.
    Emulated runs complete in wall-clock milliseconds regardless of the
    virtual ``duration_s`` (the whole point of the emulator)."""
    from ..manage.sync import Flag

    send_log = logging.getLogger("bench.sender")
    recv_log = logging.getLogger("bench.receiver")
    # ≙ defaultLogConfig: measure streams at Info, comm muted to Error
    # (levels restored below — launch must not permanently reconfigure
    # the host process's logging)
    prior_levels = {name: logging.getLogger(name).level
                    for name in ("bench", "timewarp.comm")}
    configure_logging({
        "bench": {"severity": "Info"},
        "timewarp": {"comm": {"severity": "Error"}},
    })
    sh, rh = _ListHandler(), _ListHandler()
    send_log.addHandler(sh)
    recv_log.addHandler(rh)
    try:
        duration_us = duration_s * 1_000_000
        if real:
            from ..interp.aio.timed import run_real_time
            from ..net.backend import AioBackend
            host = "127.0.0.1"
            backend = AioBackend()
            run = run_real_time
        else:
            from ..interp.ref.des import run_emulation
            from ..net.backend import EmulatedBackend
            from ..net.delays import FixedDelay
            host = "receiver-host"
            backend = EmulatedBackend(FixedDelay(delay_us), seed=seed)
            run = run_emulation

        recv_ready = Flag()
        recv_prog = receiver(backend, port=port, host=host,
                             duration_us=duration_us + 2_000_000,
                             no_pong=no_pong, ready=recv_ready,
                             logger=recv_log)
        send_prog = sender(backend, [(host, port)], threads=threads,
                           msg_num=msgs, msg_rate=rate or None,
                           duration_us=duration_us,
                           payload_bound=payload_bound, seed=seed,
                           logger=send_log)

        recv_done, send_done = Flag(), Flag()

        def wrap(prog, flag):
            def w() -> Program:
                yield from prog()
                yield from flag.set()
            return w

        def main_prog() -> Program:
            # the realtime interpreter ends the run when the main
            # program returns — block until both nodes finish; the
            # sender starts only once the receiver is bound
            # (≙ launch.sh starting the receiver first, launch.sh:3-5)
            yield from fork_(lambda: modify_log_name(
                "receiver", wrap(recv_prog, recv_done)))
            yield from recv_ready.wait()
            yield from fork_(lambda: modify_log_name(
                "sender", wrap(send_prog, send_done)))
            yield from send_done.wait()
            yield from recv_done.wait()

        run(main_prog)
    finally:
        send_log.removeHandler(sh)
        recv_log.removeHandler(rh)
        for name, level in prior_levels.items():
            logging.getLogger(name).setLevel(level)

    if logs_dir:
        os.makedirs(logs_dir, exist_ok=True)
        for name, h in (("sender.log", sh), ("receiver.log", rh)):
            with open(os.path.join(logs_dir, name), "w",
                      encoding="utf-8") as f:
                f.write("\n".join(h.lines) + "\n")
    return join_measures(sh.lines, rh.lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="time-warp network bench (≙ bench/launch.sh)")
    # ≙ SenderOptions.hs:20-99 / ReceiverOptions.hs:26-60
    p.add_argument("--msgs", type=int, default=1000,
                   help="messages per thread set (default 1000)")
    p.add_argument("--threads", type=int, default=5,
                   help="concurrent sender threads (default 5)")
    p.add_argument("--rate", type=int, default=0,
                   help="messages/sec/thread (0 = unthrottled)")
    p.add_argument("--duration", type=int, default=10,
                   help="virtual seconds to run (default 10)")
    p.add_argument("--payload-bound", type=int, default=0,
                   help="max payload bytes (uniform 0..bound)")
    p.add_argument("--port", type=int, default=3456)
    p.add_argument("--no-pong", action="store_true",
                   help="receiver does not reply (≙ --no-pong)")
    p.add_argument("--real", action="store_true",
                   help="kernel TCP loopback instead of the emulator")
    p.add_argument("--delay-us", type=int, default=1000,
                   help="emulated link latency µs (default 1000)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--logs-dir", default=None,
                   help="also write raw sender.log / receiver.log here")
    p.add_argument("--out", default="measures.csv")
    p.add_argument("--stats", action="store_true",
                   help="also print an RTT/throughput summary JSON line")
    a = p.parse_args(argv)

    table = launch(
        msgs=a.msgs, threads=a.threads, rate=a.rate,
        duration_s=a.duration, payload_bound=a.payload_bound,
        port=a.port, no_pong=a.no_pong, real=a.real,
        delay_us=a.delay_us, seed=a.seed, logs_dir=a.logs_dir)
    n = write_csv(table, a.out)
    complete = sum(1 for k, v in table.items()
                   if isinstance(k, int) and len(v) == 5)
    print(f"{a.out}: {n} message timelines ({complete} complete)")
    if a.stats:
        import json as _json
        print(_json.dumps(summarize(table)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
