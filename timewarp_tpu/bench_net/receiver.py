"""Bench receiver — ≙ `/root/reference/bench/Network/Receiver/Main.hs`:
listen at a port; on every ``Ping`` log PingReceived and (unless
``no_pong``) log PongSent and reply ``Pong`` on the inbound connection
(Main.hs:32-41); stop after ``duration_us``.
"""

from __future__ import annotations

import logging

from ..core.effects import Program, Wait
from ..net.backend import NetBackend
from ..net.dialog import Dialog, Listener
from ..net.transfer import AtPort, Transport, localhost
from .commons import MeasureEvent, Ping, Pong, log_measure

__all__ = ["receiver"]


def receiver(backend: NetBackend, *,
             port: int = 3456,
             host: str = localhost,
             duration_us: int = 10_000_000,
             no_pong: bool = False,
             ready=None,
             logger: logging.Logger = None):
    """Build the receiver program (run under any interpreter).
    ``ready`` (an optional :class:`~timewarp_tpu.manage.sync.Flag`) is
    set once the listener is bound — the launcher starts the sender
    after it, like launch.sh starting the receiver first (launch.sh:3-5)."""
    log = logger or logging.getLogger("bench.receiver")

    def main() -> Program:
        tr = Transport(backend, host=host)
        d = Dialog(tr)

        def on_ping(msg: Ping, ctx) -> Program:
            yield from log_measure(log, MeasureEvent.PING_RECEIVED,
                                   msg.mid, len(msg.payload))
            if not no_pong:
                yield from log_measure(log, MeasureEvent.PONG_SENT,
                                       msg.mid, len(msg.payload))
                yield from ctx.reply(Pong(msg.mid, msg.payload))

        stop = yield from d.listen(AtPort(port), [Listener(Ping, on_ping)])
        if ready is not None:
            yield from ready.set()
        yield Wait(duration_us)  # ≙ wait (for duration sec)
        yield from stop()

    return main
