"""Network bench harness — the reference's 4-point latency measurement
pipeline (`/root/reference/bench/Network/`): sender + receiver programs
emitting PingSent/PingReceived/PongSent/PongReceived measure events,
and a log reader joining them per message id into ``measures.csv``."""

from .commons import (MeasureEvent, Ping, Pong, log_measure,
                      parse_measure_line)
from .log_reader import join_measures, write_csv
from .receiver import receiver
from .sender import sender

__all__ = [
    "MeasureEvent", "Ping", "Pong", "log_measure", "parse_measure_line",
    "join_measures", "write_csv", "receiver", "sender",
]
