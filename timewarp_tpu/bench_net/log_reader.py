"""Bench log reader — ≙ `/root/reference/bench/Network/LogReader/
Main.hs:61-119`: parse sender and receiver logs, join the four
timestamps of each message id, emit aligned ``measures.csv`` rows
``MsgId,PingSent,PingReceived,PongSent,PongReceived`` (missing points
left empty, like the reference's sparse LogEntry merge).
"""

from __future__ import annotations

import csv
from typing import Dict, Iterable, List

from .commons import MeasureEvent, parse_measure_line

__all__ = ["join_measures", "write_csv", "read_log_lines", "summarize"]

_COLS = [MeasureEvent.PING_SENT, MeasureEvent.PING_RECEIVED,
         MeasureEvent.PONG_SENT, MeasureEvent.PONG_RECEIVED]


def read_log_lines(path: str) -> List[str]:
    with open(path, encoding="utf-8") as f:
        return f.readlines()


def join_measures(*line_sources: Iterable[str]) -> Dict[int, dict]:
    """Merge measure lines from any number of logs into
    ``{mid: {event: µs, "payload": len}}`` (≙ ``analyze`` building the
    per-id map, LogReader/Main.hs:76-96). A duplicate event for one id
    keeps the first occurrence and counts the duplicate."""
    table: Dict[int, dict] = {}
    dups = 0
    for lines in line_sources:
        for line in lines:
            parsed = parse_measure_line(line)
            if parsed is None:
                continue
            ev, mid, plen, t = parsed
            row = table.setdefault(mid, {"payload": plen})
            if ev in row:
                dups += 1
                continue
            row[ev] = t
    if dups:
        table["__duplicates__"] = dups  # surfaced, never silent
    return table


def write_csv(table: Dict[int, dict], path: str) -> int:
    """Write aligned rows sorted by message id (≙ the printed table,
    LogReader/Main.hs:97-119); returns the row count. The
    ``__duplicates__`` sentinel (if any) is left untouched in the
    table — the int-key filter below skips it."""
    with open(path, "w", newline="", encoding="utf-8") as f:
        w = csv.writer(f)
        w.writerow(["MsgId", "PayloadBytes"] + [c.name for c in _COLS])
        n = 0
        for mid in sorted(k for k in table if isinstance(k, int)):
            row = table[mid]
            w.writerow([mid, row.get("payload", "")] +
                       [row.get(c, "") for c in _COLS])
            n += 1
    return n


def summarize(table: Dict[int, dict]) -> dict:
    """Aggregate the joined 4-point timelines: message counts, RTT
    (PingSent -> PongReceived) percentiles, one-way (PingSent ->
    PingReceived) percentiles, and throughput over the sending window —
    the numbers the reference computed by hand in its spreadsheet
    (bench/calc-template.ods)."""
    rows = [v for k, v in table.items() if isinstance(k, int)]
    complete = [r for r in rows
                if MeasureEvent.PING_SENT in r
                and MeasureEvent.PONG_RECEIVED in r]
    one_way = [r for r in rows
               if MeasureEvent.PING_SENT in r
               and MeasureEvent.PING_RECEIVED in r]

    def pct(xs, q):
        if not xs:
            return None
        xs = sorted(xs)
        # nearest-rank percentile: ceil(q*n)-1, not int(q*n) (which
        # selects one rank high and degenerates at small n)
        import math
        return xs[max(0, min(len(xs) - 1, math.ceil(q * len(xs)) - 1))]

    rtts = [r[MeasureEvent.PONG_RECEIVED] - r[MeasureEvent.PING_SENT]
            for r in complete]
    ows = [r[MeasureEvent.PING_RECEIVED] - r[MeasureEvent.PING_SENT]
           for r in one_way]
    sends = [r[MeasureEvent.PING_SENT] for r in rows
             if MeasureEvent.PING_SENT in r]
    window_us = (max(sends) - min(sends)) if len(sends) > 1 else 0
    return {
        "messages": len(rows),
        "complete_timelines": len(complete),
        "send_window_us": window_us,
        "send_rate_msg_s": (round(len(sends) / (window_us / 1e6), 1)
                            if window_us else None),
        "rtt_us": {"p50": pct(rtts, 0.50), "p90": pct(rtts, 0.90),
                   "p99": pct(rtts, 0.99), "max": max(rtts, default=None)},
        "one_way_us": {"p50": pct(ows, 0.50), "p90": pct(ows, 0.90),
                       "p99": pct(ows, 0.99)},
    }
