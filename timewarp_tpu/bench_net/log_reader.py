"""Bench log reader — ≙ `/root/reference/bench/Network/LogReader/
Main.hs:61-119`: parse sender and receiver logs, join the four
timestamps of each message id, emit aligned ``measures.csv`` rows
``MsgId,PingSent,PingReceived,PongSent,PongReceived`` (missing points
left empty, like the reference's sparse LogEntry merge).
"""

from __future__ import annotations

import csv
from typing import Dict, Iterable, List

from .commons import MeasureEvent, parse_measure_line

__all__ = ["join_measures", "write_csv", "read_log_lines"]

_COLS = [MeasureEvent.PING_SENT, MeasureEvent.PING_RECEIVED,
         MeasureEvent.PONG_SENT, MeasureEvent.PONG_RECEIVED]


def read_log_lines(path: str) -> List[str]:
    with open(path, encoding="utf-8") as f:
        return f.readlines()


def join_measures(*line_sources: Iterable[str]) -> Dict[int, dict]:
    """Merge measure lines from any number of logs into
    ``{mid: {event: µs, "payload": len}}`` (≙ ``analyze`` building the
    per-id map, LogReader/Main.hs:76-96). A duplicate event for one id
    keeps the first occurrence and counts the duplicate."""
    table: Dict[int, dict] = {}
    dups = 0
    for lines in line_sources:
        for line in lines:
            parsed = parse_measure_line(line)
            if parsed is None:
                continue
            ev, mid, plen, t = parsed
            row = table.setdefault(mid, {"payload": plen})
            if ev in row:
                dups += 1
                continue
            row[ev] = t
    if dups:
        table["__duplicates__"] = dups  # surfaced, never silent
    return table


def write_csv(table: Dict[int, dict], path: str) -> int:
    """Write aligned rows sorted by message id (≙ the printed table,
    LogReader/Main.hs:97-119); returns the row count. The
    ``__duplicates__`` sentinel (if any) is left untouched in the
    table — the int-key filter below skips it."""
    with open(path, "w", newline="", encoding="utf-8") as f:
        w = csv.writer(f)
        w.writerow(["MsgId", "PayloadBytes"] + [c.name for c in _COLS])
        n = 0
        for mid in sorted(k for k in table if isinstance(k, int)):
            row = table[mid]
            w.writerow([mid, row.get("payload", "")] +
                       [row.get(c, "") for c in _COLS])
            n += 1
    return n
