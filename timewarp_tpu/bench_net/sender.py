"""Bench sender — ≙ `/root/reference/bench/Network/Sender/Main.hs`:
spread message ids over ``threads`` concurrent workers, listen
``AtConnTo`` each recipient for ``Pong`` replies (logging
PongReceived), rate-limit sends, stop at the duration deadline, give
replies one extra second, close connections (Main.hs:34-64). Options
mirror SenderOptions.hs:20-99.
"""

from __future__ import annotations

import logging
import random
from typing import Optional, Sequence

from ..core.effects import Program, Wait, fork_, start_timer
from ..manage.sync import Flag
from ..net.backend import NetBackend, NetworkAddress
from ..net.dialog import Dialog, Listener
from ..net.transfer import AtConnTo, Transport, localhost
from .commons import MeasureEvent, Ping, Pong, log_measure, payload_of

__all__ = ["sender"]


def sender(backend: NetBackend, peers: Sequence[NetworkAddress], *,
           threads: int = 5,
           msg_num: int = 1000,
           msg_rate: Optional[int] = None,
           duration_us: int = 10_000_000,
           payload_bound: int = 0,
           drain_us: int = 1_000_000,
           host: str = localhost,
           seed: int = 0,
           logger: logging.Logger = None):
    """Build the sender program. ``msg_rate`` is messages/sec/thread
    (None = unthrottled, ≙ ``sendDelay = 0``); payload sizes are drawn
    uniformly in [0, payload_bound] from a seeded RNG."""
    log = logger or logging.getLogger("bench.sender")
    send_delay = 0 if not msg_rate else 1_000_000 // msg_rate

    def main() -> Program:
        tr = Transport(backend, host=host)
        d = Dialog(tr)
        rng = random.Random(seed)
        done = [Flag() for _ in range(threads)]

        def on_pong(msg: Pong, ctx) -> Program:
            yield from log_measure(log, MeasureEvent.PONG_RECEIVED,
                                   msg.mid, len(msg.payload))

        stops = []
        for addr in peers:
            stop = yield from d.listen(AtConnTo(addr),
                                       [Listener(Pong, on_pong)])
            stops.append(stop)

        def worker(tid: int) -> Program:
            # ids tid, tid+threads, ... ≙ tasksIds (Main.hs:40)
            work_timer = yield from start_timer()
            for mid in range(tid, msg_num + 1, threads):
                if send_delay:
                    yield Wait(send_delay)
                elapsed = yield from work_timer()
                if elapsed > duration_us:  # ≙ the duration mzero cutoff
                    break
                for no, addr in enumerate(peers):
                    smid = no * msg_num + mid
                    payload = payload_of(rng.randint(0, payload_bound))
                    yield from log_measure(
                        log, MeasureEvent.PING_SENT, smid, len(payload))
                    yield from d.send(addr, Ping(smid, payload))
            yield from done[tid - 1].set()

        for tid in range(1, threads + 1):
            yield from fork_(lambda t=tid: worker(t))
        for f in done:
            yield from f.wait()
        yield Wait(drain_us)  # ≙ wait (for 1 sec) for responses
        for stop in stops:
            yield from stop()
        for addr in peers:
            yield from tr.close(addr)

    return main
