"""The timed-program effect interface.

This is the TPU-native re-design of the reference's ``MonadTimed``
typeclass (`/root/reference/src/Control/TimeWarp/Timed/MonadTimed.hs:107-141`).
Instead of a monad transformer stack, a *timed program* is a Python
generator that ``yield``s effect objects and receives results back; the
same program text runs under any interpreter:

- :class:`timewarp_tpu.interp.ref.des.PureEmulation` — deterministic
  discrete-event emulation (≙ ``TimedT``); ``wait`` costs zero wall-clock.
- :class:`timewarp_tpu.interp.aio.timed.RealTime` — real wall-clock over
  asyncio (≙ ``TimedIO``).

Sub-programs compose with ``yield from`` (which is what the reference's
``do``-notation bought it), and *exception handling is plain Python
``try/except``* — the interpreter delivers async exceptions by throwing
into the generator at its suspension point, which makes handler scoping
across waits (the reference's hardest machinery, TimedT.hs:183-204,
259-284) fall out of the language for free.

Effect vocabulary ≙ the class methods at MonadTimed.hs:107-141:

=============  =====================================================
``Wait``       ``wait`` (:125)
``Fork``       ``fork`` (:128) — returns the new ThreadId
``GetTime``    ``virtualTime``/``currentTime`` (:109-112)
``MyTid``      ``myThreadId`` (:131)
``ThrowTo``    ``throwTo`` (:134)
=============  =====================================================

Derived combinators (schedule/invoke/work/kill_thread/start_timer/
timeout) mirror MonadTimed.hs:162-206, 315-318 and TimedT.hs:370-376.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Generator, Union

from .errors import ThreadKilled, TimeoutExpired
from .time import Microsecond, RelativeToNow, after, mcs, till

#: A timed program: a generator yielding effects.
Program = Generator["Effect", Any, Any]
#: A zero-arg factory producing a timed program (used by Fork so the
#: child's frame is created inside the interpreter).
ProgramFn = Callable[[], Program]


class Effect:
    """Base class of all yieldable effects."""
    __slots__ = ()


@dataclass(frozen=True)
class Wait(Effect):
    """Suspend until the time spec fires (≙ ``wait``, MonadTimed.hs:125).

    ``spec`` is a :data:`RelativeToNow` or a bare relative duration in µs.
    Target time clamps to ``max(now, spec(now))`` (TimedT.hs:349).
    """
    spec: Union[RelativeToNow, Microsecond]


@dataclass(frozen=True)
class Fork(Effect):
    """Start a new thread running ``program()`` (≙ ``fork``, MonadTimed.hs:128).

    Yields back the child's ThreadId. Reference semantics preserved
    (TimedT.hs:326-342): the child is enqueued at the current instant and
    the parent *yields for 1 µs* (emulating the forkIO handoff), so the
    child runs first. Uncaught child exceptions are logged, not
    propagated (TimedT.hs:153-158, 306-316).
    """
    program: ProgramFn


@dataclass(frozen=True)
class ForkSlave(Effect):
    """Start a *linked* child thread (≙ ``forkSlave``,
    MonadTimed.hs:140-141, bound to the slave-thread library in real
    mode, TimedIO.hs:78; the reference's emulator leaves it
    ``undefined`` — TimedT.hs:377 — this framework implements it under
    BOTH interpreters). Handoff semantics are :class:`Fork`'s; the
    linked lifetime adds:

    - when the parent terminates (returns *or* dies), every live slave
      receives ``ThreadKilled`` at its next suspension point — and a
      dying slave kills its own slaves, so whole slave subtrees unwind;
    - an uncaught exception in a slave (other than ``ThreadKilled``) is
      *forwarded to the parent* as an async exception instead of being
      logged-and-dropped like a plain fork's.
    """
    program: ProgramFn


@dataclass(frozen=True)
class GetTime(Effect):
    """Yields back the current virtual time in µs (≙ ``virtualTime``)."""


@dataclass(frozen=True)
class MyTid(Effect):
    """Yields back the current thread id (≙ ``myThreadId``)."""


@dataclass(frozen=True)
class GetLogName(Effect):
    """Yields back this thread's hierarchical logger name (≙
    ``getLoggerName`` of the ``HasLoggerName`` instance, TimedT.hs:171-174).
    Children inherit the name at fork time (TimedT.hs:331-338)."""


@dataclass(frozen=True)
class SetLogName(Effect):
    """Replace this thread's logger name for the rest of its life (the
    scoped form is :func:`modify_log_name`)."""
    name: str


@dataclass(frozen=True)
class ThrowTo(Effect):
    """Raise ``exc`` inside thread ``tid`` (≙ ``throwTo``, MonadTimed.hs:134).

    Reference delivery contract (TimedT.hs:357-368): the target is woken
    — its pending resume event is pulled to *now* — and the exception is
    raised at that resume point. If several exceptions race to one
    thread, the first one wins (TimedT.hs:359 keeps the existing entry).
    A thread may only be interrupted at a suspension point; straight-line
    code between waits is uninterruptible (TimedT.hs:324-325).

    Self-throw contract (also inherited from the reference): throwing at
    the *currently running* thread stores the exception but cannot wake
    a resume event that does not exist yet — it is delivered when the
    thread's next suspension fires (at that suspension's own time), and
    silently evaporates if the thread finishes without suspending again.
    """
    tid: Any
    exc: BaseException


@dataclass(frozen=True)
class Park(Effect):
    """Suspend this thread until some other thread :class:`Unpark`\\ s it;
    yields back the value the unparker sent.

    This effect pair plays the role STM plays under the reference (its
    JobCurator blocks on ``TVar`` retries, Job.hs:48-49, 158-161; its
    Transfer blocks on ``TBMChan``, Transfer.hs:236-242): the one
    blocking primitive from which MVar/Channel/Flag are built
    (:mod:`timewarp_tpu.manage.sync`). If an unpark token is already
    pending, ``Park`` consumes it and continues immediately — no virtual
    time passes — so the park/unpark race is benign.
    """


@dataclass(frozen=True)
class Unpark(Effect):
    """Wake a :class:`Park`\\ ed thread ``tid`` at the current instant,
    sending it ``value``. If the target is not parked, the value is
    stored as a token consumed by its next ``Park`` (last token wins).
    No-op on dead/unknown threads."""
    tid: Any
    value: Any = None


@dataclass(frozen=True)
class AwaitIO(Effect):
    """Await a real awaitable (coroutine/future) — **real-IO interpreter
    only**; the pure emulator rejects it, because arbitrary host IO has
    no deterministic virtual-time meaning. The TCP transport layer is
    built on this; the emulated transport uses only timed effects and
    therefore runs under both interpreters.

    Cancellation contract: if the thread receives an async exception
    (``throw_to``) while awaiting, the awaitable is cancelled and the
    exception is raised at this yield point.
    """
    awaitable: Any


# ----------------------------------------------------------------------
# Derived combinators (generator helpers)
# ----------------------------------------------------------------------

def wait(spec: Union[RelativeToNow, Microsecond]) -> Program:
    """``yield from wait(for_(sec(1)))``."""
    yield Wait(spec)


def virtual_time() -> Program:
    """Returns current virtual time."""
    return (yield GetTime())


def my_thread_id() -> Program:
    return (yield MyTid())


def fork(program: ProgramFn) -> Program:
    """Fork; returns child ThreadId."""
    return (yield Fork(program))


def park() -> Program:
    """Suspend until unparked; returns the unparker's value."""
    return (yield Park())


def unpark(tid: Any, value: Any = None) -> Program:
    yield Unpark(tid, value)


def await_io(awaitable: Any) -> Program:
    """Await real IO (real-IO interpreter only); returns its result."""
    # the combinator's definition site — the pure-context lint (TW302)
    # applies to *uses*, not to this wrapper
    return (yield AwaitIO(awaitable))  # tw-lint: ignore[TW302]


def fork_(program: ProgramFn) -> Program:
    """``fork`` discarding the tid (≙ ``fork_``, MonadTimed.hs:194-195)."""
    yield Fork(program)


def fork_slave(program: ProgramFn) -> Program:
    """Fork a linked (slave) thread; returns the child ThreadId
    (≙ ``forkSlave``, MonadTimed.hs:141)."""
    return (yield ForkSlave(program))


def invoke(spec: Union[RelativeToNow, Microsecond], program: ProgramFn) -> Program:
    """Wait, then run ``program`` in *this* thread; returns its result
    (≙ ``invoke time action = wait time >> action``, MonadTimed.hs:182-183)."""
    yield Wait(spec)
    return (yield from program())


def schedule(spec: Union[RelativeToNow, Microsecond], program: ProgramFn) -> Program:
    """Run ``program`` at a future instant in a *new* thread
    (≙ ``schedule time action = fork_ $ invoke time action``,
    MonadTimed.hs:162-163)."""
    yield Fork(lambda: invoke(spec, program))


def kill_thread(tid: Any) -> Program:
    """≙ ``killThread = flip throwTo ThreadKilled`` (MonadTimed.hs:204-206)."""
    yield ThrowTo(tid, ThreadKilled())


def work(spec: Union[RelativeToNow, Microsecond], program: ProgramFn) -> Program:
    """Run ``program`` in a thread and kill it when the spec fires
    (≙ ``work``, MonadTimed.hs:201-202)."""
    tid = yield Fork(program)
    yield from schedule(spec, lambda: kill_thread(tid))


def start_timer() -> Program:
    """Returns a program measuring time since this call
    (≙ ``startTimer``, MonadTimed.hs:315-318)."""
    start = yield GetTime()

    def elapsed() -> Program:
        cur = yield GetTime()
        return cur - start

    return elapsed


def timeout(t: Microsecond, program: ProgramFn) -> Program:
    """Run ``program``; raise :class:`TimeoutExpired` in this thread if it
    overruns ``t`` µs.

    Same construction as the reference (TimedT.hs:370-376): schedule a
    killer thread that checks a done-flag and, when unset, ``throwTo``s
    the parent; the body runs under ``finally done=True``. The deadline
    is measured from where the *body* starts (one µs after this call,
    because of the fork handoff), and is inclusive: a body that finishes
    exactly at the deadline is timed out.
    """
    pid = yield MyTid()
    start = yield GetTime()
    done = [False]

    def killer() -> Program:
        # till(start + 1 + t): anchor the deadline to the body's actual
        # start instant so the fork handoff doesn't shave a µs off ``t``.
        yield Wait(till(start + 1 + int(t)))
        if not done[0]:
            yield ThrowTo(pid, TimeoutExpired("Timeout exceeded"))

    yield Fork(killer)
    try:
        return (yield from program())
    finally:
        done[0] = True


def modify_log_name(suffix: str, program: ProgramFn) -> Program:
    """Run ``program`` with ``suffix`` appended to the hierarchical logger
    name, restoring it afterwards (≙ ``modifyLoggerName (<> suffix)``,
    used throughout the reference examples, e.g. token-ring Main.hs:109-116)."""
    old = yield GetLogName()
    yield SetLogName(f"{old}.{suffix}" if old else suffix)
    try:
        return (yield from program())
    finally:
        yield SetLogName(old)


def sleep_forever() -> Program:
    """Sleep until killed (≙ ``sleepForever``, Misc.hs:50-51 — the
    reference loops 100500-minute waits; we loop long waits the same way)."""
    while True:
        yield Wait(after(mcs(100500 * 60_000_000)))


def repeat_forever(period: Microsecond,
                   handler: Callable[[BaseException], Microsecond],
                   program: ProgramFn) -> Program:
    """Run ``program`` every ``period`` µs; on failure ask ``handler`` for
    the retry delay (≙ ``repeatForever``, Misc.hs:21-45).

    The reference polls a TVar with the next-start time every 10 ms; the
    rewrite keeps the observable contract (action at start of each
    period, handler-controlled backoff) without the polling loop.
    """
    while True:
        start = yield GetTime()
        try:
            yield from program()
            nxt = start + int(period)
        except ThreadKilled:
            raise
        except BaseException as e:  # noqa: BLE001 — mirrors catchAll
            nxt = (yield GetTime()) + int(handler(e))
        cur = yield GetTime()
        if nxt > cur:
            yield Wait(nxt - cur)
