"""State-machine scenario IR — the batchable form of a timed program.

This is *the* key design move of the TPU build (SURVEY.md §7): the
continuation that the reference captures at every ``wait``
(`/root/reference/src/Control/TimeWarp/Timed/TimedT.hs:343-355`) becomes
an explicit ``(state, next_wake)`` pair, and the per-node behavior is a
pure **step function** that XLA can ``vmap`` over a million nodes:

    step(state, inbox, now, node_id, key) -> (state', outbox, next_wake)

A scenario written this way runs under *both* interpreters and must
produce identical event traces:

- :class:`timewarp_tpu.interp.ref.superstep.SuperstepOracle` — the pure
  host reference executor (the oracle).
- :class:`timewarp_tpu.interp.jax_engine.engine.JaxEngine` — the batched
  XLA engine (``vmap`` + ``lax.scan``; sharded over the TPU mesh).

Superstep semantics (shared contract)
-------------------------------------

Virtual time advances to the *global* minimum next-event time each
superstep, and **all** nodes whose next event is at that instant fire
simultaneously (the reference pops one event at a time, TimedT.hs:
239-263; firing all-at-min is the batched equivalent and coincides with
it because co-temporal events cannot observe each other's effects —
messages take ≥ 1 µs, below).

Determinism contract (SURVEY.md §5.2 — explicit where the reference
leaned on heap internals):

1. A node's next event time = ``min(next_wake, earliest pending message
   deliver-time)``.
2. The inbox a firing node sees = all pending messages with
   ``deliver_time <= now``, ordered by ``(deliver_time, arrival order)``.
3. Messages are routed after all co-temporal fires, in sender-major
   order (node 0's outbox slot 0, slot 1, …, node 1's …) — globally,
   arrival order == chronological routing order.
4. A delivered message is in flight for ``max(sampled_delay, 1)`` µs —
   a zero-latency link still crosses a scheduling point, as in the
   reference where a 0-delay ``ConnectedIn`` message is still handled by
   a later event (examples/token-ring/Main.hs:73-77).
5. A fired node's new ``next_wake`` is clamped to ``> now`` (or NEVER);
   re-arming at the same instant would stall virtual time.
6. Mailboxes are bounded (``mailbox_cap``); overflowing messages are
   counted and dropped, never silently lost (SURVEY.md §7).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, NamedTuple, Optional, Tuple

from .time import FOREVER, Microsecond

#: next_wake sentinel: the node has no timer armed.
NEVER: Microsecond = FOREVER


class Inbox(NamedTuple):
    """Messages visible to one node at its firing instant.

    Arrays are fixed-width ``mailbox_cap`` (K); invalid slots padded.
    Slot order follows the determinism contract: (deliver_time, arrival).
    """
    valid: Any    # bool[K]
    src: Any      # int32[K]
    time: Any     # int64[K] — deliver time in µs
    payload: Any  # int32[K, P]


class Outbox(NamedTuple):
    """Messages one node emits from one firing; fixed width ``max_out``."""
    valid: Any    # bool[M]
    dst: Any      # int32[M]
    payload: Any  # int32[M, P]


#: step(state, inbox, now, node_id, key) -> (state', outbox, next_wake)
StepFn = Callable[[Any, Inbox, Any, Any, Any], Tuple[Any, Outbox, Any]]

#: init(node_id) -> (state pytree, first_wake) — host-level, per node.
InitFn = Callable[[int], Tuple[Any, Microsecond]]

#: init_batched(n) -> (stacked state pytree [N,...], wake int64[N])
InitBatchedFn = Callable[[int], Tuple[Any, Any]]


@dataclass
class Scenario:
    """A complete batchable scenario (≙ a whole multi-node program that
    the reference would run via fork-per-node, e.g. token-ring
    examples/token-ring/Main.hs:63-72).

    ``step`` must be a pure, jittable function of fixed-shape arrays —
    no Python control flow on traced values. ``init`` gives per-node
    initial state for the host oracle; ``init_batched`` (optional) gives
    the same states natively vectorized for million-node engine runs.
    """
    name: str
    n_nodes: int
    step: StepFn
    init: InitFn
    payload_width: int = 2
    max_out: int = 1
    mailbox_cap: int = 8
    init_batched: Optional[InitBatchedFn] = None
    #: whether ``step`` consumes its entropy argument (core/rng.py
    #: ``fire_bits`` pair); engines skip deriving it when False
    needs_key: bool = False
    #: static communication graph: int32 [N, M] destination of each
    #: outbox slot (-1 = slot never used), when the scenario only ever
    #: sends along fixed edges. Enables the sort/scatter-free edge
    #: engine (interp/jax_engine/edge_engine.py).
    static_dst: Optional[Any] = None
    #: True when ``step`` is insensitive to inbox slot *order* (it
    #: reduces over the inbox commutatively). Lets engines skip the
    #: contract-#2 inbox sort; parity still holds bit-for-bit because
    #: digests are order-independent and the step result is too.
    commutative_inbox: bool = False
    #: False when ``step`` never reads ``inbox.src`` (sender identity
    #: is not part of the scenario's semantics — e.g. a gossip adopt is
    #: a pure payload reduction). Engines then skip storing/scattering
    #: the mailbox src field (mailbox scatters are the dense
    #: random-delivery cost floor on TPU, PERF_r04.md), ``inbox.src``
    #: reads as 0, and ALL interpreters hash src as 0 in the RECV
    #: digest — the parity law still pins every delivered message's
    #: (dst, time, payload), just not its sender.
    inbox_src: bool = True
    #: metadata for bench/trace tooling
    meta: dict = field(default_factory=dict)

    def __post_init__(self) -> None:
        """Declaration sanity — actionable errors at build time instead
        of shape mismatches (or silence) deep inside an engine. The
        deeper semantic checks (step dataflow, capacity proofs, flag
        validation) live in :mod:`timewarp_tpu.analysis`."""
        import numpy as _np
        for attr, why in (
                ("n_nodes", "a scenario needs at least one node"),
                ("mailbox_cap",
                 "every node needs at least one mailbox slot "
                 "(determinism contract #6 bounds, not eliminates, it)"),
                ("max_out",
                 "the outbox is fixed-width; width 0 could never send "
                 "(use valid=False lanes for silent firings)"),
                ("payload_width",
                 "payload arrays are fixed-width [max_out, "
                 "payload_width]; width 0 has no batchable layout")):
            v = getattr(self, attr)
            # numpy integer scalars (array shapes, loaded configs) are
            # fine; bools are not (True would silently mean 1)
            if isinstance(v, bool) \
                    or not isinstance(v, (int, _np.integer)) or v < 1:
                raise ValueError(
                    f"scenario {self.name!r}: {attr} must be an int "
                    f">= 1, got {v!r} — {why}")
        if self.static_dst is not None:
            shape = tuple(_np.shape(self.static_dst))
            want = (self.n_nodes, self.max_out)
            if shape != want:
                raise ValueError(
                    f"scenario {self.name!r}: static_dst shape {shape} "
                    f"must be [n_nodes, max_out] = {list(want)} — one "
                    "destination per outbox slot per node (-1 = slot "
                    "never used)")

    def empty_outbox(self, np_mod: Any) -> Outbox:
        """Convenience for step functions: an all-invalid outbox."""
        M, P = self.max_out, self.payload_width
        return Outbox(
            valid=np_mod.zeros((M,), dtype=bool),
            dst=np_mod.zeros((M,), dtype=np_mod.int32),
            payload=np_mod.zeros((M, P), dtype=np_mod.int32),
        )
