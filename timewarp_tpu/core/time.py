"""Virtual-time types and the time-specification DSL.

TPU-native re-design of the reference's time layer
(`/root/reference/src/Control/TimeWarp/Timed/MonadTimed.hs:252-299`).

All virtual time is **int64 microseconds since origin** — never floats —
so the pure oracle, the JAX engine, and the real-IO interpreter agree
bit-for-bit (SURVEY.md §7 "hard parts" #2: fixed-point time).

A *time spec* (`RelativeToNow` in the reference, MonadTimed.hs:66) is a
function from the current virtual time to an absolute target time:

- ``for_(t)`` / ``after(t)``   -> now + t      (MonadTimed.hs:286-292)
- ``till(t)`` / ``at(t)``      -> t            (MonadTimed.hs:278-284)
- ``now``                      -> now          (MonadTimed.hs:298-299)

Unit helpers mirror MonadTimed.hs:253-266 but return plain ints.
"""

from __future__ import annotations

from typing import Callable, Union

# Type aliases -----------------------------------------------------------

#: Virtual time in microseconds since origin (int64 range).
Microsecond = int

#: A time spec: maps current virtual time -> absolute target time.
RelativeToNow = Callable[[Microsecond], Microsecond]

#: Anything accepted where a duration is expected.
Duration = Union[int, float]

#: Sentinel for "never" — far enough that sums never overflow int64.
FOREVER: Microsecond = (1 << 62) - 1


# Units ------------------------------------------------------------------
# MonadTimed.hs:253-258 (integral) and :261-266 (fractional, rounded).

def mcs(n: Duration) -> Microsecond:
    return int(round(n))


def ms(n: Duration) -> Microsecond:
    return int(round(n * 1_000))


def sec(n: Duration) -> Microsecond:
    return int(round(n * 1_000_000))


def minute(n: Duration) -> Microsecond:
    return int(round(n * 60_000_000))


def hour(n: Duration) -> Microsecond:
    return int(round(n * 3_600_000_000))


# Time specs -------------------------------------------------------------

def for_(t: Microsecond, *ts: Microsecond) -> RelativeToNow:
    """Relative spec: fire ``t + sum(ts)`` microseconds after now
    (MonadTimed.hs:286-290). Variadic like the reference's time
    accumulators (``for 1 minute 30 sec`` — MonadTimed.hs:351-376):
    ``for_(minute(1), sec(30))``. At least one duration is required —
    a zero-argument call is a bug, not a zero wait."""
    total = int(t) + sum(int(x) for x in ts)
    return lambda cur: cur + total


def after(t: Microsecond, *ts: Microsecond) -> RelativeToNow:
    """Synonym of :func:`for_`, reads better with schedule/invoke
    (MonadTimed.hs:291-292)."""
    return for_(t, *ts)


def till(t: Microsecond, *ts: Microsecond) -> RelativeToNow:
    """Absolute spec: fire at virtual time ``t + sum(ts)``
    (MonadTimed.hs:278-282; variadic accumulator like :func:`for_`)."""
    total = int(t) + sum(int(x) for x in ts)
    return lambda _cur: total


def at(t: Microsecond, *ts: Microsecond) -> RelativeToNow:
    """Synonym of :func:`till` (MonadTimed.hs:283-284)."""
    return till(t, *ts)


def now(cur: Microsecond) -> Microsecond:
    """The identity spec (MonadTimed.hs:298-299)."""
    return cur


def resolve(spec: Union[RelativeToNow, Microsecond], cur: Microsecond) -> Microsecond:
    """Resolve a spec (or a bare relative duration) against the clock,
    clamped to never travel back in time — the reference clamps with
    ``max cur (relativeToNow cur)`` (TimedT.hs:349)."""
    target = spec(cur) if callable(spec) else cur + int(spec)
    return max(cur, int(target))
