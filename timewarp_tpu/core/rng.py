"""Counter-based RNG: elementwise threefry2x32 over uint32 words.

The reference threads one sequential ``StdGen`` through the emulated
network (seeded ``mkStdGen 0``, examples/token-ring/Main.hs:60, 82-85);
the TPU build keys every draw by *what it is for* — ``(node, time)``
for a firing, ``(src, dst, time, slot)`` for a link sample — so any
interpreter, batched or sequential, sharded or not, derives
bit-identical streams (SURVEY.md §5.2).

Round-2 note: round 1 used ``jax.random.fold_in`` chains, which
materialize a ``[batch, 2]`` key array per draw — on TPU that minor
dim of 2 pads to 128 lanes and the chain becomes multi-ms per
superstep. This module is the redesign: Threefry-2x32 written as pure
elementwise uint32 ops that broadcast in whatever layout the caller
already has ([N], [E, N], [S] …), never materializing key structures.
Integer-only ⇒ bit-exact across CPU/TPU.
"""

from __future__ import annotations

from typing import Tuple

from ..utils import jaxconfig  # noqa: F401  (int64 time words need x64)

import jax.numpy as jnp

__all__ = [
    "threefry2x32", "seed_words", "fire_bits", "msg_bits", "split_bits",
    "uniform_int", "bernoulli", "normal_f32",
]

_PARITY = 0x1BD11BDA  # threefry key-schedule parity constant
_GOLD = 0x9E3779B9    # golden ratio — domain separation for seeding

# Domain tags: distinct streams for fires vs link samples vs user splits.
_FIRE_TAG = 0xF14EF14E
_MSG_TAG = 0x4D534721

_ROT_A = (13, 15, 26, 6)
_ROT_B = (17, 29, 16, 24)


def _rotl(x, r: int):
    r = jnp.uint32(r)
    return (x << r) | (x >> (jnp.uint32(32) - r))


def threefry2x32(k0, k1, c0, c1) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Standard 20-round Threefry-2x32 block cipher: key (k0,k1),
    counter (c0,c1) -> two uint32 words. All args broadcast; pure
    elementwise integer ops (VPU-friendly in any layout)."""
    k0 = jnp.asarray(k0).astype(jnp.uint32)
    k1 = jnp.asarray(k1).astype(jnp.uint32)
    x0 = jnp.asarray(c0).astype(jnp.uint32) + k0
    x1 = jnp.asarray(c1).astype(jnp.uint32) + k1
    ks = (k0, k1, k0 ^ k1 ^ jnp.uint32(_PARITY))
    for g in range(5):
        rots = _ROT_A if g % 2 == 0 else _ROT_B
        for r in rots:
            x0 = x0 + x1
            x1 = _rotl(x1, r) ^ x0
        x0 = x0 + ks[(g + 1) % 3]
        x1 = x1 + ks[(g + 2) % 3] + jnp.uint32(g + 1)
    return x0, x1


def seed_words(seed: int) -> Tuple[int, int]:
    """Host-side: expand a Python int seed into two uint32 words."""
    import numpy as np
    s0 = np.uint32(seed & 0xFFFFFFFF)
    s1 = np.uint32((seed >> 32) & 0xFFFFFFFF)
    a, b = threefry2x32(s0, s1 ^ np.uint32(_GOLD), np.uint32(0), np.uint32(1))
    return int(a), int(b)


def _t_words(t):
    t = jnp.asarray(t, jnp.int64)
    lo = (t & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = ((t >> jnp.int64(32)) & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    return lo, hi


def fire_bits(s0, s1, node, t) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Entropy for one node's firing at virtual time ``t``.

    ≙ the per-event randomness of the reference's threaded StdGen, made
    order-independent. Broadcasting: ``node`` may be [N] while ``t`` is
    scalar.
    """
    tlo, thi = _t_words(t)
    a0, a1 = threefry2x32(jnp.uint32(s0) ^ jnp.uint32(_FIRE_TAG),
                          jnp.uint32(s1), node, tlo)
    return threefry2x32(a0, a1, thi, jnp.uint32(0))


def msg_bits(s0, s1, src, dst, t, slot) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Entropy for the link sample of one message ``src -> dst`` emitted
    at time ``t`` from outbox slot ``slot`` (≙ the seeded ``Delays``
    draw, examples/token-ring/Main.hs:73-77)."""
    tlo, thi = _t_words(t)
    a0, a1 = threefry2x32(jnp.uint32(s0) ^ jnp.uint32(_MSG_TAG),
                          jnp.uint32(s1), src, dst)
    b0, b1 = threefry2x32(a0, a1, tlo, thi)
    return threefry2x32(b0, b1, slot, jnp.uint32(0))


def split_bits(b0, b1, tag: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Derive an independent substream from an entropy pair (≙
    ``jax.random.split``); ``tag`` must be a static int."""
    return threefry2x32(b0, b1, jnp.uint32(tag), jnp.uint32(1))


def uniform_int(bits, lo: int, hi: int):
    """Uniform integer in [lo, hi] from one uint32 word (modulo scheme:
    deterministic and identical everywhere; the ≤2^-32-scale modulo
    bias is irrelevant for link-delay sampling)."""
    span = jnp.uint32(hi - lo + 1)
    return jnp.asarray(lo, jnp.int64) + (bits % span).astype(jnp.int64)


def bernoulli(bits, p: float):
    """True with (static) probability ``p`` from one uint32 word —
    integer threshold compare, bit-exact on every backend."""
    if p <= 0.0:
        return jnp.zeros(jnp.shape(bits), bool)
    thr = int(p * 4294967296.0)
    if thr >= 1 << 32:
        return jnp.ones(jnp.shape(bits), bool)
    return bits < jnp.uint32(thr)


def normal_f32(b0, b1):
    """Standard normal via Box-Muller from two uint32 words (float32).

    Transcendental lowering may differ across backends by an ulp —
    integer models stay bit-exact; float models carry the documented
    LogNormalDelay caveat (net/delays.py).
    """
    # 24-bit mantissa uniforms in (0, 1)
    u1 = (b0 >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(2 ** -24) \
        + jnp.float32(2 ** -25)
    u2 = (b1 >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(2 ** -24)
    r = jnp.sqrt(jnp.float32(-2.0) * jnp.log(u1))
    return r * jnp.cos(jnp.float32(2.0 * 3.141592653589793) * u2)
