"""Exception hierarchy for the framework.

Mirrors the error surface of the reference:

- ``TimedError`` / ``TimeoutExpired``  ≙  ``MonadTimedError(MTTimeoutError)``
  (`/root/reference/src/Control/TimeWarp/Timed/MonadTimed.hs:69-73`)
- ``ThreadKilled``  ≙  ``Control.Exception.AsyncException(ThreadKilled)``
  as used by ``killThread`` (MonadTimed.hs:204-206)
- ``TransferError`` family  ≙  ``TransferException``/``PeerClosedConnection``
  (`/root/reference/src/Control/TimeWarp/Rpc/Transfer.hs:154-170`)
"""

from __future__ import annotations


class TimeWarpError(Exception):
    """Root of all framework-raised errors."""


# Timed layer ------------------------------------------------------------

class TimedError(TimeWarpError):
    """≙ ``MonadTimedError`` (MonadTimed.hs:69-73)."""


class TimeoutExpired(TimedError):
    """Raised by ``timeout`` when the action overruns
    (≙ ``MTTimeoutError``, MonadTimed.hs:69-73; thrown at TimedT.hs:370-376)."""


class DeadlockError(TimedError):
    """Delivered by the pure emulator to every thread still ``Park``\\ ed
    when the event queue drains: nothing can ever wake it again.

    ≙ GHC's ``BlockedIndefinitelyOnMVar`` — the reference inherits that
    detection from the RTS; the emulator must provide it explicitly or a
    deadlocked scenario would be indistinguishable from quiescence.
    Delivered *into* the thread (catchable; ``finally`` blocks run).
    """


class ThreadKilled(Exception):
    """Async exception delivered by ``kill_thread``
    (≙ ``AsyncException ThreadKilled``, MonadTimed.hs:204-206).

    Deliberately *not* a ``TimeWarpError``: user code catching the
    framework error root should not swallow kill signals by accident.
    """


# Network layer ----------------------------------------------------------

class TransferError(TimeWarpError):
    """≙ ``TransferException`` (Transfer.hs:154-161)."""


class AlreadyListening(TransferError):
    """Second listener attached to one connection
    (≙ ``AlreadyListeningOutbound``, Transfer.hs:157-161; single-listener
    rule documented at MonadTransfer.hs:23-33)."""


class PeerClosedConnection(TransferError):
    """Remote end closed the socket (≙ Transfer.hs:163-170)."""


class ConnectError(TransferError):
    """Connection could not be established — port unbound, peer
    unreachable, or the link model dropped the connect attempt (≙ the
    OS-level connect failure that feeds ``withRecovery``'s
    ``reconnectPolicy`` loop, Transfer.hs:585-603, and the old API's
    ``NeverConnected`` outcome)."""


class SocketBroken(TransferError):
    """The connection broke mid-stream — abrupt reset, not a clean EOF
    (≙ the socket IOErrors that ``sfProcessSocket``'s workers surface to
    ``withRecovery``, Transfer.hs:383-401)."""


class MailboxOverflow(TimeWarpError):
    """A simulated node's bounded mailbox overflowed in the batched engine.

    The reference's unbounded event queue can't overflow; the XLA engine's
    fixed-capacity mailboxes can, and overflow must be *detected and
    reported*, never silent (SURVEY.md §7 build-plan requirement).
    """


class NetworkError(TimeWarpError):
    """RPC/dialog-level failure (≙ the removed RpcError surface referenced
    by MonadRpc.hs.unused)."""
