"""Causal queries over a recorded flight log: "why was this message
delivered at t?"

Given a :class:`~timewarp_tpu.obs.flight.FlightLog` (decoded from a
run, or loaded back from the JSONL event log), :func:`explain_delivery`
reconstructs one delivery's causal chain:

1. **the send** that produced it — joined on ``(dst, deliver_t)``
   refined by ``src`` (``inbox_src=False`` scenarios elide the source
   at delivery — all interpreters present 0 — so the deliver event's
   src is 0 and the join falls back to ``(dst, deliver_t)``; a
   deliveries-only log has no send events at all, and the chain says
   so rather than guessing);
2. **every fault window that acted on it along the way** — ``defer``
   events for the destination between send and consumption (a crash
   window slid the node's firing), cross-referenced against the
   :class:`~timewarp_tpu.faults.schedule.FaultSchedule` itself:
   ``LinkWindow`` degradations covering the send instant (with the
   exact rational transform), ``NodeCrash`` windows of the
   destination overlapping the flight, ``ClockSkew`` on either end;
3. **the delivery** — due instant vs the superstep instant it was
   actually consumed at (a gap is deferral evidence even in a
   deliveries-only log).

:func:`add_flight_flows` draws the chains onto the Perfetto
virtual-time timeline as flow arrows (send→deliver across node
tracks, obs/perfetto.py). CLI: ``timewarp-tpu explain``
(docs/observability.md).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from .flight import (ACTION_NAMES, EV_DELIVER, EV_FAULT, EV_SEND,
                     FlightLog, TAG_DEFER)

__all__ = ["explain_delivery", "find_deliveries", "chain_lines",
           "add_flight_flows"]


def find_deliveries(log: FlightLog, *, dst: int,
                    t_us: Optional[int] = None,
                    src: Optional[int] = None) -> List[int]:
    """Indices of deliver events matching the query, in log order."""
    m = (log.kind == EV_DELIVER) & (log.dst == dst)
    if t_us is not None:
        m &= log.t == t_us
    if src is not None:
        m &= log.src == src
    return [int(i) for i in np.nonzero(m)[0]]


def _schedule(faults):
    if faults is None:
        return None
    if isinstance(faults, str):
        from ..faults.schedule import parse_faults
        return parse_faults(faults)
    return faults


def explain_delivery(log: FlightLog, *, dst: int,
                     t_us: Optional[int] = None, nth: int = 0,
                     src: Optional[int] = None,
                     faults=None) -> dict:
    """Reconstruct one delivery's causal chain (module docstring).
    ``dst`` + optional ``t_us``/``src`` select the delivery (``nth``
    among the matches); ``faults`` (a FaultSchedule or a ``--faults``
    grammar string) enables the schedule cross-reference. Raises
    ``ValueError`` naming what WAS found when nothing matches —
    never an empty chain."""
    hits = find_deliveries(log, dst=dst, t_us=t_us, src=src)
    if not hits:
        total = int((log.kind == EV_DELIVER).sum())
        raise ValueError(
            f"no delivery to node {dst}"
            + (f" at t={t_us}" if t_us is not None else "")
            + (f" from {src}" if src is not None else "")
            + f" in this log ({total} deliveries total"
            + (f", {log.dropped} events dropped over record_cap —"
               " raise it and re-record" if log.dropped else "")
            + ")")
    if nth >= len(hits):
        raise ValueError(
            f"delivery #{nth} to node {dst} out of range — the log "
            f"holds {len(hits)} matching deliveries")
    i = hits[nth]
    d_src, d_dst = int(log.src[i]), int(log.dst[i])
    d_t = int(log.t[i])           # the message's DUE instant
    d_tsup = int(log.t_sup[i])    # the superstep that consumed it
    chain: List[dict] = []

    # 1. the producing send — (dst, deliver_t) join, src-refined when
    # the scenario carries sources (inbox_src)
    sm = (log.kind == EV_SEND) & (log.dst == d_dst) & (log.t == d_t)
    if d_src != 0:
        sm_ref = sm & (log.src == d_src)
        if sm_ref.any():
            sm = sm_ref
    si = np.nonzero(sm)[0]
    send_t = None
    if si.size:
        j = int(si[0])
        send_t = int(log.send_t[j])
        chain.append({"step": "send", "src": int(log.src[j]),
                      "dst": d_dst, "t_us": send_t,
                      "deliver_t_us": d_t,
                      "flight_us": d_t - send_t,
                      "superstep": int(log.superstep[j]),
                      "ambiguous": int(si.size) > 1})
    else:
        chain.append({"step": "send", "unknown": True,
                      "why": "no matching send event — the log was "
                             "recorded with record='deliveries' "
                             "(sends need record='full'), or the "
                             "send predates the recorded span"})

    # 2. fault windows that acted on the message, from the schedule…
    sched = _schedule(faults)
    if sched is not None:
        for w in sched.link_windows:
            # send_t is only known when a send event matched (si
            # non-empty), so the src refinement reads that event
            s_ok = w.src is None or (send_t is not None
                                     and int(log.src[int(si[0])])
                                     in w.src)
            d_ok = w.dst is None or d_dst in w.dst
            in_w = send_t is not None \
                and w.t_start <= send_t < w.t_end
            if s_ok and d_ok and in_w:
                chain.append({
                    "step": "degrade",
                    "window": [int(w.t_start), int(w.t_end)],
                    "scale": w.scale, "extra_us": int(w.extra_us),
                    "detail": f"LinkWindow [{w.t_start}, {w.t_end}) "
                              f"transformed the sampled delay "
                              f"(×{w._num}/{w._den} + {w.extra_us} "
                              "µs)"})
        for c in sched.crashes:
            if c.node != d_dst:
                continue
            lo = send_t if send_t is not None else d_t
            if c.t_down < max(d_t, d_tsup) and c.t_up > lo:
                chain.append({
                    "step": "crash_window", "node": c.node,
                    "window": [int(c.t_down), int(c.t_up)],
                    "reset": bool(c.reset_state),
                    "detail": f"NodeCrash({c.node}) "
                              f"[{c.t_down}, {c.t_up}) overlapped "
                              "the flight — deliveries inside drop; "
                              "pending events slide to t_up"})
        for s in sched.skews:
            if s.node == d_dst and s.offset_us:
                chain.append({
                    "step": "skew", "node": s.node,
                    "offset_us": int(s.offset_us),
                    "detail": f"ClockSkew({s.node}) shifts the "
                              "node's VIEW of time; true-time "
                              "delivery is unaffected"})

    # …and from the log itself: defer events for the destination
    # between send and consumption (each crash superstep re-records
    # the slide, so dedup on the deferred-to instant)
    lo = send_t if send_t is not None else d_t
    dm = ((log.kind == EV_FAULT) & (log.tag == TAG_DEFER)
          & (log.dst == d_dst) & (log.t_sup >= lo)
          & (log.send_t <= d_tsup))
    seen = set()
    for j in np.nonzero(dm)[0]:
        key = int(log.t[j])
        if key in seen:
            continue
        seen.add(key)
        chain.append({"step": "defer", "node": d_dst,
                      "from_t_us": int(log.send_t[j]),
                      "to_t_us": key,
                      "detail": f"node {d_dst}'s pending event slid "
                                f"{int(log.send_t[j])} -> {key} "
                                "(crash window)"})

    # 3. the delivery
    chain.append({"step": "deliver", "src": d_src, "dst": d_dst,
                  "t_us": d_t, "consumed_t_us": d_tsup,
                  "superstep": int(log.superstep[i]),
                  "deferred_us": max(d_tsup - d_t, 0)})
    return {"dst": d_dst, "src": d_src, "t_us": d_t,
            "send_t_us": send_t, "chain": chain}


def chain_lines(result: dict) -> List[str]:
    """Human rendering of an :func:`explain_delivery` result — one
    line per chain step (the ``explain`` CLI's text output)."""
    out = []
    for step in result["chain"]:
        kind = step["step"]
        if kind == "send" and step.get("unknown"):
            out.append(f"send    ? {step['why']}")
        elif kind == "send":
            amb = " (ambiguous join: several sends share this "\
                  "deliver instant)" if step.get("ambiguous") else ""
            out.append(
                f"send    {step['src']} -> {step['dst']} at "
                f"t={step['t_us']} (flight {step['flight_us']} µs, "
                f"superstep {step['superstep']}){amb}")
        elif kind == "degrade":
            out.append(f"degrade {step['detail']}")
        elif kind == "crash_window":
            out.append(f"crash   {step['detail']}")
        elif kind == "defer":
            out.append(f"defer   {step['detail']}")
        elif kind == "skew":
            out.append(f"skew    {step['detail']}")
        elif kind == "deliver":
            extra = (f", consumed at t={step['consumed_t_us']} "
                     f"(+{step['deferred_us']} µs deferred)"
                     if step["deferred_us"] else "")
            out.append(
                f"deliver {step['src']} -> {step['dst']} due at "
                f"t={step['t_us']} (superstep {step['superstep']}"
                f"){extra}")
    return out


def add_flight_flows(tb, log: FlightLog, *, limit: int = 512,
                     dst: Optional[int] = None) -> int:
    """Draw send→deliver flow arrows onto a TraceBuilder's
    virtual-time timeline (obs/perfetto.py ``flow_arrow``): every
    full-mode send event becomes an arrow from its source node track
    at the send instant to the destination track at the deliver
    instant. ``limit`` bounds the arrow count (a dense log would
    drown the view — the skipped count is returned alongside via the
    builder's instant marker, never silent)."""
    sm = log.kind == EV_SEND
    if dst is not None:
        sm &= log.dst == dst
    idx = np.nonzero(sm)[0]
    n = 0
    for j in idx[:limit]:
        tb.flow_arrow("msg", int(log.src[j]), int(log.send_t[j]),
                      int(log.dst[j]), int(log.t[j]), flow_id=int(j))
        n += 1
    if idx.size > limit:
        tb.instant(f"flight flows truncated: {idx.size - limit} of "
                   f"{idx.size} arrows skipped (limit={limit})")
    return n
