"""On-device telemetry rows and their host-side decode.

A :class:`TelemetryRow` is the fixed-shape per-superstep counter plane
an engine threads through its traced scan when ``telemetry != "off"``:
every field is derived from values the superstep already computes
(the firing mask, the routed outbox, the post-insertion mailbox, the
post-step wake array), so turning telemetry on can never change a
digest, a counter, or a checkpoint — and turning it off removes the
ops entirely (the zero-overhead-when-off law, obs/__init__.py).

The row rides as the ``telem`` field of the engines' per-superstep
trace row (``StepOut``, interp/jax_engine/common.py). ``None`` is a
registered empty pytree in JAX, so the off-mode default adds zero
leaves, zero scan outputs, and zero jaxpr equations — off mode is not
a cheap mode, it is the *absence* of the subsystem.

Modes:

- ``"counters"`` — cheap scalars only: no reduction the superstep was
  not already paying for, plus one O(N) wake/mailbox min it shares
  with the quiescence check. Bench-gated at <= 5% throughput cost on
  the traced driver (bench.py gossip_100k_fused).
- ``"full"`` — adds the mailbox occupancy plane ([K, N] / [E, C, N]
  reductions): total live entries and the per-node fill high-water
  mark. Costs one extra pass over the mailbox per superstep.

Batched engines vmap the row like everything else, so every field is
per-world ([B]) for free — per-world quiescence slack is exactly the
signal the ROADMAP's online-adaptive-dispatch item needs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, NamedTuple, Optional

import numpy as np

__all__ = ["TELEMETRY_MODES", "TelemetryRow", "TelemetryFrames",
           "validate_mode", "decode_frames", "summarize_frames",
           "concat_frames"]

#: the engine knob's legal values, in increasing cost order
TELEMETRY_MODES = ("off", "counters", "full")


def validate_mode(mode: str, who: str = "engine") -> str:
    """Loud knob validation — a typo'd mode must not silently run
    without (or with unexpected) telemetry."""
    if mode not in TELEMETRY_MODES:
        raise ValueError(
            f"{who}: telemetry must be one of {TELEMETRY_MODES}, got "
            f"{mode!r} ('off' = zero overhead, 'counters' = cheap "
            "per-superstep scalars, 'full' = + mailbox occupancy)")
    return mode


class TelemetryRow(NamedTuple):
    """One superstep's counter plane (all device scalars; [B] per
    world under the batch vmap). ``mb_fill``/``mb_peak`` are ``None``
    outside ``"full"`` mode — None is an empty pytree node, so the
    counters-mode row carries exactly its five populated leaves."""
    #: int32 — senders that emitted >= 1 valid outbox message
    active_senders: Any
    #: int32 — static width of the routing rung this superstep ran at
    #: (the adaptive ladder's selected branch / the fused engine's
    #: batch slice); -1 = the path has no rung ladder
    rung: Any
    #: int32 — messages dropped by engine routing capacity this step
    route_drop: Any
    #: int32 — messages the fault schedule killed this step
    fault_dropped: Any
    #: int64 — virtual µs from this superstep's instant to the next
    #: pending event (-1 = quiesced): the dispatch-slack signal
    qslack_us: Any
    #: int32 — total live mailbox entries after insertion (full mode)
    mb_fill: Any = None
    #: int32 — max per-node mailbox occupancy after insertion (the
    #: high-water mark against mailbox_cap; full mode)
    mb_peak: Any = None


#: row fields in stable (schema) order
FIELDS = TelemetryRow._fields


@dataclass
class TelemetryFrames:
    """Host-side decode of one run's telemetry: per-superstep virtual
    times plus one column per populated row field, already filtered to
    the supersteps that actually fired."""
    t_us: np.ndarray                  # int64[S]
    data: Dict[str, np.ndarray]       # field -> [S]

    def __len__(self) -> int:
        return len(self.t_us)

    def to_json(self) -> dict:
        return {"t_us": self.t_us.tolist(),
                **{k: v.tolist() for k, v in self.data.items()}}


def _col(x, mask, world: Optional[int]) -> np.ndarray:
    a = np.asarray(x)
    if world is not None:
        return a[mask, world]
    return a[mask]


def decode_frames(telem, valid, t_us, n_worlds: Optional[int] = None):
    """Decode the scan's stacked telemetry rows ([T] leaves; [T, B]
    batched) into a :class:`TelemetryFrames` (solo) or one per world
    (batched), masked to the valid supersteps — the host-side mirror
    of the engines' trace decode."""
    valid = np.asarray(valid)
    t_us = np.asarray(t_us)

    def one(world: Optional[int]) -> TelemetryFrames:
        m = valid if world is None else valid[:, world]
        data = {f: _col(getattr(telem, f), m, world)
                for f in FIELDS if getattr(telem, f) is not None}
        return TelemetryFrames(t_us=_col(t_us, m, world), data=data)

    if n_worlds is None:
        return one(None)
    return [one(b) for b in range(n_worlds)]


def concat_frames(chunks):
    """Concatenate per-chunk decodes into one run-level view — what
    the controller drivers (interp/jax_engine/controlled.py) leave on
    ``last_run_telemetry`` so post-run exporters (the CLI's
    ``--metrics-out``/``--trace-out``) see the WHOLE run, not the
    final chunk. ``chunks`` is a list of ``TelemetryFrames`` (solo)
    or a list of per-world lists (batched) — returns the same shape
    as one chunk."""
    chunks = [c for c in chunks if c is not None]
    if not chunks:
        return None
    if isinstance(chunks[0], list):
        B = len(chunks[0])
        return [concat_frames([c[b] for c in chunks])
                for b in range(B)]
    keys = [k for k in FIELDS if k in chunks[0].data]
    return TelemetryFrames(
        t_us=np.concatenate([c.t_us for c in chunks]),
        data={k: np.concatenate([c.data[k] for c in chunks])
              for k in keys})


def _stats(v: np.ndarray) -> dict:
    if v.size == 0:
        return {"min": 0, "mean": 0.0, "max": 0}
    return {"min": int(v.min()), "mean": round(float(v.mean()), 3),
            "max": int(v.max())}


def summarize_frames(frames: TelemetryFrames) -> dict:
    """One aggregate dict per chunk of supersteps — what the metrics
    registry flushes as a ``supersteps`` line. Sums for the
    never-silent drop counters, min/mean/max for load signals, and the
    minimum observed quiescence slack (ignoring quiesced -1 rows)."""
    d = frames.data
    out: dict = {"supersteps": len(frames)}
    if len(frames):
        out["t_first_us"] = int(frames.t_us[0])
        out["t_last_us"] = int(frames.t_us[-1])
    for f in ("active_senders", "mb_fill", "mb_peak"):
        if f in d:
            out[f] = _stats(d[f])
    if "rung" in d:
        # -1 is the "no ladder ran" sentinel, not a width — aggregate
        # only real rung selections (absent = the ladder never ran),
        # or the adaptive-dispatch signal would average flags with
        # widths
        ran = d["rung"][d["rung"] >= 0]
        if ran.size:
            out["rung"] = _stats(ran)
    for f in ("route_drop", "fault_dropped"):
        if f in d:
            out[f] = int(d[f].sum())
    if "qslack_us" in d:
        live = d["qslack_us"][d["qslack_us"] >= 0]
        out["qslack_us_min"] = int(live.min()) if live.size else -1
    return out
