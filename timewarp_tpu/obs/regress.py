"""Cross-run regression gates and single-run anomaly detectors.

Two consumers of the run ledger (obs/ledger.py):

**compare** — ``timewarp-tpu ledger compare A B`` joins two
selections (run ids, batches, or config_key substrings) per
``config_key`` and gates each shared measurement with a *noise-aware*
relative-change check:

- rates (``value``, the median-of-``--reps`` msg/s) fail when the
  candidate drops more than ``rate_gate`` below the baseline **and**
  the two runs' min/max spread bands (when ``--reps`` recorded them)
  do not overlap — an overlap means the tunnel's ±12% swing
  (PERF_r05.md) could explain the delta, which is reported as a note,
  never a failure;
- wall seconds (``seconds``, the smoke per-config timing) fail when
  the candidate exceeds ``1 + wall_gate`` times the baseline — the
  default 0.75 is loose enough for CI runner jitter and strict
  enough that a 2x slowdown always trips.

Byte-identical re-ingest of the same run compares with zero delta
and exits 0 — determinism is the contract the CI gate stands on.
Every failure is ONE pinned line (the TraceMismatch convention):
metric, configs, values, relative change, gate, run ids, git shas.

**anomalies** — detectors over a single run's telemetry/journal,
each reporting one pinned line:

- *rollback storm*: speculation rollbacks swamping committed
  decisions (the misspeculation ledger turned red), or repeated
  integrity violations (an SDC-prone host);
- *rung thrash*: the dispatch controller flip-flopping its rung pin
  on most consecutive decisions — the policy is oscillating, not
  adapting;
- *bucket_util collapse*: a bucket whose ``budget_efficiency`` or
  ``worlds_active_mean`` fell under the floor — the pack is
  mis-bucketed (docs/sweeps.md);
- *quiescence straggler*: a world still burning supersteps long
  after the fleet median quiesced — re-pack or split it.

Everything here is host-side and read-only: journals and metrics are
opened for reading only, so the bit-exact laws and the journal
compare surfaces are untouched by construction.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["Delta", "Anomaly", "CompareReport", "compare_runs",
           "compare_selections", "detect_anomalies",
           "detect_target_anomalies"]


# -- cross-run comparison -------------------------------------------------

#: metric field -> (better direction, default gate attr)
_METRICS = {"value": ("higher", "rate_gate"),
            "seconds": ("lower", "wall_gate"),
            # serving admission throughput (bench.py serve_gossip):
            # present only on serve lines; _compare_one skips metrics
            # missing on either side, so every other config is inert
            "admit_per_s": ("higher", "rate_gate"),
            # packing rollups (sweep/journal.py util_rollup,
            # docs/sweeps.md "Predictive packing"): present only on
            # sweep_hetero / serve_gossip bench lines — efficiency
            # regresses DOWN, pad waste regresses UP
            "budget_efficiency": ("higher", "rate_gate"),
            "pad_waste_frac": ("lower", "rate_gate")}


@dataclass
class Delta:
    """One gated measurement comparison between two ledger runs."""
    config_key: str
    metric: str                 # "value" | "seconds"
    a_run: str
    b_run: str
    a: float
    b: float
    #: signed relative change b vs a; None when the baseline is 0
    #: and the candidate is not (the ratio is undefined — a 0-second
    #: baseline with a nonzero candidate still GATES, see below)
    rel: Optional[float]
    gate: float
    regression: bool
    #: bands overlapped (noise could explain the delta) — never fails
    within_spread: bool = False
    a_git: str = "unknown"
    b_git: str = "unknown"

    def line(self) -> str:
        arrow = f"{self.a:g} -> {self.b:g}"
        pct = ("baseline 0, ratio undefined" if self.rel is None
               else f"{self.rel:+.1%}")
        if self.regression:
            why = ("any nonzero increase gates" if self.rel is None
                   else f"beyond the {self.gate:.0%} gate")
            return (f"REGRESSION {self.config_key} {self.metric}: "
                    f"{arrow} ({pct} — {why}) "
                    f"[{self.a_run} vs {self.b_run}, git {self.a_git} "
                    f"vs {self.b_git}]")
        note = (" within measured spread" if self.within_spread
                else "")
        return (f"ok {self.config_key} {self.metric}: {arrow} "
                f"({pct}{note}) [{self.a_run} vs {self.b_run}]")

    def to_json(self) -> dict:
        return {"config_key": self.config_key, "metric": self.metric,
                "a_run": self.a_run, "b_run": self.b_run,
                "a": self.a, "b": self.b,
                "rel": None if self.rel is None else round(self.rel,
                                                           6),
                "gate": self.gate, "regression": self.regression,
                "within_spread": self.within_spread}


@dataclass
class CompareReport:
    deltas: List[Delta] = field(default_factory=list)
    #: config_keys present on only one side (reported, never fatal —
    #: a grown config inventory is not a regression)
    unmatched_a: List[str] = field(default_factory=list)
    unmatched_b: List[str] = field(default_factory=list)

    @property
    def regressions(self) -> List[Delta]:
        return [d for d in self.deltas if d.regression]

    def lines(self) -> List[str]:
        out = [d.line() for d in self.deltas]
        for key in self.unmatched_a:
            out.append(f"note {key}: only in the baseline selection")
        for key in self.unmatched_b:
            out.append(f"note {key}: only in the candidate selection")
        n = len(self.regressions)
        out.append(f"({len(self.deltas)} compared, {n} regressions)")
        return out

    def to_json(self) -> dict:
        return {"deltas": [d.to_json() for d in self.deltas],
                "unmatched_a": self.unmatched_a,
                "unmatched_b": self.unmatched_b,
                "regressions": len(self.regressions),
                "ok": not self.regressions}


def _band(rec: Dict[str, Any]) -> Optional[Tuple[float, float]]:
    """The run's measured min/max spread (``--reps`` recorded it), or
    a point band at the value."""
    if "min" in rec and "max" in rec:
        return float(rec["min"]), float(rec["max"])
    if "value" in rec:
        v = float(rec["value"])
        return v, v
    return None


def _compare_one(a: Dict[str, Any], b: Dict[str, Any],
                 rate_gate: float, wall_gate: float) -> List[Delta]:
    out: List[Delta] = []
    gates = {"rate_gate": rate_gate, "wall_gate": wall_gate}
    for metric, (direction, gate_name) in _METRICS.items():
        va, vb = a.get(metric), b.get(metric)
        if not isinstance(va, (int, float)) \
                or not isinstance(vb, (int, float)) \
                or isinstance(va, bool) or isinstance(vb, bool):
            continue
        va, vb = float(va), float(vb)
        gate = gates[gate_name]
        if va > 0:
            rel = vb / va - 1.0
            worse = (rel < -gate) if direction == "higher" \
                else (rel > gate)
        elif vb == va:
            rel, worse = 0.0, False
        else:
            # 0 baseline, nonzero candidate: the ratio is undefined —
            # a lower-is-better metric (wall seconds) gates on ANY
            # increase (0 -> 10 s must never print "+0.0% ok"); a
            # higher-is-better metric's 0 baseline means the BASELINE
            # was broken, and a nonzero candidate only improves it
            rel, worse = None, direction == "lower"
        within = False
        if worse and metric == "value":
            # noise-awareness: overlapping spread bands mean the
            # measured variance could explain the delta — note it,
            # never fail on it
            ba, bb = _band(a), _band(b)
            if ba and bb and ba[0] <= bb[1] and bb[0] <= ba[1]:
                within, worse = True, False
        out.append(Delta(
            config_key=a.get("config_key", "?"), metric=metric,
            a_run=a.get("run_id", "?"), b_run=b.get("run_id", "?"),
            a=va, b=vb, rel=rel, gate=gate, regression=worse,
            within_spread=within,
            a_git=a.get("git_sha", "unknown"),
            b_git=b.get("git_sha", "unknown")))
    return out


def compare_runs(a_runs: List[dict], b_runs: List[dict], *,
                 rate_gate: float = 0.30,
                 wall_gate: float = 0.75) -> CompareReport:
    """Join two run selections per ``config_key`` (latest run of a
    key wins within each side — re-ingests supersede) and gate every
    shared measurement. Non-bench records (sweep/metrics ingests)
    carry no comparable rate and are skipped."""
    def keyed(runs: List[dict]) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for r in runs:                  # index order = oldest first
            if r.get("kind") == "bench":
                out[r["config_key"]] = r
        return out

    ka, kb = keyed(a_runs), keyed(b_runs)
    rep = CompareReport(
        unmatched_a=sorted(set(ka) - set(kb)),
        unmatched_b=sorted(set(kb) - set(ka)))
    for key in sorted(set(ka) & set(kb)):
        rep.deltas.extend(_compare_one(ka[key], kb[key],
                                       rate_gate, wall_gate))
    return rep


def compare_selections(ledger, a: str, b: str, *,
                       rate_gate: float = 0.30,
                       wall_gate: float = 0.75) -> CompareReport:
    """Resolve two CLI selectors and compare. A selector is a run_id
    (``r0007``), a batch label (``b0002`` / ``BENCH_r03``), or a
    config_key substring (the latest matching run wins)."""
    return compare_runs(_select(ledger, a, "A"),
                        _select(ledger, b, "B"),
                        rate_gate=rate_gate, wall_gate=wall_gate)


def _select(ledger, sel: str, who: str) -> List[dict]:
    index = ledger.index()
    hit = [r for r in index if r.get("run_id") == sel]
    if hit:
        return hit
    hit = [r for r in index if r.get("batch") == sel]
    if hit:
        return hit
    hit = [r for r in index if sel in (r.get("config_key") or "")]
    if hit:
        return hit[-1:]     # latest run of the key
    from .ledger import LedgerError
    raise LedgerError(
        f"selector {who}={sel!r} matches no run_id, batch, or "
        f"config_key in this ledger (batches: {ledger.batches()})")


# -- single-run anomaly detectors -----------------------------------------

@dataclass
class Anomaly:
    """One detector firing — rendered as one pinned line, the
    TraceMismatch convention (never an array dump)."""
    kind: str
    subject: str            # bucket / world / stream the line names
    detail: str
    severity: str = "warn"

    def line(self) -> str:
        return f"ANOMALY {self.kind} [{self.subject}]: {self.detail}"

    def to_json(self) -> dict:
        return {"kind": self.kind, "subject": self.subject,
                "detail": self.detail, "severity": self.severity}


#: detector thresholds — overridable per call, defaults chosen so a
#: healthy smoke sweep (tests, CI) never fires
THRESHOLDS = dict(
    rollback_rate=0.5,      # spec rollbacks / (rollbacks + decisions)
    rollback_min=3,         # ... but never on fewer events than this
    integrity_min=3,        # detected corruptions before "storm"
    thrash_frac=0.5,        # rung changes / consecutive pairs
    thrash_min_decisions=8,
    util_floor=0.25,        # budget_efficiency / worlds_active_mean
    straggler_factor=4.0,   # supersteps vs fleet median
    straggler_min_worlds=4,
)


def detect_anomalies(scan=None, metrics_path: Optional[str] = None,
                     **overrides) -> List[Anomaly]:
    """Run every detector over a journal scan (a ``JournalState``)
    and/or a metrics JSONL stream. Read-only; returns pinned-line
    findings, empty when healthy."""
    th = dict(THRESHOLDS)
    unknown = set(overrides) - set(th)
    if unknown:
        raise ValueError(
            f"unknown anomaly thresholds {sorted(unknown)}; known: "
            f"{sorted(th)}")
    th.update(overrides)
    out: List[Anomaly] = []
    if scan is not None:
        out += _journal_anomalies(scan, th)
    if metrics_path is not None:
        out += _metrics_anomalies(metrics_path, th)
    return out


def _journal_anomalies(scan, th) -> List[Anomaly]:
    out: List[Anomaly] = []
    # rollback storm — speculation: rollbacks vs committed decisions
    rb = len(scan.spec_rollbacks)
    decs = sum(len(v) for v in scan.decisions.values())
    if rb >= th["rollback_min"]:
        rate = rb / (rb + decs) if (rb + decs) else 1.0
        if rate > th["rollback_rate"]:
            out.append(Anomaly(
                "rollback-storm", "speculation",
                f"{rb} causality rollbacks vs {decs} committed "
                f"decisions (rate {rate:.2f} > "
                f"{th['rollback_rate']:.2f}) — the window policy is "
                "betting past the link's real support "
                "(docs/speculation.md)"))
    # rollback storm — integrity: repeated detected corruptions
    iv = len(scan.integrity)
    if iv >= th["integrity_min"]:
        out.append(Anomaly(
            "rollback-storm", "integrity",
            f"{iv} detected-and-rolled-back state corruptions in one "
            f"run (>= {th['integrity_min']}) — an SDC-prone host "
            "(docs/integrity.md)", severity="error"))
    # rung thrash — per bucket, consecutive decision flip-flops
    for bucket, dl in sorted(scan.decisions.items()):
        if len(dl) < th["thrash_min_decisions"]:
            continue
        pairs = list(zip(dl, dl[1:]))
        changes = sum(1 for a, b in pairs
                      if a.get("rung_pin") != b.get("rung_pin"))
        frac = changes / len(pairs)
        if frac > th["thrash_frac"]:
            out.append(Anomaly(
                "rung-thrash", f"bucket {bucket}",
                f"rung pin changed on {changes}/{len(pairs)} "
                f"consecutive decisions (frac {frac:.2f} > "
                f"{th['thrash_frac']:.2f}) — the controller is "
                "oscillating, not adapting (docs/dispatch.md)"))
    # bucket_util collapse
    for bucket, u in sorted(scan.util.items()):
        for sig in ("budget_efficiency", "worlds_active_mean"):
            v = u.get(sig)
            if isinstance(v, (int, float)) and v < th["util_floor"]:
                out.append(Anomaly(
                    "bucket-util-collapse", f"bucket {bucket}",
                    f"{sig} {v:.3f} < floor {th['util_floor']:.2f} — "
                    "the pack is mis-bucketed: split skewed budgets "
                    "or re-pack early-quiescing worlds "
                    "(docs/sweeps.md)"))
    # quiescence stragglers — per-world supersteps vs the fleet median
    totals = {rid: int(res.get("supersteps", 0))
              for rid, res in scan.done.items()}
    if len(totals) >= th["straggler_min_worlds"]:
        import statistics
        med = statistics.median(totals.values())
        if med > 0:
            for rid, s in sorted(totals.items()):
                if s > th["straggler_factor"] * med:
                    out.append(Anomaly(
                        "quiescence-straggler", f"world {rid}",
                        f"{s} supersteps vs fleet median {med:g} "
                        f"(> {th['straggler_factor']:g}x) — this "
                        "world kept the bucket's scan alive long "
                        "after its siblings quiesced; re-pack it"))
    return out


def _metrics_anomalies(path: str, th) -> List[Anomaly]:
    """Detectors over a metrics JSONL stream alone (no journal): the
    speculation/integrity rollups and the decision sequence."""
    spec = {"committed": 0, "rollback": 0}
    integ = 0
    decisions: List[dict] = []
    with open(path) as f:
        lines = f.read().splitlines()
    for i, raw in enumerate(lines):
        if not raw.strip():
            continue
        try:
            rec = json.loads(raw)
        except json.JSONDecodeError as e:
            if i == len(lines) - 1:
                continue    # a torn FINAL line: a live writer caught
                            # mid-append — the journal crash model
            from .ledger import LedgerError
            raise LedgerError(
                f"{path} line {i + 1} is corrupt mid-file ({e}); "
                "refusing to under-count anomalies over damaged "
                "telemetry — a crash can only tear the last "
                "line") from None
        k = rec.get("kind")
        if k == "speculation" and rec.get("outcome") in spec:
            spec[rec["outcome"]] += 1
        elif k == "integrity" and rec.get("event") == "rollback":
            integ += 1
        elif k == "decision":
            decisions.append(rec)
    out: List[Anomaly] = []
    rb, ok = spec["rollback"], spec["committed"]
    if rb >= th["rollback_min"]:
        rate = rb / (rb + ok) if (rb + ok) else 1.0
        if rate > th["rollback_rate"]:
            out.append(Anomaly(
                "rollback-storm", os.path.basename(path),
                f"{rb} speculation rollbacks vs {ok} commits (rate "
                f"{rate:.2f} > {th['rollback_rate']:.2f}) "
                "(docs/speculation.md)"))
    if integ >= th["integrity_min"]:
        out.append(Anomaly(
            "rollback-storm", os.path.basename(path),
            f"{integ} integrity rollbacks (>= "
            f"{th['integrity_min']}) — an SDC-prone host",
            severity="error"))
    if len(decisions) >= th["thrash_min_decisions"]:
        pairs = list(zip(decisions, decisions[1:]))
        changes = sum(1 for a, b in pairs
                      if a.get("rung_pin") != b.get("rung_pin"))
        frac = changes / len(pairs)
        if frac > th["thrash_frac"]:
            out.append(Anomaly(
                "rung-thrash", os.path.basename(path),
                f"rung pin changed on {changes}/{len(pairs)} "
                f"consecutive decisions (frac {frac:.2f} > "
                f"{th['thrash_frac']:.2f}) (docs/dispatch.md)"))
    return out


def detect_target_anomalies(target: str, **overrides) -> List[Anomaly]:
    """CLI entry: ``target`` is a sweep journal dir (its metrics
    stream, when present, is read too) or a metrics JSONL file."""
    if os.path.isdir(target):
        from ..sweep.journal import SweepJournal
        j = SweepJournal(target)
        if not j.exists():
            from .ledger import LedgerError
            raise LedgerError(
                f"{target!r} holds no sweep journal (no "
                "journal.jsonl) and is not a metrics file")
        mpath = os.path.join(target, "metrics.jsonl")
        return detect_anomalies(
            scan=j.scan(),
            metrics_path=mpath if os.path.exists(mpath) else None,
            **overrides)
    return detect_anomalies(metrics_path=target, **overrides)
