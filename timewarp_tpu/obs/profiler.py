"""Optional ``jax.profiler`` integration.

The telemetry counters (obs/telemetry.py) answer *what the emulation
did*; the XLA profiler answers *where the chip time went*. This module
wraps the latter so callers can always write ``with
profile_session(logdir):`` — when profiling is unavailable (no
tensorboard-plugin-profile, an unsupported backend, a tunnel that
refuses the trace RPC) the session degrades to a warned no-op instead
of killing the run. Nothing here ever imports at engine-construction
time; the zero-overhead law is untouched.
"""

from __future__ import annotations

import contextlib
import logging
from contextlib import contextmanager
from typing import Optional

__all__ = ["profile_session", "annotate"]

_log = logging.getLogger("timewarp.obs")


@contextmanager
def profile_session(logdir: Optional[str]):
    """A ``jax.profiler`` trace session writing to ``logdir`` (view
    with TensorBoard or xprof). ``logdir=None`` — and any profiler
    failure — yields a plain no-op session; the emulation must never
    die for its own instrumentation."""
    if not logdir:
        yield None
        return
    try:
        import jax.profiler as _jp
        _jp.start_trace(logdir)
    except Exception as e:  # noqa: BLE001 — degrade, never kill the run
        _log.warning("jax.profiler session unavailable (%s); running "
                     "without a device profile", e)
        yield None
        return
    try:
        yield logdir
    finally:
        try:
            _jp.stop_trace()
        except Exception as e:  # noqa: BLE001
            _log.warning("jax.profiler stop_trace failed: %s", e)


def annotate(name: str):
    """A named ``TraceAnnotation`` context (shows up as a labeled span
    in the device profile), or a null context when unavailable."""
    try:
        from jax.profiler import TraceAnnotation
        return TraceAnnotation(name)
    except Exception:  # noqa: BLE001
        return contextlib.nullcontext()
