"""Persistent cross-run ledger: the fleet's measurement memory.

Every run already emits rich artifacts — BENCH_SCHEMA JSON lines,
schema-validated metrics JSONL, sweep journals — but each one is an
island: nothing reads *across* runs, so the bench trajectory lives in
hand-curated ``BENCH_r*.json`` files and a regression is invisible
until a human diffs two of them. Revati (PAPERS.md) frames the
emulator itself as a production serving system; SCALE-Sim TPU makes
utilization reporting the first-class objective — both presuppose a
durable measurement ledger. This module is that ledger, and the
standing home for the chip-round measurement debt the ROADMAP carries.

Layout — one directory, append-only::

    <ledger>/
      index.jsonl        # one line per ingested run (flushed+fsync'd)
      runs/<run_id>/
        record.json      # the full record incl. the raw source line

Every record carries a stable ``config_key`` (bench config name +
requested shape + platform — BENCH_SCHEMA v2 lines stamp their own;
v1 archives get a deterministic derivation, below) and the producing
``git_sha``, so cross-run joins are unambiguous. ``run_id`` is a
monotone ``rNNNN``; each ingest session shares a ``batch`` label
(``bNNNN``, or a caller-chosen name such as the seed artifacts'
``BENCH_r01``), which is what :mod:`~timewarp_tpu.obs.regress`
compares batch-against-batch.

Crash model: ``record.json`` is written atomically *before* the index
line is appended (the index append is the commitment point, same
discipline as the sweep journal); a torn final index line is dropped
on read — the run it described simply is not in the ledger.

CLI (``timewarp-tpu ledger``)::

    ledger add     --ledger DIR SOURCE...   # bench JSONL / metrics /
                                            # sweep journal dir
    ledger import  --ledger DIR FILE...     # BENCH_r0*.json artifacts
    ledger list    --ledger DIR [--config SUBSTR] [--json]
    ledger show    --ledger DIR RUN_ID
    ledger compare --ledger DIR A B [...]   # obs/regress.py
    ledger anomalies [--ledger DIR] TARGET  # obs/regress.py

``bench.py --ledger DIR`` auto-appends every emitted bench line (one
batch per bench invocation), so running the bench *is* recording it.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
from typing import Any, Dict, List, Optional

__all__ = ["LEDGER_SCHEMA", "LedgerError", "RunLedger",
           "derive_config_key", "resolve_git_sha", "ledger_main"]

#: index/record line schema — bumped when the record contract changes
LEDGER_SCHEMA = 1

#: index fields kept out of runs/<id>/record.json duplication: the
#: index line is the record minus the raw source line (kept slim so
#: `ledger list` scans stay cheap at thousands of runs)
_INDEX_DROP = ("line",)


class LedgerError(ValueError):
    """Bad ingest input or a self-contradictory ledger — never
    silently reconciled (the sweep-journal convention)."""


def resolve_git_sha(cwd: Optional[str] = None) -> str:
    """The producing commit, for cross-run provenance: ``TW_GIT_SHA``
    when set (hermetic CI), else ``git rev-parse``, else ``unknown``
    — a ledger outside a checkout still ingests, honestly marked."""
    env = os.environ.get("TW_GIT_SHA")
    if env:
        return env
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            cwd=cwd or os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        return "unknown"
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else "unknown"


def _slug(text: str) -> str:
    return re.sub(r"-+", "-",
                  re.sub(r"[^a-z0-9]+", "-", text.lower())).strip("-")


def derive_config_key(line: Dict[str, Any]) -> str:
    """The stable join key for one bench line. BENCH_SCHEMA >= 2
    lines stamp their own ``config_key`` (bench.py names the config +
    requested shape + platform); v1 archive lines (the r01–r05
    artifacts) get a deterministic derivation — the metric text minus
    its boilerplate unit phrase, slugged, plus the platform — so the
    historical trajectory joins under keys that cannot collide with
    differently-shaped runs."""
    key = line.get("config_key")
    if isinstance(key, str) and key:
        return key
    metric = line.get("metric") or line.get("config")
    if not isinstance(metric, str) or not metric:
        raise LedgerError(
            "bench line carries neither config_key nor metric/config "
            f"— not a bench line: {json.dumps(line)[:120]}")
    for noise in ("delivered-messages/sec/chip",
                  "delivered-messages/sec", "aggregate"):
        metric = metric.replace(noise, " ")
    return f"{_slug(metric)}|{line.get('platform', 'unknown')}"


class RunLedger:
    """Append-only run ledger over one directory (module docstring)."""

    def __init__(self, root: str) -> None:
        self.root = root
        self.index_path = os.path.join(root, "index.jsonl")
        self.runs_dir = os.path.join(root, "runs")
        #: highest run number seen (in-memory after the first scan,
        #: so multi-line ingest stays O(lines), not O(lines^2))
        self._max_run: Optional[int] = None

    # -- reading -----------------------------------------------------------

    def index(self) -> List[dict]:
        """Every index line, oldest first. A torn *final* line (crash
        mid-append) is dropped; earlier damage is corruption and
        fails loudly — the sweep journal's crash model."""
        if not os.path.exists(self.index_path):
            return []
        with open(self.index_path) as f:
            lines = f.read().splitlines()
        out: List[dict] = []
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError as e:
                if i == len(lines) - 1:
                    continue    # torn final append: the run is not in
                raise LedgerError(
                    f"ledger index {self.index_path!r} line {i + 1} "
                    f"is corrupt mid-file ({e}); a crash can only "
                    "tear the last line — this index has been "
                    "damaged externally") from None
        return out

    def runs(self, *, config_key: Optional[str] = None,
             batch: Optional[str] = None) -> List[dict]:
        """Index lines filtered by exact batch and/or config_key
        substring (keys embed shape + platform, so substring is the
        ergonomic selector)."""
        out = self.index()
        if batch is not None:
            out = [r for r in out if r.get("batch") == batch]
        if config_key is not None:
            out = [r for r in out
                   if config_key in (r.get("config_key") or "")]
        return out

    def get(self, run_id: str) -> dict:
        """The full record (raw source line included)."""
        path = os.path.join(self.runs_dir, run_id, "record.json")
        if not os.path.exists(path):
            known = [r["run_id"] for r in self.index()]
            raise LedgerError(
                f"ledger has no run {run_id!r} (known: "
                f"{known[-8:] if known else 'none — empty ledger'})")
        with open(path) as f:
            return json.load(f)

    def batches(self) -> List[str]:
        """Distinct batch labels, in first-seen order."""
        seen: List[str] = []
        for r in self.index():
            b = r.get("batch")
            if b and b not in seen:
                seen.append(b)
        return seen

    # -- writing -----------------------------------------------------------

    def new_batch(self) -> str:
        """The next free ``bNNNN`` label — one per ingest session
        (``bench.py --ledger`` takes one for its whole invocation).
        Two ingests racing the same ledger can still pick the same
        label (batches are selection labels, not identities — run
        ids never collide, see ``_commit``); pass an explicit
        ``--batch`` when parallel writers must stay separable."""
        mx = 0
        for b in self.batches():
            m = re.fullmatch(r"b(\d+)", b)
            if m:
                mx = max(mx, int(m.group(1)))
        return f"b{mx + 1:04d}"

    def _next_run_id(self) -> str:
        """The next free ``rNNNN``: max over the index AND over the
        ``runs/`` dir names — a crash between record write and index
        append leaves an orphan dir (the documented model: that run
        is not in the ledger), which must never be re-claimed."""
        if self._max_run is None:
            mx = 0
            for r in self.index():
                m = re.fullmatch(r"r(\d+)", r.get("run_id", ""))
                if m:
                    mx = max(mx, int(m.group(1)))
            if os.path.isdir(self.runs_dir):
                for name in os.listdir(self.runs_dir):
                    m = re.fullmatch(r"r(\d+)", name)
                    if m:
                        mx = max(mx, int(m.group(1)))
            self._max_run = mx
        self._max_run += 1
        return f"r{self._max_run:04d}"

    def _commit(self, rec: Dict[str, Any]) -> str:
        """Durably add one record: claim the run dir (mkdir is the
        atomic claim — a concurrent writer racing the same id loses
        the mkdir and takes the next number, so two ingests into one
        shared ledger can never clobber each other's records), then
        the atomic record.json, then the fsync'd index append (the
        commitment point)."""
        from ..utils.checkpoint import atomic_write
        while True:
            run_dir = os.path.join(self.runs_dir, rec["run_id"])
            try:
                os.makedirs(run_dir)
                break
            except FileExistsError:
                # another writer (or a crash orphan created since our
                # scan) holds this id — rescan and take the next
                self._max_run = None
                rec["run_id"] = self._next_run_id()

        def write(f):
            json.dump(rec, f, indent=1, sort_keys=True)
            f.write("\n")
        atomic_write(os.path.join(run_dir, "record.json"), write,
                     mode="w")
        slim = {k: v for k, v in rec.items() if k not in _INDEX_DROP}
        with open(self.index_path, "a") as f:
            f.write(json.dumps(slim, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())
        return rec["run_id"]

    def add_bench_line(self, line: Dict[str, Any], *,
                       batch: Optional[str] = None,
                       source: Optional[str] = None) -> str:
        """Ingest one BENCH_SCHEMA JSON line (v1 archives welcome —
        ``derive_config_key`` gives them a deterministic join key).
        Returns the new run_id."""
        if not isinstance(line, dict):
            raise LedgerError(
                f"bench line must be a JSON object, got "
                f"{type(line).__name__}")
        key = derive_config_key(line)
        os.makedirs(self.runs_dir, exist_ok=True)
        rec: Dict[str, Any] = {
            "ledger_schema": LEDGER_SCHEMA,
            "run_id": self._next_run_id(),
            "batch": batch or self.new_batch(),
            "kind": "bench",
            "config_key": key,
            "config": line.get("config"),
            "git_sha": line.get("git_sha", "unknown"),
            "bench_schema": line.get("schema"),
            "platform": line.get("platform"),
            "device_kind": line.get("device_kind"),
            "jax_version": line.get("jax_version"),
            "metric": line.get("metric"),
            "unit": line.get("unit"),
            "smoke": bool(line.get("smoke", False)),
            "source": source,
            "line": line,
        }
        # the comparable measurements ride the index line itself:
        # the rate (median-of-reps, with min/max bands when --reps
        # ran), the smoke wall seconds, and the serving layer's
        # admission throughput (bench.py serve_gossip — gateable now
        # that its causal explanation, the engine_builds/compiles
        # counters, rides the same line)
        # budget_efficiency / pad_waste_frac are the packing rollups
        # (sweep/journal.py util_rollup) the predictive-packing gate
        # compares (docs/sweeps.md "Predictive packing")
        for f in ("value", "min", "max", "reps", "seconds",
                  "admit_per_s", "budget_efficiency",
                  "pad_waste_frac"):
            if isinstance(line.get(f), (int, float)) \
                    and not isinstance(line.get(f), bool):
                rec[f] = line[f]
        return self._commit(rec)

    def add_search(self, journal_dir: str, *,
                   batch: Optional[str] = None) -> str:
        """Ingest a chaos-search campaign journal (timewarp_tpu/
        search/, docs/search.md) as the ``search`` kind: campaign
        identity (base config, objective, knobs, seed), per-
        generation progress, fork savings, and — when found — the
        counterexample and its minimized repro string, so found
        violations are queryable history."""
        from ..sweep.journal import SweepJournal
        j = SweepJournal(journal_dir)
        if not j.exists():
            raise LedgerError(
                f"{journal_dir!r} holds no campaign journal "
                "(no journal.jsonl)")
        recs = j.records()
        meta = next((r for r in recs
                     if r.get("ev") == "search_campaign"), None)
        if meta is None:
            raise LedgerError(
                f"{journal_dir!r} holds no search_campaign record — "
                "not a chaos-search journal (sweep journals ingest "
                "as the 'sweep' kind)")
        gens = [r for r in recs if r.get("ev") == "search_gen"]
        done = next((r for r in recs
                     if r.get("ev") == "search_done"), None)
        minimized = next((r for r in recs
                          if r.get("ev") == "search_minimized"), None)
        ce = next((r for r in recs
                   if r.get("ev") == "search_counterexample"), None)
        forks = [r for r in recs if r.get("ev") == "search_fork"]
        base = meta.get("base", {})
        os.makedirs(self.runs_dir, exist_ok=True)
        rec = {
            "ledger_schema": LEDGER_SCHEMA,
            "run_id": self._next_run_id(),
            "batch": batch or self.new_batch(),
            "kind": "search",
            "config_key": (f"search|{base.get('scenario', '?')}"
                           f"|{_slug(str(meta.get('objective')))}"
                           f"|s{meta.get('seed')}"),
            "git_sha": resolve_git_sha(journal_dir),
            "source": os.path.abspath(journal_dir),
            "search": {
                "objective": meta.get("objective"),
                "base": base,
                "population": meta.get("population"),
                "generations_planned": meta.get("generations"),
                "generations_run": len(gens),
                "seed": meta.get("seed"),
                "found": bool(done and done.get("found")),
                "evaluations": (done or {}).get("evaluations"),
                "counterexample": (ce or {}).get("faults"),
                "minimized": (minimized or {}).get("faults"),
                "fork": (done or {}).get("fork"),
                "forks": len(forks),
            },
        }
        return self._commit(rec)

    def add_serve(self, journal_dir: str, *,
                  batch: Optional[str] = None) -> str:
        """Ingest a service journal dir (serve/, docs/serving.md) as
        the ``serve`` kind: admission/steal/lease-reclaim/repack
        rollups, the per-host lease table, and the shared event-counts
        block — so a serving fleet's history is queryable next to
        bench and sweep runs."""
        from ..sweep.journal import SweepJournal, status_fields
        j = SweepJournal(journal_dir)
        if not j.exists():
            raise LedgerError(
                f"{journal_dir!r} holds no service journal "
                "(no journal*.jsonl)")
        scan = j.scan()
        if not (scan.hosts or scan.admits or scan.serve_buckets):
            raise LedgerError(
                f"{journal_dir!r} holds no serve_open/admit/lease "
                "records — not a service journal (sweep journals "
                "ingest as the 'sweep' kind)")
        open_rec = next((e for e in scan.events
                         if e.get("ev") == "serve_open"), None)
        host0 = (open_rec or {}).get("host") \
            or (sorted(scan.hosts) or ["?"])[0]
        os.makedirs(self.runs_dir, exist_ok=True)
        fields = status_fields(scan, len(scan.admits))
        # (features, budget, supersteps) training rows for the
        # packing forecaster (pack/predict.py) — assembled at ingest
        # so `pack fit` reads the index alone, never the journals
        from ..sweep.spec import RunConfig, SweepConfigError
        cfgs = []
        for a in scan.admits.values():
            try:
                cfgs.append(RunConfig.from_json(dict(a["config"]), 0))
            except (SweepConfigError, KeyError, TypeError):
                continue
        from ..pack.predict import training_rows
        pack_stats = training_rows(cfgs, scan.done)
        rec = {
            "ledger_schema": LEDGER_SCHEMA,
            "run_id": self._next_run_id(),
            "batch": batch or self.new_batch(),
            "kind": "serve",
            # stable across re-ingest of the same dir: the frontend
            # host + its journaled open ts anchor the identity
            "config_key": (f"serve|{host0}|"
                           f"{int((open_rec or {}).get('ts', 0))}"),
            "git_sha": resolve_git_sha(journal_dir),
            "source": os.path.abspath(journal_dir),
            "serve": {
                **fields.get("serve", {}),
                "completed": len(scan.done),
                "failed": sorted(scan.failed),
                "hosts": fields.get("hosts", {}),
                "events": scan.event_counts(),
                "utilization": scan.util,
                "pack_stats": pack_stats,
            },
        }
        return self._commit(rec)

    def add_sweep(self, journal_dir: str, *,
                  batch: Optional[str] = None) -> str:
        """Ingest a finished (or killed) sweep journal: worlds done/
        failed, retries, the event-counts block (identical to ``sweep
        status --json``'s ``events`` by construction), and the
        per-bucket utilization records."""
        from ..sweep.journal import SweepJournal, status_fields
        j = SweepJournal(journal_dir)
        if not j.exists():
            raise LedgerError(
                f"{journal_dir!r} holds no sweep journal "
                "(no journal.jsonl)")
        scan = j.scan()
        total = None
        pack_stats = []
        if os.path.exists(j.pack_path):
            with open(j.pack_path) as f:
                total = len(json.load(f))
            # forecaster training rows (pack/predict.py), assembled
            # at ingest so `pack fit` reads the index alone
            from ..pack.predict import training_rows
            from ..sweep.spec import SweepPack
            try:
                pack_stats = training_rows(
                    SweepPack.load(j.pack_path).configs, scan.done)
            except Exception:  # noqa: BLE001 — archival best-effort
                pack_stats = []
        os.makedirs(self.runs_dir, exist_ok=True)
        sha = scan.pack_sha or "unpacked"
        sweep_fields = status_fields(scan, total)
        sweep_fields["pack_stats"] = pack_stats
        rec = {
            "ledger_schema": LEDGER_SCHEMA,
            "run_id": self._next_run_id(),
            "batch": batch or self.new_batch(),
            "kind": "sweep",
            "config_key": f"sweep|{sha[:12]}",
            "git_sha": resolve_git_sha(journal_dir),
            "source": os.path.abspath(journal_dir),
            "sweep": sweep_fields,
        }
        return self._commit(rec)

    def add_metrics(self, path: str, *,
                    batch: Optional[str] = None) -> str:
        """Ingest a metrics JSONL stream (validated first — a stream
        the CI gate would reject must not enter the ledger): per-kind
        line counts plus the decision/speculation/integrity rollups
        the anomaly detectors read."""
        from .metrics import validate_metrics_file
        validate_metrics_file(path)     # raises, naming file + line
        kinds: Dict[str, int] = {}
        spec = {"committed": 0, "rollback": 0}
        integ = {"verified": 0, "rollback": 0}
        supersteps = 0
        run_label = None
        with open(path) as f:
            for raw in f:
                if not raw.strip():
                    continue
                rec = json.loads(raw)
                k = rec["kind"]
                kinds[k] = kinds.get(k, 0) + 1
                run_label = run_label or rec.get("run")
                if k == "supersteps":
                    supersteps += int(rec.get("supersteps", 0))
                elif k == "speculation" \
                        and rec.get("outcome") in spec:
                    spec[rec["outcome"]] += 1
                elif k == "integrity" and rec.get("event") in integ:
                    integ[rec["event"]] += 1
        os.makedirs(self.runs_dir, exist_ok=True)
        rec = {
            "ledger_schema": LEDGER_SCHEMA,
            "run_id": self._next_run_id(),
            "batch": batch or self.new_batch(),
            "kind": "metrics",
            "config_key": f"metrics|{run_label or _slug(os.path.basename(path))}",
            "git_sha": resolve_git_sha(os.path.dirname(path) or "."),
            "source": os.path.abspath(path),
            "metrics": {"kinds": kinds, "supersteps": supersteps,
                        "speculation": spec, "integrity": integ},
        }
        return self._commit(rec)

    def add_source(self, path: str, *,
                   batch: Optional[str] = None) -> List[str]:
        """Auto-detecting ingest of one source: a sweep journal dir,
        a metrics JSONL stream, a bench-artifact wrapper
        (``BENCH_r0N.json``: ``{"parsed": <line>, ...}``), or a file
        of bench JSON lines. Returns the new run_ids."""
        if os.path.isdir(path):
            # a journal dir is a sweep unless a FIRST record says it
            # is a chaos-search campaign (search/, docs/search.md) or
            # a service journal (serve/, docs/serving.md — the
            # frontend's per-host file opens with serve_open) —
            # sniffed from first lines only, so a large finished
            # journal is not fully parsed twice
            import glob as _glob
            firsts = []
            jp = os.path.join(path, "journal.jsonl")
            paths = ([jp] if os.path.exists(jp) else []) + sorted(
                p for p in _glob.glob(
                    os.path.join(path, "journal-*.jsonl"))
                if p != jp)
            for p in paths:
                with open(p) as f:
                    for line in f:
                        if line.strip():
                            try:
                                firsts.append(json.loads(line))
                            except json.JSONDecodeError:
                                pass
                            break
            evs = {f.get("ev") for f in firsts
                   if isinstance(f, dict)}
            if "search_campaign" in evs:
                return [self.add_search(path, batch=batch)]
            if "serve_open" in evs:
                return [self.add_serve(path, batch=batch)]
            return [self.add_sweep(path, batch=batch)]
        with open(path) as f:
            text = f.read()
        lines = [ln for ln in text.splitlines() if ln.strip()]
        if not lines:
            raise LedgerError(
                f"{path!r} is empty — the producing run wrote "
                "nothing (the empty-stream refusal, obs/metrics.py)")
        try:
            first = json.loads(lines[0])
        except json.JSONDecodeError:
            # a pretty-printed artifact is ONE object across lines
            first = json.loads(text)
            lines = [text]
        if isinstance(first, dict) and "parsed" in first:
            # the historical bench-artifact wrapper: the measured
            # line lives under "parsed", the round number under "n"
            batch = batch or _artifact_batch(path, first)
            return [self.add_bench_line(first["parsed"], batch=batch,
                                        source=os.path.abspath(path))]
        if isinstance(first, dict) and "kind" in first \
                and "schema" in first:
            return [self.add_metrics(path, batch=batch)]
        batch = batch or self.new_batch()
        out = []
        for ln in lines:
            out.append(self.add_bench_line(
                json.loads(ln) if isinstance(ln, str) else ln,
                batch=batch, source=os.path.abspath(path)))
        return out


def _artifact_batch(path: str, wrapper: Dict[str, Any]) -> str:
    """Batch label for a historical wrapper artifact: the file stem
    (``BENCH_r01``) — the trajectory `ledger list` should read as
    r01..r05 — falling back to the wrapper's round number."""
    stem = os.path.splitext(os.path.basename(path))[0]
    if stem:
        return stem
    return f"round{wrapper.get('n', '?')}"


# -- CLI ------------------------------------------------------------------

def _fmt_run(r: Dict[str, Any]) -> str:
    val = ""
    if "value" in r:
        val = f"  {r['value']:g} {r.get('unit') or ''}".rstrip()
        if "min" in r and "max" in r:
            val += f" [{r['min']:g}..{r['max']:g}]"
    elif "seconds" in r:
        val = f"  {r['seconds']:g} s"
    elif r.get("kind") == "sweep":
        sw = r.get("sweep", {})
        val = (f"  worlds {sw.get('completed')}/{sw.get('worlds')} "
               f"events {sw.get('events')}")
    elif r.get("kind") == "search":
        se = r.get("search", {})
        val = (f"  FOUND {se.get('minimized')!r}"
               if se.get("found") else "  no counterexample") + \
            f" gens {se.get('generations_run')}"
    elif r.get("kind") == "serve":
        sv = r.get("serve", {})
        val = (f"  admitted {sv.get('admitted')} completed "
               f"{sv.get('completed')} steals {sv.get('steals')} "
               f"repacks {sv.get('repacks')} hosts "
               f"{sorted(sv.get('hosts', {}))}")
    smoke = " smoke" if r.get("smoke") else ""
    return (f"{r['run_id']}  {r.get('batch', '?'):>10}  "
            f"{r.get('kind', '?'):7s}{smoke}  "
            f"git {r.get('git_sha', 'unknown')}  "
            f"{r.get('config_key', '?')}{val}")


def _add(argv, prog="ledger add", seed=False) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog=f"timewarp-tpu {prog}",
        description=("Seed the ledger from historical bench "
                     "artifacts (BENCH_r0*.json)" if seed else
                     "Ingest runs: bench JSONL, metrics JSONL, or "
                     "sweep journal dirs."))
    p.add_argument("--ledger", required=True,
                   help="ledger directory (created on first add)")
    p.add_argument("sources", nargs="+",
                   help="bench line file | metrics.jsonl | sweep "
                        "journal dir | chaos-search campaign journal "
                        "dir" + (" | BENCH_r0N.json artifact"
                                 if seed else ""))
    p.add_argument("--batch", default=None,
                   help="batch label (default: one fresh bNNNN per "
                        "invocation; artifact wrappers default to "
                        "their file stem)")
    args = p.parse_args(argv)
    led = RunLedger(args.ledger)
    # one shared batch per invocation for non-wrapper sources (so
    # `ledger compare bNNNN bMMMM` compares ingest-against-ingest);
    # wrapper artifacts pick their own file-stem batch (BENCH_r01...)
    batch = args.batch
    added: List[str] = []
    for src in args.sources:
        if _is_wrapper(src):
            added += led.add_source(src, batch=args.batch)
        else:
            if batch is None:
                batch = led.new_batch()
            added += led.add_source(src, batch=batch)
    by_id = {r["run_id"]: r for r in led.index()}
    for rid in added:
        print(_fmt_run(by_id[rid]))
    return 0


def _is_wrapper(path: str) -> bool:
    if os.path.isdir(path):
        return False
    try:
        with open(path) as f:
            return "parsed" in json.load(f)
    except (json.JSONDecodeError, OSError):
        return False


def _list(argv) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="timewarp-tpu ledger list",
        description="One line per ingested run, oldest first.")
    p.add_argument("--ledger", required=True)
    p.add_argument("--config", default=None,
                   help="config_key substring filter")
    p.add_argument("--batch", default=None, help="exact batch filter")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    runs = RunLedger(args.ledger).runs(config_key=args.config,
                                       batch=args.batch)
    if args.json:
        print(json.dumps({"runs": runs, "count": len(runs)}))
        return 0
    for r in runs:
        print(_fmt_run(r))
    print(f"({len(runs)} runs)")
    return 0


def _show(argv) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="timewarp-tpu ledger show",
        description="The full record of one run (raw line included).")
    p.add_argument("--ledger", required=True)
    p.add_argument("run_id")
    args = p.parse_args(argv)
    print(json.dumps(RunLedger(args.ledger).get(args.run_id),
                     indent=1, sort_keys=True))
    return 0


def _compare(argv) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="timewarp-tpu ledger compare",
        description="Noise-aware cross-run regression gate "
                    "(obs/regress.py): exit 1 on any gated "
                    "regression, one pinned line each.")
    p.add_argument("--ledger", required=True)
    p.add_argument("a", help="baseline: run_id | batch | config_key "
                             "substring (latest run wins)")
    p.add_argument("b", help="candidate: same selector forms")
    p.add_argument("--rate-gate", type=float, default=0.30,
                   help="relative rate drop that fails (default "
                        "0.30 — the tunnel swings ±12%%, PERF_r05.md)")
    p.add_argument("--wall-gate", type=float, default=0.75,
                   help="relative wall-time increase that fails "
                        "(default 0.75: a 2x slowdown always trips)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    from .regress import compare_selections
    led = RunLedger(args.ledger)
    report = compare_selections(led, args.a, args.b,
                                rate_gate=args.rate_gate,
                                wall_gate=args.wall_gate)
    if args.json:
        print(json.dumps(report.to_json()))
    else:
        for line in report.lines():
            print(line)
    return 1 if report.regressions else 0


def _anomalies(argv) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="timewarp-tpu ledger anomalies",
        description="Single-run anomaly detectors (obs/regress.py): "
                    "rollback storms, rung thrash, bucket_util "
                    "collapse, quiescence stragglers — one pinned "
                    "line each; exit 1 when any fire.")
    p.add_argument("target",
                   help="a ledger run_id (with --ledger), a sweep "
                        "journal dir, or a metrics.jsonl file")
    p.add_argument("--ledger", default=None)
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    from .regress import detect_target_anomalies
    target = args.target
    if args.ledger is not None and not os.path.exists(target):
        rec = RunLedger(args.ledger).get(target)
        if rec.get("kind") == "bench":
            raise SystemExit(
                f"ledger run {args.target!r} is a bench line — it "
                "carries no telemetry/journal to detect over; point "
                "at a sweep journal dir or metrics.jsonl (or a "
                "sweep/metrics ledger run)")
        target = rec.get("source")
        if not target or not os.path.exists(target):
            raise SystemExit(
                f"ledger run {args.target!r} names source "
                f"{target!r}, which does not exist here — run "
                "anomalies where the artifact lives, or pass its "
                "path directly")
    findings = detect_target_anomalies(target)
    if args.json:
        print(json.dumps({"anomalies": [f.to_json() for f in findings],
                          "count": len(findings)}))
    else:
        for f in findings:
            print(f.line())
        print(f"({len(findings)} anomalies)")
    return 1 if findings else 0


def ledger_main(argv) -> int:
    cmds = {"add": lambda rest: _add(rest),
            "import": lambda rest: _add(rest, prog="ledger import",
                                        seed=True),
            "list": _list, "show": _show,
            "compare": _compare, "anomalies": _anomalies}
    if not argv or argv[0] not in cmds:
        raise SystemExit(
            "usage: timewarp-tpu ledger "
            "add|import|list|show|compare|anomalies ... "
            "(docs/observability.md 'Fleet observability')")
    try:
        return cmds[argv[0]](argv[1:])
    except (LedgerError, OSError, json.JSONDecodeError) as e:
        # the CLI convention everywhere else (test_zgrammar): exit 1
        # with the actionable message, never a raw traceback
        raise SystemExit(str(e)) from None
