"""Live sweep watch: a read-only tail over a running sweep's journal
and metrics streams.

``timewarp-tpu sweep status`` is a snapshot you poll by hand;
``timewarp-tpu sweep watch`` attaches to the journal directory and
renders refreshing aggregates while the sweep runs: buckets in
flight, worlds done (and done/sec), retry / speculation-rollback /
integrity-violation counts, utilization — the mission-control face
of the fleet.

Hard properties, by construction:

- **Read-only.** The watcher opens ``journal.jsonl`` /
  ``metrics.jsonl`` / ``pack.json`` for reading only — it can never
  perturb the sweep, its journal, or the survival law's compare
  surface (a post-run ``sweep resume --verify`` is oblivious to any
  number of attached watchers).
- **Torn-tail tolerant.** The journal's appends are whole fsync'd
  lines, but a watcher can catch one mid-write: :class:`TailReader`
  consumes only newline-complete lines and leaves a torn tail in
  place for the next poll — the same crash model
  :meth:`~timewarp_tpu.sweep.journal.SweepJournal.records` applies
  to the final line, incrementalized.
- **Status-equal.** Records fold through
  :meth:`~timewarp_tpu.sweep.journal.JournalState.apply` — the SAME
  fold ``sweep status`` scans with — and the snapshot's shared
  fields come from the same :func:`~timewarp_tpu.sweep.journal.
  status_fields` assembly, so a watcher's final aggregates equal
  ``sweep status --json`` exactly (pinned in
  tests/test_zzzzzzzledger.py).

Output contract: plain append-only stdout lines, one per refresh in
which anything changed — no escape codes, no keybinds, no terminal
control — so ``sweep watch | tee`` and CI logs read identically to a
terminal (``--json`` swaps the text line for one JSON object per
refresh).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ..sweep.journal import JournalState, status_fields

__all__ = ["TailReader", "SweepWatch"]


class TailReader:
    """Incremental, torn-tail-tolerant JSONL reader (read-only).

    Consumes bytes from a growing file in whole newline-terminated
    lines; an incomplete tail (a writer caught mid-append) stays
    unconsumed until its newline lands. A complete line that fails to
    parse is counted in ``parse_errors``, never raised — a watcher
    must keep watching."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._off = 0
        self.parse_errors = 0

    def poll(self) -> List[dict]:
        """Every newly completed record since the last poll."""
        if not os.path.exists(self.path):
            return []
        with open(self.path, "rb") as f:
            f.seek(self._off)
            buf = f.read()
        end = buf.rfind(b"\n")
        if end < 0:
            return []               # nothing complete yet
        chunk = buf[:end + 1]
        self._off += end + 1
        out: List[dict] = []
        for raw in chunk.splitlines():
            if not raw.strip():
                continue
            try:
                out.append(json.loads(raw))
            except json.JSONDecodeError:
                self.parse_errors += 1
        return out


class SweepWatch:
    """Fold a sweep directory's streams into refreshing aggregates."""

    def __init__(self, journal_dir: str) -> None:
        self.root = journal_dir
        #: journal tails, one per file — multi-host journals
        #: (journal-<host>.jsonl, serve/ + docs/serving.md) are
        #: discovered per poll so a host that joins mid-watch is
        #: picked up; single-host dirs tail journal.jsonl exactly as
        #: before
        self._journal_tails: Dict[str, TailReader] = {}
        self.metrics = TailReader(os.path.join(journal_dir,
                                               "metrics.jsonl"))
        self.state = JournalState()
        self.finished = False
        #: buckets started but not yet done/split — "in flight"
        self._open_buckets: set = set()
        self._total_worlds: Optional[int] = None
        #: metrics-stream aggregates (kind counts + superstep total)
        self.metric_kinds: Dict[str, int] = {}
        self.metric_supersteps = 0
        self._t0 = time.monotonic()
        self._done0: Optional[int] = None

    # -- folding -----------------------------------------------------------

    def _apply_journal(self, rec: Dict[str, Any]) -> None:
        self.state.apply(rec)       # the one shared fold (journal.py)
        ev = rec.get("ev")
        if ev == "bucket_start":
            self._open_buckets.add(rec.get("bucket"))
        elif ev in ("bucket_done", "bucket_split"):
            self._open_buckets.discard(rec.get("bucket"))
        elif ev in ("sweep_done", "serve_done"):
            self.finished = True

    def _apply_metrics(self, rec: Dict[str, Any]) -> None:
        k = rec.get("kind")
        if not isinstance(k, str):
            return
        self.metric_kinds[k] = self.metric_kinds.get(k, 0) + 1
        if k == "supersteps":
            s = rec.get("supersteps")
            if isinstance(s, int):
                self.metric_supersteps += s

    def _poll_journal(self) -> List[dict]:
        """New records across every journal file, merge-sorted by the
        same ``(ts, host, seq)`` key :meth:`SweepJournal.records`
        uses — so a watch over a finished multi-host journal folds in
        the exact order ``sweep status``'s scan does. (A LIVE
        multi-host watch can see cross-poll inversions — a slow
        host's old record arriving after a fast host's new one — but
        the fold is commutative on everything except the loud
        double-journal refusals, which compare content, not order.)"""
        from ..sweep.journal import SweepJournal, merge_key
        fresh: List[dict] = []
        for p in SweepJournal(self.root).journal_files():
            tail = self._journal_tails.get(p)
            if tail is None:
                tail = self._journal_tails[p] = TailReader(p)
            fresh.extend(tail.poll())
        fresh.sort(key=merge_key)
        return fresh

    @property
    def parse_errors_total(self) -> int:
        return (sum(t.parse_errors
                    for t in self._journal_tails.values())
                + self.metrics.parse_errors)

    def poll(self) -> Dict[str, Any]:
        """Consume everything new and return the current snapshot."""
        for rec in self._poll_journal():
            self._apply_journal(rec)
        for rec in self.metrics.poll():
            self._apply_metrics(rec)
        if self._total_worlds is None:
            pack = os.path.join(self.root, "pack.json")
            if os.path.exists(pack):
                try:
                    with open(pack) as f:
                        self._total_worlds = len(json.load(f))
                except (json.JSONDecodeError, OSError):
                    pass            # mid-atomic-write; next poll
        done = len(self.state.done)
        if self._done0 is None:
            # worlds completed before we attached don't count toward
            # the observed rate — only progress we actually saw
            self._done0 = done
        return self.snapshot()

    # -- snapshots ---------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The shared ``sweep status --json`` fields (identical by
        construction: same fold, same assembly) plus watch-only
        extras under keys status does not use."""
        total = self._total_worlds
        if total is None and self.state.admits:
            # a serve journal has no pack — the admission ledger is
            # the world count (exactly what `sweep status` uses)
            total = len(self.state.admits)
        snap = status_fields(self.state, total)
        elapsed = time.monotonic() - self._t0
        seen = len(self.state.done) - (self._done0 or 0)
        snap["watch"] = {
            "buckets_in_flight": sorted(
                b for b in self._open_buckets if b is not None),
            "elapsed_s": round(elapsed, 3),
            "worlds_done_per_s": round(seen / elapsed, 4)
            if elapsed > 0 else 0.0,
            "finished": self.finished,
            "metrics_kinds": dict(self.metric_kinds),
            "metrics_supersteps": self.metric_supersteps,
            "parse_errors": self.parse_errors_total,
        }
        return snap

    def render(self, snap: Dict[str, Any]) -> str:
        """One plain text line per refresh (module docstring output
        contract)."""
        w = snap["watch"]
        worlds = snap["worlds"] if snap["worlds"] is not None else "?"
        ev = snap["events"]
        util = snap["utilization"]
        parts = [
            f"worlds {snap['completed']}/{worlds} done"
            + (f", {len(snap['failed'])} failed" if snap["failed"]
               else ""),
            f"buckets {len(w['buckets_in_flight'])} in flight / "
            f"{len(snap['buckets_done'])} done",
            f"retries {snap['retries']}",
            "events "
            f"decision={ev['dispatch_decision']} "
            f"spec_rollback={ev['spec_rollback']} "
            f"integrity={ev['integrity_violation']}",
        ]
        if util:
            import statistics
            eff = statistics.mean(
                u.get("budget_efficiency", 1.0)
                for u in util.values())
            parts.append(f"util eff {eff:.2f}")
            builds = sum(u.get("engine_builds", 0)
                         for u in util.values())
            if builds:
                # the zero-recompile serving law's live face: builds
                # should track bucket count, never admission count
                parts.append(f"engine builds {builds}")
        if w["metrics_kinds"]:
            parts.append(
                f"metrics {sum(w['metrics_kinds'].values())} lines")
        hosts = snap.get("hosts")
        if hosts:
            # the serving fleet's per-host line: leases held,
            # heartbeat AGE (derived at render time from the folded
            # ts — the folded fields themselves stay deterministic),
            # stolen-bucket counts
            now = time.time()
            bits = []
            for name, h in hosts.items():
                hb = h.get("last_heartbeat")
                age = f"{now - hb:.1f}s" if hb is not None else "?"
                bits.append(f"{name}:{len(h['leases'])}lease"
                            f"/hb {age}"
                            + (f"/stole {h['stolen']}"
                               if h["stolen"] else ""))
            parts.append("hosts " + " ".join(bits))
        parts.append(f"{w['worlds_done_per_s']:g} worlds/s")
        status = "DONE" if w["finished"] else "live"
        return f"sweep {status} | " + " | ".join(parts)
