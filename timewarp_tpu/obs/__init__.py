"""Opt-in observability: on-device telemetry, metrics, Perfetto traces.

The emulator at production scale is a black box in flight unless it
reports on itself — SCALE-Sim TPU (PAPERS.md) reports utilization per
workload so packing decisions are measurable, and Revati frames the
emulator as a *serving* system, which demands runtime observability.
This package is that sensor layer, under one hard contract:

**Zero overhead when off, bit-exact when on.** Every engine takes
``telemetry="off"|"counters"|"full"``. ``"off"`` (the default) lowers
to the exact pre-telemetry jaxpr — not "cheap", *absent* — and every
mode produces bit-identical digests, traces, and checkpoints, because
telemetry planes are *derived only from values the superstep already
computes* and ride as extra scan outputs that feed nothing back
(tests/test_zztelemetry.py pins both halves of the law).

Layers:

- :mod:`~timewarp_tpu.obs.telemetry` — the on-device per-superstep
  counter row (:class:`TelemetryRow`) and its host-side decode
  (:class:`TelemetryFrames`): active senders, selected routing rung,
  mailbox fill high-water, per-world quiescence slack, route/fault
  drop deltas.
- :mod:`~timewarp_tpu.obs.metrics` — :class:`MetricsRegistry`, a
  schema-validated JSONL metrics stream aggregating chunk-flushed
  telemetry, spans, and run summaries (``python -m
  timewarp_tpu.obs.metrics validate FILE`` is the CI gate).
- :mod:`~timewarp_tpu.obs.perfetto` — :class:`TraceBuilder`, a
  Chrome-trace/Perfetto exporter: wall-clock spans (sweep attempts,
  retries, checkpoints, journal fsyncs, jit compiles) on one process
  track, virtual-time superstep counters on another. Open the file at
  https://ui.perfetto.dev.
- :mod:`~timewarp_tpu.obs.profiler` — optional ``jax.profiler``
  session wrapping with named annotations (degrades to a no-op when
  profiling is unavailable).
- :mod:`~timewarp_tpu.obs.flight` — the causal flight recorder:
  ``record="off"|"deliveries"|"full"`` on every scan-driver engine
  threads a bounded per-superstep event plane (delivered messages;
  full adds sends and fault actions) through the traced scan, under
  the same zero-overhead/bit-exactness contract, drained into a
  schema'd JSONL event log.
- :mod:`~timewarp_tpu.obs.query` — causal queries over a recorded
  log: reconstruct a delivery's full chain (send → fault windows →
  delivery) and draw it as Perfetto flow arrows. CLI: ``timewarp-tpu
  explain``.
- :mod:`~timewarp_tpu.obs.bisect` — divergence bisection: binary-
  search two runs' per-chunk digest chains to the first diverging
  chunk, re-run it recorded, and report the first diverging
  superstep, field, and event delta in one pinned line. CLI:
  ``timewarp-tpu bisect``.
- :mod:`~timewarp_tpu.obs.ledger` — the persistent cross-run
  measurement ledger: git-sha-stamped, ``config_key``-joined ingest
  of bench lines, sweep journals, and metrics streams into one
  append-only index + per-run artifact dirs. CLI: ``timewarp-tpu
  ledger add|import|list|show|compare|anomalies``; ``bench.py
  --ledger DIR`` auto-appends every bench line.
- :mod:`~timewarp_tpu.obs.regress` — noise-aware cross-run
  regression gates (median-of-reps with min/max spread bands,
  per-metric relative-change gates) and single-run anomaly
  detectors (rollback storms, rung thrash, bucket_util collapse,
  quiescence stragglers), each finding one pinned line.
- :mod:`~timewarp_tpu.obs.watch` — the live, read-only sweep tail
  behind ``timewarp-tpu sweep watch``: torn-tail-tolerant
  incremental readers over the journal + metrics streams, folded
  through the SAME :class:`~timewarp_tpu.sweep.journal.JournalState`
  fold as ``sweep status`` (the two surfaces agree by construction).

docs/observability.md is the user-facing guide ("Fleet
observability" covers the cross-run plane).
"""

from .bisect import (DivergenceReport, bisect_engines, chain_bisect,
                     first_trail_divergence)
from .flight import (RECORD_MODES, FlightLog, FlightRecorderMixin,
                     FlightWriter, RecordRow, concat_flight,
                     decode_flight, load_flight_jsonl, validate_record)
from .ledger import (LEDGER_SCHEMA, LedgerError, RunLedger,
                     derive_config_key, resolve_git_sha)
from .metrics import (METRICS_SCHEMA, MetricsRegistry, validate_line,
                      validate_metrics_file)
from .perfetto import TraceBuilder
from .profiler import annotate, profile_session
from .query import (add_flight_flows, chain_lines, explain_delivery,
                    find_deliveries)
from .regress import (Anomaly, CompareReport, Delta, compare_runs,
                      compare_selections, detect_anomalies,
                      detect_target_anomalies)
from .telemetry import (TELEMETRY_MODES, TelemetryFrames, TelemetryRow,
                        decode_frames, summarize_frames, validate_mode)
from .watch import SweepWatch, TailReader

__all__ = [
    "TELEMETRY_MODES", "TelemetryRow", "TelemetryFrames",
    "decode_frames", "summarize_frames", "validate_mode",
    "METRICS_SCHEMA", "MetricsRegistry", "validate_line",
    "validate_metrics_file",
    "TraceBuilder", "profile_session", "annotate",
    "RECORD_MODES", "RecordRow", "FlightLog", "FlightWriter",
    "FlightRecorderMixin", "validate_record", "decode_flight",
    "concat_flight", "load_flight_jsonl",
    "explain_delivery", "find_deliveries", "chain_lines",
    "add_flight_flows",
    "DivergenceReport", "bisect_engines", "chain_bisect",
    "first_trail_divergence",
    "LEDGER_SCHEMA", "LedgerError", "RunLedger", "derive_config_key",
    "resolve_git_sha",
    "Delta", "Anomaly", "CompareReport", "compare_runs",
    "compare_selections", "detect_anomalies",
    "detect_target_anomalies",
    "SweepWatch", "TailReader",
]
