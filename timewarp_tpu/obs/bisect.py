"""Divergence bisection: find WHERE two runs part ways, exactly.

The repo can *detect* divergence — TraceMismatch, digest chains, the
integrity detection law — but detection alone answers "something
differs", not "what happened at t". This module turns the existing
replay machinery (runs are pure functions of config + seed, so any
prefix is re-runnable bit-for-bit) into a localizer:

1. **Chain phase** — run both sides chunk by chunk, folding a digest
   per chunk into a sha256 chain (state digests for same-engine
   comparisons — they see payload-only corruption the trace digests
   cannot; trace-row chains for cross-engine comparisons, where state
   layouts legitimately differ). The chains are *prefix-consistent*
   (``chain[i]`` equal ⇒ all earlier entries equal), so the first
   diverging chunk falls to a **binary search** over the chain —
   :func:`chain_bisect`.
2. **Replay phase** — re-run both sides to that chunk's entry (pure
   replay; injected ``flip:`` corruption re-fires deterministically),
   then run the one diverging chunk again with the flight recorder
   on (``record=``, obs/flight.py) and traces enabled.
3. **Diff phase** — the chunk's trace rows give the first diverging
   superstep and field; the two flight logs give the specific message
   events that differ.

The result is ONE pinned diagnostic line — chunk, superstep, field,
event delta — extending the TraceMismatch format (trace/events.py),
never an array dump (tests/test_zzzzzflight.py pins it the way
tests/test_zzdiag.py pins TraceMismatch). CLI: ``timewarp-tpu
bisect`` (docs/observability.md).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Tuple

import numpy as np

__all__ = ["DivergenceReport", "chain_bisect", "bisect_engines",
           "first_trail_divergence"]


@dataclass
class DivergenceReport:
    """Where two runs first part ways. ``line()`` is the pinned
    one-line diagnostic; everything else is the structured view the
    CLI emits as JSON."""
    a_name: str
    b_name: str
    chunk: int                       # first diverging chunk (0-based)
    chunk_steps: Tuple[int, int]     # that chunk's superstep span
    superstep: Optional[int] = None  # run-global first diverging row
    t_us: Optional[int] = None
    fields: Optional[str] = None     # "recv_hash: 1 != 2" style
    only_a: int = 0                  # events only the A log holds
    only_b: int = 0
    first_delta: Optional[str] = None
    basis: str = "state"             # what the chains digested
    rows_compared: bool = False      # did the re-run diff trace rows?

    def line(self) -> str:
        """The pinned diagnostic: one line, both names, scalar values
        only — the TraceMismatch contract extended with the chunk and
        the event delta."""
        lo, hi = self.chunk_steps
        where = f"chunk {self.chunk} (supersteps {lo}..{hi})"
        if self.superstep is not None:
            where += f", superstep {self.superstep}"
            if self.t_us is not None:
                where += f" (t={self.t_us})"
        msg = f"{where}: {self.a_name} != {self.b_name}"
        if self.fields:
            msg += f" — {self.fields}"
        elif self.basis == "state" and self.rows_compared:
            msg += " — state digests diverge with identical trace " \
                   "rows (a non-observable plane, e.g. a payload word)"
        else:
            msg += f" — the {self.basis} digest chains diverge " \
                   "(the chunk re-run yielded no trace rows to diff)"
        if self.only_a or self.only_b:
            msg += (f"; events: {self.only_a} only-in-{self.a_name}, "
                    f"{self.only_b} only-in-{self.b_name}")
            if self.first_delta:
                msg += f", first: {self.first_delta}"
        return msg

    def to_json(self) -> dict:
        return {"a": self.a_name, "b": self.b_name,
                "basis": self.basis,
                "chunk": self.chunk,
                "chunk_steps": list(self.chunk_steps),
                "superstep": self.superstep, "t_us": self.t_us,
                "fields": self.fields, "only_a": self.only_a,
                "only_b": self.only_b, "first_delta": self.first_delta,
                "line": self.line()}


def chain_bisect(chain_a, chain_b) -> Optional[int]:
    """First index where two prefix-consistent digest chains differ —
    O(log n) compares (each chain entry folds everything before it,
    so equality at i implies prefix equality). Returns None when the
    chains agree entry-for-entry AND have equal length; a shorter
    chain that is a prefix of the longer diverges at its end (one
    side kept running — that IS the divergence)."""
    n = min(len(chain_a), len(chain_b))
    lo, hi = 0, n
    while lo < hi:
        mid = (lo + hi) // 2
        if chain_a[mid] == chain_b[mid]:
            lo = mid + 1
        else:
            hi = mid
    if lo < n:
        return lo
    if len(chain_a) != len(chain_b):
        return n
    return None


def _fresh(inject):
    """Each phase needs a FRESH injector (FlipInjector fires once);
    ``inject`` is a zero-arg factory, or None."""
    return None if inject is None else inject()


def _chain_run(engine, budget: int, chunk: int, inject, basis: str,
               stop_before: Optional[int] = None):
    """Run ``engine`` (fresh state) chunk by chunk. Returns
    ``(chain_per_chunk, steps_after_chunk, state, next_inject_applied)``
    where ``chain_per_chunk[i]`` is the sha256 chain value AFTER chunk
    i and ``steps_after_chunk[i]`` the cumulative superstep count.
    With ``stop_before=c`` the loop exits at chunk c's ENTRY — with
    c's injection (if any) already applied to the returned state,
    exactly as the full run would have."""
    from ..integrity.digest import (VERIFY_CHAIN_ZERO,
                                    chain_state_digest, host_digests)
    from ..sweep.spec import DIGEST_ZERO, chain_digest
    st = engine.init_state()
    chain = []
    steps = []
    cur = VERIFY_CHAIN_ZERO if basis == "state" else DIGEST_ZERO
    done = 0
    ci = 0
    while True:
        remaining = budget - done
        active = bool(np.asarray(
            _get(engine.world_active(st))).any()) and remaining > 0
        if inject is not None and (active or stop_before == ci):
            mut = inject(ci, st)
            if mut is not None:
                st = mut
        if stop_before == ci:
            return chain, steps, st, True
        if not active:
            return chain, steps, st, False
        st, tr = engine.run(int(min(remaining, chunk)), state=st)
        done += len(tr)
        if basis == "state":
            cur = chain_state_digest(
                cur, host_digests(st, getattr(engine, "batch",
                                              None))[0])
        else:
            cur = chain_digest(cur, tr)
        chain.append(cur)
        steps.append(done)
        ci += 1


def bisect_engines(make_a: Callable, make_b: Callable, budget: int,
                   *, chunk: int = 64, names=("a", "b"),
                   inject_a=None, inject_b=None, basis: str = "state",
                   record: str = "full"
                   ) -> Optional[DivergenceReport]:
    """Bisect two runs to their first divergence (module docstring).

    ``make_a`` / ``make_b`` build a FRESH engine, accepting a
    ``record=`` keyword (the chain phase runs ``record="off"`` — the
    zero-overhead law makes it free; the diverging chunk re-runs with
    ``record=record``). ``inject_a`` / ``inject_b`` are zero-arg
    factories of deterministic corruption hooks (``FlipInjector``
    factories — each phase needs a fresh one; the flip re-fires at
    the same chunk on replay, which is what makes the corrupted run
    re-runnable evidence). ``basis="state"`` chains full state
    digests (same-engine comparisons — sees payload-only divergence);
    ``"trace"`` chains trace rows (cross-engine comparisons, where
    state layouts legitimately differ). Returns None when the runs
    are bit-identical at every chunk boundary."""
    if basis not in ("state", "trace"):
        raise ValueError(f"basis must be 'state' or 'trace', "
                         f"got {basis!r}")
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    a_name, b_name = names
    # ONE off-mode engine per side serves the guard, the chain phase,
    # and the replay-to-entry below — runs are pure functions of
    # config + state, and construction (sanitize, fault lowering,
    # topology) is the expensive part
    eng_a, eng_b = make_a(record="off"), make_b(record="off")
    for eng in (eng_a, eng_b):
        if getattr(eng, "batch", None) is not None:
            raise ValueError(
                "bisect_engines localizes one run's divergence; "
                "batched fleets bisect per world (slice the config "
                "solo — bit-identical by the batch exactness law)")
    ch_a, steps_a, _, _ = _chain_run(eng_a, budget, chunk,
                                     _fresh(inject_a), basis)
    ch_b, steps_b, _, _ = _chain_run(eng_b, budget, chunk,
                                     _fresh(inject_b), basis)
    c = chain_bisect(ch_a, ch_b)
    if c is None:
        return None
    lo = steps_a[c - 1] if c > 0 else 0
    hi = steps_a[c] if c < len(steps_a) else (
        steps_b[c] if c < len(steps_b) else lo)

    # replay to the diverging chunk's entry (pure replay — chunks
    # before c are bit-identical by the chain), then run THAT chunk
    # with the flight recorder + traces on
    def chunk_rerun(off_eng, make, inject):
        _, _, st, _ = _chain_run(off_eng, budget, chunk,
                                 _fresh(inject), basis, stop_before=c)
        eng = make(record=record)
        remaining = max(budget - lo, 0)
        tr = log = None
        if remaining and bool(np.asarray(
                _get(eng.world_active(st))).any()):
            try:
                _, tr = eng.run(int(min(remaining, chunk)), state=st)
            finally:
                log = eng.last_run_flight
        return tr, log
    tr_a, log_a = chunk_rerun(eng_a, make_a, inject_a)
    tr_b, log_b = chunk_rerun(eng_b, make_b, inject_b)

    rep = DivergenceReport(a_name=a_name, b_name=b_name, chunk=c,
                           chunk_steps=(lo, hi), basis=basis)
    from ..trace.events import _FIELDS
    if (tr_a is None) != (tr_b is None):
        # one side had already quiesced at this chunk's entry — that
        # asymmetry IS the divergence (the strict-prefix chain case)
        quiet, ran = ((a_name, b_name) if tr_a is None
                      else (b_name, a_name))
        rep.fields = (f"{quiet} had already quiesced at this "
                      f"chunk's entry while {ran} kept running")
    elif tr_a is not None and tr_b is not None:
        rep.rows_compared = True
        m = min(len(tr_a), len(tr_b))
        for i in range(m):
            ra, rb = tr_a.row(i), tr_b.row(i)
            if ra != rb:
                rep.superstep = lo + i
                rep.t_us = int(ra[0])
                rep.fields = ", ".join(
                    f"{f}: {x} != {y}" for f, x, y in
                    zip(_FIELDS, ra, rb) if x != y)
                break
        else:
            if len(tr_a) != len(tr_b):
                rep.superstep = lo + m
                rep.fields = (f"trace length: {a_name} ran "
                              f"{len(tr_a)} supersteps, {b_name} "
                              f"{len(tr_b)}")
    if log_a is not None and log_b is not None:
        ka, kb = log_a.keyset(), log_b.keyset()
        rep.only_a, rep.only_b = len(ka - kb), len(kb - ka)
        delta = sorted((ka - kb) | (kb - ka),
                       key=lambda e: (e[4], e[0], e[1], e[2]))
        if delta:
            from .flight import ACTION_NAMES, EV_FAULT, KIND_NAMES
            k, src, dst, send_t, t, tag = delta[0]
            name = KIND_NAMES.get(k, str(k))
            if k == EV_FAULT:
                name += f"/{ACTION_NAMES.get(tag, tag)}"
            rep.first_delta = (f"{name} src={src} dst={dst} "
                               f"send_t={send_t} t={t}")
    return rep


def first_trail_divergence(trail, solo_trace) -> Optional[dict]:
    """The sweep ``--verify`` auto-bisect (sweep/cli.py): compare a
    world's journaled per-chunk digest trail (``[[supersteps,
    chain_hex], ...]`` — the prefix values of the row chain at the
    bucket's chunk boundaries) against the solo twin's trace,
    re-chained to the same row counts. Returns the first diverging
    chunk (index, superstep span, both chain values), or None when
    the trail agrees everywhere (the divergence then lies past the
    journaled chunks — e.g. in the counters)."""
    from ..sweep.spec import DIGEST_ZERO, chain_digest

    class _Slice:
        # chain_digest folds rows [0, n) of a trace-like view
        def __init__(self, tr, a, b):
            self.tr, self.a, self.b = tr, a, b

        def __len__(self):
            return self.b - self.a

        def row(self, i):
            return self.tr.row(self.a + i)

    cur = DIGEST_ZERO
    prev_steps = 0
    for k, (steps, want) in enumerate(trail):
        steps = int(steps)
        if steps > len(solo_trace):
            return {"chunk": k, "supersteps": [prev_steps, steps],
                    "streamed": want,
                    "solo": f"(solo ran only {len(solo_trace)} "
                            "supersteps)"}
        cur = chain_digest(cur, _Slice(solo_trace, prev_steps, steps))
        if cur != want:
            return {"chunk": k, "supersteps": [prev_steps, steps],
                    "streamed": want, "solo": cur}
        prev_steps = steps
    return None


def _get(x):
    import jax
    return jax.device_get(x)
