"""MetricsRegistry: a schema-validated JSONL metrics stream.

One line per observation, every line self-describing::

    {"schema": 2, "kind": "supersteps", "label": "...", ...}

Kinds:

- ``supersteps`` — one chunk of per-superstep telemetry, aggregated
  (obs/telemetry.py ``summarize_frames``): supersteps covered, virtual
  time span, load-signal min/mean/max, drop-counter sums, minimum
  quiescence slack. Batched engines flush one line per world.
- ``span`` — a wall-clock span (name + ``wall_s``): sweep bucket
  attempts, retry backoffs, checkpoint writes, journal fsyncs.
- ``run_summary`` — one line per driver run: the engine's uniform
  ``last_run_stats`` (supersteps, wall seconds, driver compiles).
- ``utilization`` — per-bucket sweep utilization (sweep/runner.py):
  worlds-active occupancy, budget-mask efficiency, pow2 scan-pad
  waste.
- ``decision`` — one online-dispatch controller decision per chunk
  (dispatch/, docs/dispatch.md): window width, rung pin, chunk
  length.
- ``integrity`` — one state-integrity verification event per checked
  chunk (integrity/, docs/integrity.md): the verify mode, the chunk,
  and whether the chunk verified or rolled back.
- ``speculation`` — one optimistic-execution outcome per chunk
  (speculate/, docs/speculation.md): the speculative window the
  chunk ran with, and whether it committed or rolled back (rollback
  lines carry the violation scalars — superstep/horizon/straggler).
- ``event`` — a point event (OOM split, terminal failure,
  integrity violation, …).

The registry validates every line at emit time AND the file is
re-validatable after the fact — ``python -m timewarp_tpu.obs.metrics
validate FILE`` is the CI gate (a malformed stream fails loudly,
never parses "close enough").

A registry with no path accumulates lines in memory only (the CLI's
summary aggregation); with a path it appends one flushed line per
emit, so a crashed run keeps every line up to the crash.
"""

from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

__all__ = ["METRICS_SCHEMA", "MetricsRegistry", "validate_line",
           "validate_metrics_file"]

#: bump when a kind's required fields change shape (or the kind
#: inventory grows: v2 added the dispatch-controller `decision`
#: kind, v3 the state-integrity `integrity` kind, v4 the flight-
#: recorder event form — `event` lines with name="flight" carry the
#: per-message provenance fields below — v5 the optimistic-execution
#: `speculation` kind — a v1 reader would mis-skip lines it cannot
#: interpret)
METRICS_SCHEMA = 5

_NUM = (int, float)
#: kind -> {required field: type tuple}; extra fields are allowed
#: (forward-compatible), missing/badly-typed required ones are not
_KINDS: Dict[str, Dict[str, tuple]] = {
    "supersteps": {"label": (str,), "supersteps": (int,)},
    "span": {"name": (str,), "wall_s": _NUM},
    "run_summary": {"label": (str,), "supersteps": (int,),
                    "wall_seconds": _NUM, "compiles": (int,)},
    "utilization": {"bucket": (str,), "worlds": (int,),
                    "chunks": (int,), "world_supersteps": (int,),
                    "scan_supersteps": (int,),
                    "budget_efficiency": _NUM,
                    "pad_waste_frac": _NUM,
                    "worlds_active_mean": _NUM},
    # one online-dispatch controller decision per chunk (dispatch/,
    # docs/dispatch.md): the knob values a chunk ran with — the same
    # record the decision trace and the sweep journal carry
    "decision": {"chunk": (int,), "window_us": (int,),
                 "rung_pin": (int,), "chunk_len": (int,)},
    # one state-integrity verification event per checked chunk
    # (integrity/runner.py, docs/integrity.md): event is "verified"
    # (the chunk passed every check) or "rollback" (a violation was
    # detected and the run restored its last verified snapshot)
    "integrity": {"label": (str,), "mode": (str,), "chunk": (int,),
                  "event": (str,)},
    # one optimistic-execution outcome per chunk (speculate/,
    # docs/speculation.md): outcome is "committed" (the chunk's
    # causality plane decoded clean) or "rollback" (a straggler
    # violated the committed horizon; the run restored its snapshot
    # and re-ran at the conservative floor)
    "speculation": {"label": (str,), "chunk": (int,),
                    "window_us": (int,), "outcome": (str,)},
    "event": {"name": (str,)},
}

#: extra required fields of the flight-recorder event form (v4,
#: obs/flight.py): an `event` line with name="flight" is one recorded
#: message/fault event and must carry the full provenance tuple
_FLIGHT_FIELDS: Dict[str, tuple] = {
    "ev": (str,), "superstep": (int,), "src": (int,), "dst": (int,),
    "send_t_us": (int,), "t_us": (int,),
}


def validate_line(rec: Any) -> None:
    """Validate one metrics record against the schema; raises
    ``ValueError`` naming the offense (never a KeyError/TypeError)."""
    if not isinstance(rec, dict):
        raise ValueError(f"metrics line must be a JSON object, got "
                         f"{type(rec).__name__}")
    sv = rec.get("schema")
    # accept every schema this reader understands: bumps so far are
    # purely additive (v2 added the `decision` kind, v3 `integrity`),
    # so a v1 archive must keep validating — only a FUTURE schema is
    # unreadable
    if isinstance(sv, bool) or not isinstance(sv, int) \
            or not 1 <= sv <= METRICS_SCHEMA:
        raise ValueError(
            f"metrics line schema {sv!r} outside this reader's range "
            f"[1, {METRICS_SCHEMA}]")
    kind = rec.get("kind")
    if kind not in _KINDS:
        raise ValueError(f"unknown metrics kind {kind!r}; known: "
                         f"{sorted(_KINDS)}")
    for field, types in _KINDS[kind].items():
        v = rec.get(field)
        if isinstance(v, bool) or not isinstance(v, types):
            raise ValueError(
                f"metrics kind {kind!r}: field {field!r} must be "
                f"{'/'.join(t.__name__ for t in types)}, got {v!r}")
    if kind == "event" and rec.get("name") == "flight":
        # the flight-recorder event form (v4): name="flight" promises
        # the per-message provenance tuple — a half-written event is
        # worse than none (the causal-query layer would join garbage)
        for field, types in _FLIGHT_FIELDS.items():
            v = rec.get(field)
            if isinstance(v, bool) or not isinstance(v, types):
                raise ValueError(
                    f"flight event: field {field!r} must be "
                    f"{'/'.join(t.__name__ for t in types)}, got "
                    f"{v!r} (obs/flight.py)")


def validate_metrics_file(path: str) -> int:
    """Validate every line of a metrics JSONL file; returns the line
    count, raises ``ValueError`` naming file and line on the first
    offense — the CI telemetry-smoke gate."""
    n = 0
    with open(path) as f:
        for i, line in enumerate(f, 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{i}: not JSON ({e})") from None
            try:
                validate_line(rec)
            except ValueError as e:
                raise ValueError(f"{path}:{i}: {e}") from None
            n += 1
    if n == 0:
        # an empty stream validating "OK" would let a CI gate pass on
        # a run that never recorded anything — fail actionably,
        # naming the file
        raise ValueError(
            f"{path}: contains no metrics records (empty or "
            "whitespace-only file) — the producing run wrote "
            "nothing; check its --telemetry/--record/--metrics-out "
            "flags (docs/observability.md)")
    return n


class MetricsRegistry:
    """Aggregating sink for telemetry frames, spans, and summaries
    (module docstring). ``tracer`` (an obs.perfetto.TraceBuilder)
    optionally mirrors spans/events onto the Perfetto timeline so one
    instrumentation call feeds both outputs."""

    def __init__(self, path: Optional[str] = None,
                 run: Optional[str] = None, tracer=None) -> None:
        self.path = path
        self.run = run
        self.tracer = tracer
        self.lines: List[dict] = []
        self._fh = None
        #: emits may race: the sweep's chunk executor flushes engine
        #: telemetry while the supervisor thread emits spans — and a
        #: watchdog-abandoned zombie chunk may still flush after its
        #: retry started. Metrics are observability (a duplicate
        #: chunk line is harmless), but a TORN line would fail the
        #: validate gate, so writes serialize under one lock.
        self._lock = threading.Lock()

    # -- emission ----------------------------------------------------------

    def emit(self, kind: str, **fields) -> dict:
        rec = {"schema": METRICS_SCHEMA, "kind": kind}
        if self.run is not None:
            rec["run"] = self.run
        rec.update(fields)
        validate_line(rec)  # never write a line the gate would reject
        with self._lock:
            self.lines.append(rec)
            if self.path is not None:
                if self._fh is None:
                    self._fh = open(self.path, "a")
                self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
                self._fh.flush()
        return rec

    def superstep_chunk(self, label: str, frames,
                        world: Optional[int] = None) -> None:
        """Flush one chunk of decoded telemetry (a TelemetryFrames, or
        the batched engines' per-world list) as ``supersteps`` lines."""
        from .telemetry import summarize_frames
        if isinstance(frames, list):
            for b, fr in enumerate(frames):
                self.emit("supersteps", label=label, world=b,
                          **summarize_frames(fr))
            return
        extra = {} if world is None else {"world": world}
        self.emit("supersteps", label=label, **extra,
                  **summarize_frames(frames))

    def run_summary(self, label: str, stats: dict, **fields) -> None:
        """One line per driver run from the engine's uniform
        ``last_run_stats``."""
        self.emit("run_summary", label=label,
                  supersteps=int(stats["supersteps"]),
                  wall_seconds=float(stats["wall_seconds"]),
                  compiles=int(stats["compiles"]), **fields)

    def event(self, name: str, **fields) -> None:
        self.emit("event", name=name, **fields)
        if self.tracer is not None:
            self.tracer.instant(name, args=fields or None)

    @contextmanager
    def span(self, name: str, **fields):
        """Wall-clock span, mirrored onto the Perfetto timeline when a
        tracer is attached."""
        t0 = time.perf_counter()
        ts = None if self.tracer is None else self.tracer.now_us()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.emit("span", name=name, wall_s=round(dt, 6), **fields)
            if self.tracer is not None:
                self.tracer.complete(name, dur_us=dt * 1e6, ts_us=ts,
                                     args=fields or None)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def _main(argv) -> int:
    if len(argv) != 2 or argv[0] != "validate":
        raise SystemExit(
            "usage: python -m timewarp_tpu.obs.metrics validate FILE")
    try:
        n = validate_metrics_file(argv[1])
    except (OSError, ValueError) as e:
        # the CLI convention everywhere else (test_zgrammar): exit 1
        # with the actionable message, never a raw traceback
        raise SystemExit(str(e))
    print(json.dumps({"file": argv[1], "lines": n, "ok": True}))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(_main(sys.argv[1:]))
