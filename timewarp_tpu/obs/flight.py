"""The causal flight recorder: per-message provenance on-device.

A :class:`RecordRow` is the fixed-shape, bounded per-superstep event
plane an engine threads through its traced scan when ``record !=
"off"`` — the third rider on the ``StepOut`` vehicle after telemetry
(``telem``) and integrity (``integ``), under the same hard contract:
**zero overhead when off, bit-exact when on**. ``None`` when off, so
the off-mode jaxpr is byte-identical to the pre-knob engine; every
recorded value is derived only from values the superstep already
computes (the deliver mask, the routed batch, the fault masks), so
states, traces, digests, and checkpoints are bit-identical in every
mode (tests/test_zzzzzflight.py).

Modes:

- ``"deliveries"`` — one event per delivered message: ``(src, dst,
  deliver_t)`` (``send_t`` is unknown at delivery and recorded -1;
  ``full`` mode's send events carry it, and the causal-query layer
  joins the two on ``(src, dst, deliver_t)``).
- ``"full"`` — adds send events ``(src, dst, send_t, deliver_t)``
  and fault-action events: ``defer`` (a crash window slid a node's
  pending event to ``t_up``), ``cut`` (a partition killed a send),
  ``down`` (a delivery landed inside the destination's down window),
  ``purge`` (a reset restart dropped pre-crash mailbox entries),
  ``restart`` (the injected reboot firing itself).

The plane is a bounded ring: ``record_cap`` events per superstep
(default 256). Events beyond capacity are dropped while ``n_ev``
keeps counting — ``n_ev`` exceeding the stored count IS the overflow
evidence, never silent (the same contract as the engines' device
event ring). Within a superstep the event order is pinned:
deliveries (node-major, slot order), then the fault/send captures in
superstep order (defer, restart, purge, cut, sends) — deterministic
per engine, so a recorded log is replayable evidence.

Host side: :func:`decode_flight` turns the scan's stacked rows into a
:class:`FlightLog` (per world, batched), and :class:`FlightWriter`
drains logs per chunk into a schema'd JSONL event log —
METRICS_SCHEMA v4 ``event`` lines with ``name="flight"``, validated
by ``python -m timewarp_tpu.obs.metrics validate`` like every other
metrics stream (obs/metrics.py).
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass
from typing import Any, List, NamedTuple, Optional

import numpy as np

__all__ = ["RECORD_MODES", "RecordRow", "FlightLog", "FlightWriter",
           "FlightRecorderMixin", "validate_record", "empty_row",
           "record_masked", "record_compacted", "compact",
           "record_deliveries",
           "decode_flight", "concat_flight", "load_flight_jsonl",
           "EV_DELIVER", "EV_SEND", "EV_FAULT", "TAG_DEFER",
           "TAG_CUT", "TAG_DOWN", "TAG_PURGE", "TAG_RESTART",
           "KIND_NAMES", "ACTION_NAMES"]

#: the engine knob's legal values, in increasing cost order
RECORD_MODES = ("off", "deliveries", "full")

#: event kinds (RecordRow.kind; 0 = empty slot)
EV_DELIVER, EV_SEND, EV_FAULT = 1, 2, 3
KIND_NAMES = {EV_DELIVER: "deliver", EV_SEND: "send", EV_FAULT: "fault"}

#: fault-action tags (RecordRow.tag for EV_FAULT events; a SEND whose
#: delivery lands in the destination's down window is recorded as an
#: EV_FAULT with TAG_DOWN — the send's fate rides its tag)
TAG_DEFER, TAG_CUT, TAG_DOWN, TAG_PURGE, TAG_RESTART = 1, 2, 3, 4, 5
ACTION_NAMES = {TAG_DEFER: "defer", TAG_CUT: "cut", TAG_DOWN: "down",
                TAG_PURGE: "purge", TAG_RESTART: "restart"}


def validate_record(mode: str, who: str = "engine") -> str:
    """Loud knob validation — a typo'd mode must not silently run
    unrecorded (mirrors obs.telemetry.validate_mode)."""
    if mode not in RECORD_MODES:
        raise ValueError(
            f"{who}: record must be one of {RECORD_MODES}, got "
            f"{mode!r} ('off' = zero overhead, 'deliveries' = one "
            "event per delivered message, 'full' = + sends and fault "
            "actions — docs/observability.md)")
    return mode


class RecordRow(NamedTuple):
    """One superstep's bounded event plane (device arrays; [B, ...]
    per world under the batch vmap). ``n_ev`` counts every event the
    superstep produced — past ``cap`` they are dropped but still
    counted (the overflow evidence). Empty slots carry kind 0.

    The deliveries-mode row is SLIM: ``kind``/``send_t``/``tag`` are
    ``None`` (a single capture fills slots ``[0, min(n_ev, cap))``
    contiguously, every event is an EV_DELIVER with unknown send
    instant, so the three constant planes carry zero information —
    dropping them removes their per-superstep scan-output traffic,
    the dominant deliveries-mode cost at smoke scale; decode
    reconstructs them host-side)."""
    n_ev: Any     # int32[] — events produced (stored + dropped)
    kind: Any     # int32[R] — EV_* (0 = empty slot); None when slim
    src: Any      # int32[R]
    dst: Any      # int32[R]
    send_t: Any   # int64[R] -- send instant (-1 = unknown); None slim
    t: Any        # int64[R] — deliver / action instant
    tag: Any      # int32[R] — TAG_* for EV_FAULT rows; None when slim


# ---------------------------------------------------------------------------
# device-side builders (called inside the engines' traced superstep)
# ---------------------------------------------------------------------------

def empty_row(cap: int) -> RecordRow:
    import jax.numpy as jnp
    z32 = jnp.zeros((cap,), jnp.int32)
    z64 = jnp.zeros((cap,), jnp.int64)
    return RecordRow(n_ev=jnp.int32(0), kind=z32, src=z32, dst=z32,
                     send_t=z64, t=z64, tag=z32)


def _flat(v, shape, dtype):
    import jax.numpy as jnp
    return jnp.broadcast_to(jnp.asarray(v, dtype), shape).reshape(-1)


def record_masked(row: RecordRow, kind, mask, src, dst, send_t, t,
                  tag=0, t_off=None) -> RecordRow:
    """Append the masked events to ``row``: an inclusive cumsum over
    the mask counts live elements (flat order preserved — the pinned
    within-superstep order), each buffer lane binary-searches the
    cumsum for ITS live element (``searchsorted``: lane ``rel`` holds
    the first flat index whose running count reaches ``rel + 1``),
    and each column is then a bounded GATHER at offset ``n_ev``.
    Gathers, not scatters or sorts, deliberately — this compaction is
    the recorder's whole device cost, and the measured ladder on
    XLA:CPU is searchsorted ≈ 2× cheaper than an iota scatter ≈ 2.5×
    cheaper than a stable argsort (an XLA:CPU scatter of the column
    values themselves additionally re-materializes its producers per
    element; gossip_100k_record's overhead budget pins the choice).
    Capacity drops are counted in ``n_ev``, never silent.
    ``src``/``dst``/``send_t``/``t``/``tag``/``kind`` broadcast
    against ``mask``'s shape — a scalar column skips the gather
    entirely, and ``t_off`` (a scalar added to the gathered ``t``)
    lets callers pass the engines' int32 *relative* deliver plane
    instead of materializing a mask-wide int64 absolute one."""
    import jax.numpy as jnp
    cap = row.kind.shape[0]
    shape = mask.shape
    m = mask.reshape(-1)
    M = m.size
    cs = jnp.cumsum(m.astype(jnp.int32))
    n_new = cs[-1]
    lane = jnp.arange(cap, dtype=jnp.int32)
    rel = lane - row.n_ev             # slot in the compacted view
    pick = (rel >= 0) & (rel < n_new)
    idx = jnp.clip(jnp.searchsorted(cs, rel + 1, side="left"),
                   0, M - 1)

    def put(buf, v, dtype, off=None):
        if off is None and (np.isscalar(v)
                            or getattr(v, "ndim", 1) == 0):
            return jnp.where(pick, jnp.asarray(v, dtype), buf)
        if off is None:
            g = _flat(v, shape, dtype)[idx]
        else:
            # gather the narrow plane, widen + offset at buffer width
            g = off + jnp.broadcast_to(
                v, shape).reshape(-1)[idx].astype(dtype)
        return jnp.where(pick, g, buf)
    return RecordRow(
        n_ev=row.n_ev + n_new,
        kind=put(row.kind, kind, jnp.int32),
        src=put(row.src, src, jnp.int32),
        dst=put(row.dst, dst, jnp.int32),
        send_t=put(row.send_t, send_t, jnp.int64),
        t=put(row.t, t, jnp.int64, t_off),
        tag=put(row.tag, tag, jnp.int32),
    )


def record_deliveries(cap: int, mask, src, dst, t,
                      t_off=None) -> RecordRow:
    """The deliveries-mode fast path: one slim row straight from the
    deliver mask — the same cumsum + ``searchsorted`` compaction as
    :func:`record_masked`, but starting from an empty buffer (so
    ``pick`` is just ``lane < n_new``) and carrying ``None`` for the
    three constant planes (see :class:`RecordRow`)."""
    import jax.numpy as jnp
    shape = mask.shape
    m = mask.reshape(-1)
    M = m.size
    cs = jnp.cumsum(m.astype(jnp.int32))
    n_new = cs[-1]
    lane = jnp.arange(cap, dtype=jnp.int32)
    pick = lane < n_new
    idx = jnp.clip(jnp.searchsorted(cs, lane + 1, side="left"),
                   0, M - 1)

    def put(v, dtype, off=None):
        if off is None and (np.isscalar(v)
                            or getattr(v, "ndim", 1) == 0):
            g = jnp.asarray(v, dtype)
        elif off is None:
            g = _flat(v, shape, dtype)[idx]
        else:
            g = off + jnp.broadcast_to(
                v, shape).reshape(-1)[idx].astype(dtype)
        return jnp.where(pick, g, jnp.zeros((cap,), dtype))
    return RecordRow(
        n_ev=n_new, kind=None,
        src=put(src, jnp.int32), dst=put(dst, jnp.int32),
        send_t=None, t=put(t, jnp.int64, t_off), tag=None)


def compact(cap: int, kind, mask, src, dst, send_t, t,
            tag=0, t_off=None) -> RecordRow:
    """Compact one masked event source into a standalone fixed-shape
    [cap] buffer — what the routing regimes return through their
    ``lax.switch`` branches (a side-channel set inside a branch would
    be an escaped tracer; a fixed-shape return value rides the switch
    legally). Merge with :func:`record_compacted`."""
    return record_masked(empty_row(cap), kind, mask, src, dst,
                         send_t, t, tag, t_off=t_off)


def record_compacted(row: RecordRow, comp: RecordRow) -> RecordRow:
    """Append a pre-compacted buffer (:func:`compact`) onto ``row`` —
    a pure bounded gather at offset ``n_ev`` (no scatter; see
    :func:`record_masked`). ``comp.n_ev`` carries events ``comp``
    itself dropped at capacity; they stay counted (and would not have
    fit ``row`` either — the two caps are the same)."""
    import jax.numpy as jnp
    cap = row.kind.shape[0]
    lane = jnp.arange(cap, dtype=jnp.int32)
    rel = lane - row.n_ev                 # slot in comp's buffer
    pick = (rel >= 0) & (rel < jnp.minimum(comp.n_ev, jnp.int32(cap)))
    idx = jnp.clip(rel, 0, cap - 1)

    def put(buf, v):
        return jnp.where(pick, v[idx], buf)
    return RecordRow(
        n_ev=row.n_ev + comp.n_ev,
        kind=put(row.kind, comp.kind), src=put(row.src, comp.src),
        dst=put(row.dst, comp.dst),
        send_t=put(row.send_t, comp.send_t), t=put(row.t, comp.t),
        tag=put(row.tag, comp.tag),
    )


# ---------------------------------------------------------------------------
# host-side decode
# ---------------------------------------------------------------------------

_COLS = ("superstep", "t_sup", "kind", "src", "dst", "send_t", "t",
         "tag")


@dataclass
class FlightLog:
    """Host-side decode of one run's recorded events: one row per
    stored event, with the (run-global) superstep index and the
    superstep instant attached. ``dropped`` counts events past the
    per-superstep capacity (``n_ev`` overflow) — a complete log has
    ``dropped == 0``."""
    superstep: np.ndarray   # int64[M]
    t_sup: np.ndarray       # int64[M] — the superstep's instant
    kind: np.ndarray        # int32[M] — EV_*
    src: np.ndarray         # int32[M]
    dst: np.ndarray         # int32[M]
    send_t: np.ndarray      # int64[M] (-1 = unknown)
    t: np.ndarray           # int64[M]
    tag: np.ndarray         # int32[M]
    dropped: int = 0

    def __len__(self) -> int:
        return len(self.kind)

    def event(self, i: int) -> dict:
        """One event as the schema'd record body (the JSONL line's
        payload fields — FlightWriter adds the envelope)."""
        k = int(self.kind[i])
        rec = {"ev": KIND_NAMES.get(k, str(k)),
               "superstep": int(self.superstep[i]),
               "t_sup_us": int(self.t_sup[i]),
               "src": int(self.src[i]), "dst": int(self.dst[i]),
               "send_t_us": int(self.send_t[i]),
               "t_us": int(self.t[i]), "tag": int(self.tag[i])}
        if k == EV_FAULT:
            rec["action"] = ACTION_NAMES.get(int(self.tag[i]),
                                             str(int(self.tag[i])))
        return rec

    def keyset(self):
        """The event identity tuples — what the bisection's event
        delta diffs (superstep index deliberately excluded: two runs
        may chunk differently yet carry the same events)."""
        return {(int(self.kind[i]), int(self.src[i]),
                 int(self.dst[i]), int(self.send_t[i]),
                 int(self.t[i]), int(self.tag[i]))
                for i in range(len(self))}


def _empty_log() -> FlightLog:
    return FlightLog(*(np.zeros(0, np.int64) if c in
                       ("superstep", "t_sup", "send_t", "t")
                       else np.zeros(0, np.int32) for c in _COLS))


def decode_flight(rec, valid, t_us, offset=0,
                  n_worlds: Optional[int] = None):
    """Decode the scan's stacked record rows ([T, R] leaves; [T, B, R]
    batched) into a :class:`FlightLog` (solo) or one per world,
    masked to the supersteps that actually fired. ``offset`` (the
    engine state's superstep count at chunk entry; [B] batched) makes
    the indices run-global, so chunked drivers concatenate without
    bookkeeping."""
    valid = np.asarray(valid)
    t_us = np.asarray(t_us)
    offset = np.asarray(offset, np.int64)

    def one(world: Optional[int]) -> FlightLog:
        m = valid if world is None else valid[:, world]

        def col(x):
            a = np.asarray(x)
            return a[m] if world is None else a[m, world]
        n_ev = col(rec.n_ev).astype(np.int64)            # [S]
        src = col(rec.src)                               # [S, R]
        ts = col(t_us)
        S, R = src.shape
        if rec.kind is None:
            # slim deliveries-mode row (RecordRow docstring): the
            # live slots are exactly [0, min(n_ev, R)), every event
            # is an EV_DELIVER with unknown send instant
            lanes = np.arange(R, dtype=np.int64)
            live = lanes[None, :] < np.minimum(n_ev, R)[:, None]
            kind = np.where(live, np.int32(EV_DELIVER),
                            np.int32(0))
            send_t = np.full((S, R), -1, np.int64)
            tag = np.zeros((S, R), np.int32)
        else:
            kind = col(rec.kind)
            send_t = np.asarray(col(rec.send_t), np.int64)
            tag = col(rec.tag)
        off = int(offset if world is None else offset[world])
        sel = kind.reshape(-1) > 0
        sup = np.repeat(np.arange(S, dtype=np.int64) + off, R)[sel]
        tsup = np.repeat(ts, R)[sel]
        stored = (kind > 0).sum()
        return FlightLog(
            superstep=sup, t_sup=tsup.astype(np.int64),
            kind=kind.reshape(-1)[sel],
            src=src.reshape(-1)[sel],
            dst=col(rec.dst).reshape(-1)[sel],
            send_t=send_t.reshape(-1)[sel],
            t=col(rec.t).reshape(-1)[sel].astype(np.int64),
            tag=tag.reshape(-1)[sel],
            dropped=int(np.maximum(n_ev.sum() - stored, 0)))

    if n_worlds is None:
        return one(None)
    return [one(b) for b in range(n_worlds)]


def concat_flight(chunks):
    """Concatenate per-chunk :class:`FlightLog`\\ s (or per-world
    lists of them) into one run-level log — superstep indices are
    already run-global (decode's ``offset``), so this is a plain
    column concat."""
    chunks = [c for c in chunks if c is not None]
    if not chunks:
        return None
    if isinstance(chunks[0], list):
        B = len(chunks[0])
        return [concat_flight([c[b] for c in chunks])
                for b in range(B)]
    return FlightLog(
        *(np.concatenate([getattr(c, col) for c in chunks])
          for col in _COLS),
        dropped=sum(c.dropped for c in chunks))


# ---------------------------------------------------------------------------
# the JSONL event log (METRICS_SCHEMA `event` kind, name="flight")
# ---------------------------------------------------------------------------

class FlightWriter:
    """Append-only schema'd JSONL event log. Every line is a
    METRICS_SCHEMA ``event`` record with ``name="flight"`` — the
    stream re-validates with ``python -m timewarp_tpu.obs.metrics
    validate`` (a malformed line refuses to be written at all). Safe
    for concurrent buckets: appends serialize under one lock.
    ``events`` counts recorded events (drop-marker lines excluded —
    the count agrees with per-world ``len(FlightLog)`` everywhere).
    ``truncate=True`` starts the file fresh — the solo CLI uses it so
    re-running a command does not silently merge two runs' events
    into one un-disambiguatable log (solo lines carry no ``run_id``,
    so :func:`load_flight_jsonl`'s multi-run refusal could not catch
    the merge); the sweep service keeps appending, its lines are
    ``run_id``-stamped."""

    def __init__(self, path: str, run: Optional[str] = None,
                 truncate: bool = False) -> None:
        self.path = path
        self.run = run
        self.events = 0
        self._fh = None
        self._mode = "w" if truncate else "a"
        self._lock = threading.Lock()

    def write(self, log: FlightLog, world: Optional[int] = None,
              run_id: Optional[str] = None) -> int:
        from .metrics import METRICS_SCHEMA, validate_line

        def envelope(rec):
            if self.run is not None:
                rec["run"] = self.run
            if world is not None:
                rec["world"] = int(world)
            if run_id is not None:
                rec["run_id"] = run_id
            validate_line(rec)
            return json.dumps(rec, sort_keys=True)
        lines = []
        for i in range(len(log)):
            lines.append(envelope(
                {"schema": METRICS_SCHEMA, "kind": "event",
                 "name": "flight", **log.event(i)}))
        if log.dropped:
            # the overflow evidence must cross the file boundary too:
            # without this line a reloaded log would look complete
            # (load_flight_jsonl sums these back into
            # FlightLog.dropped)
            lines.append(envelope(
                {"schema": METRICS_SCHEMA, "kind": "event",
                 "name": "flight_drops", "dropped": int(log.dropped)}))
        with self._lock:
            if self._fh is None:
                self._fh = open(self.path, self._mode)
                self._mode = "a"          # one truncation per writer
            for ln in lines:
                self._fh.write(ln + "\n")
            self._fh.flush()
            self.events += len(log)
        return len(log)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def load_flight_jsonl(path: str, run_id: Optional[str] = None,
                      world: Optional[int] = None) -> FlightLog:
    """Load a :class:`FlightWriter` event log back into a
    :class:`FlightLog` (the ``explain`` CLI's input). Non-flight
    metrics lines in the same file are skipped; ``run_id``/``world``
    filter a sweep's shared log down to one world. A log that still
    spans several runs or worlds after the given filters REFUSES to
    load — one merged FlightLog would let the causal join pair a send
    from one run with a delivery from another, a confidently wrong
    chain (the module's loud-failure convention)."""
    names = {v: k for k, v in KIND_NAMES.items()}
    cols: dict = {c: [] for c in _COLS}
    seen_runs: set = set()
    seen_worlds: set = set()
    n = dropped = 0
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            rec = json.loads(line)
            if rec.get("kind") != "event" \
                    or rec.get("name") not in ("flight",
                                               "flight_drops"):
                continue
            if run_id is not None and rec.get("run_id") != run_id:
                continue
            if world is not None and rec.get("world") != world:
                continue
            seen_runs.add(rec.get("run_id"))
            seen_worlds.add(rec.get("world"))
            if rec["name"] == "flight_drops":
                # the writer's overflow evidence (FlightWriter.write)
                dropped += int(rec.get("dropped", 0))
                continue
            n += 1
            cols["superstep"].append(rec["superstep"])
            cols["t_sup"].append(rec.get("t_sup_us", -1))
            cols["kind"].append(names.get(rec["ev"], 0))
            cols["src"].append(rec["src"])
            cols["dst"].append(rec["dst"])
            cols["send_t"].append(rec.get("send_t_us", -1))
            cols["t"].append(rec["t_us"])
            cols["tag"].append(rec.get("tag", 0))
    if n == 0:
        raise ValueError(
            f"{path!r} holds no flight events"
            + (f" for run_id {run_id!r}" if run_id is not None else "")
            + (f" world {world}" if world is not None else "")
            + " — record one with --record deliveries|full "
            "--record-out FILE (docs/observability.md)")
    if run_id is None and len(seen_runs) > 1:
        raise ValueError(
            f"{path!r} holds flight events from "
            f"{len(seen_runs)} runs ({sorted(map(str, seen_runs))}) "
            "— pick one with run_id=/--run-id; a merged log would "
            "join causal chains across unrelated runs")
    if world is None and len(seen_worlds) > 1:
        raise ValueError(
            f"{path!r} holds flight events from "
            f"{len(seen_worlds)} worlds "
            f"({sorted(map(str, seen_worlds))}) — pick one with "
            "world=/--world; a merged log would join causal chains "
            "across unrelated worlds")
    return FlightLog(
        *(np.asarray(cols[c],
                     np.int64 if c in ("superstep", "t_sup",
                                       "send_t", "t")
                     else np.int32) for c in _COLS),
        dropped=dropped)


# ---------------------------------------------------------------------------
# engine wiring
# ---------------------------------------------------------------------------

class FlightRecorderMixin:
    """``record=`` wiring + the host-side drain every scan-driver
    engine shares. Host state only: an engine with ``record="off"``
    lowers byte-identical jaxprs to the pre-knob engine (the event
    plane is a ``None`` StepOut field, exactly like telemetry)."""

    #: the engine's record mode ("off" | "deliveries" | "full")
    record = "off"
    #: per-superstep event capacity (overflow counted, never silent)
    record_cap = 256
    #: optional FlightWriter the traced drivers drain each chunk
    flight_out = None
    #: the last traced run's FlightLog (list per world, batched)
    last_run_flight = None

    def _bind_record(self, record: str,
                     record_cap: Optional[int]) -> None:
        self.record = validate_record(record, type(self).__name__)
        if record_cap is not None:
            if record_cap < 1:
                raise ValueError(
                    f"record_cap must be >= 1, got {record_cap}")
            self.record_cap = int(record_cap)

    def _rec_cut(self, rec_full: bool, cutm, src, dst, tmsg) -> None:
        """Flight-recorder capture of partition-cut sends (full mode)
        — called where each routing regime computes its cut mask, with
        the PRE-cut destination values (``cutm``'s positions still
        carry them). Appends onto the engine's per-trace
        ``_rec_extra`` side channel (merged into the StepOut event
        plane by the superstep's tail)."""
        if not rec_full:
            return
        self._rec_extra.append(compact(
            self.record_cap, EV_FAULT, cutm, src, dst, tmsg, tmsg,
            TAG_CUT))

    def _rec_sends(self, ok, downm, src, dst, tmsg, dt_abs):
        """Compacted send-event buffer (full mode): kind SEND, except
        a send whose delivery lands inside the destination's down
        window is re-recorded as EV_FAULT with TAG_DOWN — the send's
        fate rides its tag. Returns the fixed-shape buffer rather
        than appending it, because JaxEngine's adaptive ladder calls
        this inside ``lax.switch`` branches, which must return it (a
        ``self`` side channel set inside a branch would be an escaped
        tracer); non-branch callers append the return themselves."""
        import jax.numpy as jnp
        if downm is None:
            kind, tag = EV_SEND, 0
        else:
            kind = jnp.where(downm, jnp.int32(EV_FAULT),
                             jnp.int32(EV_SEND))
            tag = jnp.where(downm, jnp.int32(TAG_DOWN), jnp.int32(0))
        return compact(self.record_cap, kind, ok, src, dst, tmsg,
                       dt_abs, tag)

    def _capture_flight(self, ys, state_before) -> None:
        """Host-side decode of one traced run's record plane onto
        ``last_run_flight`` (+ a chunk drain to an attached
        FlightWriter) — a no-op in off mode."""
        import jax
        self.last_run_flight = None
        if self.record == "off" or ys is None \
                or getattr(ys, "rec", None) is None:
            return
        batch = getattr(self, "batch", None)
        off = np.asarray(jax.device_get(state_before.steps), np.int64)
        self.last_run_flight = decode_flight(
            ys.rec, np.asarray(ys.valid), np.asarray(ys.t),
            offset=off, n_worlds=None if batch is None else batch.B)
        if self.flight_out is not None:
            if isinstance(self.last_run_flight, list):
                for b, lg in enumerate(self.last_run_flight):
                    self.flight_out.write(lg, world=b)
            else:
                self.flight_out.write(self.last_run_flight)
