"""Chrome-trace / Perfetto exporter.

Emits the Trace Event JSON format (``{"traceEvents": [...]}``) that
https://ui.perfetto.dev and ``chrome://tracing`` open directly. Two
process tracks:

- **pid 1, "host"** — wall-clock spans and instants: sweep bucket
  attempts, retries and their backoff waits, OOM splits, checkpoint
  writes, journal fsyncs, jit compiles. Timestamps are µs since the
  builder was created.
- **pid 2, "virtual time"** — per-superstep counter tracks on the
  *emulated* clock: fired/delivered counts from the trace rows and
  the telemetry signals (active senders, selected rung, mailbox
  fill/peak, quiescence slack). Perfetto renders counters as stepped
  graphs, so superstep density and rung shifts are visible at a
  glance. Batched runs get one counter series per world.

The builder is append-only and host-side: it never touches the jitted
path, so it exists only when telemetry is on (the zero-overhead law
concerns the device program; this file concerns what you do with the
counters once they are off the chip).
"""

from __future__ import annotations

import json
import time
from contextlib import contextmanager
from typing import Optional

__all__ = ["TraceBuilder"]

#: the host wall-clock track / the virtual-time counter track
PID_HOST = 1
PID_VIRTUAL = 2


class TraceBuilder:
    def __init__(self, process: str = "timewarp-tpu") -> None:
        self._t0 = time.perf_counter()
        self.events: list = [
            {"name": "process_name", "ph": "M", "pid": PID_HOST,
             "args": {"name": f"{process} (host wall clock)"}},
            {"name": "process_name", "ph": "M", "pid": PID_VIRTUAL,
             "args": {"name": f"{process} (virtual time)"}},
        ]

    def now_us(self) -> float:
        """µs since the builder was created (the host track's clock)."""
        return (time.perf_counter() - self._t0) * 1e6

    # -- host wall-clock track ---------------------------------------------

    def complete(self, name: str, dur_us: float,
                 ts_us: Optional[float] = None, cat: str = "host",
                 args: Optional[dict] = None, tid: int = 1) -> None:
        """A complete ('X') span on the host track. ``ts_us`` defaults
        to ending *now* (span measured by the caller)."""
        if ts_us is None:
            ts_us = self.now_us() - dur_us
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": round(ts_us, 3), "dur": round(dur_us, 3),
              "pid": PID_HOST, "tid": tid}
        if args:
            ev["args"] = args
        self.events.append(ev)

    def instant(self, name: str, cat: str = "host",
                args: Optional[dict] = None, tid: int = 1) -> None:
        ev = {"name": name, "cat": cat, "ph": "i",
              "ts": round(self.now_us(), 3), "s": "p",
              "pid": PID_HOST, "tid": tid}
        if args:
            ev["args"] = args
        self.events.append(ev)

    @contextmanager
    def span(self, name: str, cat: str = "host",
             args: Optional[dict] = None, tid: int = 1):
        t0 = time.perf_counter()
        ts = self.now_us()
        try:
            yield
        finally:
            self.complete(name, (time.perf_counter() - t0) * 1e6,
                          ts_us=ts, cat=cat, args=args, tid=tid)

    # -- virtual-time counter track ----------------------------------------

    def counter(self, name: str, ts_us, values: dict) -> None:
        """One counter ('C') sample on the virtual-time track."""
        self.events.append({
            "name": name, "ph": "C", "ts": float(ts_us),
            "pid": PID_VIRTUAL,
            "args": {k: float(v) for k, v in values.items()}})

    def flow_arrow(self, name: str, src_tid: int, src_ts_us,
                   dst_tid: int, dst_ts_us, flow_id: int,
                   cat: str = "flow",
                   args: Optional[dict] = None) -> None:
        """One causal arrow on the virtual-time timeline: a flow
        ('s' -> 'f') pair between two node tracks, each end anchored
        to a thin slice (Perfetto binds flow events to enclosing
        slices, so the anchors are part of the arrow). The flight
        recorder's causal queries (obs/query.py) emit send->deliver
        arrows this way — message journeys become visible lines
        across the node tracks."""
        src_ts, dst_ts = float(src_ts_us), float(dst_ts_us)
        for tid, ts in ((src_tid, src_ts), (dst_tid, dst_ts)):
            ev = {"name": name, "cat": cat, "ph": "X",
                  "ts": round(ts, 3), "dur": 1.0,
                  "pid": PID_VIRTUAL, "tid": int(tid)}
            if args:
                ev["args"] = args
            self.events.append(ev)
        self.events.append({"name": name, "cat": cat, "ph": "s",
                            "id": int(flow_id),
                            "ts": round(src_ts + 0.5, 3),
                            "pid": PID_VIRTUAL, "tid": int(src_tid)})
        self.events.append({"name": name, "cat": cat, "ph": "f",
                            "bp": "e", "id": int(flow_id),
                            "ts": round(dst_ts + 0.5, 3),
                            "pid": PID_VIRTUAL, "tid": int(dst_tid)})

    def add_superstep_track(self, frames, trace=None,
                            world: Optional[int] = None) -> None:
        """Counter series over one run's supersteps: the telemetry
        frames (obs/telemetry.py), plus fired/delivered densities when
        the SuperstepTrace is given. ``world`` suffixes the series
        names so fleet worlds get separate tracks. Zero-superstep
        inputs (an empty run, a world that never fired) add nothing —
        the empty-trace guard in :meth:`save` keeps the file valid."""
        if frames is None or (len(frames) == 0
                              and (trace is None or len(trace) == 0)):
            return
        sfx = "" if world is None else f" [w{world}]"
        for i in range(len(frames)):
            ts = int(frames.t_us[i])
            vals = {k: int(v[i]) for k, v in frames.data.items()
                    if k != "qslack_us"}
            if "qslack_us" in frames.data:
                vals["qslack_us"] = max(int(frames.data["qslack_us"][i]),
                                        0)
            self.counter(f"superstep{sfx}", ts, vals)
        if trace is not None:
            for i in range(len(trace)):
                self.counter(f"events{sfx}", int(trace.times[i]), {
                    "fired": int(trace.fired_count[i]),
                    "delivered": int(trace.recv_count[i]),
                    "sent": int(trace.sent_count[i])})

    def compile_marks(self, label: str, count: int) -> None:
        """Instant marks for jit compiles observed over a run (the
        ``_cache_size`` delta the engines' ``last_run_stats`` carries
        — compile *count*, not duration: XLA does not expose per-entry
        compile walls portably)."""
        for _ in range(count):
            self.instant(f"jit compile: {label}", cat="compile")

    # -- output ------------------------------------------------------------

    def to_json(self) -> dict:
        events = self.events
        if not any(e.get("ph") != "M" for e in events):
            # empty-run guard: a trace holding ONLY metadata records
            # renders as a blank (or rejected) file in Perfetto —
            # an explicit marker keeps the artifact valid and says
            # WHY it is empty instead of looking corrupt
            events = events + [{
                "name": "empty run (no supersteps recorded)",
                "cat": "host", "ph": "i", "ts": 0.0, "s": "p",
                "pid": PID_HOST, "tid": 1}]
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def save(self, path: str) -> str:
        """Write the trace; the file opens directly in Perfetto (the
        empty-run guard in :meth:`to_json` keeps even a zero-superstep
        run's file valid)."""
        with open(path, "w") as f:
            json.dump(self.to_json(), f)
        return path
