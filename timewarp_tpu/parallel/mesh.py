"""Device-mesh communication layer — the collectives the sharded
engines ride (SURVEY.md §2.5/§5.8).

Simulated-node message passing maps onto XLA collectives over the
mesh's ICI — ``ppermute`` for fixed shift topologies (the token ring's
neighbor exchange), ``lax.all_to_all`` for dynamic destinations —
instead of the reference's TCP sockets
(`/root/reference/src/Control/TimeWarp/Rpc/Transfer.hs:473,577`).

:class:`MeshComm` substitutes mesh collectives behind the single-chip
:class:`~timewarp_tpu.interp.jax_engine.common.LocalComm` interface so
one superstep implementation serves both; :class:`ShardedDriver` is
the shared ``shard_map`` run harness (state placement with
``NamedSharding`` so XLA keeps every per-node array resident on its
owning device across the whole loop, plus the jitted scan/while
wrappers).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple, Union

from ..utils import jaxconfig  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..interp.jax_engine.common import LocalComm, padded_scan

try:  # newer jax exports shard_map at the top level
    _shard_map = jax.shard_map
except AttributeError:  # 0.4.x: experimental home
    from jax.experimental.shard_map import shard_map as _shard_map
# the replication-check kwarg was renamed check_rep -> check_vma in a
# DIFFERENT release than the public promotion — read the signature
# instead of inferring from where shard_map lives
import inspect as _inspect

_CHECK_KW = ("check_vma" if "check_vma"
             in _inspect.signature(_shard_map).parameters
             else "check_rep")


def _smap(f, mesh, in_specs, out_specs):
    """Version-portable ``shard_map`` with replication checking off
    (the engines' collectives are hand-placed; the checker rejects the
    boundary-slice ppermute pattern on some jax versions)."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **{_CHECK_KW: False})


__all__ = ["AxisName", "Mesh", "MeshComm", "ShardedDriver", "axis_size",
           "make_mesh"]

#: a mesh axis: one name, or a tuple of names whose row-major product
#: the collectives flatten over (multi-slice meshes)
AxisName = Union[str, Tuple[str, ...]]


def make_mesh(n_devices: Optional[int] = None,
              axis: str = "nodes", *,
              shape: Optional[tuple] = None,
              axes: Optional[tuple] = None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices, or — with
    ``shape``/``axes`` — a multi-axis mesh, e.g.
    ``make_mesh(shape=(2, 4), axes=("dcn", "ici"))`` for a two-slice
    deployment. The engines accept the axis-name *tuple* wherever they
    take an axis: every collective (psum / all_gather / ppermute /
    all_to_all) runs over the flattened row-major product, so the same
    boundary-slice ring and destination-shard exchange span slices —
    lay the minor axis over ICI so the high-traffic neighbor hops stay
    intra-slice."""
    devs = jax.devices()
    if shape is not None:
        n = int(np.prod(shape))
        if axes is None or len(axes) != len(shape):
            raise ValueError("axes must name every mesh dimension")
        if len(devs) < n:
            raise ValueError(
                f"mesh shape {shape} needs {n} devices, have {len(devs)}")
        return Mesh(np.asarray(devs[:n]).reshape(shape), tuple(axes))
    if axes is not None:
        raise ValueError("axes= requires shape=")
    if n_devices is None:
        n_devices = len(devs)
    return Mesh(np.asarray(devs[:n_devices]), (axis,))


def axis_size(mesh: Mesh, axis: AxisName) -> int:
    """Total device count of ``axis`` (a name or a tuple of names)."""
    if isinstance(axis, tuple):
        return int(np.prod([mesh.shape[a] for a in axis]))
    return mesh.shape[axis]


class MeshComm(LocalComm):
    """Mesh collectives behind the LocalComm interface; valid only
    inside a ``shard_map`` body with ``axis`` bound."""

    def __init__(self, axis: AxisName, n_global: int,
                 n_shards: int) -> None:
        if n_global % n_shards:
            raise ValueError(
                f"n_nodes {n_global} not divisible by {n_shards} shards")
        self.axis = axis
        self.n_global = n_global
        self.n_shards = n_shards
        self.n_local = n_global // n_shards

    def node_ids(self) -> jax.Array:
        off = jax.lax.axis_index(self.axis).astype(jnp.int32) \
            * jnp.int32(self.n_local)
        return off + jnp.arange(self.n_local, dtype=jnp.int32)

    def all_min(self, x: jax.Array) -> jax.Array:
        # Not ``pmin``: the int64 min-all-reduce fails to lower on the
        # TPU compiler path ("Supported lowering only of Sum all
        # reduce"); gathering one scalar per device and reducing
        # locally lowers everywhere and costs D words on ICI.
        return jax.lax.all_gather(x, self.axis).min()

    def all_sum(self, x: jax.Array) -> jax.Array:
        return jax.lax.psum(x, self.axis)

    def all_max(self, x: jax.Array) -> jax.Array:
        # same gather-then-reduce shape as all_min (pmax shares pmin's
        # lowering caveat on the TPU compiler path)
        return jax.lax.all_gather(x, self.axis).max()

    def roll(self, x: jax.Array, s: int) -> jax.Array:
        """Global roll by ``s`` along the last (node) axis: local roll +
        boundary-slice ``ppermute`` to the next shard (and a whole-shard
        ``ppermute`` when ``s`` spans shards). One ICI neighbor hop for
        the ring's s=1."""
        s = s % self.n_global
        if s == 0:
            return x
        D, nl = self.n_shards, self.n_local
        whole, rem = divmod(s, nl)
        if whole:
            perm = [(i, (i + whole) % D) for i in range(D)]
            x = jax.lax.ppermute(x, self.axis, perm)
        if rem:
            tail = x[..., nl - rem:]
            perm = [(i, (i + 1) % D) for i in range(D)]
            recv = jax.lax.ppermute(tail, self.axis, perm)
            x = jnp.concatenate([recv, x[..., :nl - rem]], axis=-1)
        return x

    def local_rows(self, table: np.ndarray) -> jax.Array:
        off = jax.lax.axis_index(self.axis).astype(jnp.int32) \
            * jnp.int32(self.n_local)
        return jax.lax.dynamic_slice_in_dim(
            jnp.asarray(table), off, self.n_local, axis=-1)


class ShardedDriver:
    """Shared ``shard_map`` driver for the sharded engines. The
    concrete engine supplies ``_state_specs`` (its state's
    PartitionSpecs, built from :meth:`_leaf_spec`), ``_superstep``, and
    ``_next_event`` (the quiescence expression, inherited from its
    local base class)."""

    def _leaf_spec(self, x, last_axis: bool) -> P:
        """PartitionSpec for one state leaf: the node axis (leading or
        trailing per the engine's layout) sharded over the mesh axis,
        everything else replicated; scalars fully replicated."""
        ax = self.axis
        nd = getattr(x, "ndim", 0)
        if nd == 0:
            return P()
        if last_axis:
            return P(*([None] * (nd - 1) + [ax]))
        return P(ax, *([None] * (nd - 1)))

    def init_state(self):
        st = super().init_state()
        specs = self._state_specs(st)
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            st, specs)

    def _trace_spec(self) -> P:
        """PartitionSpec of one scan-trace leaf: replicated for the
        node-sharded engines (trace scalars are already psum'd mesh-
        wide); the world-sharded engine overrides (per-world rows live
        on the world's device)."""
        return P()

    @partial(jax.jit, static_argnums=(0, 2))
    def _run_scan(self, st, n_pad: int, max_steps, dyn=None,
                  ident=None):
        # pow2-padded scan length + masked tail, the shared
        # compile-reuse contract (jax_engine/common.py padded_scan).
        # `dyn` is the dispatch controller's traced knob operand
        # (jax_engine/controlled.py) — replicated scalars, bound onto
        # `self` inside the shard_map body exactly like the local
        # driver binds them, so one superstep implementation reads
        # them in both venues. `ident` is the world-sharded fleet's
        # per-world identity operand (jax_engine/batched.py
        # WorldIdentity) — replicated [B] arrays, bound the same way;
        # _step_all slices this device's worlds by mesh position.
        # Node-sharded engines pass None (an empty pytree: the
        # operand list is unchanged, so their jaxprs are too).
        specs = self._state_specs(st)
        # per-world budget vectors on the WORLD-sharded engine: the
        # replicated [B] budget must mask this device's local world
        # slice (the scan carry is [B/D, ...]) — slice it by mesh
        # position exactly like _step_all slices the world context.
        # Node-sharded engines never see a vector (batch is None).
        Bl = getattr(self, "worlds_local", None)
        ms_vec = getattr(max_steps, "ndim", 0) == 1

        def local_ms(ms):
            if not ms_vec or Bl is None:
                return ms
            off = jax.lax.axis_index(self.axis).astype(jnp.int32) \
                * jnp.int32(Bl)
            return jax.lax.dynamic_slice_in_dim(ms, off, Bl, 0)

        dyn_specs = jax.tree.map(lambda _: P(), dyn)
        ident_specs = jax.tree.map(lambda _: P(), ident)

        def body(s, ms, dy, idn):
            self._dyn = dy
            self._ident_in = idn
            try:
                return padded_scan(self._step_all, s, n_pad,
                                   local_ms(ms))
            finally:
                self._dyn = None
                self._ident_in = None

        return _smap(body, self.mesh,
                     (specs, P(), dyn_specs, ident_specs),
                     (specs, self._trace_spec()))(
            st, max_steps, dyn, ident)

    @partial(jax.jit, static_argnums=(0,))
    def _run_while(self, st, max_steps, ident=None):
        specs = self._state_specs(st)
        max_steps = jnp.asarray(max_steps, jnp.int64)
        ident_specs = jax.tree.map(lambda _: P(), ident)

        def body_fn(s, ms, idn):
            self._ident_in = idn
            try:
                start_steps = s.steps
                return jax.lax.while_loop(
                    self._while_cond_fn(start_steps, ms),
                    self._while_body_fn(start_steps, ms), s)
            finally:
                self._ident_in = None

        return _smap(body_fn, self.mesh, (specs, P(), ident_specs),
                     specs)(st, max_steps, ident)
