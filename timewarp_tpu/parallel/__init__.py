"""Mesh/collective layer: the TPU-native "communication backend"
(SURVEY.md §2.5) — ppermute rings, all_to_all exchanges, exact psum
digest reductions — behind one comm interface."""

from .mesh import Mesh, MeshComm, ShardedDriver, make_mesh

__all__ = ["Mesh", "MeshComm", "ShardedDriver", "make_mesh"]
