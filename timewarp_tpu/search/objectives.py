"""Property objectives: what "the schedule broke the protocol" means.

An :class:`Objective` turns one world's evaluation record into a
``(violated, score)`` verdict: ``violated`` is the hard property
violation (the counterexample condition, stated over the same
observables :mod:`timewarp_tpu.faults.properties` checks), ``score``
an integer *pressure gradient* the evolutionary loop maximizes —
schedules that delay delivery or stretch convergence outrank
schedules that merely exist, so the search hill-climbs toward the
violation instead of waiting to stumble on it. Scores are ints
(virtual-time µs and counters), so selection is bit-deterministic.

The module also owns :func:`evaluate_configs`, the batched evaluator
both the campaign driver and the minimizer share: candidates pack
into shape-shared buckets (sweep/bucket.py — one executable per
generation, the domain's ``table_pad`` pinned via
``Bucket.fault_pad``) and run under the engine's chunked fleet
driver, producing one :class:`WorldEval` per candidate.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..faults.properties import eventually_delivered
from ..sweep.spec import RunConfig

__all__ = ["WorldEval", "Objective", "DeliveryBlackout",
           "ConvergenceBlowup", "PredicateObjective",
           "parse_objective", "evaluate_configs", "repro_config",
           "rejudge_repro", "OBJECTIVE_GRAMMAR"]

#: score stamped on a hard violation — above any virtual-time value
#: (|t| < 2^61, faults/schedule.py), so violating candidates always
#: outrank every gradient score
VIOLATION_SCORE = 1 << 62


class WorldEval(NamedTuple):
    """One candidate's evaluation record: the observables objectives
    read. ``trace`` covers the evaluated span only — a fork
    continuation's trace starts at the fork instant (``trace_from``),
    which is why fork-phase verdicts are re-confirmed from t=0 before
    they are reported (campaign.py)."""
    run_id: str
    trace: object               # SuperstepTrace
    schedule: object            # FaultSchedule
    supersteps: int
    budget: int
    quiesced: bool
    trace_from: int = 0         # virtual time the trace starts at


@dataclass(frozen=True)
class Objective:
    """Base protocol: ``judge(ev) -> (violated, score)``."""
    name: str = "objective"

    def judge(self, ev: WorldEval) -> Tuple[bool, int]:
        raise NotImplementedError


def _recv_times(trace) -> np.ndarray:
    return trace.times[trace.recv_count > 0]


@dataclass(frozen=True)
class DeliveryBlackout(Objective):
    """Violation of ``eventually_delivered(after_t)``: no superstep
    at or after ``after_t`` delivers a message — the protocol starved
    (default ``after_t=0``: the rumor/token/block never reached
    anyone at all). Gradient: the virtual time of the FIRST delivery
    at/after ``after_t`` — a schedule that pushes first delivery
    later is closer to starving it entirely."""
    after_t: int = 0

    def judge(self, ev: WorldEval) -> Tuple[bool, int]:
        if not eventually_delivered(ev.trace, self.after_t):
            return True, VIOLATION_SCORE
        ts = _recv_times(ev.trace)
        first = int(ts[ts >= self.after_t][0])
        return False, first


@dataclass(frozen=True)
class ConvergenceBlowup(Objective):
    """Convergence-time blowup: the world must quiesce (within its
    superstep budget) by virtual time ``limit_us``. Violated when it
    ran out of budget still live, or quiesced past the limit.
    Gradient: the final virtual time reached."""
    limit_us: int = 0

    def judge(self, ev: WorldEval) -> Tuple[bool, int]:
        t_end = int(ev.trace.times[-1]) if len(ev.trace) else 0
        if not ev.quiesced or t_end > self.limit_us:
            return True, VIOLATION_SCORE
        return False, t_end


@dataclass(frozen=True)
class PredicateObjective(Objective):
    """Custom predicate over the evaluation record: ``fn(ev)``
    returns ``(violated, score)`` (or a bare bool — scored 0/
    VIOLATION_SCORE). The hook for campaign embedders with
    properties this vocabulary does not name."""
    fn: Optional[Callable] = None

    def judge(self, ev: WorldEval) -> Tuple[bool, int]:
        res = self.fn(ev)
        if isinstance(res, tuple):
            return bool(res[0]), int(res[1])
        return bool(res), VIOLATION_SCORE if res else 0


OBJECTIVE_GRAMMAR = ("eventually-delivered[:AFTER_T] | "
                     "convergence:LIMIT  (times µs ints or 10ms/5s)")


def parse_objective(spec: str) -> Objective:
    """Parse the CLI's ``--objective`` grammar; malformation dies
    naming :data:`OBJECTIVE_GRAMMAR` (the parse_faults convention).
    The string form round-trips through the repro artifact, so a
    repro re-judges under exactly the objective that found it."""
    from ..faults.schedule import _parse_time
    parts = spec.split(":")
    try:
        if parts[0] == "eventually-delivered" and len(parts) in (1, 2):
            t = _parse_time(parts[1], "AFTER_T") if len(parts) == 2 \
                else 0
            return DeliveryBlackout(name=f"eventually-delivered:{t}",
                                    after_t=t)
        if parts[0] == "convergence" and len(parts) == 2:
            t = _parse_time(parts[1], "LIMIT")
            return ConvergenceBlowup(name=f"convergence:{t}",
                                     limit_us=t)
        raise ValueError(f"unknown objective {parts[0]!r}")
    except (IndexError, ValueError) as e:
        raise SystemExit(
            f"malformed objective spec {spec!r} ({e}); grammar: "
            f"{OBJECTIVE_GRAMMAR}") from None


def repro_config(rec: Dict, run_id: str = "repro") -> RunConfig:
    """The :class:`RunConfig` a chaos-search repro artifact names —
    ONE reconstruction shared by ``search repro``, the bench's
    replayability gate, and tests, so a repro-schema change can never
    drift them apart."""
    return RunConfig(
        run_id=run_id, family=rec["scenario"],
        params=tuple(sorted(rec["params"].items())),
        link=rec["link"], seed=rec["seed"], window=rec["window"],
        budget=rec["budget"], faults=rec["faults"])


def rejudge_repro(rec: Dict, *, lint: str = "off"):
    """Replay a repro artifact solo and re-judge its recorded
    objective: returns ``(objective, violated, score)`` — exit-0
    semantics (``violated`` True = the repro reproduces) belong to
    the callers."""
    obj = parse_objective(rec["objective"])
    ev = evaluate_configs([repro_config(rec)], lint=lint)["repro"]
    violated, score = obj.judge(ev)
    return obj, violated, score


def evaluate_configs(configs: List[RunConfig], *,
                     fault_pad: Optional[Tuple[int, int, int]] = None,
                     max_bucket: int = 64, chunk: int = 64,
                     lint: str = "off") -> Dict[str, WorldEval]:
    """Run every config to quiescence (or budget) and return one
    :class:`WorldEval` per run_id. Candidates bucket by the standard
    plan (sweep/bucket.py); ``fault_pad`` pins each bucket's
    fault-table rows to the domain caps so every generation of a
    campaign reuses ONE executable shape (padding rows inert). This
    is plain host-side composition over the existing engines — the
    traces and final states it reads are the same objects the sweep
    survival law pins."""
    from ..faults.schedule import FaultSchedule
    from ..sweep.bucket import build_bucket_engine, plan_buckets
    out: Dict[str, WorldEval] = {}
    buckets = plan_buckets(configs, max_bucket)
    if fault_pad is not None:
        buckets = [replace(b, fault_pad=tuple(fault_pad))
                   for b in buckets]
    for bucket in buckets:
        eng = build_bucket_engine(bucket, lint=lint)
        final, traces = eng.run_stream(bucket.budgets, chunk=chunk)
        steps_done, _, _ = eng.fleet_progress(final, bucket.budgets)
        live = np.asarray(eng.world_active(final))
        for b, cfg in enumerate(bucket.configs):
            out[cfg.run_id] = WorldEval(
                run_id=cfg.run_id, trace=traces[b],
                schedule=cfg.parse_faults() or FaultSchedule(()),
                supersteps=int(steps_done[b]),
                budget=int(cfg.budget),
                quiesced=not bool(live[b]))
    return out
