"""Seeded deterministic mutation/crossover over fault schedules.

Every operator is a pure function of its :class:`random.Random`
stream — the campaign derives one stream per (campaign seed,
generation, slot) via sha256 (campaign.py), so the whole search is
replayable from its seed with no dependence on dict ordering, wall
time, or platform. Operators only generate events inside the
domain's bounds (domain.py: window-safe slow-down degradations,
in-range nodes, per-kind row caps), so every candidate of a campaign
evaluates under one shared executable shape.

The operator vocabulary is the ISSUE's: shift/widen crash windows,
retarget crashes, toggle reset, add/remove partitions (contiguous
two-group cuts — always valid, and they print as compact ``a-b|c-d``
range grammar), add/remove/perturb degrade windows, add/remove
crashes, one-point crossover. ``suffix_mutate`` is the
counterfactual-forking form: it only APPENDS events whose windows
start at or after the fork instant, so the mutated world shares the
snapshot's past bit-for-bit (fork.py validates the same invariant).
"""

from __future__ import annotations

import random
from typing import List, Optional, Tuple

from ..faults.schedule import (FaultSchedule, LinkWindow, NodeCrash,
                               Partition)
from .domain import ScheduleDomain

__all__ = ["mutate", "suffix_mutate", "crossover"]

#: degrade slow-down factors the operators draw from (>= 1 only —
#: the domain's window-invariance rule)
_SCALES = (2.0, 4.0, 8.0)


def _window(rng: random.Random, dom: ScheduleDomain,
            t_lo: int = 0) -> Tuple[int, int]:
    """One event window in ``[t_lo, dom.t_max)``. Half the draws are
    'early-long' — starting near ``t_lo`` and outlasting the horizon
    — because liveness violations usually need a window that covers
    the protocol's whole active phase; the other half are uniform
    windows that the shift/widen operators can then hill-climb."""
    h, tm = dom.horizon_us, dom.t_max
    span = tm - t_lo
    if span < 2:
        return t_lo, t_lo + 1
    if rng.random() < 0.5:
        lo = t_lo + rng.randrange(0, max(1, min(span - 1, h // 8 + 1)))
        hi = max(lo + 1, t_lo + (3 * span) // 4
                 + rng.randrange(0, max(1, span // 4)))
    else:
        lo = t_lo + rng.randrange(0, span - 1)
        ln = rng.randrange(max(1, h // 8), h + 1)
        hi = lo + ln
    return lo, min(max(hi, lo + 1), tm)


def _node(rng: random.Random, dom: ScheduleDomain) -> int:
    """A target node: biased toward low ids (protocol roles — rumor
    origins, token holders, leaders — concentrate there in every
    shipped family), uniform otherwise, so the bias helps at any
    node count without ever excluding a target."""
    if rng.random() < 0.25:
        return 0
    return rng.randrange(dom.n_nodes)


def _add_crash(rng, dom, t_lo=0) -> NodeCrash:
    lo, hi = _window(rng, dom, t_lo)
    return NodeCrash(_node(rng, dom), lo, hi,
                     reset_state=rng.random() < 0.25)


def _add_partition(rng, dom, t_lo=0) -> Partition:
    # contiguous prefix cuts, half of them small (isolate a few
    # low-id nodes) — the low-id role bias again, and small cuts
    # print as tight `0-k|...` range grammar
    if rng.random() < 0.5:
        cut = rng.randrange(1, max(2, dom.n_nodes // 8))
    else:
        cut = rng.randrange(1, dom.n_nodes)
    lo, hi = _window(rng, dom, t_lo)
    return Partition((tuple(range(cut)),
                      tuple(range(cut, dom.n_nodes))), lo, hi)


def _add_degrade(rng, dom, t_lo=0) -> LinkWindow:
    lo, hi = _window(rng, dom, t_lo)
    if rng.random() < 0.5:
        src = dst = None                       # all:all
    else:
        src, dst = (rng.randrange(dom.n_nodes),), None
    return LinkWindow(src, dst, lo, hi, rng.choice(_SCALES),
                      extra_us=rng.choice((0, dom.horizon_us // 20,
                                           dom.horizon_us // 8)))


def _ops(evs: List, dom: ScheduleDomain) -> List[str]:
    """The applicable operator deck, weighted by repetition (adds
    dominate while the schedule is small; perturbations once there
    is something to climb on)."""
    crashes = [e for e in evs if isinstance(e, NodeCrash)]
    parts = [e for e in evs if isinstance(e, Partition)]
    links = [e for e in evs if isinstance(e, LinkWindow)]
    deck: List[str] = []
    if len(crashes) < dom.crash_cap:
        deck += ["add_crash"] * 3
    if len(parts) < dom.part_cap:
        deck += ["add_partition"] * 2
    if len(links) < dom.link_cap:
        deck += ["add_degrade"]
    if evs:
        deck += ["drop", "shift", "shift"]
    if crashes:
        deck += ["widen", "widen", "retarget", "toggle_reset"]
    if links:
        deck += ["perturb_degrade"]
    return deck or ["add_crash"]


def _apply(op: str, rng: random.Random, evs: List,
           dom: ScheduleDomain) -> Optional[List]:
    out = list(evs)
    idx = {
        "crash": [i for i, e in enumerate(out)
                  if isinstance(e, NodeCrash)],
        "link": [i for i, e in enumerate(out)
                 if isinstance(e, LinkWindow)],
    }
    if op == "add_crash":
        out.append(_add_crash(rng, dom))
    elif op == "add_partition":
        out.append(_add_partition(rng, dom))
    elif op == "add_degrade":
        out.append(_add_degrade(rng, dom))
    elif op == "drop":
        out.pop(rng.randrange(len(out)))
    elif op == "shift":
        i = rng.randrange(len(out))
        e = out[i]
        d = rng.randrange(1, dom.horizon_us) * rng.choice((-1, 1))
        if isinstance(e, NodeCrash):
            e = NodeCrash(e.node, max(0, e.t_down + d),
                          max(1, e.t_up + d), e.reset_state)
        elif isinstance(e, Partition):
            e = Partition(e.groups, e.t_start + d, e.t_end + d)
        elif isinstance(e, LinkWindow):
            e = LinkWindow(e.src, e.dst, e.t_start + d, e.t_end + d,
                           e.scale, e.extra_us)
        else:
            return None                       # skews are not mutated
        e = dom.clamp_event(e)
        if e is None:
            return None
        out[i] = e
    elif op == "widen":
        i = rng.choice(idx["crash"])
        e = out[i]
        grow = rng.randrange(1, dom.horizon_us)
        if rng.random() < 0.5:
            e = NodeCrash(e.node, max(0, e.t_down - grow), e.t_up,
                          e.reset_state)
        else:
            e = NodeCrash(e.node, e.t_down, e.t_up + grow,
                          e.reset_state)
        out[i] = dom.clamp_event(e)
    elif op == "retarget":
        i = rng.choice(idx["crash"])
        e = out[i]
        out[i] = NodeCrash(_node(rng, dom), e.t_down,
                           e.t_up, e.reset_state)
    elif op == "toggle_reset":
        i = rng.choice(idx["crash"])
        e = out[i]
        out[i] = NodeCrash(e.node, e.t_down, e.t_up,
                           not e.reset_state)
    elif op == "perturb_degrade":
        i = rng.choice(idx["link"])
        e = out[i]
        out[i] = LinkWindow(e.src, e.dst, e.t_start, e.t_end,
                            rng.choice(_SCALES),
                            extra_us=rng.choice(
                                (0, dom.horizon_us // 20,
                                 dom.horizon_us // 8)))
    else:
        raise ValueError(f"unknown mutation op {op!r}")
    return [e for e in out if e is not None]


def mutate(rng: random.Random, schedule: FaultSchedule,
           dom: ScheduleDomain) -> FaultSchedule:
    """One seeded mutation of ``schedule`` inside ``dom`` (module
    docstring). Always returns an admissible schedule; an operator
    that no-ops (empty clamp, inadmissible result) retries from the
    same stream, falling back to the input unchanged after a bounded
    number of draws — determinism over cleverness."""
    evs = list(schedule.events)
    for _ in range(8):
        deck = _ops(evs, dom)
        out = _apply(rng.choice(deck), rng, evs, dom)
        if out is None:
            continue
        cand = FaultSchedule(tuple(out))
        if dom.admissible(cand):
            return cand
    return FaultSchedule(tuple(evs))


def suffix_mutate(rng: random.Random, base: FaultSchedule,
                  t_open: int,
                  dom: ScheduleDomain) -> Optional[FaultSchedule]:
    """The counterfactual-forking mutation: ``base``'s events plus
    ONE appended event whose window starts at or after ``t_open`` —
    the snapshot's executed horizon, fork instant + window
    (fork.validate_fork_suffix re-validates; the last snapshot
    superstep already fired every instant below it). The only
    mutation shape that provably leaves the snapshot's past
    untouched. Returns None when the appended kind's row cap is
    already full or no window fits."""
    if t_open >= dom.t_max - 1:
        return None
    deck: List[str] = []
    if len(base.crashes) < dom.crash_cap:
        deck += ["crash"] * 3
    if len(base.partitions) < dom.part_cap:
        deck += ["partition"] * 2
    if len(base.link_windows) < dom.link_cap:
        deck += ["degrade"]
    if not deck:
        return None
    kind = rng.choice(deck)
    if kind == "crash":
        ev = _add_crash(rng, dom, t_lo=t_open)
    elif kind == "partition":
        ev = _add_partition(rng, dom, t_lo=t_open)
    else:
        ev = _add_degrade(rng, dom, t_lo=t_open)
    return FaultSchedule(tuple(base.events) + (ev,))


def crossover(rng: random.Random, a: FaultSchedule,
              b: FaultSchedule,
              dom: ScheduleDomain) -> Optional[FaultSchedule]:
    """One-point recombination: a prefix of ``a``'s events spliced to
    a suffix of ``b``'s. Returns None when the child is inadmissible
    (over a row cap) — the campaign falls back to mutation."""
    i = rng.randrange(0, len(a.events) + 1)
    j = rng.randrange(0, len(b.events) + 1)
    child = FaultSchedule(tuple(a.events[:i]) + tuple(b.events[j:]))
    return child if dom.admissible(child) else None
