"""``timewarp-tpu search run|repro`` — the adversarial chaos search CLI.

::

    timewarp-tpu search run FAMILY --params JSON [--link SPEC]
        [--seed S] [--window W|auto] [--budget N]
        [--objective eventually-delivered[:T] | convergence:LIMIT]
        [--population P] [--generations G] [--search-seed S]
        [--fork K] [--fork-frac F] [--horizon-us H]
        [--base-faults SPEC] [--journal DIR]
    timewarp-tpu search repro REPRO.json

``run`` drives one :class:`~timewarp_tpu.search.campaign.ChaosSearch`
campaign and prints one JSON result line; with ``--journal DIR`` the
campaign journals its history (``search_*`` events) and writes the
minimized repro artifact to ``DIR/repro.json``. Exit 0 = a violation
was found, minimized, and its repro emitted; 3 = the search exhausted
its generations without a counterexample — 3, not 2, because argparse
exits 2 on usage errors, and CI must be able to tell "no bug found"
from "search never started".

``repro`` replays a repro artifact solo and re-judges the recorded
objective: exit 0 iff the violation REPRODUCES (the artifact's whole
point), 1 with a loud message when it does not.
"""

from __future__ import annotations

import argparse
import json

from ..sweep.spec import RunConfig, SweepConfigError

__all__ = ["search_main"]


def _loud(fn):
    try:
        return fn()
    except (SweepConfigError, ValueError) as e:
        raise SystemExit(str(e)) from None


def _run(argv) -> int:
    p = argparse.ArgumentParser(
        prog="timewarp-tpu search run",
        description="Adversarial chaos search over fault-schedule "
                    "space (timewarp_tpu/search/, docs/search.md).")
    p.add_argument("family",
                   choices=["token-ring", "gossip", "praos",
                            "ping-pong"],
                   help="scenario family (the sweep pack families)")
    p.add_argument("--params", default="{}",
                   help="scenario builder params as one JSON object, "
                        "e.g. '{\"nodes\": 8, \"fanout\": 2, "
                        "\"end_us\": 120000, \"burst\": true}'")
    p.add_argument("--link", default="uniform:1000:5000")
    p.add_argument("--seed", type=int, default=0,
                   help="the emulated world's engine seed (part of "
                        "the repro identity)")
    from ..cli import _window_arg
    p.add_argument("--window", type=_window_arg, default="auto",
                   help="superstep window µs, or 'auto' (a bad value "
                        "is an argparse usage error, never a raw "
                        "traceback)")
    p.add_argument("--budget", type=int, default=1000,
                   help="superstep budget per evaluation")
    p.add_argument("--base-faults", default=None,
                   help="seed schedule for generation 0 (--faults "
                        "grammar); default: start from no faults")
    p.add_argument("--objective", default="eventually-delivered",
                   help="the property to violate: "
                        "eventually-delivered[:AFTER_T] | "
                        "convergence:LIMIT")
    p.add_argument("--population", type=int, default=12)
    p.add_argument("--generations", type=int, default=8)
    p.add_argument("--search-seed", type=int, default=0,
                   help="campaign seed: the whole search is a pure "
                        "function of (config, knobs, this seed)")
    p.add_argument("--fork", type=int, default=0, metavar="K",
                   help="counterfactual forking: fan K fault-suffix "
                        "continuations out from a mid-run snapshot "
                        "of each generation's best candidate, "
                        "paying only for the suffix that differs")
    p.add_argument("--fork-frac", type=float, default=0.5,
                   help="fork point as a fraction of the supersteps "
                        "the candidate's own evaluation actually "
                        "executed (worlds usually quiesce far below "
                        "the nominal budget — docs/search.md)")
    p.add_argument("--horizon-us", type=int, default=None,
                   help="search-domain time horizon (default: the "
                        "params' end_us)")
    p.add_argument("--max-bucket", type=int, default=64)
    p.add_argument("--chunk", type=int, default=64)
    p.add_argument("--minimize-trials", type=int, default=256)
    p.add_argument("--journal", default=None,
                   help="journal directory: search_* event history "
                        "+ repro.json (ingest with `timewarp-tpu "
                        "ledger add` — the 'search' kind)")
    args = p.parse_args(argv)

    try:
        params = json.loads(args.params)
        if not isinstance(params, dict):
            raise ValueError("must be a JSON object")
    except (json.JSONDecodeError, ValueError) as e:
        raise SystemExit(f"--params must be one JSON object of "
                         f"builder params ({e})")

    def build():
        from .campaign import ChaosSearch
        from .domain import domain_for
        base = RunConfig(
            run_id="search-base", family=args.family,
            params=tuple(sorted(params.items())), link=args.link,
            seed=args.seed, window=args.window, budget=args.budget,
            faults=args.base_faults)
        base.parse_link()
        base.parse_faults()
        return ChaosSearch(
            base=base, objective=args.objective,
            domain=domain_for(base, horizon_us=args.horizon_us),
            population=args.population,
            generations=args.generations, seed=args.search_seed,
            fork_k=args.fork, fork_frac=args.fork_frac,
            max_bucket=args.max_bucket, chunk=args.chunk,
            minimize_trials=args.minimize_trials,
            journal_dir=args.journal)
    campaign = _loud(build)
    # run() raises user-input-shaped ValueErrors too (e.g. the
    # gen-0 "base already violates" guard) — same clean-exit wrap
    result = _loud(campaign.run)
    print(json.dumps(result.to_json()))
    # 3, not 2: argparse owns exit 2 for usage errors (docstring)
    return 0 if result.found else 3


def _repro(argv) -> int:
    p = argparse.ArgumentParser(
        prog="timewarp-tpu search repro",
        description="Replay a chaos-search repro artifact solo and "
                    "re-judge its objective: exit 0 iff the recorded "
                    "violation reproduces.")
    p.add_argument("repro", help="repro.json written by `search run`")
    args = p.parse_args(argv)
    try:
        with open(args.repro) as f:
            rec = json.load(f)
    except OSError as e:
        raise SystemExit(f"cannot read repro artifact: {e}") from None
    except json.JSONDecodeError as e:
        raise SystemExit(
            f"{args.repro!r} is not JSON ({e}) — expected the "
            "repro.json `search run` writes") from None
    if not isinstance(rec, dict) \
            or rec.get("kind") != "chaos-search-repro":
        raise SystemExit(
            f"{args.repro!r} is not a chaos-search repro artifact "
            "(kind != 'chaos-search-repro')")

    def judge():
        from .objectives import rejudge_repro
        try:
            return rejudge_repro(rec)
        except KeyError as e:
            raise SystemExit(
                f"{args.repro!r} is missing repro field {e} — "
                "truncated or hand-edited artifact "
                "(docs/search.md names the format)") from None
    obj, violated, score = _loud(judge)
    out = {"repro": args.repro, "objective": obj.name,
           "faults": rec["faults"], "reproduced": bool(violated)}
    print(json.dumps(out))
    if not violated:
        import sys
        sys.stderr.write(
            f"repro FAILED to reproduce: {obj.name} holds under "
            f"--faults {rec['faults']!r} (score {score})\n")
        return 1
    return 0


def search_main(argv) -> int:
    if not argv or argv[0] not in ("run", "repro"):
        raise SystemExit(
            "usage: timewarp-tpu search run FAMILY --params JSON "
            "[--objective ...] | search repro REPRO.json  "
            "(docs/search.md)")
    return _run(argv[1:]) if argv[0] == "run" else _repro(argv[1:])
