"""Counterfactual forking: spend chips only on the suffix that differs.

A campaign evaluating K variations of one promising schedule from
t=0 re-executes the shared prefix K times. The repo already has
everything needed to skip that: per-world, digest-verified state
snapshots (utils/checkpoint.py — every leaf sha256'd at save,
re-verified at load) and a batched engine whose worlds differ only
by their fault tables. So: snapshot the base world at superstep t,
load ONE world's slice (:func:`~timewarp_tpu.utils.checkpoint.
load_world_state`), broadcast it across a fresh K-world fleet
(:func:`~timewarp_tpu.sweep.bucket.tile_world_state`), and hand each
world a *divergent fault suffix* — the base schedule's events plus
appended events whose windows open at or after the snapshot's
EXECUTED horizon, fork instant + resolved window
(:func:`validate_fork_suffix` explains the ``+ window``).

The fork exactness argument (pinned by tests): every fork world runs
the base seed (identical entropy streams — entropy is a pure function
of seed/instant/node, core/rng.py), the base window (the domain's
slow-down-only rule keeps the resolved window candidate-invariant),
and a fault table whose rows agree with the base schedule for all
past time — appended rows are windows that have not opened yet, and
until a window opens its row is indistinguishable from padding
(faults/schedule.py FaultTables). Therefore world b's continuation ≡
a from-scratch solo run of (snapshot prefix schedule + suffix b),
and the world whose suffix is EMPTY is bit-identical to the
uninterrupted base run — the fork law.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..faults.schedule import (ClockSkew, FaultSchedule, LinkWindow,
                               NodeCrash, Partition)
from ..sweep.bucket import Bucket, build_bucket_engine, tile_world_state
from ..sweep.spec import RunConfig, resolve_window
from .domain import candidate_config

__all__ = ["validate_fork_suffix", "fork_bucket", "load_fork_state",
           "run_fork", "ForkRun"]


def validate_fork_suffix(base: FaultSchedule, sched: FaultSchedule,
                         t_fork: int, window: int = 1) -> None:
    """A fork schedule must be ``base``'s events plus appended events
    whose windows open at or after ``t_fork + window`` — anything
    else could rewrite the snapshot's past, silently breaking the
    fork law. The ``+ window`` is not pedantry: a windowed superstep
    at virtual time t executes EVERY instant in ``[t, t + W)`` and
    leaves ``state.time == t``, so the snapshot's last superstep
    already fired the whole band ``[t_fork, t_fork + W)`` without
    the suffix fault — an event opening inside it would produce a
    continuation matching NO from-scratch schedule run. Skews are
    refused outright: a skew shifts a node's *view* of all time,
    past included."""
    evs = tuple(sched.events)
    horizon = t_fork + max(int(window), 1)
    if evs[:len(base.events)] != tuple(base.events):
        raise ValueError(
            "a fork schedule must carry the snapshot's base events "
            "as an unmodified prefix (suffix-append only); got "
            f"{evs[:len(base.events)]!r} vs base {base.events!r}")
    for e in evs[len(base.events):]:
        if isinstance(e, ClockSkew):
            raise ValueError(
                "a ClockSkew cannot be a fork suffix event: it "
                "shifts the node's view of ALL time, the snapshot's "
                "past included (docs/search.md)")
        opens = e.t_down if isinstance(e, NodeCrash) else e.t_start
        if opens < horizon:
            raise ValueError(
                f"fork suffix event {e!r} opens at {opens} µs, "
                f"inside the snapshot's executed horizon "
                f"{horizon} µs (fork instant {t_fork} + window "
                f"{window}: the last superstep already fired that "
                "band) — it would rewrite the snapshot's past")
        if isinstance(e, LinkWindow) and (e._num < e._den):
            raise ValueError(
                f"fork suffix degrade {e!r} shrinks delays "
                "(scale < 1): it could undercut the base run's "
                "resolved window (docs/search.md)")
        if not isinstance(e, (NodeCrash, Partition, LinkWindow)):
            raise ValueError(f"unknown fork suffix event {e!r}")


def fork_bucket(base_cfg: RunConfig,
                schedules: Sequence[FaultSchedule], t_fork: int, *,
                fault_pad: Optional[Tuple[int, int, int]] = None,
                lint: str = "off"):
    """Build the K-world continuation fleet: one batched engine whose
    world b runs ``schedules[b]`` — each validated as a suffix-append
    of the base config's own schedule — at the base seed and the base
    config's resolved window. ``fault_pad`` (the snapshot engine's
    realized pad, or the search domain's caps) pins the fault-table
    rows so the loaded ``restart_done`` columns line up. Returns
    ``(engine, configs)``."""
    base_sched = base_cfg.parse_faults() or FaultSchedule(())
    window = resolve_window(base_cfg)
    cfgs: List[RunConfig] = []
    for k, s in enumerate(schedules):
        validate_fork_suffix(base_sched, s, t_fork, window)
        cfgs.append(candidate_config(base_cfg, s, f"fork{k}"))
    bucket = Bucket("fork", tuple(cfgs), window,
                    fault_pad=tuple(fault_pad) if fault_pad else None)
    return build_bucket_engine(bucket, lint=lint), cfgs


def load_fork_state(engine, ckpt_path: str, world: int):
    """Admit one snapshot world into the fork fleet: load world
    ``world``'s digest-verified slice at the fork engine's solo shape
    (``restart_done`` growing False rows for appended crashes —
    utils/checkpoint.py), then broadcast it across the fleet.
    Returns ``(state, t_fork, meta)``."""
    import jax

    from ..utils.checkpoint import load_world_state
    solo_template = jax.tree.map(lambda x: x[0], engine.init_state())
    solo, meta = load_world_state(ckpt_path, solo_template, world)
    t_fork = int(np.asarray(jax.device_get(solo.time)))
    return tile_world_state(engine, solo), t_fork, meta


class ForkRun(NamedTuple):
    """One fork fleet's outcome: per-world suffix traces (virtual
    time starts at the fork instant), per-world suffix superstep
    counts, the shared prefix superstep count, and the chip saving —
    ``1 - (prefix + suffix)/(K*prefix + suffix)``. HONEST
    accounting: the numerator charges the snapshot run's own prefix
    (executed once, purely to create the fork point) as well as the
    suffixes, against what K from-scratch re-runs would have cost;
    K=1 therefore saves exactly nothing, by construction."""
    final: object
    traces: list
    prefix_supersteps: int
    suffix_supersteps: List[int]
    quiesced: List[bool]

    @property
    def saving_frac(self) -> float:
        K = len(self.suffix_supersteps)
        suffix = sum(self.suffix_supersteps)
        full = K * self.prefix_supersteps + suffix
        spent = self.prefix_supersteps + suffix
        return round(1.0 - spent / full, 4) if full else 0.0


def run_fork(engine, state, budget: int, *,
             chunk: int = 64) -> ForkRun:
    """Drive the fork fleet to quiescence (or the base config's
    remaining budget) with the chunked fleet driver. ``budget`` is
    the base config's TOTAL superstep budget; each world continues
    from the snapshot's executed count, so prefix + suffix never
    exceeds what the from-scratch run would have spent."""
    import jax
    prefix = int(np.asarray(jax.device_get(state.steps))[0])
    remaining = max(int(budget) - prefix, 0)
    B = engine.batch.B
    final, traces = engine.run_stream(
        np.full(B, remaining, np.int64), state=state, chunk=chunk)
    steps = np.asarray(jax.device_get(final.steps), np.int64)
    live = np.asarray(jax.device_get(engine.world_active(final)))
    return ForkRun(
        final=final, traces=traces, prefix_supersteps=prefix,
        suffix_supersteps=[int(s - prefix) for s in steps],
        quiesced=[not bool(a) for a in live])
