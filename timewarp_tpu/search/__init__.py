"""Adversarial chaos search: schedule-space fuzzing over the emulator.

The repo's existing pieces — deterministic fault schedules (faults/),
the shape-bucketed batched evaluator (sweep/), per-world protocol
properties (faults/properties.py), digest-verified per-world
snapshots (utils/checkpoint.py) — compose into a search harness:
treat :class:`~timewarp_tpu.faults.schedule.FaultSchedule` space as a
search domain and drive batched fleets as the evaluator, evolving
schedules toward property violations. Found counterexamples are
delta-minimized and emitted as replayable repro artifacts (config +
seed + ``--faults`` grammar string) that re-fail the property
bit-for-bit solo.

Everything here is host-side composition over the existing engines:
zero search-subsystem state lives inside any engine, so the exactness
laws are untouched by construction, and the whole campaign is a pure
function of its (base config, knobs, seed) inputs — docs/search.md
"The determinism law".
"""

from .campaign import CampaignResult, ChaosSearch
from .domain import ScheduleDomain, candidate_config, domain_for
from .fork import fork_bucket, load_fork_state, run_fork
from .minimize import minimize_counterexample
from .mutate import crossover, mutate, suffix_mutate
from .objectives import (Objective, WorldEval, evaluate_configs,
                         parse_objective)

__all__ = [
    "ChaosSearch", "CampaignResult",
    "ScheduleDomain", "domain_for", "candidate_config",
    "Objective", "WorldEval", "parse_objective", "evaluate_configs",
    "mutate", "suffix_mutate", "crossover",
    "fork_bucket", "load_fork_state", "run_fork",
    "minimize_counterexample",
]
