"""The ChaosSearch campaign driver: evolve schedules, fork, minimize.

One campaign is: seed a population of fault schedules around a base
:class:`~timewarp_tpu.sweep.spec.RunConfig`, evaluate each generation
as ONE shape-shared batched fleet (objectives.evaluate_configs —
candidates differ only by fault tables, padded to the domain caps, so
the whole campaign reuses one executable shape per fleet width),
select by objective score, and breed the next generation with the
seeded operators (mutate.py). Optionally, each generation spends part
of its budget on **counterfactual forking** (fork.py): snapshot the
current best candidate mid-run (digest-verified checkpoint) and fan K
suffix mutations out from that snapshot, paying only for the suffix
that differs; fork-discovered candidates join the breeding pool, and
any fork-phase violation is RE-CONFIRMED from t=0 before it is ever
reported (a suffix trace cannot soundly witness a whole-run property
on its own).

Found counterexamples are delta-minimized (minimize.py) and emitted
as a replayable repro artifact — config + seed + ``--faults`` grammar
string — written atomically into the journal dir as ``repro.json``.

**The determinism law** (tests/test_zzzzzzzzsearch.py): the whole
campaign is a pure function of (base config, knobs, seed). Mutation
streams derive from sha256(seed, generation, slot); evaluation is the
deterministic engines; selection breaks ties on candidate index;
journal records carry no wall-clock facts. Re-running a campaign
yields an identical generation history, identical counterexample, and
an identical minimized repro string — and the repro replays the
violation bit-for-bit solo. No search state lives inside any engine:
this module is host-side composition only.

Campaigns journal through the sweep journal (``search_campaign``,
``search_gen``, ``search_fork``, ``search_counterexample``,
``search_minimized``, ``search_done`` events in ``journal.jsonl``)
and ingest into the run ledger as the ``search`` kind
(obs/ledger.py), so counterexamples and search progress are
queryable history.
"""

from __future__ import annotations

import hashlib
import os
import random
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..faults.schedule import FaultSchedule, format_faults
from ..sweep.spec import RunConfig, resolve_window
from .domain import ScheduleDomain, candidate_config, domain_for
from .minimize import minimize_counterexample
from .mutate import crossover, mutate, suffix_mutate
from .objectives import (Objective, WorldEval, evaluate_configs,
                         parse_objective)

__all__ = ["ChaosSearch", "CampaignResult"]


def _rng(seed: int, *words) -> random.Random:
    """One deterministic stream per (campaign seed, role words) —
    sha256-derived so streams are independent and platform-stable."""
    tag = f"tw-search:{seed}:" + ":".join(str(w) for w in words)
    h = hashlib.sha256(tag.encode()).digest()
    return random.Random(int.from_bytes(h[:8], "big"))


def _key(s: FaultSchedule) -> str:
    return format_faults(s) if s.events else ""


class _Verdict(NamedTuple):
    violated: bool
    score: int
    origin: str            # "fleet" | "confirm"
    supersteps: int = 0    # what the evaluation actually executed —
    #                        anchors the fork point (a world usually
    #                        quiesces far below its superstep budget)


class CampaignResult(NamedTuple):
    found: bool
    counterexample: Optional[str]     # --faults grammar string
    minimized: Optional[str]          # minimized grammar string
    repro: Optional[dict]             # the repro artifact
    repro_path: Optional[str]         # repro.json (journaled runs)
    generations: List[dict]           # per-gen history (journal twin)
    evaluations: int                  # full t=0 world evaluations
    fork: dict                        # fork bookkeeping + saving

    def to_json(self) -> dict:
        return {"found": self.found,
                "counterexample": self.counterexample,
                "minimized": self.minimized,
                "repro_path": self.repro_path,
                "generations": len(self.generations),
                "evaluations": self.evaluations,
                "fork": self.fork}


@dataclass
class ChaosSearch:
    """One adversarial campaign (module docstring). ``base`` supplies
    everything but the fault schedule; ``objective`` the violation
    predicate + pressure gradient; ``domain`` the mutation bounds
    (default: derived from the base config's params). ``fork_k > 0``
    enables the counterfactual-forking refinement phase."""
    base: RunConfig
    objective: Objective
    domain: Optional[ScheduleDomain] = None
    population: int = 12
    generations: int = 8
    seed: int = 0
    elites: int = 0                    # 0 = max(2, population // 4)
    fork_k: int = 0
    fork_frac: float = 0.5
    max_bucket: int = 64
    chunk: int = 64
    lint: str = "off"
    journal_dir: Optional[str] = None
    stop_on_violation: bool = True
    minimize_trials: int = 256
    _journal: object = field(default=None, repr=False)

    def __post_init__(self):
        if isinstance(self.objective, str):
            self.objective = parse_objective(self.objective)
        if self.domain is None:
            self.domain = domain_for(self.base)
        if self.population < 2:
            raise ValueError("a campaign needs population >= 2")
        if self.generations < 1:
            raise ValueError("a campaign needs generations >= 1")
        if not (0.0 < self.fork_frac < 1.0):
            raise ValueError(
                f"fork_frac must be in (0, 1), got {self.fork_frac}")
        if self.elites == 0:
            # always strictly below the population: elites ==
            # population would silently disable breeding (every
            # generation re-ranks the same cached schedules forever)
            self.elites = max(1, min(self.population - 1,
                                     max(2, self.population // 4)))
        if self.elites >= self.population:
            raise ValueError(
                f"elites={self.elites} >= population="
                f"{self.population}: no offspring would ever be "
                "bred — the campaign would re-rank the same "
                "schedules every generation")
        base_sched = self.base.parse_faults() or FaultSchedule(())
        if not self.domain.admissible(base_sched):
            raise ValueError(
                "the base config's own fault schedule exceeds the "
                "search domain's table caps "
                f"{self.domain.table_pad} — raise the caps "
                "(ScheduleDomain) so every candidate shares one "
                "executable shape")
        if self.journal_dir:
            from ..sweep.journal import SweepJournal
            self._journal = SweepJournal(self.journal_dir)
            if self._journal.exists():
                # campaigns have no resume: appending a second
                # campaign's stream to an existing journal would mix
                # histories (and the ledger's `search` ingest reads
                # the FIRST campaign records next to the LAST
                # repro.json) — one journal dir per campaign, the
                # sweep's one-dir-per-pack convention
                raise ValueError(
                    f"{self.journal_dir!r} already holds a campaign "
                    "journal — campaigns have no resume; use a "
                    "fresh --journal dir per campaign")
            self._journal.ensure_dir()

    # -- journaling --------------------------------------------------------

    def _append(self, rec: dict) -> None:
        if self._journal is not None:
            self._journal.append(rec)

    # -- evaluation --------------------------------------------------------

    def _evaluate_fresh(self, gen: int,
                        population: List[FaultSchedule],
                        cache: Dict[str, _Verdict]) -> int:
        """Evaluate every not-yet-seen candidate of this generation
        as one fleet; fold verdicts into the cache. Returns the
        number of fresh t=0 evaluations."""
        fresh: List[Tuple[str, RunConfig]] = []
        seen = set(cache)
        for i, s in enumerate(population):
            k = _key(s)
            if k in seen:
                continue
            seen.add(k)
            fresh.append((k, candidate_config(self.base, s,
                                              f"g{gen}c{i}")))
        if fresh:
            evals = evaluate_configs(
                [c for _, c in fresh],
                fault_pad=self.domain.table_pad,
                max_bucket=self.max_bucket, chunk=self.chunk,
                lint=self.lint)
            for k, cfg in fresh:
                ev = evals[cfg.run_id]
                violated, score = self.objective.judge(ev)
                cache[k] = _Verdict(violated, score, "fleet",
                                    ev.supersteps)
        return len(fresh)

    def _confirm(self, s: FaultSchedule,
                 cache: Dict[str, _Verdict]) -> _Verdict:
        """A from-scratch verdict for one schedule (the sound form a
        fork-phase violation must pass before it is reported)."""
        cfg = candidate_config(self.base, s, "confirm")
        ev = evaluate_configs([cfg],
                              fault_pad=self.domain.table_pad,
                              chunk=self.chunk,
                              lint=self.lint)["confirm"]
        violated, score = self.objective.judge(ev)
        v = _Verdict(violated, score, "confirm", ev.supersteps)
        cache[_key(s)] = v
        return v

    # -- the fork refinement phase ----------------------------------------

    def _fork_phase(self, gen: int, best: FaultSchedule,
                    cache: Dict[str, _Verdict], stats: dict,
                    pool: List[FaultSchedule]
                    ) -> Optional[FaultSchedule]:
        """Snapshot the generation's best candidate at
        ``fork_frac × budget`` supersteps and fan ``fork_k`` suffix
        mutations out from the snapshot (module docstring). Returns a
        CONFIRMED counterexample schedule, or None; scored suffix
        candidates join ``pool`` for breeding either way."""
        import tempfile

        import jax

        from ..sweep.bucket import Bucket, build_bucket_engine
        from ..utils.checkpoint import save_state
        from .fork import fork_bucket, load_fork_state, run_fork
        base_cfg = candidate_config(self.base, best, f"g{gen}fb")
        bucket = Bucket(f"g{gen}fb", (base_cfg,),
                        resolve_window(base_cfg),
                        fault_pad=self.domain.table_pad)
        eng = build_bucket_engine(bucket, lint=self.lint)
        # fork at fork_frac of the supersteps this candidate ACTUALLY
        # executed (its cached evaluation) — a world usually quiesces
        # far below its nominal budget, and forking past quiescence
        # forks nothing
        executed = cache[_key(best)].supersteps or self.base.budget
        fork_budget = max(1, int(executed * self.fork_frac))
        # the engine's own chunked fleet driver runs to quiesce-or-
        # budget — the one quiesce/budget-law implementation, never
        # a hand-rolled twin
        st, _ = eng.run_stream(
            np.asarray([fork_budget], np.int64), chunk=self.chunk)
        if not bool(np.asarray(
                jax.device_get(eng.world_active(st)))[0]):
            return None      # quiesced before the fork point
        t_fork = int(np.asarray(jax.device_get(st.time))[0])
        # suffix events must open past the snapshot's EXECUTED
        # horizon — the last superstep already fired the whole band
        # [t_fork, t_fork + window) (fork.validate_fork_suffix)
        t_open = t_fork + resolve_window(base_cfg)
        suffixes: List[FaultSchedule] = []
        seen = {_key(best)}
        for k in range(4 * self.fork_k):
            if len(suffixes) == self.fork_k:
                break
            s = suffix_mutate(_rng(self.seed, "fork", gen, k), best,
                              t_open, self.domain)
            if s is not None and _key(s) not in seen:
                seen.add(_key(s))
                suffixes.append(s)
        if not suffixes:
            return None
        tmp = None
        if self.journal_dir:
            ckpt = os.path.join(self.journal_dir,
                                f"fork-g{gen}.npz")
        else:
            tmp = tempfile.mkdtemp(prefix="tw_fork_")
            ckpt = os.path.join(tmp, "fork.npz")
        save_state(ckpt, st, meta={"fork_gen": gen,
                                   "t_fork_us": t_fork,
                                   "base": format_faults(best)
                                   if best.events else ""})
        fengine, _fcfgs = fork_bucket(
            base_cfg, suffixes, t_fork,
            fault_pad=self.domain.table_pad, lint=self.lint)
        state, t_fork2, _meta = load_fork_state(fengine, ckpt, 0)
        # the snapshot is only needed until the fleet admitted it:
        # nothing reads it afterwards (campaigns have no resume), so
        # a full engine-state .npz per generation must not pile up —
        # in the journal dir OR /tmp
        import shutil
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)
        else:
            try:
                os.unlink(ckpt)
            except OSError:
                pass
        fr = run_fork(fengine, state, self.base.budget,
                      chunk=self.chunk)
        stats["forks"] += 1
        stats["fork_worlds"] += len(suffixes)
        stats["prefix_supersteps"] += fr.prefix_supersteps
        stats["suffix_supersteps"] += sum(fr.suffix_supersteps)
        # what from-scratch evaluation of these K suffix candidates
        # would have cost: every world re-executes the shared prefix
        stats["full_supersteps"] += (
            len(suffixes) * fr.prefix_supersteps
            + sum(fr.suffix_supersteps))
        self._append({"ev": "search_fork", "gen": gen,
                      "t_fork_us": t_fork, "worlds": len(suffixes),
                      "prefix_supersteps": fr.prefix_supersteps,
                      "suffix_supersteps": fr.suffix_supersteps,
                      "saving_frac": fr.saving_frac})
        found: Optional[FaultSchedule] = None
        for k, s in enumerate(suffixes):
            ev = WorldEval(
                run_id=f"g{gen}f{k}", trace=fr.traces[k],
                schedule=s,
                supersteps=fr.prefix_supersteps
                + fr.suffix_supersteps[k],
                budget=self.base.budget, quiesced=fr.quiesced[k],
                trace_from=t_fork)
            violated, _score = self.objective.judge(ev)
            if violated:
                # EVERY fork-judged violation is confirmed from t=0
                # (sound), and the confirmed verdict is what lands in
                # the cache — a second genuine counterexample must
                # never be mislabeled non-violating just because an
                # earlier suffix already hit
                v = self._confirm(s, cache)
                stats["confirmations"] += 1
                if v.violated and found is None:
                    found = s
            # non-violating suffixes deliberately leave NO cache
            # entry: their scores are suffix-relative (incomparable
            # to full-run scores), so a fork schedule that later
            # enters a population evaluates from t=0 like any other
            # candidate — fork influence on the search is pool
            # membership (breeding), nothing else
            pool.append(s)
        return found

    @staticmethod
    def _fork_saving(stats: dict) -> float:
        """``fork_saving_frac``: 1 − supersteps actually spent
        (each fork's snapshot-prefix run PLUS all suffixes — the
        prefix run exists only to create the fork point, so honest
        accounting charges it) / what from-scratch re-runs of every
        fork world would have cost (K × prefix + suffix per fork) —
        0.0 when no fork ran."""
        full = stats["full_supersteps"]
        spent = stats["prefix_supersteps"] + stats["suffix_supersteps"]
        return round(1.0 - spent / full, 4) if full else 0.0

    # -- the campaign ------------------------------------------------------

    def run(self) -> CampaignResult:
        """Run the campaign (module docstring). The journal handle
        closes on EVERY exit — a raise mid-campaign (the fault-free-
        world guard, an engine failure) must not leak the append
        handle for the embedding process's lifetime."""
        try:
            return self._run()
        finally:
            if self._journal is not None:
                self._journal.close()

    def _run(self) -> CampaignResult:
        dom = self.domain
        base_sched = self.base.parse_faults() or FaultSchedule(())
        self._append({
            "ev": "search_campaign",
            "base": self.base.to_json(),
            "objective": self.objective.name,
            "population": self.population,
            "generations": self.generations,
            "elites": self.elites, "seed": self.seed,
            "fork_k": self.fork_k, "fork_frac": self.fork_frac,
            "domain": {"n_nodes": dom.n_nodes,
                       "horizon_us": dom.horizon_us,
                       "table_pad": list(dom.table_pad)}})
        population = [base_sched]
        for i in range(1, self.population):
            population.append(
                mutate(_rng(self.seed, 0, i), base_sched, dom))
        cache: Dict[str, _Verdict] = {}
        history: List[dict] = []
        evaluations = 0
        fork_stats = {"forks": 0, "fork_worlds": 0,
                      "prefix_supersteps": 0, "suffix_supersteps": 0,
                      "full_supersteps": 0, "confirmations": 0}
        counterexample: Optional[FaultSchedule] = None
        found_gen = None
        for g in range(self.generations):
            evaluations += self._evaluate_fresh(g, population, cache)
            scored = [(cache[_key(s)], i, s)
                      for i, s in enumerate(population)]
            violations = sorted(
                (i, s) for v, i, s in scored if v.violated)
            if any(not s.events for _, s in violations):
                # an EMPTY schedule judged violated — gen 0's base,
                # or a later drop-mutation candidate — means the
                # property fails with no faults at all: not a
                # counterexample (it has no grammar form and nothing
                # to minimize), a broken objective/scenario pairing
                raise ValueError(
                    f"the fault-free world already violates "
                    f"{self.objective.name!r} — there is nothing to "
                    "search for; fix the objective (or the "
                    "scenario) first")
            best_v, _, best_s = max(
                scored, key=lambda t: (t[0].score, -t[1]))
            gen_rec = {
                "ev": "search_gen", "gen": g,
                "population": len(population),
                "evaluations": evaluations,
                "best_score": min(best_v.score, 1 << 62),
                "best_faults": _key(best_s),
                "violations": [_key(s) for _, s in violations]}
            history.append({k: v for k, v in gen_rec.items()
                            if k != "ev"})
            self._append(gen_rec)
            if violations:
                if counterexample is None:
                    counterexample = violations[0][1]
                    found_gen = g
                if self.stop_on_violation:
                    break
            # selection: rank by (score desc, index asc), dedupe
            ranked = sorted(scored,
                            key=lambda t: (-t[0].score, t[1]))
            pool: List[FaultSchedule] = []
            seen_k = set()
            for _, _, s in ranked:
                k = _key(s)
                if k not in seen_k:
                    seen_k.add(k)
                    pool.append(s)
                if len(pool) == self.elites:
                    break
            if self.fork_k > 0 and g + 1 < self.generations:
                hit = self._fork_phase(g, pool[0], cache,
                                       fork_stats, pool)
                if hit is not None:
                    if counterexample is None:
                        counterexample = hit
                        found_gen = g
                    if self.stop_on_violation:
                        break
            if g + 1 == self.generations:
                break
            # breed the next generation
            nxt = list(pool[:self.elites])
            slot = 0
            while len(nxt) < self.population:
                rng = _rng(self.seed, g + 1, "breed", slot)
                slot += 1
                a = rng.choice(pool)
                child = None
                if len(pool) >= 2 and rng.random() < 0.3:
                    b = rng.choice(pool)
                    child = crossover(rng, a, b, dom)
                if child is None:
                    child = mutate(rng, a, dom)
                nxt.append(child)
            population = nxt
        fork_out = dict(fork_stats)
        fork_out["saving_frac"] = self._fork_saving(fork_stats)
        if counterexample is None:
            self._append({"ev": "search_done", "found": False,
                          "evaluations": evaluations,
                          "fork": fork_out})
            return CampaignResult(False, None, None, None, None,
                                  history, evaluations, fork_out)
        ce_str = format_faults(counterexample)
        self._append({"ev": "search_counterexample",
                      "gen": found_gen, "faults": ce_str,
                      "objective": self.objective.name})
        mres = minimize_counterexample(
            self.base, counterexample, self.objective,
            max_trials=self.minimize_trials, chunk=self.chunk,
            fault_pad=dom.table_pad, lint=self.lint)
        evaluations += mres.trials
        min_str = format_faults(mres.schedule)
        self._append({"ev": "search_minimized", "faults": min_str,
                      "trials": mres.trials,
                      "dropped_events": mres.dropped_events,
                      "tightened_us": mres.tightened_us})
        repro = {
            "repro_schema": 1, "kind": "chaos-search-repro",
            "scenario": self.base.family,
            "params": dict(self.base.params),
            "link": self.base.link, "seed": self.base.seed,
            "window": self.base.window, "budget": self.base.budget,
            "objective": self.objective.name,
            "faults": min_str, "events": len(mres.schedule.events),
            "search_seed": self.seed, "found_gen": found_gen,
        }
        repro_path = None
        if self.journal_dir:
            import json

            from ..utils.checkpoint import atomic_write
            repro_path = os.path.join(self.journal_dir, "repro.json")

            def write(f):
                json.dump(repro, f, indent=1, sort_keys=True)
                f.write("\n")
            atomic_write(repro_path, write, mode="w")
        self._append({"ev": "search_done", "found": True,
                      "evaluations": evaluations,
                      "counterexample": ce_str,
                      "minimized": min_str, "fork": fork_out})
        return CampaignResult(True, ce_str, min_str, repro,
                              repro_path, history, evaluations,
                              fork_out)
