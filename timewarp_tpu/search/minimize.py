"""Delta-minimization: shrink a counterexample until it stops failing.

A raw counterexample schedule carries everything evolution happened
to accrete — events that do nothing, windows wider than needed. The
minimizer is a deterministic greedy delta-debugger over the schedule
structure: (1) drop whole events — each round evaluates EVERY
single-event drop as ONE batched fleet (they share a bucket key by
construction) and applies the lowest-index still-violating drop,
restarting until no single event can be removed (bit-identical to
the sequential front-to-back greedy, one engine build per round
instead of one per trial); (2) tighten every surviving event's
window by binary search — latest still-violating open, earliest
still-violating close. Every trial is a full from-scratch evaluation
of the trial schedule under the SAME objective
(objectives.evaluate_configs — the batched evaluator), so "still
fails" means exactly what the campaign's verdict meant; no state is
shared between trials. The result is the repro artifact's schedule:
re-parse its grammar string and the violation reproduces bit-for-bit
by the determinism the engines already pin.
"""

from __future__ import annotations

from typing import Callable, List, NamedTuple, Optional, Tuple

from ..faults.schedule import (FaultSchedule, LinkWindow, NodeCrash,
                               Partition)
from ..sweep.spec import RunConfig
from .domain import candidate_config
from .objectives import Objective, evaluate_configs

__all__ = ["minimize_counterexample", "MinimizeResult"]


class MinimizeResult(NamedTuple):
    schedule: FaultSchedule
    trials: int
    dropped_events: int
    tightened_us: int


def _with_window(e, lo: int, hi: int):
    if isinstance(e, NodeCrash):
        return NodeCrash(e.node, lo, hi, e.reset_state)
    if isinstance(e, Partition):
        return Partition(e.groups, lo, hi)
    if isinstance(e, LinkWindow):
        return LinkWindow(e.src, e.dst, lo, hi, e.scale, e.extra_us)
    return None


def _window_of(e) -> Optional[Tuple[int, int]]:
    if isinstance(e, NodeCrash):
        return e.t_down, e.t_up
    if isinstance(e, (Partition, LinkWindow)):
        return e.t_start, e.t_end
    return None                                   # skew: no window


def minimize_counterexample(
        base: RunConfig, schedule: FaultSchedule,
        objective: Objective, *,
        max_trials: int = 256, chunk: int = 64,
        fault_pad: Optional[Tuple[int, int, int]] = None,
        lint: str = "off",
        _judge: Optional[Callable] = None) -> MinimizeResult:
    """Greedy-minimize ``schedule`` while ``objective`` still judges
    the world violated (module docstring). ``base`` is the config the
    counterexample was found against (family/params/link/seed/window/
    budget — everything but the faults). Deterministic: fixed scan
    order, integer binary search, bounded by ``max_trials`` (budget
    exhaustion returns the best-so-far, never a non-violating
    schedule). ``_judge`` overrides the evaluation (tests)."""
    trials = 0

    def _eval_many(schedules: List[FaultSchedule]) -> List[bool]:
        """One batched verdict per trial schedule. All trials of a
        round share the base config's bucket key (faults only ever
        differ), so the round is ONE fleet — one engine build instead
        of one per trial; ``fault_pad`` (the campaign passes its
        domain caps) additionally pins the fault-table shape."""
        nonlocal trials
        trials += len(schedules)
        if _judge is not None:
            return [bool(_judge(s)) for s in schedules]
        cfgs = [candidate_config(base, s, f"min{i}")
                for i, s in enumerate(schedules)]
        evals = evaluate_configs(cfgs, chunk=chunk,
                                 fault_pad=fault_pad, lint=lint)
        return [objective.judge(evals[c.run_id])[0] for c in cfgs]

    def violates(s: FaultSchedule) -> bool:
        if trials >= max_trials:
            return False                # budget gone: stop shrinking
        return _eval_many([s])[0]

    # the entry check runs UNCONDITIONALLY and OUTSIDE the trial
    # budget (the count resets after): with max_trials=0 a genuinely
    # violating input must still return unminimized, never be
    # misreported as non-violating
    if not _eval_many([schedule])[0]:
        raise ValueError(
            "minimize_counterexample was handed a schedule that does "
            f"not violate {objective.name!r} — nothing to minimize "
            "(the campaign confirms counterexamples from t=0 before "
            "minimizing)")
    trials = 0

    # phase 1 — drop whole events: each round batch-evaluates every
    # single-event drop and applies the LOWEST still-violating index
    # (≡ the sequential front-to-back greedy with restart). A round
    # is clipped to the REMAINING budget, so `trials` never exceeds
    # max_trials — the docstring's bound is exact
    events: List = list(schedule.events)
    dropped = 0
    changed = True
    while changed and len(events) > 1 and trials < max_trials:
        changed = False
        drops = [FaultSchedule(tuple(events[:i] + events[i + 1:]))
                 for i in range(len(events))]
        drops = drops[:max_trials - trials]
        for i, ok in enumerate(_eval_many(drops)):
            if ok:
                events = list(drops[i].events)
                dropped += 1
                changed = True
                break

    # phase 2 — tighten windows: latest open / earliest close that
    # still violates, by integer binary search per edge
    tightened = 0

    def _edge_violates(i: int, lo: int, hi: int) -> bool:
        trial = list(events)
        trial[i] = _with_window(events[i], lo, hi)
        return violates(FaultSchedule(tuple(trial)))

    def try_edges(i: int, pick_lo: bool) -> None:
        nonlocal events, tightened
        win = _window_of(events[i])
        if win is None:
            return
        lo, hi = win
        good = lo if pick_lo else hi          # known-violating edge
        bad = (hi - 1) if pick_lo else (lo + 1)   # tightest possible
        if (good >= bad if pick_lo else good <= bad):
            return
        # establish the bisection invariant by TESTING the tightest
        # edge first: if even the minimal window still violates, it
        # IS the answer — an untested 'bad' endpoint could otherwise
        # never be converged onto, leaving the window 1 µs wider
        # than the tightest still-violating form
        if _edge_violates(i, bad if pick_lo else lo,
                          hi if pick_lo else bad):
            good = bad
        else:
            while (abs(bad - good) > 1) and trials < max_trials:
                mid = (good + bad) // 2
                if _edge_violates(i, mid if pick_lo else lo,
                                  hi if pick_lo else mid):
                    good = mid
                else:
                    bad = mid
        if good != (lo if pick_lo else hi):
            tightened += abs(good - (lo if pick_lo else hi))
            events[i] = _with_window(events[i],
                                     good if pick_lo else lo,
                                     hi if pick_lo else good)

    for i in range(len(events)):
        try_edges(i, pick_lo=False)     # close early first
        try_edges(i, pick_lo=True)      # then open late

    return MinimizeResult(FaultSchedule(tuple(events)), trials,
                          dropped, tightened)
