"""The schedule-space search domain: bounds, seeding, candidates.

A :class:`ScheduleDomain` bounds what the mutation operators
(mutate.py) may generate for one scenario: node range, time horizon,
and per-kind fault-table row caps. The caps are load-bearing for the
evaluator, not just taste: every candidate of a campaign stays within
``(crash_cap, part_cap, link_cap)`` rows, the evaluation buckets pin
``fault_pad`` to exactly those caps, and so every generation maps
onto ONE batched executable shape (padding rows are inert —
faults/schedule.py FaultTables) instead of recompiling per candidate
mix.

Operators generate only **liveness-relevant, window-safe** events:
crashes, partitions, and slow-down degradations (``scale >= 1``,
``extra_us >= 0``). A shrink degradation (scale < 1) could undercut
the link model's declared delay floor and change the config's
resolved window — which would scatter candidates across bucket keys
AND change superstep granularity mid-search; slow-downs can only
raise delays, so :func:`~timewarp_tpu.sweep.spec.resolve_window` is
candidate-invariant by construction. Clock skews are excluded from
the generated space (a skew rewrites a node's *view* of all time, so
it can never be a valid fork suffix — fork.py).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional, Tuple

from ..faults.schedule import (FaultSchedule, LinkWindow, NodeCrash,
                               Partition, format_faults)
from ..sweep.spec import RunConfig

__all__ = ["ScheduleDomain", "domain_for", "candidate_config"]


@dataclass(frozen=True)
class ScheduleDomain:
    """Mutation bounds for one scenario (module docstring)."""
    n_nodes: int
    horizon_us: int
    crash_cap: int = 3
    part_cap: int = 2
    link_cap: int = 2

    def __post_init__(self):
        if self.n_nodes < 2:
            raise ValueError(
                f"a schedule domain needs >= 2 nodes, got "
                f"{self.n_nodes}")
        if self.horizon_us < 2:
            raise ValueError(
                f"horizon_us must be >= 2 µs, got {self.horizon_us}")
        for name in ("crash_cap", "part_cap", "link_cap"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0")

    @property
    def table_pad(self) -> Tuple[int, int, int]:
        """The fixed fault-table row shape every campaign bucket pins
        via ``Bucket.fault_pad`` — one executable per generation."""
        return (self.crash_cap, self.part_cap, self.link_cap)

    @property
    def t_max(self) -> int:
        """Latest event-window end the operators generate: past the
        horizon (so a window can outlast the scenario's own deadline)
        but bounded, keeping candidate times small and printable."""
        return 2 * self.horizon_us

    def admissible(self, schedule: FaultSchedule) -> bool:
        """Whether a schedule fits this domain's table caps (the
        mutation operators maintain this invariant; crossover uses it
        to reject over-full recombinations)."""
        return (len(schedule.crashes) <= self.crash_cap
                and len(schedule.partitions) <= self.part_cap
                and len(schedule.link_windows) <= self.link_cap)

    def clamp_event(self, e):
        """An event with its window clamped into ``[0, t_max]``
        (shift/widen mutations may push past either edge); returns
        None when clamping empties the window."""
        tm = self.t_max
        if isinstance(e, NodeCrash):
            # same rule as the other kinds: a crash shifted entirely
            # past t_max empties (None → the operator retries), it
            # does NOT clamp to a phantom sliver at the horizon edge
            # that would squat on a crash_cap row forever
            lo = max(e.t_down, 0)
            hi = min(max(e.t_up, 0), tm)
            if hi <= lo:
                return None
            return NodeCrash(e.node % self.n_nodes, lo, hi,
                             e.reset_state)
        if isinstance(e, Partition):
            lo, hi = max(e.t_start, 0), min(max(e.t_end, 0), tm)
            if hi <= lo:
                return None
            return Partition(e.groups, lo, hi)
        if isinstance(e, LinkWindow):
            lo, hi = max(e.t_start, 0), min(max(e.t_end, 0), tm)
            if hi <= lo:
                return None
            return LinkWindow(e.src, e.dst, lo, hi, e.scale,
                              e.extra_us)
        return e


def domain_for(cfg: RunConfig, *,
               horizon_us: Optional[int] = None,
               **caps) -> ScheduleDomain:
    """The natural domain of one base config: node count from the
    family params (ping-pong is the fixed 2-node scenario), horizon
    from an explicit override or the params' own ``end_us`` deadline.
    A family without a deadline param must pass ``horizon_us`` —
    guessing one silently would make campaign identity depend on a
    heuristic."""
    params = dict(cfg.params)
    n = int(params.get("nodes", 2))
    h = horizon_us if horizon_us is not None else params.get("end_us")
    if h is None:
        raise ValueError(
            f"config {cfg.run_id!r} ({cfg.family}) declares no "
            "end_us param — pass horizon_us= explicitly so the "
            "search domain's time bounds are part of the campaign's "
            "identity")
    return ScheduleDomain(n, int(h), **caps)


def candidate_config(base: RunConfig, schedule: FaultSchedule,
                     run_id: str) -> RunConfig:
    """One candidate as a :class:`~timewarp_tpu.sweep.spec.RunConfig`:
    the base config with ``faults`` replaced by the schedule's grammar
    string (None for an empty schedule — the RunConfig convention).
    Candidates differ ONLY in their fault schedule, so a whole
    generation shares one bucket key (family, params, link signature,
    window — window invariance is the domain's slow-down-only rule)."""
    return dataclasses.replace(
        base, run_id=run_id,
        faults=format_faults(schedule) if schedule.events else None)
