"""Per-bucket leases over a shared journal directory.

The coordination primitive of multi-host serving (docs/serving.md
"The lease protocol"): one lease file per bucket under
``<journal>/leases/``, holding ``{host, gen, ts}``. All writes are
atomic (temp + ``os.link`` for creation — fails if the file exists —
or temp + ``os.replace`` for renewal/steal), so a reader never sees a
torn lease.

- **acquire**: create the file with generation 1; creation races
  between hosts are arbitrated by ``os.link`` (exactly one wins).
- **heartbeat renewal**: the holder atomically rewrites its lease
  with a fresh ``ts`` (same host, same gen) and re-reads — if the
  file is no longer its own content, the lease was stolen and
  :class:`LeaseLost` is raised.
- **stale reclaim (work-stealing)**: a lease whose ``ts`` is older
  than the TTL may be stolen. Stealers race on an ``O_EXCL`` claim
  file named by the NEXT generation, so exactly one claims each
  generation; the winner atomically replaces the lease.

What the protocol guarantees — and what it deliberately does not:
with renewal interval ≪ TTL (every curator chunk renews; TTL
defaults to many chunks), a live holder is never stolen from, and a
dead host's buckets are reclaimed within one TTL. If a host is
paused longer than the TTL (not dead — a VM freeze), holder and
thief can briefly overlap; execution being bit-deterministic, the
overlap degrades to *identical duplicate* ``world_done`` records,
which the journal fold tolerates with a warning — while two
DIFFERENT results for one world remain the loud
``SweepJournalError`` refusal. Commits additionally verify the lease
first (:meth:`Lease.check`), so the overlap window is one chunk.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import Dict, Optional

__all__ = ["Lease", "LeaseDir", "LeaseLost"]


class LeaseLost(RuntimeError):
    """The lease file no longer carries our (host, gen): a peer stole
    the bucket (we must have missed heartbeats past the TTL). The
    holder abandons the bucket without committing — the thief owns it
    now."""


@dataclass
class Lease:
    bucket: str
    host: str
    gen: int
    path: str
    #: the previous holder when this lease was acquired by stale
    #: reclaim (None for a free acquisition) — journaled so steals
    #: are visible in `sweep status` / the ledger
    stolen_from: Optional[str] = None


class LeaseDir:
    def __init__(self, root: str, host: str, *,
                 ttl_s: float = 10.0) -> None:
        if not host:
            raise ValueError("a LeaseDir needs a host name")
        if ttl_s <= 0:
            raise ValueError(f"lease ttl must be > 0 s, got {ttl_s}")
        self.root = os.path.join(root, "leases")
        self.host = host
        self.ttl_s = float(ttl_s)

    def path(self, bucket: str) -> str:
        return os.path.join(self.root, f"{bucket}.lease")

    # -- reading ----------------------------------------------------------

    def read(self, bucket: str) -> Optional[dict]:
        """The current lease record, or None when the bucket is free.
        Writes are atomic, so a parse failure means external damage —
        treated as a stale gen-0 lease (reclaimable), never a crash."""
        try:
            with open(self.path(bucket)) as f:
                rec = json.load(f)
            if not isinstance(rec, dict):
                raise ValueError
            return rec
        except FileNotFoundError:
            return None
        except (json.JSONDecodeError, ValueError, OSError):
            return {"host": "?", "gen": 0, "ts": 0.0}

    def stale(self, rec: dict) -> bool:
        return (time.time() - float(rec.get("ts", 0.0))) > self.ttl_s

    def table(self) -> Dict[str, dict]:
        """bucket -> lease record for every lease file on disk (the
        curators' claim-scan view; `sweep status` reads the journaled
        lease events instead, so status needs no lease-dir access)."""
        out: Dict[str, dict] = {}
        if not os.path.isdir(self.root):
            return out
        for fn in sorted(os.listdir(self.root)):
            if fn.endswith(".lease"):
                rec = self.read(fn[:-len(".lease")])
                if rec is not None:
                    out[fn[:-len(".lease")]] = rec
        return out

    # -- writing ----------------------------------------------------------

    def _write_atomic(self, path: str, rec: dict, *,
                      create: bool) -> bool:
        os.makedirs(self.root, exist_ok=True)
        tmp = f"{path}.w.{self.host}.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(rec, f)
            f.flush()
            os.fsync(f.fileno())
        try:
            if create:
                try:
                    os.link(tmp, path)  # atomic, fails if path exists
                except FileExistsError:
                    return False
                return True
            os.replace(tmp, path)
            return True
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def try_acquire(self, bucket: str) -> Optional[Lease]:
        """One non-blocking claim attempt: a free bucket is acquired
        at generation 1; a stale lease (dead holder, or our own
        previous incarnation) is stolen at the next generation; a
        fresh peer lease returns None."""
        cur = self.read(bucket)
        path = self.path(bucket)
        if cur is None:
            rec = {"host": self.host, "gen": 1, "ts": time.time()}
            if self._write_atomic(path, rec, create=True):
                return Lease(bucket, self.host, 1, path)
            cur = self.read(bucket)
            if cur is None:
                return None       # creation race resolved oddly; retry later
        own = cur.get("host") == self.host
        if not own and not self.stale(cur):
            return None
        # steal (or re-acquire after our own crash — a same-host lease
        # is always ours to bump: the previous holder under this name
        # was a prior incarnation of this very process identity)
        gen = int(cur.get("gen", 0)) + 1
        claim = f"{path}.claim{gen}"
        try:
            fd = os.open(claim, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            # a peer is stealing this generation — OR a peer DIED
            # between claiming and replacing the lease (the lease
            # then keeps its old gen forever and every stealer keeps
            # computing the same claim name). A claim older than the
            # TTL is that crash's residue: remove it so the next
            # attempt can claim; never act on it this round (the
            # unlink itself may race a live claimant — one lost poll
            # round is the safe price)
            try:
                if time.time() - os.stat(claim).st_mtime > self.ttl_s:
                    os.unlink(claim)
            except OSError:
                pass
            return None
        os.close(fd)
        try:
            rec = {"host": self.host, "gen": gen, "ts": time.time()}
            self._write_atomic(path, rec, create=False)
        finally:
            try:
                os.unlink(claim)
            except OSError:
                pass
        got = self.read(bucket)
        if not (got and got.get("host") == self.host
                and int(got.get("gen", -1)) == gen):
            return None           # lost a replace race; not ours
        return Lease(bucket, self.host, gen, path,
                     stolen_from=None if own else cur.get("host"))

    def renew(self, lease: Lease) -> None:
        """Heartbeat: refresh ``ts`` and verify the file is still our
        content afterwards; raises :class:`LeaseLost` otherwise."""
        self.check(lease)
        self._write_atomic(lease.path,
                           {"host": lease.host, "gen": lease.gen,
                            "ts": time.time()}, create=False)
        self.check(lease)

    def check(self, lease: Lease) -> None:
        got = self.read(lease.bucket)
        if not (got and got.get("host") == lease.host
                and int(got.get("gen", -1)) == lease.gen):
            raise LeaseLost(
                f"bucket {lease.bucket!r}: lease (host {lease.host}, "
                f"gen {lease.gen}) was reclaimed by "
                f"{got.get('host') if got else 'nobody — released'}; "
                "abandoning without commit (docs/serving.md)")

    def release(self, lease: Lease) -> None:
        """Drop the lease iff it is still ours (a stolen lease belongs
        to the thief — never unlink someone else's)."""
        try:
            self.check(lease)
        except LeaseLost:
            return
        try:
            os.unlink(lease.path)
        except OSError:
            pass
