"""``timewarp-tpu serve`` / ``timewarp-tpu submit`` — the service CLI.

::

    timewarp-tpu serve --journal DIR --hosts NAME[,...] \\
        [--listen HOST:PORT] [--slots W] [--chunk N] [--lease-ttl-s T]
        [--no-curator | --no-repack] [--max-seconds S]
    timewarp-tpu submit CONFIGS --connect HOST:PORT \\
        [--timeout-s T] [--verify] [--drain] [--no-wait]

``serve`` with ``--listen`` runs the streaming frontend (RPC over
real TCP, frontend.py) plus — unless ``--no-curator`` — an embedded
execution curator; without ``--listen`` it joins the fleet as a
curator-only host, claiming and stealing buckets through the shared
journal directory's leases (curator.py). Any number of hosts share
one ``--journal`` dir; each needs a unique first ``--hosts`` name.

``submit`` loads a pack-shaped JSON/JSONL file (or one config
object), submits every config, and streams each ``world_done`` record
to stdout as its world quiesces (completion order). ``--verify``
re-runs every config solo afterwards and asserts the streamed result
is bit-identical — the extended survival law as an executable gate
(the CI serve-smoke job runs it). ``--drain`` tells the service to
stop admitting and exit once everything settles.

Exit codes: serve — 0 drained/deadline, 1 on an injected curator
death; submit — 0 all results streamed (and verified, if asked),
1 on failures/mismatches/timeouts.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
from typing import List, Optional

from ..sweep.journal import SweepJournal
from ..sweep.spec import SweepConfigError
from .curator import CuratorKilled, ServeCurator
from .hosts import parse_hosts, parse_listen

__all__ = ["serve_main", "submit_main"]


def _serve(argv) -> int:
    p = argparse.ArgumentParser(
        prog="timewarp-tpu serve",
        description="Emulation as a service: streaming RunConfig "
                    "frontend + multi-host work-stealing curators "
                    "(docs/serving.md).")
    p.add_argument("--journal", required=True,
                   help="shared journal directory (per-host JSONL "
                        "logs, lease files, bucket checkpoints)")
    p.add_argument("--hosts", required=True,
                   help="NAME[@HOST:PORT][,PEER...] — first entry is "
                        "THIS host's identity (HOST_GRAMMAR)")
    p.add_argument("--listen", default=None,
                   help="HOST:PORT to serve the RPC frontend on; "
                        "omit to run a curator-only host")
    p.add_argument("--slots", type=int, default=4,
                   help="world slots per open bucket (reserved "
                        "capacity mid-bucket admissions fill)")
    p.add_argument("--chunk", type=int, default=64,
                   help="supersteps per chunk between checkpoints / "
                        "admission points")
    p.add_argument("--lint", default="off",
                   choices=["error", "warn", "off"],
                   help="pre-flight verification: 'error' refuses a "
                        "ServeSubmit whose config fails the plan "
                        "lint / scenario sanitizer / fault-aware "
                        "capacity proof — findings in the reply, "
                        "nothing journaled; also the curators' "
                        "engine-construction lint knob "
                        "(docs/serving.md 'Pre-flight verification')")
    p.add_argument("--lease-ttl-s", type=float, default=10.0,
                   help="lease staleness TTL: a host silent this long "
                        "has its buckets stolen")
    p.add_argument("--poll-s", type=float, default=0.2,
                   help="curator idle poll interval")
    p.add_argument("--heartbeat-s", type=float, default=1.0,
                   help="min interval between journaled heartbeats")
    p.add_argument("--no-curator", action="store_true",
                   help="frontend only: admit + stream, execute "
                        "nothing (other hosts run the curators)")
    p.add_argument("--no-repack", action="store_true",
                   help="disable the between-chunk merge of "
                        "under-occupied same-key open buckets")
    p.add_argument("--pack", default="first-fit", dest="pack_mode",
                   help="slot placement / repack policy (first-fit | "
                        "predicted; docs/serving.md 'Predictive "
                        "packing'): predicted places each admission "
                        "in the open bucket whose forecast remaining "
                        "horizon best matches it, and repacks when "
                        "PREDICTED occupancy falls under the floor — "
                        "every choice journaled as a pack_decision")
    p.add_argument("--pack-artifact", default=None,
                   help="sha-stamped predictor artifact from "
                        "`timewarp-tpu pack fit` (predicted mode "
                        "falls back to declared budgets without one)")
    p.add_argument("--max-seconds", type=float, default=None,
                   help="hard deadline: exit even if not drained")
    p.add_argument("--die-after-chunks", type=int, default=None,
                   help="TEST INJECTION: abandon the curator after "
                        "K chunk calls WITHOUT releasing its lease — "
                        "what the steal law is pinned against")
    args = p.parse_args(argv)
    fleet = parse_hosts(args.hosts)
    me = fleet[0]
    if args.no_curator and args.listen is None:
        raise SystemExit("--no-curator without --listen would serve "
                         "nothing and execute nothing")

    # validate the knob (and load + sha-check the artifact) ONCE,
    # loudly, before any journal record exists
    from ..pack.allocate import validate_pack_mode
    validate_pack_mode(args.pack_mode)
    artifact = None
    if args.pack_artifact is not None:
        from ..pack.predict import load_artifact
        artifact = load_artifact(args.pack_artifact)

    journal = SweepJournal(args.journal, host=me.name)
    cur: Optional[ServeCurator] = None
    if not args.no_curator:
        cur = ServeCurator(
            args.journal, me.name, chunk=args.chunk, lint=args.lint,
            lease_ttl_s=args.lease_ttl_s, poll_s=args.poll_s,
            heartbeat_s=args.heartbeat_s, repack=not args.no_repack,
            die_after_chunks=args.die_after_chunks, journal=journal,
            pack_mode=args.pack_mode, pack_artifact=artifact)

    if args.listen is None:
        # curator-only host: the claim loop IS the process
        try:
            served = cur.run(max_seconds=args.max_seconds)
        except CuratorKilled as e:
            print(json.dumps({"serve": "killed", "host": me.name,
                              "error": str(e)}))
            return 1
        finally:
            journal.close()
        print(json.dumps({"serve": "done", "host": me.name,
                          "buckets_served": served}))
        return 0

    listen = parse_listen(args.listen)
    from ..interp.aio.timed import run_real_time
    from ..net.backend import AioBackend
    from ..net.dialog import Dialog
    from ..net.rpc import Rpc
    from ..net.transfer import Transport
    from .frontend import ServeFrontend
    front = ServeFrontend(journal, me.name, listen, slots=args.slots,
                          lint=args.lint, pack_mode=args.pack_mode,
                          pack_artifact=artifact)
    worker = None
    killed: List[BaseException] = []
    if cur is not None:
        def _work():
            try:
                cur.run()
            except CuratorKilled as e:
                killed.append(e)
            except Exception as e:  # noqa: BLE001 — surfaced at exit
                killed.append(e)
        worker = threading.Thread(target=_work, name="tw-serve-cur",
                                  daemon=True)
        worker.start()
    rpc = Rpc(Dialog(Transport(AioBackend())))
    try:
        run_real_time(lambda: front.program(
            rpc, max_seconds=args.max_seconds))
    finally:
        if cur is not None:
            cur.stop = True
        if worker is not None:
            worker.join(timeout=10.0)
        journal.close()
    if killed:
        print(json.dumps({"serve": "killed", "host": me.name,
                          "error": str(killed[0])}))
        return 1
    print(json.dumps({"serve": "done", "host": me.name,
                      "listen": args.listen,
                      "admitted": len(front._admitted),
                      "completed": len(front.results),
                      "failed": sorted(front.failed)}))
    return 0 if not front.failed else 1


def _load_configs(path: str) -> List[dict]:
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        try:
            data = [json.loads(line) for line in text.splitlines()
                    if line.strip()]
        except json.JSONDecodeError as e:
            raise SystemExit(
                f"{path!r} is neither JSON nor JSONL ({e})") from None
    if isinstance(data, dict) and "worlds" in data:
        data = data["worlds"]
    if isinstance(data, dict):
        data = [data]
    if not isinstance(data, list) or not data:
        raise SystemExit(f"{path!r} holds no configs (expected a "
                         "JSON list, {'worlds': [...]}, or one "
                         "config object)")
    out = []
    for i, d in enumerate(data):
        if isinstance(d, dict) and "id" not in d:
            d = {**d, "id": f"w{i}"}
        out.append(d)
    return out


def _submit(argv) -> int:
    p = argparse.ArgumentParser(
        prog="timewarp-tpu submit",
        description="Submit RunConfigs to a running service and "
                    "stream each world_done back as it quiesces "
                    "(docs/serving.md).")
    p.add_argument("configs", help="pack-shaped JSON/JSONL file (or "
                                   "one config object)")
    p.add_argument("--connect", required=True,
                   help="the service's HOST:PORT (HOST_GRAMMAR)")
    p.add_argument("--timeout-s", type=float, default=120.0,
                   help="overall deadline for submit + stream")
    p.add_argument("--call-timeout-s", type=float, default=10.0,
                   help="per-RPC timeout before an idempotent retry")
    p.add_argument("--verify", action="store_true",
                   help="after streaming, re-run every config solo "
                        "and assert each streamed result is "
                        "bit-identical (the extended survival law)")
    p.add_argument("--drain", action="store_true",
                   help="tell the service to stop admitting and exit "
                        "once everything settles")
    p.add_argument("--no-wait", action="store_true",
                   help="submit only; do not await results")
    args = p.parse_args(argv)
    addr = parse_listen(args.connect, who="--connect")
    configs = _load_configs(args.configs)

    from ..core.effects import Program, fork_, timeout
    from ..core.errors import TimeoutExpired
    from ..interp.aio.timed import run_real_time
    from ..manage.sync import Flag
    from ..net.backend import AioBackend
    from ..net.dialog import Dialog
    from ..net.rpc import Rpc
    from ..net.transfer import Transport
    from .frontend import (ServeAwait, ServeDrain, ServeRejected,
                           ServeSubmit)

    rpc = Rpc(Dialog(Transport(AioBackend())))
    call_us = int(args.call_timeout_s * 1e6)
    deadline_us = int(args.timeout_s * 1e6)
    results = {}
    failures = {}

    def call_retry(req) -> Program:
        # replies on a reset connection are lost (net/rpc.py delivery
        # contract); submits are idempotent by run_id and awaits are
        # reads, so timeout + retry gives at-least-once safely
        spent = 0
        while spent < deadline_us:
            try:
                return (yield from timeout(
                    call_us, lambda: rpc.call(addr, req)))
            except TimeoutExpired:
                spent += call_us
        raise TimeoutExpired(
            f"service at {args.connect} did not answer within "
            f"--timeout-s {args.timeout_s}")

    def main() -> Program:
        acks = []
        for d in configs:
            try:
                ack = yield from call_retry(
                    ServeSubmit(json.dumps(d, sort_keys=True)))
            except ServeRejected as e:
                raise SystemExit(
                    f"submit rejected for {d.get('id')!r}: "
                    f"{e.reason}") from None
            acks.append(ack)
            print(json.dumps({"submitted": ack.run_id,
                              "bucket": ack.bucket,
                              "slot": ack.slot}), flush=True)
        if not args.no_wait:
            flags = []

            def awaiter(rid, flag):
                def prog() -> Program:
                    try:
                        r = yield from call_retry(ServeAwait(rid))
                        rec = json.loads(r.record_json)
                        results[rid] = rec
                        # the streamed record, one JSONL line per
                        # world, in quiescence order
                        print(json.dumps(rec, sort_keys=True),
                              flush=True)
                    except ServeRejected as e:
                        failures[rid] = e.reason
                        print(json.dumps({"failed": rid,
                                          "error": e.reason}),
                              flush=True)
                    finally:
                        yield from flag.set()
                return prog
            for ack in acks:
                flag = Flag()
                flags.append(flag)
                yield from fork_(awaiter(ack.run_id, flag))
            for flag in flags:
                yield from flag.wait()
        if args.drain:
            yield from call_retry(ServeDrain())
        yield from rpc.dialog.transport.close(addr)

    try:
        run_real_time(main)
    except TimeoutExpired as e:
        sys.stderr.write(f"submit: {e}\n")
        return 1
    out = {"submitted": len(configs), "streamed": len(results),
           "failed": sorted(failures)}
    if args.verify and not args.no_wait:
        from ..sweep.spec import RunConfig, solo_result
        mism = []
        for d in configs:
            rid = d["id"]
            if rid not in results:
                continue
            cfg = RunConfig.from_json(d, 0)
            want = solo_result(cfg, lint="off")
            got = results[rid]["result"]
            if want != got:
                mism.append({"run_id": rid, "solo": want,
                             "streamed": got})
        out["verified"] = len(results) - len(mism)
        if mism:
            out["verify_mismatches"] = mism
            print(json.dumps(out))
            sys.stderr.write(
                "serve survival law VIOLATED: streamed results "
                "diverge from solo runs\n")
            return 1
    print(json.dumps(out))
    return 0 if not failures else 1


def serve_main(argv) -> int:
    def run():
        return _serve(argv)
    try:
        return run()
    except SweepConfigError as e:
        raise SystemExit(str(e)) from None


def submit_main(argv) -> int:
    try:
        return _submit(argv)
    except SweepConfigError as e:
        raise SystemExit(str(e)) from None
