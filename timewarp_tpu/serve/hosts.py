"""The ``--hosts``/``--listen`` address-spec grammar — ONE parser for
every serving surface.

Same discipline as ``net/links.py`` (LINK_GRAMMAR) and
``faults/schedule.py`` (FAULT_GRAMMAR): malformed specs die with a
``SystemExit`` naming :data:`HOST_GRAMMAR`, never a raw
IndexError/ValueError traceback (the loud-grammar contract,
tests/test_zgrammar.py BAD_HOSTS). Library callers that want an
exception catch the SystemExit and rewrap.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional, Tuple

__all__ = ["HOST_GRAMMAR", "HostSpec", "parse_host", "parse_hosts",
           "parse_listen"]

#: the --hosts / --listen grammar, named in every parse error
HOST_GRAMMAR = (
    "--hosts NAME[@HOST:PORT][,NAME[@HOST:PORT]...] — first NAME is "
    "THIS host's identity, the rest are expected peers; "
    "--listen HOST:PORT  "
    "(NAME = [A-Za-z0-9_.-]+, unique within a list; HOST nonempty, "
    "no ':'/'@'/','; PORT integer 1..65535)")

_NAME_RE = re.compile(r"^[A-Za-z0-9_.\-]+$")


def _die(spec: str, why: str, who: str) -> "SystemExit":
    return SystemExit(f"malformed {who} spec {spec!r} ({why}); "
                      f"grammar: {HOST_GRAMMAR}")


@dataclass(frozen=True)
class HostSpec:
    """One host of a serving fleet: a stable NAME (the lease/journal
    identity) and an optional frontend address (only hosts that run
    ``--listen`` have one)."""
    name: str
    addr: Optional[Tuple[str, int]] = None


def parse_listen(spec: str, who: str = "--listen") -> Tuple[str, int]:
    """``HOST:PORT`` — the frontend bind (or ``submit --connect``)
    address. Dies naming :data:`HOST_GRAMMAR` on malformation."""
    if not isinstance(spec, str) or not spec.strip():
        raise _die(spec, "empty spec", who)
    host, sep, port_s = spec.rpartition(":")
    if not sep or not host:
        raise _die(spec, "expected HOST:PORT", who)
    if any(c in ":@," or c.isspace() for c in host):
        raise _die(spec, f"bad host {host!r}", who)
    try:
        port = int(port_s)
    except ValueError:
        raise _die(spec, f"non-integer port {port_s!r}", who) from None
    if not 1 <= port <= 65535:
        raise _die(spec, f"port {port} outside 1..65535", who)
    return host, port


def parse_host(spec: str, who: str = "--hosts") -> HostSpec:
    """One ``NAME[@HOST:PORT]`` entry."""
    if not isinstance(spec, str) or not spec.strip():
        raise _die(spec, "empty host entry", who)
    name, sep, addr_s = spec.partition("@")
    if not _NAME_RE.match(name or ""):
        raise _die(spec, f"bad NAME {name!r}", who)
    if not sep:
        return HostSpec(name)
    if not addr_s:
        raise _die(spec, "'@' without HOST:PORT", who)
    try:
        return HostSpec(name, parse_listen(addr_s, who))
    except SystemExit as e:
        # re-raise naming the WHOLE entry, not just the address tail
        raise _die(spec, str(e).split(" (")[0]
                   if " (" in str(e) else str(e), who) from None


def parse_hosts(spec: str, who: str = "--hosts") -> Tuple[HostSpec, ...]:
    """A ','-joined host list; the FIRST entry names this process's
    own identity (the lease and per-host-journal key), the rest are
    expected peers. Duplicate names are refused — two curators under
    one name would share a lease identity and defeat the steal
    protocol's at-most-one-holder intent."""
    if not isinstance(spec, str) or not spec.strip():
        raise _die(spec, "empty spec", who)
    parts = spec.split(",")
    if any(not p.strip() for p in parts):
        raise _die(spec, "empty list entry", who)
    out = tuple(parse_host(p.strip(), who) for p in parts)
    names = [h.name for h in out]
    dups = sorted({n for n in names if names.count(n) > 1})
    if dups:
        raise _die(spec, f"duplicate host name(s) {dups}", who)
    return out
