"""Per-host serving curator: claim, execute, steal, repack.

One :class:`ServeCurator` runs per host process (the ``timewarp-tpu
serve`` frontend embeds one; extra hosts run curator-only ``serve
--host NAME`` processes). The shared journal directory is the entire
coordination surface:

- the **admission queue** is the journal itself: ``bucket_open`` /
  ``admit`` records (written by the frontend) tell every curator
  which open buckets exist and which configs sit in which slots;
- **claims** go through per-bucket lease files (lease.py): a free
  bucket is acquired, a dead host's stale lease is *stolen* and the
  bucket continues from its shared-dir checkpoint (work-stealing);
- every lease transition and a throttled heartbeat are journaled, so
  ``sweep status`` / ``sweep watch`` render the per-host lease table
  from the same fold (journal.py ``hosts_block``).

Between chunks of a held bucket the curator: renews the lease, admits
any newly journaled configs for that bucket (worker.py — no state
splice needed, reserved slots are pristine by construction), and runs
the **re-packing pass**: if another same-key open bucket is
under-occupied (the journaled ``bucket_util`` arithmetic), its lease
is free, and its active worlds fit into this bucket's free slots, the
two merge and the donor closes — one executable where two
half-empty ones ran.

The curator exits when a ``serve_drain`` record exists and every
admitted world has settled. A hard kill (the CI scenario) simply
stops renewing; survivors steal after one lease TTL.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional

from ..sweep.journal import JournalState, SweepJournal
from ..sweep.spec import RunConfig
from .lease import Lease, LeaseDir, LeaseLost
from .worker import OpenBucketRunner

__all__ = ["ServeCurator", "CuratorKilled"]

_log = logging.getLogger("timewarp.serve")


class CuratorKilled(RuntimeError):
    """Deterministic test/CI injection: abandon the curator loop
    mid-bucket WITHOUT releasing the lease — the death the steal
    protocol is pinned against (tests/test_zzzzzzzzzserve.py)."""


class ServeCurator:
    def __init__(self, journal_dir: str, host: str, *,
                 chunk: int = 64, lint: str = "off",
                 lease_ttl_s: float = 10.0, poll_s: float = 0.2,
                 heartbeat_s: float = 1.0, repack: bool = True,
                 repack_below: float = 0.5, max_attempts: int = 3,
                 die_after_chunks: Optional[int] = None,
                 journal: Optional[SweepJournal] = None,
                 pack_mode: str = "first-fit",
                 pack_artifact=None) -> None:
        # the embedded curator shares the frontend's journal handle
        # (append is locked) so one host's seq stamps stay unique
        self.journal = journal if journal is not None \
            else SweepJournal(journal_dir, host=host)
        self.host = host
        self.chunk = int(chunk)
        self.lint = lint
        self.leases = LeaseDir(journal_dir, host, ttl_s=lease_ttl_s)
        self.poll_s = float(poll_s)
        self.heartbeat_s = float(heartbeat_s)
        self.repack = bool(repack)
        self.repack_below = float(repack_below)
        #: proactive repack policy (docs/serving.md "Predictive
        #: packing"): "predicted" ALSO triggers a merge when the
        #: donor's forecast remaining occupancy — predicted work left,
        #: not heads admitted — falls under ``repack_below``, so a
        #: bucket of nearly-quiesced worlds drains into a live one
        #: before its slots sit budget-masked
        from ..pack.allocate import validate_pack_mode
        self.pack_mode = validate_pack_mode(pack_mode)
        self.pack_artifact = None
        if pack_artifact is not None:
            if isinstance(pack_artifact, str):
                from ..pack.predict import load_artifact
                self.pack_artifact = load_artifact(pack_artifact)
            else:
                self.pack_artifact = dict(pack_artifact)
        #: chunk-executor call counter + the injected-death threshold
        #: (counted across the whole curator lifetime, 1-based like
        #: the sweep InjectPlan's K)
        self._calls = 0
        self.die_after_chunks = die_after_chunks
        #: buckets this host gave up on after max_attempts local
        #: failures (terminal world_failed journaled)
        self.max_attempts = int(max_attempts)
        self.stop = False
        #: run_id -> result, shared view filled from the merged scan
        self.done: Dict[str, dict] = {}
        self.stolen = 0
        #: incrementally-folded view of the merged journal: a
        #: long-lived service must not re-read its whole history per
        #: chunk (the journal only grows), so the curator tails every
        #: host file with the watch layer's torn-tail-tolerant
        #: TailReader and folds new records through the one shared
        #: JournalState.apply — the same fold a full scan() runs,
        #: incrementalized
        self._state = JournalState()
        self._tails: Dict[str, object] = {}

    # -- journal views -----------------------------------------------------

    def scan(self) -> JournalState:
        """The current merged-journal view (incremental — consumes
        only records appended since the last call)."""
        from ..obs.watch import TailReader
        from ..sweep.journal import merge_key
        batch = []
        for p in SweepJournal(self.journal.root).journal_files():
            tail = self._tails.get(p)
            if tail is None:
                tail = self._tails[p] = TailReader(p)
            batch.extend(tail.poll())
        batch.sort(key=merge_key)
        for rec in batch:
            self._state.apply(rec)
        return self._state

    @staticmethod
    def bucket_members(scan: JournalState,
                       bucket_id: str) -> Dict[int, RunConfig]:
        """slot -> RunConfig for every config admitted to the bucket
        (the journal IS the membership truth — frontends journal
        ``admit`` before acknowledging the client)."""
        out: Dict[int, RunConfig] = {}
        for rid, a in scan.admits.items():
            if a.get("bucket") == bucket_id:
                out[int(a["slot"])] = RunConfig.from_json(
                    dict(a["config"]), 0)
        return out

    @staticmethod
    def unfinished(scan: JournalState, bucket_id: str) -> bool:
        return any(a.get("bucket") == bucket_id
                   and rid not in scan.done
                   and rid not in scan.failed
                   for rid, a in scan.admits.items())

    def _heartbeat(self, lease: Lease) -> None:
        self.leases.renew(lease)
        self.journal.maybe_heartbeat(self.heartbeat_s)

    def _tick(self) -> None:
        self._calls += 1
        if self.die_after_chunks is not None \
                and self._calls >= self.die_after_chunks:
            raise CuratorKilled(
                f"injected curator death at chunk call {self._calls} "
                "(lease deliberately NOT released)")

    # -- one claimed bucket ------------------------------------------------

    def _restore_runner(self, bucket_id: str,
                        scan: JournalState,
                        lease: Lease) -> OpenBucketRunner:
        meta = scan.serve_buckets[bucket_id]
        self.done.update(scan.done)
        runner = OpenBucketRunner(
            bucket_id, self.journal, self.done,
            capacity=int(meta["capacity"]), window=meta["window"],
            chunk=self.chunk, lint=self.lint,
            precommit=lambda: self.leases.check(lease))
        for slot, cfg in self.bucket_members(scan, bucket_id).items():
            runner.admit(slot, cfg)
        runner.restore()
        return runner

    def _predicted_occupancy(self, bid: str, donor_active,
                             scan: JournalState,
                             capacity: int) -> Optional[float]:
        """Forecast remaining occupancy of a donor bucket: remaining
        work (forecast supersteps minus checkpointed progress, per
        active world) over the work the bucket's slots will PAY for
        (capacity x its longest remaining member — the pow2 scan runs
        every slot until the slowest world drains). Near 0.0 the
        bucket's slots are about to idle budget-masked even though
        heads still occupy them — the proactive trigger the observed
        head-count occupancy cannot see. None when nothing is active
        (the head-count trigger already fires there)."""
        from ..pack.predict import predict_supersteps
        from .worker import checkpoint_meta
        if not donor_active:
            return None
        done_ss: Dict[str, int] = {}
        meta = checkpoint_meta(self.journal.checkpoint_path(bid))
        if meta is not None:
            done_ss = dict(zip(meta.get("members", ()),
                               meta.get("supersteps", ())))
        rem = []
        for rid in donor_active:
            cfg = RunConfig.from_json(
                dict(scan.admits[rid]["config"]), 0)
            rem.append(max(0, predict_supersteps(
                cfg, self.pack_artifact) - int(done_ss.get(rid, 0))))
        longest = max(rem)
        if longest <= 0:
            return 0.0
        return sum(rem) / (capacity * longest)

    def _try_repack(self, runner: OpenBucketRunner, lease: Lease,
                    scan: JournalState) -> None:
        """The re-packing pass (module docstring): pull one
        under-occupied same-key open bucket into ``runner``."""
        if not runner.free_slots():
            return
        my_key = scan.serve_buckets[runner.bucket_id].get("key")
        for bid, meta in sorted(scan.serve_buckets.items()):
            if bid == runner.bucket_id or meta.get("key") != my_key \
                    or bid in scan.bucket_done:
                continue
            if not self.unfinished(scan, bid):
                continue
            donor_active = [
                rid for rid, a in scan.admits.items()
                if a.get("bucket") == bid and rid not in scan.done
                and rid not in scan.failed]
            occ = len(donor_active) / max(1, int(meta["capacity"]))
            pocc = None
            if self.pack_mode == "predicted":
                pocc = self._predicted_occupancy(
                    bid, donor_active, scan,
                    max(1, int(meta["capacity"])))
            under = occ <= self.repack_below or (
                pocc is not None and pocc <= self.repack_below)
            if not under \
                    or len(donor_active) > len(runner.free_slots()):
                continue
            dl = self.leases.try_acquire(bid)
            if dl is None:
                continue
            try:
                if self.pack_mode == "predicted":
                    # journaled BEFORE its effect (the merge + the
                    # repack/admit records below), so resume and
                    # sibling hosts see WHY the donor drained — and a
                    # replay needs only the record, never the artifact
                    self.journal.append({
                        "ev": "pack_decision", "kind": "repack",
                        "bucket": bid, "into": runner.bucket_id,
                        "mode": self.pack_mode,
                        "observed_occupancy": round(occ, 4),
                        "predicted_occupancy":
                            None if pocc is None else round(pocc, 4),
                        "artifact_sha":
                            (self.pack_artifact or {}).get("sha"),
                        "host": self.host})
                self.journal.append(
                    {"ev": "lease_acquire", "bucket": bid,
                     "host": self.host, "gen": dl.gen,
                     "stolen_from": dl.stolen_from})
                donor = self._restore_runner(bid, scan, dl)
                moved = runner.merge_from(donor)
                self.leases.check(lease)
                self.journal.append(
                    {"ev": "repack", "from": bid,
                     "into": runner.bucket_id, "moved": moved,
                     "host": self.host})
                for rid in moved:
                    a = dict(scan.admits[rid])
                    self.journal.append(
                        {"ev": "admit", "run_id": rid,
                         "bucket": runner.bucket_id,
                         "slot": runner.slot_of(rid),
                         "config": a["config"],
                         "repacked_from": bid})
                self.journal.append({"ev": "bucket_done",
                                     "bucket": bid})
            finally:
                self.journal.append({"ev": "lease_release",
                                     "bucket": bid,
                                     "host": self.host})
                self.leases.release(dl)
            return

    def _drive(self, bucket_id: str, lease: Lease) -> None:
        scan = self.scan()
        runner = self._restore_runner(bucket_id, scan, lease)
        self.journal.append({"ev": "bucket_start",
                             "bucket": bucket_id,
                             "attempt": 1 + sum(
                                 1 for e in scan.events
                                 if e.get("ev") == "bucket_start"
                                 and e.get("bucket") == bucket_id)})
        while not self.stop:
            self._heartbeat(lease)
            self._tick()
            status = runner.step()
            if status == "idle":
                # poll admissions once more — a config may have been
                # admitted to this bucket while the last chunk ran
                scan = self.scan()
                fresh = False
                for slot, cfg in self.bucket_members(
                        scan, bucket_id).items():
                    if runner.members[slot] is None:
                        runner.admit(slot, cfg)
                        fresh = True
                if fresh:
                    continue
                if scan.draining and not self.unfinished(scan,
                                                         bucket_id):
                    self.journal.append({"ev": "bucket_done",
                                         "bucket": bucket_id})
                return
            scan = self.scan()
            for slot, cfg in self.bucket_members(scan,
                                                 bucket_id).items():
                if runner.members[slot] is None:
                    runner.admit(slot, cfg)
            if self.repack:
                self._try_repack(runner, lease, scan)

    # -- the claim loop ----------------------------------------------------

    def run(self, max_seconds: Optional[float] = None) -> int:
        """Claim-and-execute until drained (or ``stop``/deadline).
        Returns the number of buckets this host completed or drove to
        idle."""
        deadline = None if max_seconds is None \
            else time.monotonic() + max_seconds
        served = 0
        while not self.stop:
            if deadline is not None and time.monotonic() >= deadline:
                break
            scan = self.scan()
            work = [bid for bid in sorted(scan.serve_buckets)
                    if bid not in scan.bucket_done
                    and self.unfinished(scan, bid)]
            claimed = None
            for bid in work:
                lease = self.leases.try_acquire(bid)
                if lease is not None:
                    claimed = (bid, lease)
                    break
            if claimed is None:
                if scan.draining and not work:
                    break
                time.sleep(self.poll_s)
                continue
            bid, lease = claimed
            if lease.stolen_from:
                self.stolen += 1
                _log.warning("serve[%s]: STOLE bucket %s from dead "
                             "host %s (stale lease reclaimed)",
                             self.host, bid, lease.stolen_from)
            self.journal.append(
                {"ev": "lease_acquire", "bucket": bid,
                 "host": self.host, "gen": lease.gen,
                 "stolen_from": lease.stolen_from})
            try:
                self._drive(bid, lease)
                served += 1
            except CuratorKilled:
                # the injected hard death: abandon WITHOUT releasing
                # the lease — exactly what a SIGKILL leaves behind,
                # and what the steal law is pinned against
                raise
            except LeaseLost as e:
                # stolen from US (we must have stalled past the TTL):
                # the thief owns the bucket — abandon, never commit
                _log.warning("serve[%s]: %s", self.host, e)
                continue
            except Exception as e:  # noqa: BLE001 — loud, never hung
                # an execution failure: transient ones (a device
                # hiccup, an OOM) get retried — releasing the lease
                # re-queues the bucket for ANY host to continue from
                # its checkpoint — while a deterministic failure
                # would crash-loop across every host that claims it,
                # so after max_attempts journaled starts the failure
                # turns terminal LOUDLY (awaiting clients get a
                # ServeRejected, drain can settle)
                scan = self.scan()
                attempts = sum(1 for ev in scan.events
                               if ev.get("ev") == "bucket_start"
                               and ev.get("bucket") == bid)
                if attempts < self.max_attempts:
                    _log.warning(
                        "serve[%s]: bucket %s attempt %d failed "
                        "(%s) — releasing for retry", self.host,
                        bid, attempts, e)
                else:
                    _log.error(
                        "serve[%s]: bucket %s FAILED after %d "
                        "attempt(s): %s", self.host, bid, attempts, e)
                    for rid, a in sorted(scan.admits.items()):
                        if a.get("bucket") == bid \
                                and rid not in scan.done \
                                and rid not in scan.failed:
                            self.journal.append(
                                {"ev": "world_failed", "run_id": rid,
                                 "bucket": bid, "attempts": attempts,
                                 "error":
                                     f"{type(e).__name__}: {e}"})
            self.journal.append({"ev": "lease_release",
                                 "bucket": bid, "host": self.host})
            self.leases.release(lease)
        return served
