"""The streaming RunConfig frontend on the ``net/`` real-IO fabric.

``timewarp-tpu serve --listen HOST:PORT`` runs this: an
:class:`~timewarp_tpu.net.rpc.Rpc` server (``Rpc.serve``/``Method``)
that accepts :class:`~timewarp_tpu.sweep.spec.RunConfig`\\ s over the
wire *continuously*, admits each into an open bucket (worker.py —
between chunks, into reserved pow2-fleet slots), and streams each
``world_done`` back to the submitting client as its world quiesces.

Wire surface (all payloads ride as canonical JSON strings — the
result a client receives is byte-identical to the journaled record):

- ``ServeSubmit(config_json) -> ServeAccepted(run_id, bucket, slot)``
  — admission. **Idempotent by run_id**: re-submitting the same
  config (a client retrying a lost reply) returns the original
  placement; a different config under a taken run_id is
  ``ServeRejected``. The ``admit`` journal record is durable BEFORE
  the ack leaves, so an acked config survives a frontend kill.
- ``ServeAwait(run_id) -> ServeResult(record_json)`` — long-poll
  streaming: the handler suspends until the world's ``world_done``
  lands in the (merged, possibly another host's) journal, then
  returns the full record. Clients fork one await per submitted
  config and receive results in quiescence order.
- ``ServeStatus -> ServeStatusRep(status_json)`` — the same
  ``status_fields`` block ``sweep status --json`` prints.
- ``ServeDrain -> ServeDrained(admitted)`` — stop admitting; the
  frontend (and every curator, via the journaled ``serve_drain``)
  exits once all admitted worlds settle.

Results are discovered by tailing the journal directory with the
watch layer's torn-tail-tolerant :class:`TailReader` — so a result
computed by ANY host of the fleet streams back through this frontend.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Any, Dict, List, Optional, Tuple

from ..core.effects import Program, Wait
from ..manage.sync import Flag
from ..net.message import message
from ..net.rpc import Method, request
from ..sweep.journal import SweepJournal
from ..sweep.spec import (RunConfig, SweepConfigError, link_signature,
                          resolve_window)

__all__ = ["ServeFrontend", "ServeSubmit", "ServeAccepted",
           "ServeRejected", "ServeAwait", "ServeResult",
           "ServeStatus", "ServeStatusRep", "ServeDrain",
           "ServeDrained", "bucket_key_sha"]

_log = logging.getLogger("timewarp.serve")


# -- wire messages ---------------------------------------------------------

@message
class ServeSubmit:
    config_json: str


@message
class ServeAccepted:
    run_id: str
    bucket: str
    slot: int


@message
class ServeRejected(Exception):
    reason: str

    def __post_init__(self):
        Exception.__init__(self, self.reason)


@message
class ServeAwait:
    run_id: str


@message
class ServeResult:
    record_json: str


@message
class ServeStatus:
    pass


@message
class ServeStatusRep:
    status_json: str


@message
class ServeDrain:
    pass


@message
class ServeDrained:
    admitted: int


request(response=ServeAccepted, error=ServeRejected)(ServeSubmit)
request(response=ServeResult, error=ServeRejected)(ServeAwait)
request(response=ServeStatusRep)(ServeStatus)
request(response=ServeDrained)(ServeDrain)


def bucket_key_sha(cfg: RunConfig) -> str:
    """The open-bucket identity: same family/params/link-structure/
    resolved-window/speculate configs share a batched executable —
    exactly the sweep bucketer's key (sweep/bucket.py), hashed so it
    can ride a journal record. The key is pure *shape* plus the
    per-bucket decision-source mode: per-world identity (seed, link
    values, fault tables) rides the executable as traced operands and
    never splits a bucket (docs/serving.md)."""
    key = (cfg.family, cfg.params, link_signature(cfg.parse_link()),
           resolve_window(cfg), cfg.speculate)
    return hashlib.sha256(repr(key).encode()).hexdigest()[:16]


class ServeFrontend:
    """Admission book + result streamer (module docstring). Journal
    appends happen on the event-loop thread and (from the embedded
    curator) a worker thread — the SweepJournal handle is shared and
    its append is locked, so per-host seq stamps stay unique."""

    def __init__(self, journal: SweepJournal, host: str,
                 listen: Tuple[str, int], *, slots: int = 4,
                 poll_us: int = 100_000, lint: str = "off",
                 pack_mode: str = "first-fit",
                 pack_artifact=None) -> None:
        if slots < 1:
            raise ValueError(f"--slots must be >= 1, got {slots}")
        from ..analysis import LINT_MODES
        if lint not in LINT_MODES:
            raise ValueError(
                f"lint must be one of {LINT_MODES}, got {lint!r}")
        from ..pack.allocate import validate_pack_mode
        #: slot placement policy (docs/serving.md "Predictive
        #: packing"): "first-fit" (the historical default — first
        #: same-key bucket with a free slot), or "predicted" — join
        #: the open bucket whose forecast remaining horizon best
        #: matches the admitted config's own forecast, journaled as a
        #: pack_decision record before the admit
        self.pack_mode = validate_pack_mode(pack_mode)
        self.pack_artifact = None
        if pack_artifact is not None:
            if isinstance(pack_artifact, str):
                from ..pack.predict import load_artifact
                self.pack_artifact = load_artifact(pack_artifact)
            else:
                self.pack_artifact = dict(pack_artifact)
        self.journal = journal
        self.host = host
        self.listen = listen
        self.slots = int(slots)
        self.poll_us = int(poll_us)
        #: admission-time pre-flight verification (plan_lint.py,
        #: docs/serving.md "Pre-flight verification"): "error"
        #: refuses a submission with the findings in the ServeRejected
        #: reply — BEFORE any journal record (no bucket_open, no
        #: admit), so a refused config leaves no admission trace
        self.lint = lint
        #: key sha -> [bucket_id, ...] (newest last) — open buckets
        self._by_key: Dict[str, List[str]] = {}
        #: bucket_id -> {"capacity", "used": set(slot), "key"}
        self._buckets: Dict[str, dict] = {}
        self._admitted: Dict[str, dict] = {}     # run_id -> admit info
        self.results: Dict[str, dict] = {}       # run_id -> world_done rec
        self.failed: Dict[str, dict] = {}
        self._waiters: Dict[str, List[Flag]] = {}
        self._tails: Dict[str, Any] = {}
        self._next_bucket = 0
        self.draining = False
        self._seed_from_journal()
        self.journal.append({"ev": "serve_open", "host": host,
                             "listen": f"{listen[0]}:{listen[1]}",
                             "slots": self.slots})

    # -- state reconstruction (resume) ------------------------------------

    def _seed_from_journal(self) -> None:
        scan = SweepJournal(self.journal.root).scan()
        for bid, meta in scan.serve_buckets.items():
            self._buckets[bid] = {"capacity": int(meta["capacity"]),
                                  "used": set(), "key": meta["key"],
                                  "closed": bid in scan.bucket_done}
            self._by_key.setdefault(meta["key"], []).append(bid)
            n = int(bid[2:]) if bid.startswith("sb") \
                and bid[2:].isdigit() else -1
            self._next_bucket = max(self._next_bucket, n + 1)
        for rid, a in scan.admits.items():
            self._admitted[rid] = dict(a)
            b = self._buckets.get(a.get("bucket"))
            if b is not None:
                b["used"].add(int(a["slot"]))
        for rid, res in scan.done.items():
            # seed full records so pre-restart results stream again
            rec = next((e for e in scan.events
                        if e.get("ev") == "world_done"
                        and e["result"]["run_id"] == rid), None)
            if rec is not None:
                self.results[rid] = {
                    k: v for k, v in rec.items()
                    if k not in ("host", "seq", "ts")}
        self.failed.update(scan.failed)
        self.draining = scan.draining

    # -- admission ---------------------------------------------------------

    def admit(self, d: Any) -> Tuple[str, str, int]:
        if self.draining:
            raise ServeRejected(
                "service is draining — no new admissions "
                "(docs/serving.md)")
        if not isinstance(d, dict):
            raise ServeRejected(
                f"a submission is one JSON config object, got "
                f"{type(d).__name__}")
        if "id" not in d:
            raise ServeRejected(
                "a submitted config needs an explicit \"id\" — "
                "run_ids are the idempotence key for retried "
                "submissions (the submit client assigns w0..wN "
                "automatically)")
        try:
            cfg = RunConfig.from_json(d, 0)
        except SweepConfigError as e:
            raise ServeRejected(str(e)) from None
        if cfg.controller != "off":
            raise ServeRejected(
                f"config {cfg.run_id!r}: the serving layer admits "
                "static-dispatch and speculate configs; controller "
                "packs run through `timewarp-tpu sweep run` — the "
                "telemetry controller's per-bucket decision source "
                "assumes a fixed fleet (docs/serving.md)")
        prev = self._admitted.get(cfg.run_id)
        if prev is not None:
            if prev.get("config") == cfg.to_json():
                return cfg.run_id, prev["bucket"], int(prev["slot"])
            raise ServeRejected(
                f"run_id {cfg.run_id!r} is already admitted with a "
                "different config — run_ids are unique per service")
        if self.lint != "off":
            # pre-flight verification at admission (plan_lint.py):
            # every refusal the curator would hit mid-bucket — window
            # undercuts, doomed speculation, the scenario sanitizer,
            # fault-aware capacity proofs — refused HERE, with the
            # pinned findings in the reply and nothing journaled
            from ..analysis import lint_run_config
            rep = lint_run_config(cfg)
            if self.lint == "error" and not rep.ok:
                raise ServeRejected(
                    f"config {cfg.run_id!r} failed pre-flight lint "
                    "(docs/serving.md 'Pre-flight verification'):\n"
                    + "\n".join(f.render() for f in rep.errors))
            for f in rep.errors:
                _log.warning("admission lint: %s", f.render())
            for f in rep.warnings:
                _log.info("admission lint: %s", f.render())
        try:
            key = bucket_key_sha(cfg)
        except SweepConfigError as e:
            raise ServeRejected(str(e)) from None
        bid = slot = None
        cands = [c for c in self._by_key.get(key, [])
                 if not self._buckets[c].get("closed")
                 and len(self._buckets[c]["used"])
                 < self._buckets[c]["capacity"]]
        if self.pack_mode == "predicted":
            # predictive placement (docs/serving.md "Predictive
            # packing"): join the open bucket whose forecast remaining
            # horizon is CLOSEST to this config's own forecast —
            # journaled BEFORE its effect (the admit / bucket_open
            # below), so resume and stealing curators replay the same
            # placement from the record, never the predictor
            from ..pack.allocate import best_horizon_bucket
            from ..pack.predict import predict_supersteps
            pred = predict_supersteps(cfg, self.pack_artifact)
            horizon = None
            if cands:
                pairs = [(c, self._predicted_horizon(c))
                         for c in cands]
                bid = best_horizon_bucket(pred, pairs)
                horizon = dict(pairs)[bid]
            self.journal.append({
                "ev": "pack_decision", "kind": "place",
                "run_id": cfg.run_id,
                "bucket": bid if bid is not None
                else f"sb{self._next_bucket}",
                "mode": self.pack_mode, "predicted": pred,
                "horizon": horizon,
                "artifact_sha":
                    (self.pack_artifact or {}).get("sha")})
        elif cands:
            bid = cands[0]
        if bid is not None:
            b = self._buckets[bid]
            slot = min(set(range(b["capacity"])) - b["used"])
        if bid is None:
            bid = f"sb{self._next_bucket}"
            self._next_bucket += 1
            self._buckets[bid] = {"capacity": self.slots,
                                  "used": set(), "key": key}
            self._by_key.setdefault(key, []).append(bid)
            self.journal.append({"ev": "bucket_open", "bucket": bid,
                                 "key": key, "capacity": self.slots,
                                 "window": resolve_window(cfg)})
            slot = 0
        # durable BEFORE the ack (module docstring): an acked config
        # survives a frontend kill — resume re-seeds from this record
        rec = {"ev": "admit", "run_id": cfg.run_id, "bucket": bid,
               "slot": slot, "config": cfg.to_json()}
        self.journal.append(rec)
        self._admitted[cfg.run_id] = {
            k: v for k, v in rec.items() if k != "ev"}
        self._buckets[bid]["used"].add(slot)
        return cfg.run_id, bid, slot

    def _predicted_horizon(self, bid: str) -> int:
        """Forecast remaining horizon of an open bucket: the max
        predicted supersteps over its active (admitted, unsettled)
        members — 0 when every member has settled, i.e. the bucket is
        about to quiesce and a short config should join IT rather
        than pin a long-running fleet's pow2 pad."""
        from ..pack.predict import predict_supersteps
        horizon = 0
        for rid, a in self._admitted.items():
            if a.get("bucket") != bid or rid in self.results \
                    or rid in self.failed:
                continue
            try:
                mcfg = RunConfig.from_json(dict(a["config"]), 0)
            except SweepConfigError:
                continue
            horizon = max(horizon, predict_supersteps(
                mcfg, self.pack_artifact))
        return horizon

    # -- result tailing ----------------------------------------------------

    def _poll_records(self) -> List[str]:
        """Consume new journal records from every host file (same
        file discovery as :meth:`SweepJournal.journal_files`, same
        merge order as its reader); returns the run_ids newly settled
        (done or failed). Beyond results, the tail also folds the
        records CURATORS write that move admission state — repack
        re-points and bucket closures — so the frontend can never
        assign a slot a repack just filled, or admit into a closed
        donor bucket."""
        from ..obs.watch import TailReader
        from ..sweep.journal import merge_key
        fresh: List[str] = []
        batch: List[dict] = []
        for p in SweepJournal(self.journal.root).journal_files():
            tail = self._tails.get(p)
            if tail is None:
                tail = self._tails[p] = TailReader(p)
            batch.extend(tail.poll())
        batch.sort(key=merge_key)
        for rec in batch:
            ev = rec.get("ev")
            if ev == "world_done":
                rid = rec["result"]["run_id"]
                if rid not in self.results:
                    self.results[rid] = {
                        k: v for k, v in rec.items()
                        if k not in ("host", "seq", "ts")}
                    fresh.append(rid)
            elif ev == "world_failed":
                rid = rec["run_id"]
                if rid not in self.failed:
                    self.failed[rid] = rec
                    fresh.append(rid)
            elif ev == "admit":
                # a curator's repack re-point (the frontend's own
                # admits are applied synchronously in admit()): track
                # the world's new home and mark the target slot used
                rid = rec["run_id"]
                prev = self._admitted.get(rid)
                if prev is None or "repacked_from" in rec \
                        or "repacked_from" not in prev:
                    self._admitted[rid] = {
                        k: v for k, v in rec.items() if k != "ev"}
                b = self._buckets.get(rec.get("bucket"))
                if b is not None:
                    b["used"].add(int(rec["slot"]))
            elif ev == "bucket_done":
                # a closed bucket (repack donor, or drained) never
                # takes another admission
                b = self._buckets.get(rec.get("bucket"))
                if b is not None:
                    b["closed"] = True
        return fresh

    def settled(self) -> bool:
        return all(rid in self.results or rid in self.failed
                   for rid in self._admitted)

    # -- rpc methods -------------------------------------------------------

    def methods(self) -> List[Method]:
        front = self

        def submit(req: ServeSubmit, ctx) -> Program:
            try:
                d = json.loads(req.config_json)
            except json.JSONDecodeError as e:
                raise ServeRejected(f"config is not JSON: {e}") \
                    from None
            rid, bid, slot = front.admit(d)
            _log.info("serve[%s]: admitted %r -> bucket %s slot %d",
                      front.host, rid, bid, slot)
            return ServeAccepted(rid, bid, slot)
            yield  # pragma: no cover — generator marker

        def await_(req: ServeAwait, ctx) -> Program:
            rid = req.run_id
            if rid not in front._admitted:
                raise ServeRejected(
                    f"unknown run_id {rid!r} — submit it first")
            while rid not in front.results:
                if rid in front.failed:
                    raise ServeRejected(
                        f"world {rid!r} FAILED: "
                        f"{front.failed[rid].get('error', '?')}")
                flag = Flag()
                front._waiters.setdefault(rid, []).append(flag)
                yield from flag.wait()
            return ServeResult(json.dumps(front.results[rid],
                                          sort_keys=True))

        def status(req: ServeStatus, ctx) -> Program:
            from ..sweep.journal import status_fields
            scan = SweepJournal(front.journal.root).scan()
            return ServeStatusRep(json.dumps(
                status_fields(scan, len(scan.admits))))
            yield  # pragma: no cover — generator marker

        def drain(req: ServeDrain, ctx) -> Program:
            if not front.draining:
                front.draining = True
                front.journal.append({"ev": "serve_drain",
                                      "host": front.host})
            return ServeDrained(len(front._admitted))
            yield  # pragma: no cover — generator marker

        return [Method(ServeSubmit, submit),
                Method(ServeAwait, await_),
                Method(ServeStatus, status),
                Method(ServeDrain, drain)]

    # -- the server program ------------------------------------------------

    def program(self, rpc, *,
                max_seconds: Optional[float] = None) -> Program:
        """The frontend's main program (run under ``run_real_time``):
        serve, tail results to waiters, exit once drained & settled."""
        stop = yield from rpc.serve(self.listen[1], self.methods())
        elapsed_us = 0
        budget_us = None if max_seconds is None \
            else int(max_seconds * 1e6)
        try:
            while True:
                yield Wait(self.poll_us)
                elapsed_us += self.poll_us
                for rid in self._poll_records():
                    for flag in self._waiters.pop(rid, []):
                        yield from flag.set()
                if self.draining and self.settled():
                    return
                if budget_us is not None and elapsed_us >= budget_us:
                    _log.warning("serve[%s]: --max-seconds reached "
                                 "with %d world(s) unsettled",
                                 self.host,
                                 sum(1 for r in self._admitted
                                     if r not in self.results
                                     and r not in self.failed))
                    return
        finally:
            self.journal.append({"ev": "serve_done",
                                 "host": self.host,
                                 "admitted": len(self._admitted),
                                 "completed": len(self.results)})
            yield from stop()
