"""Emulation as a service: multi-host work-stealing curators + a
streaming RunConfig frontend (docs/serving.md).

The serving layer is **host-side composition only** — zero state
inside any engine, zero jaxpr changes — built from pieces the repo
already pins laws for:

- the crash-safe fsync'd sweep journal (sweep/journal.py), grown a
  per-host file mode so N cooperating processes share one directory;
- shape-bucketed batched engines with per-world budgets and inert
  padding (sweep/bucket.py — a pow2-padded bucket holds *reserved*
  world slots: a slot with budget 0 never steps, so its state stays
  the pristine shared init state until a config is admitted into it);
- per-bucket **leases** (lease.py): atomic lease files with heartbeat
  renewal and stale-lease reclaim, so per-host curators (curator.py)
  cooperate and *steal* the buckets of a dead host;
- the ``net/`` real-IO RPC fabric (frontend.py): ``timewarp-tpu
  serve`` accepts RunConfigs over the wire continuously, admits them
  into open buckets between chunks, and streams each ``world_done``
  back to the submitting client as its world quiesces.

The **extended survival law** (docs/serving.md): every result
streamed over the wire is bit-identical to the solo run of that
config — across multi-host leases, a stolen bucket after a host
kill, mid-bucket admission, re-packing, and resume
(tests/test_zzzzzzzzzserve.py; the CI serve-smoke job).
"""

from .hosts import HOST_GRAMMAR, HostSpec, parse_host, parse_hosts, \
    parse_listen
from .lease import Lease, LeaseDir, LeaseLost

__all__ = [
    "HOST_GRAMMAR", "HostSpec", "parse_host", "parse_hosts",
    "parse_listen", "Lease", "LeaseDir", "LeaseLost",
]
