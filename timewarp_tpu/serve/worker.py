"""Open buckets: reserved world slots, mid-bucket admission, repack.

An **open bucket** is a batched executable with a fixed capacity of
world slots, only some of which hold admitted configs. A reserved
(empty) slot runs with budget 0 — the per-world budget masking the
sweep drivers already pin means it never executes a superstep, so its
state stays the scenario's shared *seed-independent* initial state
(``JaxEngine.init_state`` stacks one init per world; worlds diverge
only through per-world entropy). That is the whole admission trick:

- **admitting** a config into a free slot between chunks needs NO
  state splice — the slot is already bit-identical to the admitted
  config's solo start. Per-world identity (seed words, sweepable
  link values, fault tables) rides the compiled executable as
  TRACED OPERANDS (``WorldIdentity``, interp/jax_engine/batched.py),
  so admission is an on-device operand write: recompute the slot's
  identity rows from the member table, ``rebind_identity`` them onto
  the SAME engine instance, and flip the world's budget on — zero
  rebuilds, zero recompiles (the zero-recompile law,
  tests/test_zzzzzzzzzzoperand.py). A full ``_build`` survives only
  for the first chunk and for fault-pad growth, the one admission
  shape that changes the operand *shapes* rather than their values.
  By the batch exactness law, every world — old and new — continues
  bit-identical to its solo run.
- **fault-pad growth**: an admitted faulted config may need more
  fault-table rows than the bucket realized so far; the rebuilt fleet
  pads every world up, and the in-flight state's ``restart_done``
  ledger gains False columns for the appended rows — exact, because
  pad rows are inert (the pad-inertness law, faults/schedule.py,
  re-pinned at a wider pad by the r18 fork law).
- **re-packing** (docs/serving.md): an under-occupied open bucket can
  be merged into a same-key peer between chunks — each still-active
  world's state slice, digest chain, supersteps, and trail move into
  a free slot of the target (worlds are independent; a slice splice
  is exact by the same law), and the donor closes. The occupancy
  numbers driving the decision are exactly the journaled
  ``bucket_util`` arithmetic (sweep/runner.py).

The runner is the serving analogue of ``sweep/runner.BucketRunner``
(chunk loop, digest chains, streamed ``world_done``, atomic
checkpoints) minus supervision-retry machinery — across hosts the
lease steal IS the retry — plus the mutable member table. Controller
configs are still refused at admission (frontend.py): the telemetry
controller's decision source assumes a fixed fleet. Speculate
configs ARE admitted — the bucket owns one persistent
:class:`~timewarp_tpu.speculate.policy.SpeculationPolicy`, drives
chunks through ``run_speculative`` (masked per-world rollback), and
each slot accumulates its OWN committed decision chain
(``spec_chains``; ``last_run_decisions_world``), which is what keeps
per-world replay/audit well-defined when a masked rollback gives
violating worlds a different chunk granularity than clean ones.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from ..sweep.journal import SweepJournal
from ..sweep.spec import (DIGEST_ZERO, RunConfig, build_scenario,
                          chain_digest, link_sweep_params, world_result)

__all__ = ["OpenBucketRunner", "checkpoint_meta"]


def checkpoint_meta(path: str) -> Optional[dict]:
    """Read just the meta block of a ``save_state`` checkpoint —
    through the same ``_read_verified`` discipline as a full load
    (every leaf sha checked), because this meta STEERS repack and
    resume (member table, digests, fault pad): a torn checkpoint
    must fail here, loudly, not as a mis-shaped restore or a wrong
    repack three moves later (the at-rest half of the integrity
    detection law, utils/checkpoint.py)."""
    import os
    if not os.path.exists(path):
        return None
    from ..utils.checkpoint import _read_verified
    _, _, meta = _read_verified(path)
    return meta


def _grow_restart(state, new_c: int):
    """Pad the ``restart_done`` ledger's trailing (crash-row) axis to
    ``new_c`` columns of False — the state half of fault-pad growth
    (module docstring)."""
    cur = np.asarray(state.restart_done.shape)[-1]
    if int(cur) == new_c:
        return state
    import jax.numpy as jnp
    rd = state.restart_done
    pad = jnp.zeros(rd.shape[:-1] + (new_c - rd.shape[-1],), bool)
    return state._replace(
        restart_done=jnp.concatenate([rd, pad], axis=-1))


class OpenBucketRunner:
    def __init__(self, bucket_id: str, journal: SweepJournal,
                 done: Dict[str, dict], *, capacity: int, window,
                 chunk: int = 64, lint: str = "off",
                 precommit: Optional[Callable[[], None]] = None,
                 telemetry: str = "off") -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.bucket_id = bucket_id
        self.journal = journal
        self.done = done
        self.capacity = int(capacity)
        self.window = window
        self.chunk = int(chunk)
        self.lint = lint
        self.telemetry = telemetry
        #: called (holding no lock) immediately before any journal
        #: commit — the curator wires the lease check here, so a
        #: stolen-from host abandons instead of double-journaling
        self.precommit = precommit
        self.members: List[Optional[RunConfig]] = [None] * capacity
        self.digests = [DIGEST_ZERO] * capacity
        self.supersteps = [0] * capacity
        self.trails: List[list] = [[] for _ in range(capacity)]
        self.emitted = set(done)
        self.engine = None
        self.state = None
        self.chunks = 0
        self.wall_s = 0.0
        self._dirty = False
        #: realized fault pad floor — grows monotonically (a rebuild
        #: must never shrink the in-flight ``restart_done`` width)
        self.min_pad = (0, 0, 0)
        #: pending repack splices: slot -> (state_slice, digest,
        #: supersteps, trail), applied at the next rebuild
        self._splices: Dict[int, tuple] = {}
        #: per-slot COMMITTED speculation decision chains (JSON
        #: records) — the per-world replay/audit surface under masked
        #: rollback (module docstring); [] for non-speculating buckets
        self.spec_chains: List[list] = [[] for _ in range(capacity)]
        #: the bucket's persistent speculation decision source —
        #: survives admissions/rebinds so the ladder's committed-chain
        #: state carries across chunks; rebuilt from checkpointed
        #: decisions on restore
        self._spec_policy = None
        self._util_logged = -1
        self.util = {"chunks": 0, "world_supersteps": 0,
                     "scan_supersteps": 0, "pad_supersteps": 0,
                     "active_world_chunks": 0,
                     "engine_builds": 0, "compiles": 0}

    # -- membership --------------------------------------------------------

    def free_slots(self) -> List[int]:
        return [i for i, m in enumerate(self.members) if m is None]

    def slot_of(self, run_id: str) -> Optional[int]:
        for i, m in enumerate(self.members):
            if m is not None and m.run_id == run_id:
                return i
        return None

    def admit(self, slot: int, cfg: RunConfig) -> None:
        """Place ``cfg`` into a reserved slot; takes effect (engine
        rebuild) at the next :meth:`step` entry — i.e. between
        chunks, never mid-chunk."""
        if self.members[slot] is not None:
            if self.members[slot].run_id == cfg.run_id:
                return                      # idempotent re-admit
            raise ValueError(
                f"bucket {self.bucket_id!r} slot {slot} already holds "
                f"{self.members[slot].run_id!r}")
        self.members[slot] = cfg
        self._dirty = True

    def splice_in(self, slot: int, cfg: RunConfig, state_slice,
                  digest: str, supersteps: int, trail: list,
                  spec_chain: list = ()) -> None:
        """Repack target side: admit a PARTIALLY-RUN world (its state
        slice and digest bookkeeping move with it) into a free slot."""
        self.admit(slot, cfg)
        self.digests[slot] = digest
        self.supersteps[slot] = int(supersteps)
        self.trails[slot] = list(trail)
        self.spec_chains[slot] = list(spec_chain)
        self._splices[slot] = (state_slice,)

    def world_state_slice(self, b: int):
        """Donor side of a repack: world ``b``'s state slice (host
        arrays — independent of this bucket's engine from here on)."""
        import jax
        return jax.tree.map(
            lambda x: np.asarray(jax.device_get(x))[b], self.state)

    # -- engine (re)build --------------------------------------------------

    def _fault_pad(self, scheds) -> tuple:
        need = (max(len(s.crashes) for s in scheds),
                max(len(s.partitions) for s in scheds),
                max(len(s.link_windows) for s in scheds))
        return tuple(max(a, b) for a, b in zip(need, self.min_pad))

    def _identity_parts(self):
        """``(spec, links, fleet, pad, cfg0)`` over the CURRENT
        member table — the bucket's per-world identity, computed
        separately from engine construction so :meth:`_rebuild` can
        try a zero-recompile ``rebind_identity`` before paying a
        build. Placeholder slots borrow member-0's link structure and
        an empty fault schedule; they never step, so their identity
        rows are inert."""
        from ..faults.schedule import FaultFleet, FaultSchedule
        from ..interp.jax_engine.batched import BatchSpec
        cfg0 = next(m for m in self.members if m is not None)
        links = [(m or cfg0).parse_link() for m in self.members]
        rows = [link_sweep_params(lk) for lk in links]
        link_params = {path: np.asarray([r[path] for r in rows])
                       for path in rows[0]} if rows[0] else None
        spec = BatchSpec(
            seeds=tuple(m.seed if m else 0 for m in self.members),
            link_params=link_params)
        scheds = [(m.parse_faults() or FaultSchedule(())) if m
                  else FaultSchedule(()) for m in self.members]
        pad = self._fault_pad(scheds)
        empty = all(not s.events for s in scheds)
        if empty and pad == (0, 0, 0):
            fleet = None
        else:
            scheds[0] = scheds[0].padded(
                max(pad[0], len(scheds[0].crashes)),
                max(pad[1], len(scheds[0].partitions)),
                max(pad[2], len(scheds[0].link_windows)))
            fleet = FaultFleet(tuple(scheds))
        return spec, links, fleet, pad, cfg0

    def _build(self, spec, links, fleet, cfg0):
        """One batched engine over the given identity. Mirrors
        sweep/bucket.build_bucket_engine; the bucket key guarantees
        every member shares ``speculate`` (and family/params/link
        structure/window), so member-0's mode is the bucket's."""
        from ..interp.jax_engine.engine import JaxEngine
        sc = build_scenario(cfg0.family, cfg0.params)
        eng = JaxEngine(sc, links[0], window=self.window, batch=spec,
                        faults=fleet, lint=self.lint,
                        telemetry=self.telemetry,
                        speculate=cfg0.speculate)
        eng.metrics_label = f"bucket:{self.bucket_id}"
        return eng

    def _rebuild(self) -> None:
        spec, links, fleet, pad, cfg0 = self._identity_parts()
        if not (self.engine is not None and pad == self.min_pad
                and self.engine.rebind_identity(spec, faults=fleet)):
            # first build, fault-pad growth, or a structural identity
            # change (fleet presence / static fault gates): the only
            # paths that still construct — and possibly compile — a
            # new executable. Everything else re-enters the SAME
            # executable with new operand rows.
            self.min_pad = pad
            self.engine = self._build(spec, links, fleet, cfg0)
            self.util["engine_builds"] += 1
        init = self.engine.init_state()
        if self.state is None:
            self.state = init
        else:
            new_c = int(np.asarray(init.restart_done.shape)[-1])
            self.state = _grow_restart(self.state, new_c)
        if self._splices:
            import jax
            import jax.numpy as jnp
            new_c = int(np.asarray(self.state.restart_done.shape)[-1])
            st = self.state
            for slot, (sl,) in self._splices.items():
                sl = _grow_restart(sl, new_c)
                st = jax.tree.map(
                    lambda cur, v, s=slot:
                        jnp.asarray(cur).at[s].set(jnp.asarray(v)),
                    st, sl)
            self.state = st
            self._splices.clear()
        self._dirty = False

    # -- the chunk loop ----------------------------------------------------

    @property
    def budgets(self) -> np.ndarray:
        return np.asarray([m.budget if m else 0
                           for m in self.members], np.int64)

    def _commit(self, rec: dict) -> None:
        if self.precommit is not None:
            self.precommit()    # lease check: raises LeaseLost if stolen
        self.journal.append(rec)

    def checkpoint_path(self) -> str:
        return self.journal.checkpoint_path(self.bucket_id)

    def restore(self) -> None:
        """(Re)load the bucket from its shared-dir checkpoint — what a
        thief does after a stale-lease reclaim, and what resume does
        after a kill. Worlds admitted after the checkpoint was written
        hold pristine (budget-0, never-stepped) state in it, so
        admitting them into the rebuilt engine needs nothing extra."""
        meta = checkpoint_meta(self.checkpoint_path())
        self._rebuild()
        if meta is None:
            return
        from ..utils.checkpoint import load_state
        ck_pad = tuple(meta.get("fault_pad", (0, 0, 0)))
        template = self.engine.init_state()
        ck_c = ck_pad[0]
        cur_c = int(np.asarray(template.restart_done.shape)[-1])
        if ck_c != cur_c:
            # the checkpoint predates a pad-growing admission: shrink
            # the template's restart_done to the checkpointed width,
            # load, then grow back with inert False columns
            template = template._replace(
                restart_done=template.restart_done[..., :ck_c])
        st, meta = load_state(self.checkpoint_path(), template,
                              expect_meta={"bucket": self.bucket_id})
        self.state = _grow_restart(st, cur_c)
        by_rid = {m.run_id: i for i, m in enumerate(self.members)
                  if m is not None}
        chains = meta.get("spec_chains") \
            or [[] for _ in meta["members"]]
        for rid, d, s, t, sc in zip(meta["members"], meta["digests"],
                                    meta["supersteps"], meta["trail"],
                                    chains):
            if rid and rid in by_rid:
                i = by_rid[rid]
                self.digests[i] = d
                self.supersteps[i] = int(s)
                self.trails[i] = [list(x) for x in t]
                self.spec_chains[i] = [dict(x) for x in sc]
        self.chunks = int(meta.get("chunks", 0))
        if meta.get("spec_decisions") \
                and self.engine.speculate != "off":
            # resume the policy's committed chain where the killed
            # host left it — fresh decisions continue the ladder
            # (chunk numbering included) instead of restarting it
            from ..speculate.policy import SpeculationPolicy
            self._spec_policy = SpeculationPolicy(
                self.engine.speculate, fixed_w=self.engine._spec_w,
                chunk=self.chunk, replay=meta["spec_decisions"])
        self.emitted = set(self.done)

    def step(self) -> str:
        """One chunk: emit newly quiesced worlds' results, run, chain
        digests, checkpoint. Returns ``"running"`` while any admitted
        world is active, else ``"idle"`` (an idle open bucket keeps
        its checkpoint and may be re-claimed when new admissions
        land)."""
        if self.engine is None or self._dirty:
            self._rebuild()
        eng, st = self.engine, self.state
        B = self.capacity
        _, remaining, active = eng.fleet_progress(st, self.budgets)
        for b in np.nonzero(~active)[0]:
            cfg = self.members[int(b)]
            if cfg is None or cfg.run_id in self.emitted:
                continue
            res = world_result(cfg, st, int(b), self.digests[int(b)],
                               self.supersteps[int(b)])
            rec = {"ev": "world_done",
                   "bucket": self.bucket_id,
                   "wall_s": round(self.wall_s, 6),
                   "attempts": 1,
                   "chain": self.trails[int(b)],
                   "result": res}
            if eng.speculate != "off":
                # the world's own committed decision chain — a solo
                # verify twin replays exactly this (per-slot chains,
                # module docstring); a sibling of "chain", NOT part of
                # "result", so the survival-law compare surface is
                # untouched
                rec["spec_chain"] = list(self.spec_chains[int(b)])
            self._commit(rec)
            self.done[cfg.run_id] = res
            self.emitted.add(cfg.run_id)
        if not active.any():
            if self.util["chunks"] and self.chunks != self._util_logged:
                # journal utilization at the running->idle edge (the
                # sweep's analogue journals at bucket completion);
                # last-record-wins in the fold, so re-idling after
                # more admissions just refreshes the numbers
                self._commit({"ev": "bucket_util",
                              **self.utilization()})
                self._util_logged = self.chunks
            return "idle"
        vec = np.where(active, np.minimum(remaining, self.chunk), 0)
        import time as _time

        from ..interp.jax_engine.common import scan_pad
        t0 = _time.perf_counter()
        if eng.speculate != "off":
            if self._spec_policy is None:
                from ..speculate.policy import SpeculationPolicy
                self._spec_policy = SpeculationPolicy(
                    eng.speculate, fixed_w=eng._spec_w,
                    chunk=self.chunk)
            new_state, traces = eng.run_speculative(
                vec, state=st, chunk=self.chunk,
                policy=self._spec_policy)
            for b, chain in enumerate(
                    eng.last_run_decisions_world or []):
                self.spec_chains[b].extend(d.to_json() for d in chain)
        else:
            new_state, traces = eng.run(vec, state=st)
        self.wall_s += _time.perf_counter() - t0
        for b in range(B):
            if len(traces[b]):
                self.digests[b] = chain_digest(self.digests[b],
                                               traces[b])
                self.supersteps[b] += len(traces[b])
                self.trails[b].append(
                    [self.supersteps[b], self.digests[b]])
        self.state = new_state
        self.chunks += 1
        top = int(vec.max())
        u = self.util
        u["chunks"] += 1
        u["world_supersteps"] += sum(len(traces[b]) for b in range(B))
        u["scan_supersteps"] += scan_pad(top)
        u["pad_supersteps"] += scan_pad(top) - top
        u["active_world_chunks"] += int(active.sum())
        u["compiles"] += int((eng.last_run_stats or {}
                              ).get("compiles", 0))
        from ..utils.checkpoint import save_state
        if self.precommit is not None:
            self.precommit()
        save_state(self.checkpoint_path(), new_state,
                   meta={"bucket": self.bucket_id,
                         "members": [m.run_id if m else None
                                     for m in self.members],
                         "digests": list(self.digests),
                         "supersteps": [int(s)
                                        for s in self.supersteps],
                         "trail": [list(t) for t in self.trails],
                         "chunks": self.chunks,
                         "fault_pad": list(self.min_pad),
                         "spec_chains": [list(c)
                                         for c in self.spec_chains],
                         "spec_decisions": (
                             [d.to_json() for d in
                              self._spec_policy.decisions]
                             if self._spec_policy is not None
                             else [])})
        return "running"

    def utilization(self) -> dict:
        """The ``bucket_util`` record (same arithmetic as
        sweep/runner.py — the re-packing pass reads exactly these
        numbers): occupancy here counts ADMITTED active worlds against
        the full slot capacity, so a half-empty open bucket reports
        the under-occupancy repack looks for."""
        u = self.util
        B = self.capacity
        scan_total = u["scan_supersteps"]
        return {
            "bucket": self.bucket_id,
            "worlds": B,
            "chunks": u["chunks"],
            "world_supersteps": u["world_supersteps"],
            "scan_supersteps": scan_total,
            "budget_efficiency": round(
                u["world_supersteps"] / (B * scan_total), 4)
            if scan_total else 1.0,
            "pad_waste_frac": round(
                u["pad_supersteps"] / scan_total, 4)
            if scan_total else 0.0,
            "worlds_active_mean": round(
                u["active_world_chunks"] / (u["chunks"] * B), 4)
            if u["chunks"] else 0.0,
            "engine_builds": u["engine_builds"],
            "compiles": u["compiles"],
            "wall_s": round(self.wall_s, 6),
        }

    # -- repack (docs/serving.md "Re-packing") -----------------------------

    def active_slots(self) -> List[int]:
        """Slots holding admitted worlds that have not finished (from
        this runner's view of ``done``)."""
        return [i for i, m in enumerate(self.members)
                if m is not None and m.run_id not in self.done]

    def occupancy(self) -> float:
        return len(self.active_slots()) / self.capacity

    def merge_from(self, donor: "OpenBucketRunner") -> List[str]:
        """Move every still-active world of ``donor`` into this
        bucket's free slots (caller holds BOTH leases and has driven
        both runners to a chunk boundary). Returns the moved run_ids;
        the caller journals the ``repack`` event and closes the
        donor."""
        moved = []
        free = self.free_slots()
        take = donor.active_slots()
        if len(take) > len(free):
            raise ValueError(
                f"bucket {self.bucket_id!r} has {len(free)} free "
                f"slot(s) for {len(take)} active world(s) of "
                f"{donor.bucket_id!r}")
        # a moved world's state slice carries the DONOR's realized
        # fault-pad columns (restart ledgers at donor.min_pad width).
        # The merged fleet rebuilds at the elementwise max of member
        # needs and OUR min_pad — slices only ever _grow_restart to
        # that width (pad rows are inert; shrinking would drop live
        # ledger columns), so a donor wider than the post-merge pad
        # is refused loudly instead of crashing deep in jax
        from ..faults.schedule import FaultSchedule
        scheds = [(m.parse_faults() or FaultSchedule(()))
                  for m in self.members if m is not None]
        scheds += [(donor.members[b].parse_faults() or
                    FaultSchedule(())) for b in take]
        post = self._fault_pad(scheds) if scheds else self.min_pad
        if any(d > p for d, p in zip(donor.min_pad, post)):
            raise ValueError(
                f"repack {donor.bucket_id!r} -> {self.bucket_id!r} "
                f"refused: donor's realized fault pad "
                f"{tuple(donor.min_pad)} exceeds the merged fleet's "
                f"pad {tuple(post)} — an in-flight restart ledger "
                "never shrinks (faults/schedule.py); repack the "
                "narrower bucket into the wider one instead")
        if donor.state is None or donor.engine is None:
            donor._rebuild()
        for slot, b in zip(free, take):
            cfg = donor.members[b]
            self.splice_in(slot, cfg, donor.world_state_slice(b),
                           donor.digests[b], donor.supersteps[b],
                           donor.trails[b], donor.spec_chains[b])
            moved.append(cfg.run_id)
        return moved
