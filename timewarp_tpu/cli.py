"""Scenario runner CLI — the framework's executable surface (≙ the
reference's per-executable ``optparse-simple`` CLIs, SenderOptions.hs /
ReceiverOptions.hs / the cabal executables, SURVEY.md §5.6).

Usage::

    python -m timewarp_tpu token-ring --nodes 64 --engine edge \
        --steps 500 --link uniform:1000:5000 --trace-csv trace.csv
    python -m timewarp_tpu gossip --nodes 1024 --engine general --steady
    python -m timewarp_tpu praos --nodes 4096 --engine sharded --devices 8
    python -m timewarp_tpu ping-pong --engine oracle

Prints one JSON summary line; ``--trace-csv`` dumps the superstep
trace; ``--save`` / ``--resume`` checkpoint through
utils/checkpoint.py.

Subcommands: ``timewarp-tpu lint`` (the scenario sanitizer sweep,
below), ``timewarp-tpu sweep run|resume|status|watch`` (the
fault-tolerant sweep service over heterogeneous world packs —
sweep/cli.py, docs/sweeps.md; ``watch`` is the read-only live tail,
obs/watch.py), ``timewarp-tpu ledger
add|import|list|show|compare|anomalies`` (the persistent cross-run
measurement ledger + regression/anomaly analytics — obs/ledger.py,
obs/regress.py, docs/observability.md "Fleet observability"),
``timewarp-tpu profile FAMILY`` (run a config
under full telemetry and emit a ready-to-open Perfetto trace),
``timewarp-tpu explain EVENTS.jsonl`` (reconstruct a delivery's
causal chain from a recorded flight log), and ``timewarp-tpu bisect
FAMILY`` (binary-search two divergent runs to the first diverging
chunk/superstep/field — docs/observability.md).

Observability flags on runs (docs/observability.md): ``--telemetry
off|counters|full`` (bit-exact, zero overhead when off),
``--metrics-out FILE`` (schema-validated JSONL), ``--trace-out FILE``
(Perfetto/Chrome trace), ``--jax-profile DIR`` (an XLA profiler
session around the run).
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = ["main"]


def _window_arg(s: str):
    """--window accepts a µs integer or "auto" (derive the widest
    exact window from the link model's declared minimum delay)."""
    return "auto" if s == "auto" else int(s)


def _seeds_arg(s: str):
    """--seeds takes a half-open world-seed range ``a:b`` (world k
    runs seed a+k; b-a worlds total)."""
    import argparse as _ap
    try:
        a, b = s.split(":")
        a, b = int(a), int(b)
    except ValueError:
        raise _ap.ArgumentTypeError(
            f"--seeds takes a half-open integer range a:b, got {s!r}")
    if b <= a:
        raise _ap.ArgumentTypeError(
            f"--seeds range {s!r} is empty (need b > a)")
    return range(a, b)


#: engines that carry the world axis (--batch / --seeds)
BATCH_ENGINES = ("general", "sharded-batched")


def build_batch(args):
    """The world-axis spec from --batch/--seeds, or None (solo)."""
    if args.batch is None and args.seeds is None:
        return None
    from .interp.jax_engine.batched import BatchSpec
    try:
        return BatchSpec.of(args.batch, args.seeds,
                            base_seed=args.seed)
    except ValueError as e:
        raise SystemExit(str(e)) from None


# the --link grammar + parser live with the link models they build
# (net/links.py — ONE module serving the CLI and the sweep pack
# loader, so the grammar cannot drift between surfaces); re-exported
# here because this was their historical import path
from .net.links import LINK_GRAMMAR, parse_link  # noqa: F401,E402


def build_scenario(args):
    if args.scenario == "token-ring":
        from .models.token_ring import token_ring
        return token_ring(
            args.nodes, n_tokens=args.tokens or 1,
            think_us=args.think_us, end_us=args.end_us,
            with_observer=args.observer, mailbox_cap=args.mailbox_cap)
    if args.scenario == "gossip":
        from .models.gossip import gossip
        return gossip(args.nodes, fanout=args.fanout,
                      end_us=args.end_us, steady=args.steady,
                      burst=args.burst, mailbox_cap=args.mailbox_cap)
    if args.scenario == "praos":
        from .models.praos import praos
        return praos(args.nodes, n_slots=args.slots,
                     leader_prob=args.leader_prob, fanout=args.fanout,
                     burst=args.burst, mailbox_cap=args.mailbox_cap)
    if args.scenario == "ping-pong":
        from .models.ping_pong import ping_pong
        return ping_pong(rounds=args.tokens or 10)
    raise SystemExit(f"unknown scenario {args.scenario!r}")


#: engines that can run a fault schedule (faults/: scheduled chaos)
FAULT_ENGINES = ("oracle", "general", "edge", "sharded-batched")


def build_faults(args):
    """The fault schedule from --faults, or None. A batched run
    replicates the one schedule to every world (per-world schedules
    are the library FaultFleet API)."""
    if args.faults is None:
        return None
    from .faults.schedule import parse_faults
    return parse_faults(args.faults)


#: engines the dispatch controller drives (dispatch/,
#: docs/dispatch.md) — the chunk-capable jitted engines
CONTROLLER_ENGINES = ("general", "edge", "fused-sparse",
                      "sharded-batched")

#: engines that speculate (speculate/, docs/speculation.md) — the
#: chunk-capable engines that thread the DYNAMIC per-superstep window
#: (edge runs classic W=1 supersteps; the fused/pallas kernels bake
#: the window into kernel arithmetic — no clamp point, no rollback)
SPECULATE_ENGINES = ("general", "sharded-batched")


def build_controller(args):
    """The dispatch controller from --controller, or None."""
    spec = getattr(args, "controller", None)
    if spec in (None, "off"):
        return None
    from .dispatch import parse_controller
    ctrl = parse_controller(spec)
    if ctrl is not None and ctrl.mode == "auto" \
            and getattr(args, "telemetry", "off") == "off":
        raise SystemExit(
            "--controller auto consumes per-chunk telemetry "
            "(engine.last_run_telemetry); pass --telemetry "
            "counters|full (replay:<trace> alone runs with "
            "telemetry off)")
    return ctrl


#: engines that carry the causal flight recorder (obs/flight.py) —
#: the scan-driver engines whose events live on one host (the
#: node-sharded engines refuse: events would scatter across shards)
RECORD_ENGINES = ("general", "edge", "fused-sparse",
                  "sharded-batched")


def build_engine(args, sc, link):
    batch = build_batch(args)
    faults = build_faults(args)
    telemetry = getattr(args, "telemetry", "off")
    verify = getattr(args, "verify", "off")
    record = getattr(args, "record", "off")
    record_cap = getattr(args, "record_cap", None)
    if record != "off" and args.engine not in RECORD_ENGINES:
        raise SystemExit(
            f"--record threads the flight recorder's event plane "
            f"through the scan-driver engines "
            f"({', '.join(RECORD_ENGINES)}); {args.engine} "
            "cannot carry one (the oracle is host Python — already "
            "observable; node-sharded engines scatter events across "
            "shards — record the 1-device twin, bit-identical by "
            "the sharding law; docs/observability.md)")
    controller = build_controller(args)
    if controller is not None \
            and args.engine not in CONTROLLER_ENGINES:
        raise SystemExit(
            f"--controller drives the chunk-capable jitted engines "
            f"({', '.join(CONTROLLER_ENGINES)}); {args.engine} has "
            "no chunked scan driver to adapt (docs/dispatch.md)")
    speculate = getattr(args, "speculate", "off")
    if speculate != "off" and args.engine not in SPECULATE_ENGINES:
        raise SystemExit(
            f"--speculate threads the dynamic per-superstep window "
            f"through the XLA scan engines "
            f"({', '.join(SPECULATE_ENGINES)}); {args.engine} cannot "
            "(edge runs classic supersteps; the fused/pallas kernels "
            "bake the window; the oracle is host Python — "
            "docs/speculation.md)")
    if speculate != "off" and getattr(args, "insert", None) \
            in ("pallas", "interpret"):
        raise SystemExit(
            "--speculate needs the dynamic window clamp; "
            f"--insert {args.insert} bakes the window into kernel "
            "arithmetic (docs/speculation.md)")
    if telemetry != "off" and args.engine == "oracle":
        raise SystemExit(
            "--telemetry threads on-device counter planes through the "
            "jitted engines; the oracle is host Python — its whole "
            "execution is already observable (use --record-events, or "
            "run a jitted engine: the traces are bit-identical)")
    if faults is not None and args.engine not in FAULT_ENGINES:
        raise SystemExit(
            f"--faults runs on {', '.join(FAULT_ENGINES)}; "
            f"{args.engine} has no fault masks wired into its "
            "superstep (the fused kernels bypass the mask points)")
    # never-silent: reject knobs an engine would ignore rather than
    # letting cross-engine comparisons diverge mysteriously
    if batch is not None and args.engine not in BATCH_ENGINES:
        raise SystemExit(
            f"--batch/--seeds add a world axis; only the general XLA "
            f"engines carry one ({', '.join(BATCH_ENGINES)}) — "
            f"{args.engine} runs exactly one world (run it once per "
            "seed, or switch engines)")
    if batch is None and args.engine == "sharded-batched":
        raise SystemExit(
            "sharded-batched shards the world axis over the mesh; "
            "it needs --batch B or --seeds a:b (one sharded world "
            "is --engine sharded)")
    if batch is not None and args.record_events:
        raise SystemExit(
            "--record-events is a solo-run debug ring; record world "
            "b's events by running that seed solo (bit-identical by "
            "the batch exactness law, batched.py)")
    if args.engine not in ("general", "fused-sparse") \
            and args.record_events:
        raise SystemExit(
            f"--record-events is the general engine's device-side "
            f"ring; {args.engine} does not carry one (the oracle "
            "records host-side via SuperstepOracle(record_events=True))")
    if args.events_csv and not args.record_events:
        raise SystemExit("--events-csv needs --record-events")
    if args.engine in ("edge", "sharded-edge") and args.window != 1:
        raise SystemExit(
            f"--window applies to the general engines only; "
            f"{args.engine} runs classic supersteps")
    if (args.engine not in ("general", "sharded", "sharded-batched")
            and args.route_cap is not None):
        raise SystemExit(
            f"--route-cap applies to the XLA general engines only; "
            f"{args.engine} has no XLA insertion stage to bound "
            "(fused-sparse bounds its VMEM-resident batch with "
            "--max-batch; sharded-fused sizes per-shard exchange "
            "buckets via the API's bucket_cap)")
    if args.engine not in ("fused-sparse",) \
            and args.max_batch is not None:
        raise SystemExit(
            f"--max-batch sizes the fused-sparse engine's "
            f"VMEM-resident batch; {args.engine} does not hold one")
    # never-silent: the insert knob is the single-chip general
    # engine's insertion-strategy selector (pallas_insert.py) — other
    # engines replace the insertion stage themselves
    if args.engine != "general" and getattr(args, "insert", None):
        raise SystemExit(
            f"--insert selects the general engine's insertion "
            f"strategy (docs/engines.md); {args.engine} owns its "
            "insertion stage (fused/sharded kernels)")
    if args.engine != "general" and getattr(args, "insert_cap",
                                            None) is not None:
        raise SystemExit(
            "--insert-cap sizes the general engine's fire-compacted "
            f"batch (--insert pallas|interpret); {args.engine} does "
            "not hold one")
    if args.engine == "oracle":
        from .interp.ref.superstep import SuperstepOracle
        return SuperstepOracle(sc, link, seed=args.seed,
                               window=args.window, lint=args.lint,
                               faults=faults)
    if args.engine == "general":
        from .interp.jax_engine.engine import JaxEngine
        try:
            return JaxEngine(sc, link, seed=args.seed,
                             window=args.window,
                             route_cap=args.route_cap,
                             record_events=args.record_events,
                             lint=args.lint, batch=batch,
                             faults=faults,
                             telemetry=telemetry,
                             insert=getattr(args, "insert", None),
                             insert_cap=getattr(args, "insert_cap",
                                                None),
                             controller=controller,
                             verify=verify, record=record,
                             record_cap=record_cap,
                             speculate=speculate)
        except ValueError as e:
            # construction-time speculation guards (fixed:W under the
            # floor, conflicting decision sources) are grammar-class
            # errors for a CLI caller — clean exit, not a traceback
            if speculate != "off":
                raise SystemExit(str(e)) from None
            raise
    if args.engine == "sharded-batched":
        from .interp.jax_engine.sharded import (ShardedBatchedEngine,
                                                make_mesh)
        try:
            return ShardedBatchedEngine(
                sc, link, make_mesh(args.devices, axis="worlds"),
                batch=batch, seed=args.seed, window=args.window,
                route_cap=args.route_cap, lint=args.lint,
                faults=faults, telemetry=telemetry,
                controller=controller, verify=verify, record=record,
                record_cap=record_cap, speculate=speculate)
        except ValueError as e:
            # same clean-exit contract as the general path: a
            # speculation misconfiguration is a grammar-class error
            if speculate != "off":
                raise SystemExit(str(e)) from None
            raise
    if args.engine == "fused-sparse":
        from .interp.jax_engine.fused_sparse import FusedSparseEngine
        kw = {} if args.max_batch is None else {
            "max_batch": args.max_batch}
        return FusedSparseEngine(sc, link, seed=args.seed,
                                 window=args.window,
                                 record_events=args.record_events,
                                 lint=args.lint, telemetry=telemetry,
                                 controller=controller,
                                 verify=verify, record=record,
                                 record_cap=record_cap,
                                 **kw)
    if args.engine == "edge":
        from .interp.jax_engine.edge_engine import EdgeEngine
        return EdgeEngine(sc, link, seed=args.seed, cap=args.edge_cap,
                          lint=args.lint, faults=faults,
                          telemetry=telemetry, controller=controller,
                          verify=verify, record=record,
                          record_cap=record_cap)
    if args.engine in ("sharded", "sharded-edge", "sharded-fused"):
        from .interp.jax_engine.sharded import (
            ShardedEdgeEngine, ShardedEngine,
            ShardedFusedSparseEngine, make_mesh)
        mesh = make_mesh(args.devices)
        if args.engine == "sharded-edge":
            return ShardedEdgeEngine(sc, link, mesh, seed=args.seed,
                                     cap=args.edge_cap,
                                     lint=args.lint,
                                     telemetry=telemetry,
                                     verify=verify)
        if args.engine == "sharded-fused":
            return ShardedFusedSparseEngine(
                sc, link, mesh, seed=args.seed, window=args.window,
                lint=args.lint, telemetry=telemetry,
                verify=verify)
        return ShardedEngine(sc, link, mesh, seed=args.seed,
                             window=args.window,
                             route_cap=args.route_cap,
                             lint=args.lint, telemetry=telemetry,
                             verify=verify)
    raise SystemExit(f"unknown engine {args.engine!r}")


def lint_targets(families=None, *, nodes: int = 64):
    """Every shipped model the ``lint`` subcommand sweeps: state-machine
    scenarios as builder thunks (so one bad build does not kill the
    sweep) and the effect-program ``_net`` twin modules. ``families``
    filters by scenario family name."""
    scenarios = {
        "token-ring": [
            lambda: _m("token_ring").token_ring(nodes),
            lambda: _m("token_ring").token_ring(nodes,
                                                with_observer=False),
        ],
        "gossip": [
            lambda: _m("gossip").gossip(nodes),
            lambda: _m("gossip").gossip(nodes, burst=True),
            lambda: _m("gossip").gossip(nodes, steady=True),
        ],
        "praos": [
            lambda: _m("praos").praos(nodes),
            lambda: _m("praos").praos(nodes, burst=True),
        ],
        "ping-pong": [lambda: _m("ping_pong").ping_pong()],
        "socket-state": [
            lambda: _m("socket_state").socket_state(min(nodes, 16))],
    }
    modules = {
        "token-ring": ["token_ring_net"],
        "gossip": ["gossip_net"],
        "praos": ["praos_net"],
        "ping-pong": ["ping_pong_net"],
        "socket-state": ["socket_state_net"],
    }
    if families:
        unknown = set(families) - set(scenarios)
        if unknown:
            raise SystemExit(
                f"unknown scenario families {sorted(unknown)}; "
                f"choose from {sorted(scenarios)}")
        scenarios = {k: v for k, v in scenarios.items() if k in families}
        modules = {k: v for k, v in modules.items() if k in families}
    return scenarios, modules


def _m(name):
    import importlib
    return importlib.import_module(f"timewarp_tpu.models.{name}")


def lint_sweep(families=None, *, nodes: int = 64, probe: bool = True,
               seed: int = 0, faults=None):
    """The shared sanitizer sweep behind both ``timewarp-tpu lint``
    and bench's pre-run gate: returns ``(subjects, LintReport)``. A
    subject that fails to build or import becomes a TW000 error
    finding — one broken model never kills the sweep. ``faults``
    (a FaultSchedule) additionally runs the TW5xx fault lints against
    every swept scenario."""
    from .analysis import (ERROR, Finding, LintReport,
                           lint_fault_schedule, lint_module_programs,
                           lint_scenario)
    scenarios, modules = lint_targets(families, nodes=nodes)
    report = LintReport()
    subjects = 0
    for fam, builders in scenarios.items():
        for build in builders:
            subjects += 1
            try:
                sc = build()
            except Exception as e:  # noqa: BLE001 — sweep must finish
                report.add(Finding(
                    "TW000", ERROR, fam,
                    f"scenario failed to build under lint: {e!r}"))
                continue
            report.extend(lint_scenario(sc, probe=probe, seed=seed))
            if faults is not None:
                report.extend(lint_fault_schedule(faults, sc))
    for fam, mods in modules.items():
        for mod in mods:
            subjects += 1
            try:
                report.extend(lint_module_programs(_m(mod)))
            except Exception as e:  # noqa: BLE001 — sweep must finish
                report.add(Finding(
                    "TW000", ERROR, fam,
                    f"program module {mod!r} failed to lint: {e!r}"))
    return subjects, report


def jaxpr_sweep(families=None, *, nodes: int = 8):
    """The ``lint --jaxpr`` sweep (analysis/determinism.py): build
    every shipped engine family x observability/execution mode with an
    integer-delay link, scan each lowered ``_step_all`` driver for
    TW7xx bit-exactness threats, and generically re-prove the off-mode
    jaxpr-neutrality pins (TW705) per family x engine. Returns
    ``(subjects, LintReport)``; a mode that fails to build becomes a
    TW000 error finding, never a crash. Small ``nodes`` by design —
    the scan is abstract tracing, the primitive inventory of the
    driver does not change with fleet width."""
    from .analysis import (ERROR, Finding, LintReport,
                           lint_engine_jaxpr, prove_mode_neutrality)
    from .net.delays import FixedDelay

    # integer µs delays: the heavy-tail samplers' float
    # transcendentals (TW702, deliberate + quantized) would otherwise
    # drown the sweep in known warnings
    link = FixedDelay(1000)
    modes = [
        ("baseline", {}),
        ("telemetry=counters", {"telemetry": "counters"}),
        ("telemetry=full", {"telemetry": "full"}),
        ("record=deliveries", {"record": "deliveries"}),
        ("record=full", {"record": "full"}),
        ("verify=guard", {"verify": "guard"}),
        ("speculate=fixed:2000", {"speculate": "fixed:2000"}),
    ]
    scenarios, _ = lint_targets(families, nodes=nodes)
    report = LintReport()
    subjects = 0

    def scan(subject, build):
        nonlocal subjects
        subjects += 1
        try:
            engine = build()
        except Exception as e:  # noqa: BLE001 — sweep must finish
            report.add(Finding(
                "TW000", ERROR, subject,
                f"engine failed to build under the jaxpr sweep: "
                f"{e!r}"))
            return
        report.extend(lint_engine_jaxpr(engine, subject))

    for fam, builders in scenarios.items():
        built = []
        for build in builders:
            try:
                built.append(build())
            except Exception as e:  # noqa: BLE001 — sweep must finish
                report.add(Finding(
                    "TW000", ERROR, fam,
                    f"scenario failed to build under the jaxpr "
                    f"sweep: {e!r}"))
        if not built:
            continue
        sc = built[0]

        def gen(**kw):
            from .interp.jax_engine.engine import JaxEngine
            return JaxEngine(sc, link, seed=0, lint="off", **kw)

        for label, kw in modes:
            scan(f"{fam}/general/{label}", lambda kw=kw: gen(**kw))
        subjects += 1
        report.extend(prove_mode_neutrality(gen, f"{fam}/general"))

        # the edge engine demands a static topology — sweep the
        # family's first static variant, if it ships one
        sc_e = next((s for s in built if s.static_dst is not None),
                    None)
        if sc_e is not None:
            def edge(**kw):
                from .interp.jax_engine.edge_engine import EdgeEngine
                return EdgeEngine(sc_e, link, seed=0, lint="off",
                                  **kw)

            for label, kw in modes:
                if "speculate" in kw:
                    continue    # edge engine has no speculation plane
                scan(f"{fam}/edge/{label}", lambda kw=kw: edge(**kw))
            subjects += 1
            report.extend(prove_mode_neutrality(edge, f"{fam}/edge"))
    return subjects, report


def lint_main(argv) -> int:
    """``timewarp-tpu lint``: run the scenario sanitizer (jaxpr
    contract lints + static capacity proofs + commutative-inbox
    permutation probes) over shipped state-machine models, and the
    effect-program AST linter over their ``_net`` twins. Exits 1 on
    any error-severity finding — the CI lint gate."""
    p = argparse.ArgumentParser(
        prog="timewarp-tpu lint",
        description="Static scenario sanitizer (timewarp_tpu.analysis)."
                    " With no arguments, sweeps every shipped model.")
    p.add_argument("families", nargs="*",
                   help="scenario families to lint (default: all): "
                        "token-ring gossip praos ping-pong socket-state")
    p.add_argument("--nodes", type=int, default=64,
                   help="node count the swept scenarios are built at")
    p.add_argument("--no-probe", action="store_true",
                   help="skip the commutative-inbox permutation probe "
                        "(the only check that executes the step)")
    p.add_argument("--seed", type=int, default=0,
                   help="probe permutation seed")
    p.add_argument("--json", action="store_true",
                   help="one JSON report line instead of findings text")
    p.add_argument("--faults", default=None,
                   help="also lint this fault schedule (the --faults "
                        "run grammar) against every swept scenario — "
                        "the TW5xx rules (docs/faults.md)")
    p.add_argument("--jaxpr", action="store_true",
                   help="run the engine-level determinism sanitizer "
                        "instead: scan every shipped engine x mode's "
                        "lowered driver jaxpr for bit-exactness "
                        "threats and re-prove the off-mode "
                        "neutrality pins (TW7xx, docs/authoring.md)")
    args = p.parse_args(argv)

    if args.jaxpr:
        # default shrinks to 8: the driver's primitive inventory does
        # not change with fleet width, only trace time does
        nodes = 8 if args.nodes == 64 else args.nodes
        subjects, report = jaxpr_sweep(args.families or None,
                                       nodes=nodes)
    else:
        faults = None
        if args.faults:
            from .faults.schedule import parse_faults
            faults = parse_faults(args.faults)
        subjects, report = lint_sweep(args.families or None,
                                      nodes=args.nodes,
                                      probe=not args.no_probe,
                                      seed=args.seed, faults=faults)

    if args.json:
        print(json.dumps({"subjects": subjects, **report.to_json()}))
    else:
        print(report.render())
        print(f"({subjects} subjects linted)")
    return 0 if report.ok else 1


def lint_pack_main(argv) -> int:
    """``timewarp-tpu lint-pack PACK``: the fleet-scale pre-flight
    verifier (analysis/plan_lint.py). Statically predicts the pack's
    bucket plan (engine builds, fleet widths, resolved windows, fault
    pads), mirrors every construction-time refusal the runtime would
    raise mid-bucket, and runs the full per-scenario sanitizer plus
    the fault-aware capacity proof over every world — all before any
    engine is built. Exits 1 on any error-severity finding (the same
    contract as ``lint``); ``sweep run --lint error`` applies the
    identical gate in-process."""
    p = argparse.ArgumentParser(
        prog="timewarp-tpu lint-pack",
        description="Static pre-flight verification of a sweep pack "
                    "(TW6xx + the per-world TW1xx-TW2xx/TW7xx rules; "
                    "docs/sweeps.md 'Pre-flight verification').")
    p.add_argument("pack",
                   help="pack path: a JSON file ({\"worlds\": [...]} "
                        "or a bare config list) or JSONL, the same "
                        "grammar `sweep run` takes")
    p.add_argument("--json", action="store_true",
                   help="one JSON report line instead of findings text")
    p.add_argument("--max-bucket", type=int, default=64,
                   help="bucket width the plan is predicted at (must "
                        "match the sweep run's --max-bucket to "
                        "predict the same builds)")
    args = p.parse_args(argv)

    from .analysis import lint_pack_path
    configs, report = lint_pack_path(args.pack,
                                     max_bucket=args.max_bucket)
    if args.json:
        print(json.dumps({"configs": configs, **report.to_json()}))
    else:
        print(report.render())
        print(f"({configs} config(s) linted)")
    return 0 if report.ok else 1


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    argv = list(argv)
    if argv and argv[0] == "lint-pack":
        # fleet-scale pre-flight verification of a sweep pack
        # (analysis/plan_lint.py, TW6xx — docs/sweeps.md)
        return lint_pack_main(argv[1:])
    if argv and argv[0] == "lint":
        return lint_main(argv[1:])
    if argv and argv[0] == "sweep":
        # the fault-tolerant sweep service (sweep/):
        # run|resume|status|watch
        from .sweep.cli import sweep_main
        return sweep_main(argv[1:])
    if argv and argv[0] == "ledger":
        # the persistent cross-run measurement ledger + regression
        # gates (obs/ledger.py, obs/regress.py)
        from .obs.ledger import ledger_main
        return ledger_main(argv[1:])
    if argv and argv[0] == "search":
        # adversarial chaos search over fault-schedule space
        # (timewarp_tpu/search/, docs/search.md): run|repro
        from .search.cli import search_main
        return search_main(argv[1:])
    if argv and argv[0] == "serve":
        # emulation as a service: streaming RunConfig frontend +
        # multi-host work-stealing curators (serve/, docs/serving.md)
        from .serve.cli import serve_main
        return serve_main(argv[1:])
    if argv and argv[0] == "submit":
        # the service's client: submit configs, stream world_done
        # results back as worlds quiesce (serve/, docs/serving.md)
        from .serve.cli import submit_main
        return submit_main(argv[1:])
    if argv and argv[0] == "pack":
        # fit the predictive-packing superstep forecaster from
        # run-ledger history (pack/, docs/sweeps.md "Predictive
        # packing")
        from .pack.cli import pack_main
        return pack_main(argv[1:])
    if argv and argv[0] == "profile":
        # full-telemetry run + Perfetto trace (docs/observability.md)
        return profile_main(argv[1:])
    if argv and argv[0] == "explain":
        # causal queries over a recorded flight log (obs/query.py)
        return explain_main(argv[1:])
    if argv and argv[0] == "bisect":
        # divergence bisection between two runs (obs/bisect.py)
        return bisect_main(argv[1:])
    p = argparse.ArgumentParser(
        prog="timewarp_tpu",
        description="Run a distributed-system scenario under an "
                    "interchangeable interpreter (README.md:6-15).")
    p.add_argument("scenario",
                   choices=["token-ring", "gossip", "praos", "ping-pong"])
    p.add_argument("--engine", default="general",
                   choices=["oracle", "general", "fused-sparse",
                            "edge", "sharded", "sharded-edge",
                            "sharded-fused", "sharded-batched"])
    p.add_argument("--nodes", type=int, default=64)
    p.add_argument("--steps", type=int, default=1000,
                   help="max supersteps to run")
    p.add_argument("--link", default="uniform:1000:5000",
                   help="fixed:D | uniform:LO:HI | lognormal:MED:SIGMA"
                        " | drop:P:<inner> | quantize:Q:<inner> | "
                        "never (stationary loss: drop:P wraps any "
                        "inner model with i.i.d. loss probability P; "
                        "never severs the link entirely — the old "
                        "NeverConnected)")
    p.add_argument("--faults", default=None,
                   help="deterministic fault schedule (faults/): "
                        "';'-separated events, e.g. "
                        "\"crash:3:5s:9s:reset; partition:0-3|4-7:2s:4s;"
                        " degrade:all:all:1s:2s:4.0:10ms; skew:2:250\" "
                        "— crash/restart windows, partitions, link "
                        "degradation, clock skew; see docs/faults.md")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--batch", type=int, default=None,
                   help="world count B: run B independent emulations "
                        "of this scenario in one batched engine "
                        "(seeds --seed .. --seed+B-1); general XLA "
                        "engines only")
    p.add_argument("--seeds", type=_seeds_arg, default=None,
                   help="explicit world-seed range a:b (half-open) "
                        "for the batched world axis; implies the "
                        "world count")
    p.add_argument("--devices", type=int, default=None,
                   help="mesh size for sharded engines (default: all)")
    p.add_argument("--mailbox-cap", type=int, default=8)
    p.add_argument("--edge-cap", type=int, default=2)
    p.add_argument("--tokens", type=int, default=None,
                   help="token-ring: initial tokens; ping-pong: rounds")
    p.add_argument("--think-us", type=int, default=3_000_000)
    p.add_argument("--end-us", type=int, default=20_000_000)
    p.add_argument("--observer", action="store_true")
    p.add_argument("--steady", action="store_true",
                   help="gossip: rumor-mongering steady state")
    p.add_argument("--burst", action="store_true",
                   help="gossip/praos: flood all fanout peers in one "
                        "firing (the windowed-superstep-friendly form)")
    p.add_argument("--window", type=_window_arg, default=1,
                   help="multi-instant superstep window in µs, or "
                        "'auto' to use the link model's declared "
                        "minimum delay (requires link min delay >= "
                        "window)")
    p.add_argument("--route-cap", type=int, default=None,
                   help="static active-message budget for the insertion "
                        "stage (clipped messages are counted)")
    p.add_argument("--max-batch", type=int, default=None,
                   help="fused-sparse: VMEM-resident message batch "
                        "bound per superstep (excess counted in "
                        "route_drop, never silent)")
    p.add_argument("--insert", default=None,
                   choices=["xla", "xla2d", "pallas", "interpret"],
                   help="general engine insertion strategy "
                        "(docs/engines.md; every choice is "
                        "bit-identical): 'xla' flat scatters "
                        "(default), 'xla2d' the 2D scatter form (the "
                        "promoted TW_FLAT_SCATTER hatch), 'pallas' "
                        "the fire-compaction + in-tile insertion "
                        "kernels on TPU (auto-fallback to xla "
                        "elsewhere), 'interpret' the kernels under "
                        "the Pallas interpreter; unset reads "
                        "TW_INSERT")
    p.add_argument("--insert-cap", type=int, default=None,
                   help="--insert pallas|interpret: VMEM-resident "
                        "fire-compacted batch bound in messages per "
                        "superstep (default n_nodes*max_out = can "
                        "never drop; excess counted in route_drop, "
                        "never silent)")
    p.add_argument("--fanout", type=int, default=8)
    p.add_argument("--slots", type=int, default=10)
    p.add_argument("--leader-prob", type=float, default=0.05)
    p.add_argument("--trace-csv", default=None)
    p.add_argument("--record-events", type=int, default=0,
                   help="general engine: device-side event ring "
                        "capacity (per-event records; dropped-beyond-"
                        "capacity is counted, never silent)")
    p.add_argument("--events-csv", default=None,
                   help="write the recorded events (needs "
                        "--record-events)")
    p.add_argument("--save", default=None,
                   help="write the final engine state to this .npz")
    p.add_argument("--resume", default=None,
                   help="resume from a checkpoint written by --save")
    p.add_argument("--log-config", default=None,
                   help="YAML severity tree (utils/logconfig.py)")
    p.add_argument("--lint", default="warn",
                   choices=["error", "warn", "off"],
                   help="construction-time scenario sanitizer "
                        "(analysis/): 'warn' logs findings (default), "
                        "'error' refuses to run a scenario with "
                        "error-severity findings, 'off' skips the "
                        "checks entirely")
    p.add_argument("--controller", default="off",
                   help="online adaptive dispatch (dispatch/, docs/"
                        "dispatch.md): 'auto' adapts window/rung/"
                        "chunk between jitted chunks from telemetry "
                        "(needs --telemetry counters|full) and "
                        "records a decision trace; 'replay:TRACE' "
                        "re-applies a recorded trace bit-for-bit; "
                        "'off' (default) static dispatch")
    p.add_argument("--decisions-out", default=None,
                   help="write the controller's decision trace to "
                        "this JSONL file (needs --controller; the "
                        "file replays via --controller replay:FILE)")
    p.add_argument("--telemetry", default="off",
                   choices=["off", "counters", "full"],
                   help="on-device telemetry (obs/, docs/"
                        "observability.md): per-superstep counter "
                        "planes through the jitted scan — bit-exact, "
                        "and 'off' lowers to the exact telemetry-free "
                        "program ('full' adds mailbox occupancy)")
    p.add_argument("--metrics-out", default=None,
                   help="write the telemetry metrics stream to this "
                        "JSONL file (needs --telemetry; validate with "
                        "`python -m timewarp_tpu.obs.metrics validate`)")
    p.add_argument("--trace-out", default=None,
                   help="write a Perfetto/Chrome trace of the run "
                        "(superstep counter tracks on virtual time; "
                        "needs --telemetry) — open at ui.perfetto.dev")
    p.add_argument("--jax-profile", default=None,
                   help="wrap the run in a jax.profiler session "
                        "writing to this log dir (view with xprof/"
                        "TensorBoard); degrades to a warning when "
                        "profiling is unavailable")
    p.add_argument("--record", default="off",
                   choices=["off", "deliveries", "full"],
                   help="causal flight recorder (obs/flight.py, "
                        "docs/observability.md): a bounded per-"
                        "superstep event plane through the jitted "
                        "scan — bit-exact, and 'off' lowers to the "
                        "exact record-free program. 'deliveries' = "
                        "one event per delivered message; 'full' = + "
                        "sends and fault actions (defer/cut/down/"
                        "purge/restart) — the input of `timewarp-tpu "
                        "explain` and the event side of `bisect`")
    p.add_argument("--record-cap", type=int, default=None,
                   help="flight-recorder events per superstep "
                        "(default 256); the excess is dropped but "
                        "counted, never silent")
    p.add_argument("--record-out", default=None,
                   help="drain the recorded events to this JSONL "
                        "event log (METRICS_SCHEMA event lines, "
                        "name=flight; needs --record; validate with "
                        "`python -m timewarp_tpu.obs.metrics "
                        "validate`, query with `timewarp-tpu "
                        "explain`)")
    p.add_argument("--verify", default="off",
                   choices=["off", "guard", "digest", "shadow"],
                   help="online state-integrity checking (integrity/, "
                        "docs/integrity.md): guard = on-device "
                        "invariant checks in the traced scan (loud "
                        "IntegrityViolation naming the first "
                        "violating superstep + field); digest = + "
                        "per-chunk rolling state digest with "
                        "deterministic rollback recovery; shadow = + "
                        "sampled re-execution through the pow2-cache "
                        "twin executable. 'off' lowers to the exact "
                        "verify-free program")
    p.add_argument("--verify-chunk", type=int, default=None,
                   help="supersteps per verified chunk, default 64 "
                        "(--verify digest|shadow)")
    p.add_argument("--verify-cadence", type=int, default=None,
                   help="shadow-sample every Nth chunk for "
                        "re-execution, default 1 (--verify shadow; "
                        "the cheap digest entry check runs every "
                        "chunk)")
    p.add_argument("--inject-flip", default=None,
                   help="deterministic state corruption for testing "
                        "the detection law: flip:SEED[:CHUNK[:PLANE]] "
                        "— a seeded bit-flip written into a state "
                        "plane between chunks (needs --verify; "
                        "docs/integrity.md)")
    p.add_argument("--speculate", default="off",
                   help="optimistic time-warp execution (speculate/, "
                        "docs/speculation.md): 'auto' ladders the "
                        "superstep window up past the provable link "
                        "floor, detecting causality violations "
                        "on-device and rolling back to the "
                        "conservative floor; 'fixed:W' speculates at "
                        "exactly W µs; 'off' (default) the static "
                        "window. Runs the run_speculative chunked "
                        "driver; the committed window choices form a "
                        "decision trace (--decisions-out)")
    p.add_argument("--speculate-chunk", type=int, default=None,
                   help="supersteps per speculative chunk (the "
                        "rollback granularity), default 64 "
                        "(needs --speculate)")
    p.add_argument("--canon-out", default=None,
                   help="write the run's canonical equivalence "
                        "surface (speculate/equiv.py: granularity-"
                        "invariant trace aggregates + never-silent "
                        "counters + final-state sha, one CSV row per "
                        "world) — `cmp` a speculative run's file "
                        "against the conservative run's to check the "
                        "speculation equivalence law byte-for-byte")
    args = p.parse_args(argv)
    if args.telemetry == "off" and (args.metrics_out or args.trace_out):
        raise SystemExit(
            "--metrics-out/--trace-out need --telemetry counters|full "
            "(off-mode engines record nothing, by contract)")
    if args.record_out and args.record == "off":
        raise SystemExit(
            "--record-out drains the flight recorder's event log; "
            "pass --record deliveries|full (off-mode engines record "
            "nothing, by contract)")
    if args.record_cap is not None and args.record == "off":
        raise SystemExit(
            "--record-cap sizes the flight recorder's per-superstep "
            "event plane; pass --record deliveries|full (the knob "
            "would be silently ignored)")
    if args.decisions_out and args.controller == "off" \
            and getattr(args, "speculate", "off") == "off":
        raise SystemExit("--decisions-out needs --controller "
                         "auto|replay:* or --speculate auto|fixed:W "
                         "(static runs decide nothing)")
    if args.canon_out and args.engine in ("oracle", "edge",
                                          "sharded-edge"):
        raise SystemExit(
            "--canon-out digests an EngineState's canonical surface "
            "(speculate/equiv.py); the oracle keeps host-side state "
            "and the edge engines carry EdgeState (different counter "
            "layout) — run a general-family engine (bit-identical by "
            "the parity/sharding laws)")
    if args.controller != "off" and args.resume:
        raise SystemExit(
            "--controller and --resume cannot combine: decision "
            "traces index chunks from the run start — checkpointed "
            "controller runs are the sweep service's business "
            "(timewarp-tpu sweep, docs/dispatch.md)")
    if args.controller != "off" and args.engine == "oracle":
        raise SystemExit(
            "--controller drives the jitted chunked engines; the "
            "host oracle has no compiled chunks to adapt")
    if args.verify != "off" and args.engine == "oracle":
        raise SystemExit(
            "--verify checks the jitted engines' device state; the "
            "host oracle's state is host Python (cross-check it "
            "against an engine via the parity law instead — "
            "docs/integrity.md)")
    if args.inject_flip and args.verify not in ("digest", "shadow"):
        # the guard must live HERE, not in the run branch: a
        # controller run takes run_controlled and would otherwise
        # silently never apply the flip — the user's detection-law
        # test would test nothing
        raise SystemExit(
            "--inject-flip corrupts state BETWEEN chunks (the "
            "verified driver's window); pass --verify digest|shadow "
            "— off/guard runs would leave the flip UNDETECTED (or "
            "never applied) by design (docs/integrity.md)")
    if args.verify in ("digest", "shadow") and args.controller != "off":
        raise SystemExit(
            "--verify digest|shadow runs the verified chunked driver "
            "(run_verified); --controller runs the adaptive one — "
            "combine them via the sweep service (--state-verify, "
            "docs/integrity.md). --verify guard rides any driver")
    if args.speculate != "off":
        from .speculate import parse_speculate
        try:
            parse_speculate(args.speculate, who="--speculate")
        except ValueError as e:
            raise SystemExit(str(e)) from None
        if args.controller != "off":
            raise SystemExit(
                "--speculate and --controller are both per-chunk "
                "window decision sources — pick one "
                "(docs/speculation.md)")
        if args.verify in ("digest", "shadow"):
            raise SystemExit(
                "--speculate runs the optimistic chunked driver "
                "(run_speculative); --verify digest|shadow runs the "
                "verified one — combine them via the sweep service "
                "(--state-verify + --speculate, docs/speculation.md)."
                " --verify guard rides any driver")
        if args.resume:
            raise SystemExit(
                "--speculate and --resume cannot combine: decision "
                "traces index chunks from the run start — "
                "checkpointed speculative runs are the sweep "
                "service's business (timewarp-tpu sweep --speculate, "
                "docs/speculation.md)")
    if args.speculate_chunk is not None:
        if args.speculate == "off":
            raise SystemExit(
                "--speculate-chunk shapes the optimistic chunked "
                "driver; pass --speculate auto|fixed:W (the knob "
                "would be silently ignored)")
        if args.speculate_chunk < 1:
            raise SystemExit(
                f"--speculate-chunk must be >= 1, got "
                f"{args.speculate_chunk}")
    if args.verify_chunk is not None \
            and args.verify not in ("digest", "shadow"):
        raise SystemExit(
            "--verify-chunk shapes the verified chunked driver; "
            "pass --verify digest|shadow (guard/off runs are "
            "unchunked — the knob would be silently ignored)")
    if args.verify_cadence is not None and args.verify != "shadow":
        raise SystemExit(
            "--verify-cadence samples chunks for shadow "
            "re-execution; pass --verify shadow (the digest entry "
            "check runs every chunk regardless — the knob would be "
            "silently ignored)")
    if args.verify_chunk is not None and args.verify_chunk < 1:
        raise SystemExit(
            f"--verify-chunk must be >= 1, got {args.verify_chunk}")
    if args.verify_cadence is not None and args.verify_cadence < 1:
        raise SystemExit(
            f"--verify-cadence must be >= 1, got {args.verify_cadence}")
    flip_inj = None
    if args.inject_flip:
        # parse WITH the other argument guards: a malformed spec must
        # die as a grammar-named clean exit before any engine builds,
        # never a raw mid-run ValueError traceback (the loud-grammar
        # contract, tests/test_zgrammar.py)
        from .integrity import FlipInjector
        try:
            flip_inj = FlipInjector(args.inject_flip)
        except ValueError as e:
            raise SystemExit(str(e)) from None

    from .utils.logconfig import load_log_config
    load_log_config(args.log_config)

    sc = build_scenario(args)
    link = parse_link(args.link)
    engine = build_engine(args, sc, link)

    if args.engine == "oracle":
        if args.save or args.resume:
            raise SystemExit(
                "--save/--resume need an engine state; the oracle "
                "keeps host-side state — pick a batched engine")
        trace = engine.run(args.steps)
        final_info = {"overflow": engine.overflow_total,
                      "bad_dst": engine.bad_dst_total}
        if args.faults:
            final_info["fault_dropped"] = engine.fault_dropped_total
    else:
        import numpy as np
        batched = getattr(engine, "batch", None)
        state = None
        if args.resume:
            from .utils.checkpoint import load_state
            state, ck_meta = load_state(args.resume, engine.init_state(),
                                        expect_meta={"scenario": sc.name})
            if ck_meta.get("faults") != args.faults:
                # the restart ledger (and every masked decision so
                # far) is schedule-specific: resuming under a
                # different schedule would be neither run
                raise SystemExit(
                    f"checkpoint was written under --faults "
                    f"{ck_meta.get('faults')!r}; resuming under "
                    f"{args.faults!r} would diverge — pass the "
                    "matching schedule")
            if batched is not None:
                if ck_meta.get("seeds") != list(batched.seeds):
                    # per-world RNG streams are part of the state:
                    # silently adopting different seeds would make the
                    # resumed fleet match neither run
                    raise SystemExit(
                        f"checkpoint holds the world fleet "
                        f"{ck_meta.get('seeds')}; resuming it under "
                        f"{list(batched.seeds)} would diverge — pass "
                        "the matching --batch/--seeds")
            elif ck_meta.get("seed", args.seed) != args.seed:
                # the RNG stream is part of the state: resuming under a
                # different seed would silently diverge from both runs
                args.seed = ck_meta["seed"]
                engine = build_engine(args, sc, link)
        if args.metrics_out:
            # attach BEFORE the run (the sweep service's pattern):
            # chunked drivers (run_controlled) then flush every
            # chunk's `supersteps` lines and the controller's
            # `decision` lines as they happen — a post-run export
            # would see only the final chunk
            from .obs import MetricsRegistry
            engine.metrics_label = f"{sc.name}/{args.engine}"
            engine.metrics = MetricsRegistry(
                path=args.metrics_out,
                run=engine.metrics_label)
        if args.record_out:
            # attach BEFORE the run, like the metrics registry: the
            # chunked drivers drain each committed chunk's events as
            # they happen (run_verified drains only VERIFIED chunks —
            # a rolled-back chunk's events never reach the log)
            from .obs.flight import FlightWriter
            # truncate: a re-run must replace the log, not append a
            # second run's events onto it (solo lines carry no run_id
            # to disambiguate the merge by)
            engine.flight_out = FlightWriter(args.record_out,
                                             truncate=True)
        from .obs.profiler import profile_session
        with profile_session(args.jax_profile):
            if engine.controller is not None:
                final, trace = engine.run_controlled(args.steps,
                                                     state=state)
            elif args.speculate != "off":
                # the optimistic chunked driver (speculate/,
                # docs/speculation.md): per-chunk speculative windows
                # with causality-violation rollback. Library guards
                # (a floor violation — the link model's declared
                # minimum lied) exit clean — they name the
                # misconfiguration, and a CLI traceback would bury
                # the one-line diagnostic
                from .speculate import SpeculationViolation
                try:
                    final, trace = engine.run_speculative(
                        args.steps, state=state,
                        chunk=(64 if args.speculate_chunk is None
                               else args.speculate_chunk))
                except SpeculationViolation as e:
                    raise SystemExit(str(e)) from None
            elif args.verify in ("digest", "shadow"):
                # the self-verifying chunked driver (integrity/,
                # docs/integrity.md): per-chunk digest / shadow
                # checks with deterministic rollback recovery —
                # guard mode needs no special driver (the invariant
                # plane rides any traced run and raises loudly).
                # Explicit None checks: `or` would silently rewrite
                # an (invalid) 0 instead of letting run_verified's
                # own >= 1 guard refuse it
                final, trace = engine.run_verified(
                    args.steps, state=state,
                    chunk=(64 if args.verify_chunk is None
                           else args.verify_chunk),
                    cadence=(1 if args.verify_cadence is None
                             else args.verify_cadence),
                    inject=flip_inj)
            else:
                final, trace = engine.run(args.steps, state=state)
        if args.save:
            from .utils.checkpoint import save_state
            meta = {"scenario": sc.name, "seed": args.seed}
            if batched is not None:
                meta["seeds"] = list(batched.seeds)
            if args.faults:
                meta["faults"] = args.faults
            save_state(args.save, final, meta=meta)
        if batched is not None:
            # per-world counters: the whole point of the fleet is that
            # worlds differ — aggregate in your own tooling, not here.
            # route_drop / fault_dropped ride along per WORLD (the
            # never-silent contract extended to the world axis): a
            # lossy world must not hide behind fleet aggregates
            final_info = {
                "worlds": batched.B,
                "seeds": list(batched.seeds),
                "overflow": np.asarray(final.overflow).tolist(),
                "route_drop": np.asarray(final.route_drop).tolist(),
                "fault_dropped":
                    np.asarray(final.fault_dropped).tolist(),
                "steps": np.asarray(final.steps).tolist(),
                "virtual_time_us": np.asarray(final.time).tolist()}
        else:
            final_info = {"overflow": int(final.overflow),
                          "steps": int(final.steps),
                          "virtual_time_us": int(final.time)}
            if args.faults:
                final_info["fault_dropped"] = int(final.fault_dropped)

    if args.events_csv:
        import csv
        records, dropped = engine.events(final)
        with open(args.events_csv, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["kind", "time_us", "node", "src", "payload0"])
            for r in records:
                # fire records have no src/payload: pad so the file
                # stays rectangular under the 5-column header
                w.writerow(tuple(r) + ("",) * (5 - len(r)))
        if dropped:
            print(json.dumps({"events_dropped_over_capacity": dropped}))

    if args.trace_csv:
        import csv
        with open(args.trace_csv, "w", newline="") as f:
            w = csv.writer(f)
            if isinstance(trace, list):
                # batched: one row block per world, world id leading
                w.writerow(["world", "t_us", "fired", "fired_hash",
                            "recv", "recv_hash", "sent", "sent_hash",
                            "overflow"])
                for b, tr in enumerate(trace):
                    for i in range(len(tr)):
                        w.writerow((b,) + tr.row(i))
            else:
                w.writerow(["t_us", "fired", "fired_hash", "recv",
                            "recv_hash", "sent", "sent_hash",
                            "overflow"])
                for i in range(len(trace)):
                    w.writerow(trace.row(i))

    if isinstance(trace, list):
        summary = {"scenario": sc.name, "engine": args.engine,
                   "supersteps": [len(t) for t in trace],
                   "delivered": [t.total_delivered() for t in trace],
                   **final_info}
    else:
        summary = {"scenario": sc.name, "engine": args.engine,
                   "supersteps": len(trace),
                   "delivered": trace.total_delivered(),
                   **final_info}
    if args.telemetry != "off":
        summary.update(_export_telemetry(args, sc, engine, trace))
    if args.record != "off":
        # the flight-recorder receipt: event/drop counts per run (per
        # world, batched) — a dropped count > 0 says the log is
        # incomplete and names the fix (--record-cap)
        log = getattr(engine, "last_run_flight", None)
        fo = getattr(engine, "flight_out", None)
        if fo is not None:
            fo.close()
        if isinstance(log, list):
            summary["flight"] = {"mode": args.record,
                                 "events": [len(lg) for lg in log],
                                 "dropped": [lg.dropped for lg in log]}
        else:
            summary["flight"] = {
                "mode": args.record,
                "events": 0 if log is None else len(log),
                "dropped": 0 if log is None else log.dropped}
        if args.record_out:
            summary["flight"]["out"] = args.record_out
    if args.verify != "off":
        ri = getattr(engine, "last_run_integrity", None)
        summary["integrity"] = {"mode": args.verify} if ri is None \
            else {"mode": ri["mode"], "chunks": ri["chunks"],
                  "checks": ri["checks"],
                  "rollbacks": ri["rollbacks"],
                  "violations": len(ri["violations"]),
                  "digest_chain": ri["digest_chain"]}
        if flip_inj is not None:
            # the detection law's receipt: the flip fired AND the
            # run rolled back (a fired flip with zero rollbacks is a
            # detection failure — CI greps for this)
            summary["integrity"]["flip_fired"] = flip_inj.fired
            summary["integrity"]["flip"] = flip_inj.desc
    if getattr(engine, "controller", None) is not None:
        decs = engine.last_run_decisions or []
        summary["controller"] = {
            "mode": engine.controller.mode,
            "decisions": len(decs),
            "windows": sorted({d.window_us for d in decs}),
            "chunk_lens": sorted({d.chunk_len for d in decs}),
        }
        if args.decisions_out:
            from .dispatch import DecisionTrace
            DecisionTrace.of(decs).save(args.decisions_out)
            summary["controller"]["out"] = args.decisions_out
    if args.speculate != "off":
        # the speculation receipt: committed windows, the honest
        # rollback count, and the conservative floor the run would
        # have been stuck at — the CLI face of last_run_speculation
        si = dict(engine.last_run_speculation or {})
        si.pop("violations", None)   # scalars only on the one line
        summary["speculation"] = {"spec": args.speculate, **si}
        if args.decisions_out:
            from .dispatch import DecisionTrace
            DecisionTrace.of(engine.last_run_decisions or []).save(
                args.decisions_out)
            summary["speculation"]["out"] = args.decisions_out
    if args.canon_out:
        # the equivalence-law surface (speculate/equiv.py): byte-
        # deterministic, so `cmp speculative.csv conservative.csv`
        # IS the law check — any event-level divergence moves the
        # aggregates
        from .speculate import canonical_rows, write_canon_csv
        B = None if getattr(engine, "batch", None) is None \
            else engine.batch.B
        write_canon_csv(args.canon_out,
                        canonical_rows(final, trace, B))
        summary["canon"] = args.canon_out
    print(json.dumps(summary))
    return 0


def _export_telemetry(args, sc, engine, trace) -> dict:
    """Post-run observability export (docs/observability.md): flush
    the decoded telemetry + the uniform run stats to the metrics
    JSONL, build the Perfetto trace, and return the summary-line
    fields. The run itself is already over — nothing here can touch
    the emulation."""
    from .obs import TraceBuilder
    label = f"{sc.name}/{args.engine}"
    stats = engine.last_run_stats
    frames = engine.last_run_telemetry
    info = {"telemetry": {"mode": args.telemetry,
                          "supersteps": stats["supersteps"],
                          "wall_seconds": round(stats["wall_seconds"],
                                                4),
                          "compiles": stats["compiles"]}}
    if args.metrics_out:
        # the registry was attached before the run (main()): the
        # engine already chunk-flushed its `supersteps` (and any
        # `decision`) lines — only the run-level summary is owed here
        reg = engine.metrics
        reg.run_summary(label, stats)
        reg.close()
        info["metrics"] = args.metrics_out
    if args.trace_out:
        tb = TraceBuilder(process=label)
        if isinstance(frames, list):
            for b, fr in enumerate(frames):
                tb.add_superstep_track(fr, trace[b], world=b)
        elif frames is not None:
            tb.add_superstep_track(frames, trace)
        tb.compile_marks(label, stats["compiles"])
        info["trace"] = tb.save(args.trace_out)
    return info


def explain_main(argv) -> int:
    """``timewarp-tpu explain EVENTS.jsonl --dst N``: reconstruct a
    delivery's causal chain from a recorded flight log (obs/query.py,
    docs/observability.md "Causal queries") — which send produced it,
    which fault windows deferred/degraded it along the way — and
    optionally draw the log's send→deliver arrows onto a Perfetto
    trace."""
    p = argparse.ArgumentParser(
        prog="timewarp-tpu explain",
        description="Reconstruct a delivery's causal chain from a "
                    "flight-recorder event log (--record-out).")
    p.add_argument("events", help="JSONL event log written by "
                                  "--record-out / sweep --record")
    p.add_argument("--dst", type=int, required=True,
                   help="destination node of the delivery to explain")
    p.add_argument("--t-us", type=int, default=None,
                   help="the delivery's due instant (µs); unset = "
                        "the --nth matching delivery")
    p.add_argument("--src", type=int, default=None,
                   help="restrict to deliveries from this source")
    p.add_argument("--nth", type=int, default=0,
                   help="which matching delivery (0-based, log order)")
    p.add_argument("--world", type=int, default=None,
                   help="world filter for batched/sweep logs")
    p.add_argument("--run-id", default=None,
                   help="run_id filter for sweep event logs")
    p.add_argument("--faults", default=None,
                   help="the run's --faults schedule, for the "
                        "fault-window cross-reference")
    p.add_argument("--flows", default=None,
                   help="also write a Perfetto trace with the log's "
                        "send->deliver flow arrows to this file")
    p.add_argument("--json", action="store_true",
                   help="one JSON chain instead of text lines")
    args = p.parse_args(argv)
    from .obs.flight import load_flight_jsonl
    from .obs.query import (add_flight_flows, chain_lines,
                            explain_delivery)
    try:
        log = load_flight_jsonl(args.events, run_id=args.run_id,
                                world=args.world)
        res = explain_delivery(log, dst=args.dst, t_us=args.t_us,
                               nth=args.nth, src=args.src,
                               faults=args.faults)
    except (OSError, ValueError) as e:
        raise SystemExit(str(e)) from None
    if args.flows:
        from .obs import TraceBuilder
        tb = TraceBuilder(process="timewarp-tpu explain")
        n = add_flight_flows(tb, log)
        res["flows"] = {"file": tb.save(args.flows), "arrows": n}
    if args.json:
        print(json.dumps(res))
    else:
        for line in chain_lines(res):
            print(line)
        if "flows" in res:
            print(f"flows   {res['flows']['arrows']} arrows -> "
                  f"{res['flows']['file']} (open at ui.perfetto.dev)")
    return 0


def bisect_main(argv) -> int:
    """``timewarp-tpu bisect FAMILY``: binary-search two divergent
    runs' per-chunk digest chains to the first diverging chunk, re-run
    that chunk with the flight recorder on, and name the first
    diverging superstep, field, and message-event delta in one pinned
    diagnostic line (obs/bisect.py, docs/observability.md). Two
    comparison forms: ``--inject-flip`` pits a deterministically
    corrupted run against the clean run (the integrity detection
    law's debugging half); ``--engine-b`` pits two engines against
    each other (trace-chain basis — state layouts legitimately
    differ)."""
    p = argparse.ArgumentParser(
        prog="timewarp-tpu bisect",
        description="Locate the first diverging chunk/superstep/"
                    "field between two runs of one config.")
    p.add_argument("scenario",
                   choices=["token-ring", "gossip", "praos",
                            "ping-pong"])
    p.add_argument("--engine", default="general",
                   choices=["general", "edge", "fused-sparse"])
    p.add_argument("--engine-b", default=None,
                   choices=["general", "edge", "fused-sparse"],
                   help="compare --engine against THIS engine "
                        "(default: same engine — needs "
                        "--inject-flip to have anything to find)")
    p.add_argument("--inject-flip", default=None,
                   help="corrupt run B deterministically: "
                        "flip:SEED[:CHUNK[:PLANE]] "
                        "(integrity/inject.py grammar)")
    p.add_argument("--nodes", type=int, default=64)
    p.add_argument("--steps", type=int, default=1000)
    p.add_argument("--chunk", type=int, default=64,
                   help="bisection chunk granularity (supersteps)")
    p.add_argument("--link", default="uniform:1000:5000")
    p.add_argument("--faults", default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--window", type=_window_arg, default=1)
    p.add_argument("--record-cap", type=int, default=4096,
                   help="event capacity per superstep for the "
                        "diverging chunk's recorded re-run")
    p.add_argument("--mailbox-cap", type=int, default=8)
    p.add_argument("--edge-cap", type=int, default=2)
    p.add_argument("--tokens", type=int, default=None)
    p.add_argument("--think-us", type=int, default=3_000_000)
    p.add_argument("--end-us", type=int, default=20_000_000)
    p.add_argument("--observer", action="store_true")
    p.add_argument("--steady", action="store_true")
    p.add_argument("--burst", action="store_true")
    p.add_argument("--fanout", type=int, default=8)
    p.add_argument("--slots", type=int, default=10)
    p.add_argument("--leader-prob", type=float, default=0.05)
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    engine_b = args.engine_b or args.engine
    if args.engine_b is None and not args.inject_flip:
        raise SystemExit(
            "nothing to bisect: the two sides are the same "
            "deterministic run — pass --inject-flip flip:SEED[:CHUNK"
            "[:PLANE]] (corrupt vs clean) or --engine-b ENGINE "
            "(engine vs engine)")
    if args.engine_b is not None and args.inject_flip:
        raise SystemExit(
            "--engine-b and --inject-flip are mutually exclusive: a "
            "cross-engine comparison must chain trace rows (state "
            "layouts legitimately differ), but a flip can land in a "
            "plane trace rows never observe (a payload word) and "
            "would read as a clean all-clear — bisect corrupt vs "
            "clean on ONE engine (the state basis sees every plane), "
            "or engine vs engine without the flip")
    sc = build_scenario(args)
    link = parse_link(args.link)
    faults = build_faults(args)

    def factory(engine_name):
        def make(record="off"):
            if engine_name == "general":
                from .interp.jax_engine.engine import JaxEngine
                return JaxEngine(sc, link, seed=args.seed,
                                 window=args.window, faults=faults,
                                 lint="off", record=record,
                                 record_cap=args.record_cap)
            if engine_name == "edge":
                from .interp.jax_engine.edge_engine import EdgeEngine
                return EdgeEngine(sc, link, seed=args.seed,
                                  cap=args.edge_cap, faults=faults,
                                  lint="off", record=record,
                                  record_cap=args.record_cap)
            from .interp.jax_engine.fused_sparse import \
                FusedSparseEngine
            if faults is not None:
                raise SystemExit(
                    "fused-sparse has no fault masks (the kernels "
                    "bypass the mask points); drop --faults or "
                    "bisect the general engine")
            return FusedSparseEngine(sc, link, seed=args.seed,
                                     window=args.window, lint="off",
                                     record=record,
                                     record_cap=args.record_cap)
        return make

    inject_b = None
    if args.inject_flip:
        from .integrity import FlipInjector
        spec = args.inject_flip
        try:
            FlipInjector(spec)   # grammar check BEFORE any run
        except ValueError as e:
            raise SystemExit(str(e)) from None
        def inject_b():  # noqa: F811 — the factory form bisect wants
            return FlipInjector(spec)
    from .obs.bisect import bisect_engines
    names = ((args.engine, engine_b) if args.engine_b
             else ("clean", "corrupt"))
    try:
        rep = bisect_engines(
            factory(args.engine), factory(engine_b), args.steps,
            chunk=args.chunk, names=names, inject_b=inject_b,
            basis="trace" if args.engine_b else "state")
    except ValueError as e:
        raise SystemExit(str(e)) from None
    if rep is None:
        detail = f"{names[0]} == {names[1]} at every chunk boundary"
        if args.json:
            print(json.dumps({"divergence": None, "detail": detail}))
        else:
            print(detail)
        return 1
    if args.json:
        print(json.dumps({"divergence": rep.to_json()}))
    else:
        print(rep.line())
    return 0


def profile_main(argv) -> int:
    """``timewarp-tpu profile FAMILY``: run a (small, overridable)
    config of the family under ``--telemetry full`` and emit a
    ready-to-open Perfetto trace — the one-command observability
    entry point (docs/observability.md). Extra flags pass through to
    the run CLI verbatim, so any run the CLI can express can be
    profiled."""
    p = argparse.ArgumentParser(
        prog="timewarp-tpu profile",
        description="Run a scenario under full telemetry and write a "
                    "Perfetto trace (open at ui.perfetto.dev).")
    p.add_argument("scenario",
                   choices=["token-ring", "gossip", "praos",
                            "ping-pong"])
    p.add_argument("--out", default=None,
                   help="trace file (default "
                        "tw_profile_<family>.trace.json)")
    p.add_argument("--metrics-out", default=None,
                   help="also write the metrics JSONL here")
    p.add_argument("--jax-profile", default=None,
                   help="additionally capture a jax.profiler session "
                        "into this log dir")
    args, passthrough = p.parse_known_args(argv)
    out = args.out or f"tw_profile_{args.scenario}.trace.json"
    run_argv = [args.scenario, "--telemetry", "full",
                "--trace-out", out]
    if args.metrics_out:
        run_argv += ["--metrics-out", args.metrics_out]
    if args.jax_profile:
        run_argv += ["--jax-profile", args.jax_profile]
    # profiling defaults lean small; any passthrough flag overrides
    # (argparse: the last occurrence wins)
    defaults = ["--nodes", "512", "--steps", "256"]
    rc = main(run_argv + defaults + list(passthrough))
    if rc == 0:
        print(json.dumps({"profile": args.scenario, "trace": out,
                          "open": "https://ui.perfetto.dev"}))
    return rc


if __name__ == "__main__":
    sys.exit(main())
