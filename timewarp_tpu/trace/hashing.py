"""Order-independent 32-bit trace hashing, host and device flavors.

The parity law ("all interpreters agree on observable event traces",
SURVEY.md §4.1's dual-interpreter pattern) needs a trace digest that

1. both the host oracle (Python ints) and the XLA engine (uint32
   arrays) can compute bit-identically, and
2. is independent of enumeration order — co-temporal events fire
   simultaneously in the batched engine but sequentially in the oracle,
   and shards enumerate messages locally; an *order-independent sum* of
   per-record mixes makes all of them agree without sorting.

Each record is mixed FNV/murmur-style into 32 bits, then records are
combined by wrapping uint32 addition.
"""

from __future__ import annotations

from typing import Iterable

from ..utils import jaxconfig  # noqa: F401  (int64 inputs need x64)

import jax.numpy as jnp

__all__ = ["mix32_py", "mix32_jnp", "combine_py", "FIRED", "RECV", "SENT"]

_M1 = 0x9E3779B1  # golden-ratio odd constant
_M2 = 0x85EBCA77  # murmur3 finalizer constant
_SEED = 0x811C9DC5  # FNV offset basis
_MASK = (1 << 32) - 1

# Record kind tags.
FIRED, RECV, SENT = 1, 2, 3


def mix32_py(*xs: int) -> int:
    """Host flavor: mix ints (each taken mod 2^32) into one uint32."""
    h = _SEED
    for x in xs:
        h ^= (int(x) & _MASK) * _M1 & _MASK
        h = (h * _M2) & _MASK
        h ^= h >> 16
    return h


def mix32_jnp(*xs) -> jnp.ndarray:
    """Device flavor: same algorithm on uint32 arrays (broadcasting)."""
    h = jnp.uint32(_SEED)
    for x in xs:
        x = jnp.asarray(x)
        if x.dtype == jnp.int64 or x.dtype == jnp.uint64:
            x = (x & _MASK).astype(jnp.uint32)
        else:
            x = x.astype(jnp.uint32)
        h = h ^ (x * jnp.uint32(_M1))
        h = h * jnp.uint32(_M2)
        h = h ^ (h >> jnp.uint32(16))
    return h


def combine_py(hs: Iterable[int]) -> int:
    """Order-independent combination: wrapping uint32 sum."""
    total = 0
    for h in hs:
        total = (total + h) & _MASK
    return total
