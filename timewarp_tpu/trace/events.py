"""Event-trace containers and the parity checker.

The TPU analogue of the reference's measure-event stream (bench
Commons.hs:80-83, 121-126) and the acceptance oracle for the framework's
core law: every interpreter must produce the same trace (SURVEY.md §4.1,
§6 north star: "bit-for-bit event-trace parity vs the pure emulator").

A trace is one fixed-width record per *superstep*:

  (time, fired_count, fired_hash, recv_count, recv_hash,
   sent_count, sent_hash, overflow_count)

Hashes are order-independent digests of the full per-event detail
(trace/hashing.py), so equality here pins down the set of fired nodes,
every delivered message (with source, deliver time, payload word), and
every routed message (with sampled deliver time) at each instant.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np

__all__ = ["SuperstepTrace", "TraceMismatch", "assert_states_equal",
           "assert_traces_equal"]

_FIELDS = ("times", "fired_count", "fired_hash", "recv_count", "recv_hash",
           "sent_count", "sent_hash", "overflow")


@dataclass
class SuperstepTrace:
    """Columnar trace; one row per superstep that actually fired."""
    times: np.ndarray        # int64[S]
    fired_count: np.ndarray  # int32[S]
    fired_hash: np.ndarray   # uint32[S]
    recv_count: np.ndarray   # int32[S]
    recv_hash: np.ndarray    # uint32[S]
    sent_count: np.ndarray   # int32[S]
    sent_hash: np.ndarray    # uint32[S]
    overflow: np.ndarray     # int32[S]

    def __len__(self) -> int:
        return len(self.times)

    @staticmethod
    def from_rows(rows: List[tuple]) -> "SuperstepTrace":
        cols = list(zip(*rows)) if rows else [[] for _ in _FIELDS]
        dts = (np.int64, np.int32, np.uint32, np.int32, np.uint32,
               np.int32, np.uint32, np.int32)
        return SuperstepTrace(*(np.asarray(c, dtype=d)
                                for c, d in zip(cols, dts)))

    def total_delivered(self) -> int:
        return int(self.recv_count.sum())

    def row(self, i: int) -> tuple:
        return tuple(int(getattr(self, f)[i]) for f in _FIELDS)


class TraceMismatch(AssertionError):
    """Raised by the parity checker with the first diverging superstep."""


def assert_traces_equal(a: SuperstepTrace, b: SuperstepTrace,
                        a_name: str = "oracle", b_name: str = "engine",
                        limit: Optional[int] = None) -> None:
    """Bit-for-bit comparison, reporting the first divergence precisely."""
    n = min(len(a), len(b)) if limit is None else min(len(a), len(b), limit)
    for i in range(n):
        ra, rb = a.row(i), b.row(i)
        if ra != rb:
            labels = _FIELDS
            diffs = ", ".join(f"{f}: {x} != {y}"
                              for f, x, y in zip(labels, ra, rb) if x != y)
            raise TraceMismatch(
                f"superstep {i} (t={ra[0]} vs {rb[0]}): {a_name} != {b_name}"
                f" — {diffs}")
    if limit is None and len(a) != len(b):
        raise TraceMismatch(
            f"trace lengths differ: {a_name}={len(a)} {b_name}={len(b)}"
            f" (first {n} supersteps agree)")


def assert_states_equal(a, b, tag: str = "") -> None:
    """Bit-for-bit EngineState (or any NamedTuple-of-arrays pytree
    whose ``states`` field is a dict of arrays) comparison — the
    exactness law the fused engines are held to against the XLA
    general engine (tests/test_fused_sparse.py, the in-bench gates).
    One copy, so every caller asserts the same law."""
    import jax
    suffix = f" ({tag})" if tag else ""
    for name in a._fields:
        x, y = getattr(a, name), getattr(b, name)
        if name == "states":
            for leaf in x:
                if not np.array_equal(
                        np.asarray(jax.device_get(x[leaf])),
                        np.asarray(jax.device_get(y[leaf]))):
                    raise TraceMismatch(
                        f"state.{leaf} diverged{suffix}")
        elif not np.array_equal(np.asarray(jax.device_get(x)),
                                np.asarray(jax.device_get(y))):
            raise TraceMismatch(f"{name} diverged{suffix}")
