"""Crash-safe sweep journal: append-only JSONL + atomic checkpoints.

One directory per sweep:

- ``pack.json`` — the pack, written atomically at first run; resume
  reloads it (and refuses a different pack by sha).
- ``journal.jsonl`` — append-only event log, fsync'd per append.
  Events: ``pack`` (sha, world count), ``bucket_start``, ``retry``,
  ``bucket_split``, ``world_done`` (the streamed per-world result),
  ``world_failed`` (terminal, loud), ``bucket_done``, ``sweep_done``.
- ``bucket-<id>.npz`` — per-bucket state snapshot via
  ``utils/checkpoint.save_state`` (atomic: temp + fsync + rename),
  whose meta carries the per-world digest chain, so a resumed bucket
  continues the digest exactly where the state is.

Crash model: every append is flushed and fsync'd before the action it
records is considered durable; a crash can tear at most the *last*
line, which :meth:`SweepJournal.scan` detects and drops with a
warning (the event it described simply re-happens on resume — the
done-set makes re-happening idempotent). A ``world_done`` seen twice
with *different* results is the one unforgivable state — it means two
result streams claimed the same world — and scan fails loudly rather
than pick one.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set

__all__ = ["SweepJournal", "JournalState", "SweepJournalError",
           "status_fields"]

_log = logging.getLogger("timewarp.sweep")


class SweepJournalError(RuntimeError):
    """The journal contradicts itself (double-journaled world, mixed
    packs, mid-file corruption) — never silently reconciled."""


@dataclass
class JournalState:
    """What a scan of the journal knows."""
    pack_sha: Optional[str] = None
    done: Dict[str, dict] = field(default_factory=dict)      # run_id -> result
    failed: Dict[str, dict] = field(default_factory=dict)    # run_id -> info
    bucket_done: Set[str] = field(default_factory=set)
    #: bucket_id -> [child_id, ...] in split order
    splits: Dict[str, List[str]] = field(default_factory=dict)
    retries: int = 0
    events: List[dict] = field(default_factory=list)
    #: bucket_id -> utilization record (obs: worlds-active occupancy,
    #: budget-mask efficiency, pow2 pad waste — sweep/runner.py)
    util: Dict[str, dict] = field(default_factory=dict)
    #: bucket_id -> ordered dispatch-controller decision records
    #: (dispatch/trace.py schema), journaled BEFORE each chunk runs —
    #: resume replays them so a pre-kill decision is never re-made
    #: differently (docs/dispatch.md)
    decisions: Dict[str, List[dict]] = field(default_factory=dict)
    #: run_id -> bucket_id that streamed its result (what --verify
    #: uses to assemble a controller world's decision chain)
    world_bucket: Dict[str, str] = field(default_factory=dict)
    #: integrity_violation events (integrity/, docs/integrity.md):
    #: each one a detected state corruption that was rolled back —
    #: surfaced in `sweep status` so an SDC-prone host is visible
    integrity: List[dict] = field(default_factory=list)
    #: spec_rollback events (speculate/, docs/speculation.md): each
    #: one a causality violation a speculative chunk detected and
    #: rolled back — surfaced in `sweep status` so the
    #: misspeculation rate is visible (observability only; resume
    #: re-derives rollbacks from the committed decision chain)
    spec_rollbacks: List[dict] = field(default_factory=list)
    #: run_id -> flight-recorder event count (flight_counts records,
    #: sweep/runner.py; summed across processes — a resumed sweep
    #: journals its own drain). Surfaced in `sweep status` next to
    #: utilization when the sweep ran with --record
    flight: Dict[str, int] = field(default_factory=dict)
    #: run_id -> the world's per-chunk digest trail ([[supersteps,
    #: chain_hex], ...], the world_done record's "chain" field) —
    #: what --verify's auto-bisect feeds
    #: obs.bisect.first_trail_divergence to name the first diverging
    #: chunk on a survival-law mismatch
    chains: Dict[str, list] = field(default_factory=dict)

    def apply(self, rec: Dict[str, Any]) -> None:
        """Fold ONE journal record into this state — the single fold
        both :meth:`SweepJournal.scan` and the live ``sweep watch``
        tail (obs/watch.py) run, so a watcher's aggregates and
        ``sweep status`` can never disagree about the same journal."""
        self.events.append(rec)
        ev = rec.get("ev")
        if ev == "pack":
            if self.pack_sha is not None and self.pack_sha != rec["sha"]:
                raise SweepJournalError(
                    "journal holds events for two different packs — "
                    "one journal dir per sweep")
            self.pack_sha = rec["sha"]
        elif ev == "world_done":
            rid = rec["result"]["run_id"]
            if rid in self.done:
                if self.done[rid] == rec["result"]:
                    # an interrupted attempt's straggler replayed
                    # an identical record — harmless, noted
                    _log.warning("sweep journal: duplicate "
                                 "world_done for %r (identical "
                                 "result)", rid)
                    return
                raise SweepJournalError(
                    f"world {rid!r} is double-journaled with "
                    f"DIFFERENT results — refusing to pick one:\n"
                    f"  first:  {self.done[rid]}\n"
                    f"  second: {rec['result']}")
            self.done[rid] = rec["result"]
            self.world_bucket[rid] = rec.get("bucket", "")
            self.chains[rid] = list(rec.get("chain", []))
        elif ev == "world_failed":
            self.failed[rec["run_id"]] = rec
        elif ev == "bucket_done":
            self.bucket_done.add(rec["bucket"])
        elif ev == "bucket_split":
            self.splits[rec["bucket"]] = list(rec["into"])
        elif ev == "bucket_util":
            # a resumed bucket re-journals its (process-local)
            # utilization; last record wins — wall facts are not
            # replayable, only results are
            self.util[rec["bucket"]] = {
                k: v for k, v in rec.items() if k != "ev"}
        elif ev == "retry":
            self.retries += 1
        elif ev == "integrity_violation":
            self.integrity.append(
                {k: v for k, v in rec.items() if k != "ev"})
        elif ev == "spec_rollback":
            self.spec_rollbacks.append(
                {k: v for k, v in rec.items() if k != "ev"})
        elif ev == "flight_counts":
            # per-world recorded-event counts (sweep/runner.py):
            # each process journals its own drain once per bucket
            # run, so summing across records totals the sweep
            for rid, n in rec.get("counts", {}).items():
                self.flight[rid] = self.flight.get(rid, 0) + int(n)
        elif ev == "dispatch_decision":
            dl = self.decisions.setdefault(rec["bucket"], [])
            d = rec["decision"]
            dup = next((p for p in dl
                        if p["chunk"] == d["chunk"]), None)
            if dup is not None:
                knobs = ("window_us", "rung_pin", "chunk_len")
                if any(dup[k] != d[k] for k in knobs):
                    # the one unforgivable controller state: two
                    # different decisions claim the same chunk —
                    # a replayed resume would match neither run
                    raise SweepJournalError(
                        f"bucket {rec['bucket']!r} chunk "
                        f"{d['chunk']} is double-journaled with "
                        f"DIFFERENT dispatch decisions — "
                        f"refusing to pick one:\n  first:  {dup}"
                        f"\n  second: {d}")
                _log.warning("sweep journal: duplicate dispatch "
                             "decision for bucket %r chunk %d "
                             "(identical knobs)", rec["bucket"],
                             d["chunk"])
            else:
                dl.append(d)

    def event_counts(self) -> Dict[str, int]:
        """The journal's telemetry-event tallies in one block — the
        ``events`` field of ``sweep status --json`` AND the live
        ``sweep watch`` aggregates, computed from the same fold so
        the two surfaces report identical numbers by construction."""
        return {
            "dispatch_decision": sum(len(v)
                                     for v in self.decisions.values()),
            "spec_rollback": len(self.spec_rollbacks),
            "integrity_violation": len(self.integrity),
        }

    def decision_chain(self, bucket_id: str) -> List[dict]:
        """Every decision record governing ``bucket_id``'s worlds, in
        chunk order. A split child (``b3.0.1``) continued its parent's
        chunk numbering from the parent's checkpoint, so the chain is
        the ancestor prefixes (``b3``, ``b3.0``) plus the child's own
        records — the sequence a solo replay twin re-applies. Dedup by
        chunk index (ancestor first): a chunk the parent decided but
        never durably executed is reused, not re-decided, by the
        child (sweep/runner.py)."""
        parts = bucket_id.split(".")
        ids = [".".join(parts[:i + 1]) for i in range(len(parts))]
        out: List[dict] = []
        seen: Set[int] = set()
        for bid in ids:
            for d in self.decisions.get(bid, []):
                if d["chunk"] not in seen:
                    seen.add(d["chunk"])
                    out.append(d)
        return sorted(out, key=lambda d: d["chunk"])


class SweepJournal:
    def __init__(self, root: str) -> None:
        self.root = root
        self.path = os.path.join(root, "journal.jsonl")
        self.pack_path = os.path.join(root, "pack.json")
        self._fh = None
        #: optional observability hook: called as ``on_append(ev,
        #: wall_s)`` after every durable append — the sweep service
        #: wires it to the Perfetto timeline so fsync stalls are
        #: visible (obs/perfetto.py). Purely additive: the append's
        #: durability contract does not depend on it.
        self.on_append = None

    # -- writing -----------------------------------------------------------

    def ensure_dir(self) -> None:
        os.makedirs(self.root, exist_ok=True)

    def write_pack(self, pack) -> None:
        """Atomically persist the pack (resume's source of truth)."""
        from ..utils.checkpoint import atomic_write
        self.ensure_dir()

        def write(f):
            json.dump(pack.to_json(), f, indent=1, sort_keys=True)
            f.write("\n")
        atomic_write(self.pack_path, write, mode="w")

    def append(self, rec: Dict[str, Any]) -> None:
        """Durable append: the record is on disk (flushed + fsync'd)
        before this returns — the crash-safety contract every caller
        leans on."""
        import time as _time
        t0 = _time.perf_counter()
        if self._fh is None:
            self.ensure_dir()
            self._fh = open(self.path, "a")
        self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())
        if self.on_append is not None:
            self.on_append(rec.get("ev", "?"),
                           _time.perf_counter() - t0)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def checkpoint_path(self, bucket_id: str) -> str:
        return os.path.join(self.root, f"bucket-{bucket_id}.npz")

    # -- reading -----------------------------------------------------------

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def records(self) -> List[dict]:
        """Parse the log. A torn *final* line (crash mid-append) is
        dropped with a warning; an unparsable line anywhere else is
        corruption and fails loudly."""
        if not self.exists():
            return []
        with open(self.path) as f:
            lines = f.read().splitlines()
        out: List[dict] = []
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError as e:
                if i == len(lines) - 1:
                    _log.warning(
                        "sweep journal %s: dropping torn final line "
                        "(crash mid-append): %r", self.path, line[:80])
                    continue
                raise SweepJournalError(
                    f"sweep journal {self.path!r} line {i + 1} is "
                    f"corrupt mid-file ({e}); a crash can only tear "
                    "the last line — this journal has been damaged "
                    "externally") from None
        return out

    def scan(self) -> JournalState:
        st = JournalState()
        for rec in self.records():
            try:
                st.apply(rec)
            except SweepJournalError as e:
                # re-raise with the file named (apply is path-free so
                # the live watch tail can share it verbatim)
                raise SweepJournalError(
                    f"sweep journal {self.path!r}: {e}") from None
        return st


def status_fields(scan: JournalState,
                  total_worlds: Optional[int]) -> Dict[str, Any]:
    """The shared progress block behind ``sweep status --json`` and
    the final aggregates of ``sweep watch`` (obs/watch.py): ONE
    assembly over one fold, so the two surfaces are equal by
    construction. ``total_worlds`` is the pack's world count (None
    when a watcher attached before ``pack.json`` was written)."""
    done, failed = len(scan.done), len(scan.failed)
    return {
        "worlds": total_worlds, "completed": done,
        "failed": sorted(scan.failed),
        "pending": (None if total_worlds is None
                    else total_worlds - done - failed),
        "retries": scan.retries,
        "splits": {k: v for k, v in scan.splits.items()},
        "buckets_done": sorted(scan.bucket_done),
        # per-bucket hardware utilization (sweep/runner.py): how well
        # the batched executables were used — worlds-active occupancy,
        # budget-mask efficiency, pow2 scan-pad waste
        "utilization": scan.util,
        # detected-and-rolled-back state corruptions (integrity/):
        # a nonzero count on real hardware means an SDC-prone host
        "integrity_violations": scan.integrity,
        # detected-and-rolled-back causality violations (speculate/):
        # the misspeculation ledger — each one a speculative window
        # probe the policy backed off from (docs/speculation.md)
        "spec_rollbacks": scan.spec_rollbacks,
        # the journal's event tallies in one block (event_counts):
        # dispatch decisions, speculation rollbacks, integrity
        # violations — the cross-run ledger ingests exactly this
        "events": scan.event_counts(),
        # per-world flight-recorder event counts (obs/flight.py) —
        # present when the sweep ran with --record; the events
        # themselves live in <journal>/events.jsonl (query with
        # `timewarp-tpu explain`)
        "flight_events": scan.flight,
        "pack_sha": scan.pack_sha}
