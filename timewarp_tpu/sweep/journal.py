"""Crash-safe sweep journal: append-only JSONL + atomic checkpoints.

One directory per sweep:

- ``pack.json`` — the pack, written atomically at first run; resume
  reloads it (and refuses a different pack by sha).
- ``journal.jsonl`` — append-only event log, fsync'd per append.
  Events: ``pack`` (sha, world count), ``bucket_start``, ``retry``,
  ``bucket_split``, ``world_done`` (the streamed per-world result),
  ``world_failed`` (terminal, loud), ``bucket_done``, ``sweep_done``.
- ``bucket-<id>.npz`` — per-bucket state snapshot via
  ``utils/checkpoint.save_state`` (atomic: temp + fsync + rename),
  whose meta carries the per-world digest chain, so a resumed bucket
  continues the digest exactly where the state is.

Crash model: every append is flushed and fsync'd before the action it
records is considered durable; a crash can tear at most the *last*
line, which :meth:`SweepJournal.scan` detects and drops with a
warning (the event it described simply re-happens on resume — the
done-set makes re-happening idempotent). A ``world_done`` seen twice
with *different* results is the one unforgivable state — it means two
result streams claimed the same world — and scan fails loudly rather
than pick one.

Multi-host mode (the serving layer, serve/ + docs/serving.md): with
``host="name"`` each cooperating process appends to its OWN
``journal-<name>.jsonl`` (never a shared file — concurrent appends
from two processes could interleave inside a line), with every record
stamped ``host``/``seq``/``ts`` (``ts`` monotone per journal handle).
:meth:`records` merges every journal file in the directory, sorted by
``(ts, host, seq)`` — per-host causal order is preserved, cross-host
order follows wall time — and applies the torn-final-line tolerance
*per file* (any host may have crashed mid-append). With ``host=None``
(the default) nothing changes: one ``journal.jsonl``, unstamped
records, byte-identical to the single-host service since r10.
"""

from __future__ import annotations

import glob as _glob
import json
import logging
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set

__all__ = ["SweepJournal", "JournalState", "SweepJournalError",
           "status_fields", "merge_key", "util_rollup"]


def merge_key(rec: Dict[str, Any]):
    """THE multi-host merge ordering — ``(ts, host, seq)`` — shared by
    :meth:`SweepJournal.records`, the live watch tail (obs/watch.py),
    and the serve frontend's result tail (serve/frontend.py), so the
    file-merge convention cannot drift between readers."""
    return (float(rec.get("ts", 0.0)), str(rec.get("host", "")),
            int(rec.get("seq", 0)))

_log = logging.getLogger("timewarp.sweep")


class SweepJournalError(RuntimeError):
    """The journal contradicts itself (double-journaled world, mixed
    packs, mid-file corruption) — never silently reconciled."""


@dataclass
class JournalState:
    """What a scan of the journal knows."""
    pack_sha: Optional[str] = None
    done: Dict[str, dict] = field(default_factory=dict)      # run_id -> result
    failed: Dict[str, dict] = field(default_factory=dict)    # run_id -> info
    bucket_done: Set[str] = field(default_factory=set)
    #: bucket_id -> [child_id, ...] in split order
    splits: Dict[str, List[str]] = field(default_factory=dict)
    retries: int = 0
    events: List[dict] = field(default_factory=list)
    #: bucket_id -> utilization record (obs: worlds-active occupancy,
    #: budget-mask efficiency, pow2 pad waste — sweep/runner.py)
    util: Dict[str, dict] = field(default_factory=dict)
    #: bucket_id -> ordered dispatch-controller decision records
    #: (dispatch/trace.py schema), journaled BEFORE each chunk runs —
    #: resume replays them so a pre-kill decision is never re-made
    #: differently (docs/dispatch.md)
    decisions: Dict[str, List[dict]] = field(default_factory=dict)
    #: run_id -> bucket_id that streamed its result (what --verify
    #: uses to assemble a controller world's decision chain)
    world_bucket: Dict[str, str] = field(default_factory=dict)
    #: integrity_violation events (integrity/, docs/integrity.md):
    #: each one a detected state corruption that was rolled back —
    #: surfaced in `sweep status` so an SDC-prone host is visible
    integrity: List[dict] = field(default_factory=list)
    #: spec_rollback events (speculate/, docs/speculation.md): each
    #: one a causality violation a speculative chunk detected and
    #: rolled back — surfaced in `sweep status` so the
    #: misspeculation rate is visible (observability only; resume
    #: re-derives rollbacks from the committed decision chain)
    spec_rollbacks: List[dict] = field(default_factory=list)
    #: run_id -> flight-recorder event count (flight_counts records,
    #: sweep/runner.py; summed across processes — a resumed sweep
    #: journals its own drain). Surfaced in `sweep status` next to
    #: utilization when the sweep ran with --record
    flight: Dict[str, int] = field(default_factory=dict)
    #: run_id -> the world's per-chunk digest trail ([[supersteps,
    #: chain_hex], ...], the world_done record's "chain" field) —
    #: what --verify's auto-bisect feeds
    #: obs.bisect.first_trail_divergence to name the first diverging
    #: chunk on a survival-law mismatch
    chains: Dict[str, list] = field(default_factory=dict)
    #: host name -> serving-fleet facts (serve/, docs/serving.md):
    #: leases held, last journaled heartbeat ts, stolen-bucket count,
    #: listen address — folded from serve_open / host_heartbeat /
    #: lease_* records, so `sweep status` and the live watch report
    #: the SAME hosts block from the same fold
    hosts: Dict[str, dict] = field(default_factory=dict)
    #: run_id -> admit record ({"bucket", "slot", "config"}) — the
    #: serving layer's admission ledger: curators rebuild open-bucket
    #: membership from exactly this (the journal IS the queue)
    admits: Dict[str, dict] = field(default_factory=dict)
    #: bucket_id -> bucket_open record (key sha, window, capacity) —
    #: the serving layer's open-bucket table
    serve_buckets: Dict[str, dict] = field(default_factory=dict)
    #: repack events (serve/worker.py): each one an under-occupied
    #: open bucket merged into a same-key peer between chunks
    repacks: List[dict] = field(default_factory=list)
    #: True once a serve_drain record landed: the frontend stopped
    #: admitting; curators exit when every admitted world settles
    draining: bool = False
    #: bucket_id -> the sweep plan's pack_decision record ({"members",
    #: "mode", "artifact_sha", ...}, timewarp_tpu/pack/): journaled
    #: BEFORE any bucket starts when the plan is not a pure function
    #: of the pack alone (--pack predicted), so resume re-derives the
    #: identical bucket membership from the journal — never from a
    #: re-run of the predictor (docs/sweeps.md "Predictive packing").
    #: Insertion-ordered: the fold preserves plan order.
    pack_plan: Dict[str, dict] = field(default_factory=dict)
    #: every pack_decision record (sweep plan form + the serving
    #: layer's placement/repack choices) — the packing audit trail
    pack_decisions: List[dict] = field(default_factory=list)

    def apply(self, rec: Dict[str, Any]) -> None:
        """Fold ONE journal record into this state — the single fold
        both :meth:`SweepJournal.scan` and the live ``sweep watch``
        tail (obs/watch.py) run, so a watcher's aggregates and
        ``sweep status`` can never disagree about the same journal."""
        self.events.append(rec)
        ev = rec.get("ev")
        if ev == "pack":
            if self.pack_sha is not None and self.pack_sha != rec["sha"]:
                raise SweepJournalError(
                    "journal holds events for two different packs — "
                    "one journal dir per sweep")
            self.pack_sha = rec["sha"]
        elif ev == "world_done":
            rid = rec["result"]["run_id"]
            if rid in self.done:
                if self.done[rid] == rec["result"]:
                    # an interrupted attempt's straggler replayed
                    # an identical record — harmless, noted
                    _log.warning("sweep journal: duplicate "
                                 "world_done for %r (identical "
                                 "result)", rid)
                    return
                raise SweepJournalError(
                    f"world {rid!r} is double-journaled with "
                    f"DIFFERENT results — refusing to pick one:\n"
                    f"  first:  {self.done[rid]}\n"
                    f"  second: {rec['result']}")
            self.done[rid] = rec["result"]
            self.world_bucket[rid] = rec.get("bucket", "")
            self.chains[rid] = list(rec.get("chain", []))
        elif ev == "world_failed":
            self.failed[rec["run_id"]] = rec
        elif ev == "bucket_done":
            self.bucket_done.add(rec["bucket"])
        elif ev == "bucket_split":
            self.splits[rec["bucket"]] = list(rec["into"])
        elif ev == "bucket_util":
            # a resumed bucket re-journals its (process-local)
            # utilization; last record wins — wall facts are not
            # replayable, only results are
            self.util[rec["bucket"]] = {
                k: v for k, v in rec.items() if k != "ev"}
        elif ev == "retry":
            self.retries += 1
        elif ev == "integrity_violation":
            self.integrity.append(
                {k: v for k, v in rec.items() if k != "ev"})
        elif ev == "spec_rollback":
            self.spec_rollbacks.append(
                {k: v for k, v in rec.items() if k != "ev"})
        elif ev == "flight_counts":
            # per-world recorded-event counts (sweep/runner.py):
            # each process journals its own drain once per bucket
            # run, so summing across records totals the sweep
            for rid, n in rec.get("counts", {}).items():
                self.flight[rid] = self.flight.get(rid, 0) + int(n)
        elif ev == "serve_open":
            h = self._host(rec["host"])
            h["listen"] = rec.get("listen")
            h["last_heartbeat"] = rec.get("ts")
        elif ev == "host_heartbeat":
            self._host(rec["host"])["last_heartbeat"] = rec.get("ts")
        elif ev == "lease_acquire":
            h = self._host(rec["host"])
            h["leases"].add(rec["bucket"])
            h["last_heartbeat"] = rec.get("ts", h["last_heartbeat"])
            if rec.get("stolen_from"):
                h["stolen"] += 1
                h["stolen_buckets"].append(
                    {"bucket": rec["bucket"],
                     "from": rec["stolen_from"]})
            # a steal implicitly evicts the dead holder's lease row
            prev = self.hosts.get(rec.get("stolen_from") or "")
            if prev is not None:
                prev["leases"].discard(rec["bucket"])
        elif ev == "lease_release":
            self._host(rec["host"])["leases"].discard(rec["bucket"])
        elif ev == "bucket_open":
            self.serve_buckets[rec["bucket"]] = {
                k: v for k, v in rec.items() if k != "ev"}
        elif ev == "admit":
            rid = rec["run_id"]
            prev = self.admits.get(rid)
            if prev is not None \
                    and prev.get("config") != rec.get("config"):
                raise SweepJournalError(
                    f"world {rid!r} is double-admitted with "
                    f"DIFFERENT configs — refusing to pick one:\n"
                    f"  first:  {prev.get('config')}\n"
                    f"  second: {rec.get('config')}")
            # same config: either an idempotent client re-submit (a
            # retried lost reply — harmless by design) or a repack
            # re-point to the merged bucket. A re-point (marked
            # ``repacked_from``) beats an original REGARDLESS of
            # merge order — cross-host wall clocks order the merge,
            # and a skewed clock must not resurrect the donor bucket
            # (which closed at repack); among records of equal
            # authority, last wins
            if prev is None or "repacked_from" in rec \
                    or "repacked_from" not in prev:
                self.admits[rid] = {
                    k: v for k, v in rec.items() if k != "ev"}
        elif ev == "repack":
            self.repacks.append(
                {k: v for k, v in rec.items() if k != "ev"})
        elif ev == "serve_drain":
            self.draining = True
        elif ev == "pack_decision":
            d = {k: v for k, v in rec.items() if k != "ev"}
            self.pack_decisions.append(d)
            if "members" in d:
                # the sweep plan form: exactly one per bucket. A
                # duplicate with identical membership is a resumed
                # service re-journaling its replayed plan (harmless);
                # DIFFERENT membership for one bucket id is the
                # unforgivable state — a resumed sweep would load
                # checkpoints planned for other worlds
                prev = self.pack_plan.get(d["bucket"])
                if prev is not None:
                    knobs = ("members", "mode", "artifact_sha")
                    if any(prev.get(k) != d.get(k) for k in knobs):
                        raise SweepJournalError(
                            f"bucket {d['bucket']!r} is "
                            f"double-journaled with DIFFERENT pack "
                            f"decisions — refusing to pick one:\n"
                            f"  first:  {prev}\n  second: {d}")
                    _log.warning("sweep journal: duplicate pack "
                                 "decision for bucket %r (identical "
                                 "membership)", d["bucket"])
                else:
                    self.pack_plan[d["bucket"]] = d
        elif ev == "dispatch_decision":
            dl = self.decisions.setdefault(rec["bucket"], [])
            d = rec["decision"]
            dup = next((p for p in dl
                        if p["chunk"] == d["chunk"]), None)
            if dup is not None:
                knobs = ("window_us", "rung_pin", "chunk_len")
                if any(dup[k] != d[k] for k in knobs):
                    # the one unforgivable controller state: two
                    # different decisions claim the same chunk —
                    # a replayed resume would match neither run
                    raise SweepJournalError(
                        f"bucket {rec['bucket']!r} chunk "
                        f"{d['chunk']} is double-journaled with "
                        f"DIFFERENT dispatch decisions — "
                        f"refusing to pick one:\n  first:  {dup}"
                        f"\n  second: {d}")
                _log.warning("sweep journal: duplicate dispatch "
                             "decision for bucket %r chunk %d "
                             "(identical knobs)", rec["bucket"],
                             d["chunk"])
            else:
                dl.append(d)

    def event_counts(self) -> Dict[str, int]:
        """The journal's telemetry-event tallies in one block — the
        ``events`` field of ``sweep status --json`` AND the live
        ``sweep watch`` aggregates, computed from the same fold so
        the two surfaces report identical numbers by construction."""
        return {
            "dispatch_decision": sum(len(v)
                                     for v in self.decisions.values()),
            "spec_rollback": len(self.spec_rollbacks),
            "integrity_violation": len(self.integrity),
            "pack_decision": len(self.pack_decisions),
        }

    def decision_chain(self, bucket_id: str) -> List[dict]:
        """Every decision record governing ``bucket_id``'s worlds, in
        chunk order. A split child (``b3.0.1``) continued its parent's
        chunk numbering from the parent's checkpoint, so the chain is
        the ancestor prefixes (``b3``, ``b3.0``) plus the child's own
        records — the sequence a solo replay twin re-applies. Dedup by
        chunk index (ancestor first): a chunk the parent decided but
        never durably executed is reused, not re-decided, by the
        child (sweep/runner.py)."""
        parts = bucket_id.split(".")
        ids = [".".join(parts[:i + 1]) for i in range(len(parts))]
        out: List[dict] = []
        seen: Set[int] = set()
        for bid in ids:
            for d in self.decisions.get(bid, []):
                if d["chunk"] not in seen:
                    seen.add(d["chunk"])
                    out.append(d)
        return sorted(out, key=lambda d: d["chunk"])

    # -- the serving fleet's folded views (serve/, docs/serving.md) ------

    def _host(self, name: str) -> dict:
        return self.hosts.setdefault(name, {
            "leases": set(), "last_heartbeat": None, "stolen": 0,
            "stolen_buckets": [], "listen": None})

    def hosts_block(self) -> Dict[str, dict]:
        """The per-host lease table for ``sweep status --json`` and
        the live watch — one assembly over the one fold, so the two
        surfaces agree by construction. ``last_heartbeat`` is the
        journaled wall ts (deterministic from the fold); readers
        derive heartbeat *age* from it at render time."""
        return {name: {
            "leases": sorted(h["leases"]),
            "last_heartbeat": h["last_heartbeat"],
            "stolen": h["stolen"],
            "stolen_buckets": list(h["stolen_buckets"]),
            "listen": h["listen"],
        } for name, h in sorted(self.hosts.items())}

    def serve_block(self) -> Dict[str, Any]:
        """Admission/steal/repack rollup of a service journal — what
        the ledger ingests as the ``serve`` kind and ``sweep status``
        surfaces next to the hosts block."""
        return {
            "admitted": len(self.admits),
            "open_buckets": sorted(self.serve_buckets),
            "steals": sum(h["stolen"] for h in self.hosts.values()),
            "repacks": len(self.repacks),
            "draining": self.draining,
        }


class SweepJournal:
    def __init__(self, root: str, host: Optional[str] = None) -> None:
        self.root = root
        #: multi-host mode (module docstring): this process's own
        #: append file; merged reads see every host's file
        self.host = host
        self.path = os.path.join(
            root, f"journal-{host}.jsonl" if host else "journal.jsonl")
        self.pack_path = os.path.join(root, "pack.json")
        self._fh = None
        self._seq = 0
        self._last_ts = 0.0
        # one process may append from two threads sharing a handle
        # (the serve frontend's event loop + its embedded curator,
        # serve/frontend.py) — the lock keeps lines whole and seq
        # stamps unique; cross-PROCESS writers use per-host files
        import threading
        self._wlock = threading.Lock()
        #: optional observability hook: called as ``on_append(ev,
        #: wall_s)`` after every durable append — the sweep service
        #: wires it to the Perfetto timeline so fsync stalls are
        #: visible (obs/perfetto.py). Purely additive: the append's
        #: durability contract does not depend on it.
        self.on_append = None

    # -- writing -----------------------------------------------------------

    def ensure_dir(self) -> None:
        os.makedirs(self.root, exist_ok=True)

    def write_pack(self, pack) -> None:
        """Atomically persist the pack (resume's source of truth)."""
        from ..utils.checkpoint import atomic_write
        self.ensure_dir()

        def write(f):
            json.dump(pack.to_json(), f, indent=1, sort_keys=True)
            f.write("\n")
        atomic_write(self.pack_path, write, mode="w")

    def append(self, rec: Dict[str, Any]) -> None:
        """Durable append: the record is on disk (flushed + fsync'd)
        before this returns — the crash-safety contract every caller
        leans on."""
        import time as _time
        t0 = _time.perf_counter()
        with self._wlock:
            if self._fh is None:
                self.ensure_dir()
                self._fh = open(self.path, "a")
            if self.host is not None:
                # the multi-host merge stamp: per-host seq (causal
                # order within a file) + a ts kept monotone per handle
                # so the (ts, host, seq) merge sort can never invert
                # one host's own appends even across a wall-clock
                # step back
                self._seq += 1
                self._last_ts = max(self._last_ts, _time.time())
                rec = {**rec, "host": self.host, "seq": self._seq,
                       "ts": round(self._last_ts, 6)}
            self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())
        if self.on_append is not None:
            self.on_append(rec.get("ev", "?"),
                           _time.perf_counter() - t0)

    def maybe_heartbeat(self, min_interval_s: float = 1.0) -> None:
        """Journal a throttled ``host_heartbeat`` (multi-host mode
        only) — the fold's ``last_heartbeat`` behind the hosts block's
        heartbeat-age view. The lease files carry the load-bearing
        liveness (lease.py); this is the observability mirror."""
        if self.host is None:
            return
        import time as _time
        now = _time.monotonic()
        if now - getattr(self, "_hb_mono", 0.0) >= min_interval_s:
            self._hb_mono = now
            self.append({"ev": "host_heartbeat", "host": self.host})

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def checkpoint_path(self, bucket_id: str) -> str:
        return os.path.join(self.root, f"bucket-{bucket_id}.npz")

    # -- reading -----------------------------------------------------------

    def journal_files(self) -> List[str]:
        """Every journal file in the directory: the single-host
        ``journal.jsonl`` (if present) plus every per-host
        ``journal-<name>.jsonl``, in sorted order."""
        out = []
        single = os.path.join(self.root, "journal.jsonl")
        if os.path.exists(single):
            out.append(single)
        out.extend(sorted(
            p for p in _glob.glob(os.path.join(self.root,
                                               "journal-*.jsonl"))
            if p != single))
        return out

    def exists(self) -> bool:
        return bool(self.journal_files())

    def _parse_file(self, path: str) -> List[dict]:
        with open(path) as f:
            lines = f.read().splitlines()
        out: List[dict] = []
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError as e:
                if i == len(lines) - 1:
                    _log.warning(
                        "sweep journal %s: dropping torn final line "
                        "(crash mid-append): %r", path, line[:80])
                    continue
                raise SweepJournalError(
                    f"sweep journal {path!r} line {i + 1} is "
                    f"corrupt mid-file ({e}); a crash can only tear "
                    "the last line — this journal has been damaged "
                    "externally") from None
        return out

    def records(self) -> List[dict]:
        """Parse the log(s). A torn *final* line (crash mid-append) is
        dropped with a warning — per file: in multi-host mode any host
        may have crashed mid-append; an unparsable line anywhere else
        is corruption and fails loudly. Multiple host files merge
        sorted by ``(ts, host, seq)`` (module docstring)."""
        files = self.journal_files()
        if not files:
            return []
        if len(files) == 1 and files[0] == os.path.join(
                self.root, "journal.jsonl"):
            # the single-host fast path: exactly the pre-serve reader
            return self._parse_file(files[0])
        recs = [r for p in files for r in self._parse_file(p)]
        return sorted(recs, key=merge_key)

    def scan(self) -> JournalState:
        st = JournalState()
        for rec in self.records():
            try:
                st.apply(rec)
            except SweepJournalError as e:
                # re-raise with the file named (apply is path-free so
                # the live watch tail can share it verbatim)
                raise SweepJournalError(
                    f"sweep journal {self.path!r}: {e}") from None
        return st


def util_rollup(util: Dict[str, dict]) -> Dict[str, float]:
    """Fleet-level packing efficiency from the per-bucket
    ``bucket_util`` records (sweep/runner.py, serve/worker.py): the
    work-weighted ``budget_efficiency`` (world supersteps over every
    slot-superstep the batched scans paid for) and ``pad_waste_frac``
    (pow2 scan-pad supersteps over scan supersteps), across all
    buckets. THE two numbers the predictive packer is gated on —
    surfaced on the sweep_hetero/serve_gossip bench lines and
    promoted to `ledger compare` metrics (obs/regress.py), so a
    packing regression is a gateable rate regression."""
    world = scan_total = pad = slot_total = 0.0
    for u in util.values():
        s = float(u.get("scan_supersteps", 0) or 0)
        world += float(u.get("world_supersteps", 0) or 0)
        scan_total += s
        slot_total += float(u.get("worlds", 0) or 0) * s
        pad += float(u.get("pad_waste_frac", 0.0) or 0.0) * s
    return {
        "budget_efficiency": round(world / slot_total, 4)
        if slot_total else 1.0,
        "pad_waste_frac": round(pad / scan_total, 4)
        if scan_total else 0.0,
    }


def status_fields(scan: JournalState,
                  total_worlds: Optional[int]) -> Dict[str, Any]:
    """The shared progress block behind ``sweep status --json`` and
    the final aggregates of ``sweep watch`` (obs/watch.py): ONE
    assembly over one fold, so the two surfaces are equal by
    construction. ``total_worlds`` is the pack's world count (None
    when a watcher attached before ``pack.json`` was written)."""
    done, failed = len(scan.done), len(scan.failed)
    out = {
        "worlds": total_worlds, "completed": done,
        "failed": sorted(scan.failed),
        "pending": (None if total_worlds is None
                    else total_worlds - done - failed),
        "retries": scan.retries,
        "splits": {k: v for k, v in scan.splits.items()},
        "buckets_done": sorted(scan.bucket_done),
        # per-bucket hardware utilization (sweep/runner.py): how well
        # the batched executables were used — worlds-active occupancy,
        # budget-mask efficiency, pow2 scan-pad waste
        "utilization": scan.util,
        # detected-and-rolled-back state corruptions (integrity/):
        # a nonzero count on real hardware means an SDC-prone host
        "integrity_violations": scan.integrity,
        # detected-and-rolled-back causality violations (speculate/):
        # the misspeculation ledger — each one a speculative window
        # probe the policy backed off from (docs/speculation.md)
        "spec_rollbacks": scan.spec_rollbacks,
        # the journal's event tallies in one block (event_counts):
        # dispatch decisions, speculation rollbacks, integrity
        # violations — the cross-run ledger ingests exactly this
        "events": scan.event_counts(),
        # per-world flight-recorder event counts (obs/flight.py) —
        # present when the sweep ran with --record; the events
        # themselves live in <journal>/events.jsonl (query with
        # `timewarp-tpu explain`)
        "flight_events": scan.flight,
        "pack_sha": scan.pack_sha}
    if scan.hosts or scan.admits or scan.serve_buckets:
        # the serving fleet's blocks (serve/, docs/serving.md) —
        # present ONLY when host/lease/admission events exist, so a
        # plain single-host sweep's status line stays byte-identical
        # to the pre-serve service
        out["hosts"] = scan.hosts_block()
        out["serve"] = scan.serve_block()
    return out
