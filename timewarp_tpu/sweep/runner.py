"""Per-bucket execution: chunked, checkpointed, digest-chained.

A :class:`BucketRunner` owns one bucket's engine and drives it one
chunk at a time (``engine.run`` with per-world remaining budgets —
the vector-budget driver; the active/remaining bookkeeping is the
engine's own ``fleet_progress``, shared with ``run_stream`` so the
quiesce law cannot drift between drivers). Every chunk:

1. the injection hook fires (the deterministic chaos the CI smoke and
   tests use to provoke retries / OOM splits / mid-sweep kills);
2. worlds that have quiesced or exhausted their budget since the last
   chunk stream their result record to the journal — **as they
   finish**, not at bucket end;
3. the chunk runs; each world's digest chain and superstep count
   advance;
4. the bucket checkpoint is atomically rewritten, its meta carrying
   the digest chains — so a killed sweep resumes the digests exactly
   where the state is.

Methods here are *blocking* (they execute XLA programs); the service
(service.py) calls them through ``AwaitIO`` on an executor thread so
its watchdogs stay live.

Zombie safety: a watchdog-abandoned attempt's thread may still be
inside a chunk when the retry starts. Attempts are therefore
*epoch-stamped*: the service passes each blocking call the epoch it
belongs to, the watchdog's :meth:`abandon` invalidates that epoch,
and every commit (journal append, checkpoint write, in-memory
state/digest update) happens under a lock only if the call's epoch is
still current — a stale thread raises :class:`StaleAttempt` and can
never corrupt the retry's digest chain or double-journal a world.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Dict, List, Optional, Set

import numpy as np

from .bucket import Bucket, build_bucket_engine
from .journal import SweepJournal
from .spec import DIGEST_ZERO, chain_digest, world_result

__all__ = ["BucketRunner", "StaleAttempt"]


class StaleAttempt(RuntimeError):
    """A watchdog-abandoned thread outlived its attempt: every write
    path refuses it (raised on an executor thread whose future the
    supervisor already dropped — nobody observes it, by design)."""


class BucketRunner:
    def __init__(self, bucket: Bucket, journal: SweepJournal,
                 done: Dict[str, dict], *, lint: str = "warn",
                 chunk: int = 64, inject=None,
                 telemetry: str = "off", metrics=None,
                 prior_decisions=(), verify: str = "off",
                 record: str = "off", flight=None) -> None:
        self.bucket = bucket
        self.journal = journal
        #: shared run_id -> result map (journaled results land here
        #: too, so the service reports without rescanning the log)
        self.done = done
        self.lint = lint
        self.chunk = int(chunk)
        self.inject = inject
        #: online adaptive dispatch (dispatch/, docs/dispatch.md):
        #: controller buckets decide window/rung/chunk-length per
        #: chunk, journal each FRESH decision before its chunk runs
        #: (under the epoch lock — a zombie attempt can neither
        #: decide nor journal), and REPLAY `prior_decisions` (the
        #: journaled chain, resume/split) instead of re-deciding
        self.ctrl = None
        self.prior_decisions = list(prior_decisions)
        #: optimistic time-warp execution (speculate/,
        #: docs/speculation.md): speculate buckets run under a
        #: SpeculationPolicy — the same decide/replay surface as the
        #: controller, PLUS rollback. Two discipline differences:
        #: decisions journal at COMMIT (the policy is a pure function
        #: of its committed chain — no telemetry to lose in a crash,
        #: so re-deciding after a kill is bit-deterministic), and a
        #: SpeculationViolation from the chunk rolls just this chunk
        #: back (state uncommitted, decision replaced with the floor)
        #: instead of surfacing to the retry machinery.
        self._spec = bucket.speculate != "off"
        #: chunk indices whose decisions are durably journaled — the
        #: commit-time journaling ledger (prior_decisions arriving
        #: from a resume scan are journaled by definition; a split
        #: parent's in-flight unjournaled decision is filtered out in
        #: split_children)
        self._journaled = {d["chunk"] if isinstance(d, dict)
                           else d.chunk for d in self.prior_decisions}
        #: chunks durably executed (checkpoint meta "chunks") — the
        #: next decision's index
        self.chunks = 0
        #: engine telemetry mode + optional obs.metrics.MetricsRegistry
        #: (the engine chunk-flushes `supersteps` lines into it)
        self.telemetry = telemetry
        self.metrics = metrics
        #: causal flight recorder (obs/flight.py): bucket engines
        #: built with record= thread the event plane; each chunk's
        #: per-world logs drain into the shared ``flight`` writer
        #: (<journal>/events.jsonl) tagged with the world's run_id,
        #: and per-world event counts are journaled for `sweep
        #: status`. Retried chunks may re-drain — events.jsonl is an
        #: observability artifact, deliberately OUTSIDE the survival
        #: law's compare surface (duplicates are harmless; the
        #: superstep indices make them identifiable)
        self.record = record
        self.flight = flight
        self.flight_counts: Dict[str, int] = {}
        #: per-world [(supersteps, trace-digest chain), ...] trail —
        #: the prefix values of the row chain at each chunk boundary.
        #: Journaled on the world_done record (outside "result") and
        #: persisted in checkpoint meta, it is what --verify's
        #: auto-bisect compares against the solo twin to name the
        #: first diverging chunk (obs/bisect.first_trail_divergence)
        self.trails: Optional[List[list]] = None
        #: online state-integrity mode (integrity/, docs/integrity.md):
        #: "guard" builds the bucket engine with the on-device
        #: invariant plane; "digest" additionally keeps a per-world
        #: rolling state digest, verified at every chunk ENTRY and
        #: chained into the checkpoint meta — each checkpoint is a
        #: verified epoch, and detection raises IntegrityViolation
        #: (the service journals it and retries from that checkpoint:
        #: deterministic rollback of just this bucket)
        self.verify = verify
        #: per-world uint32 state digests at the last verified epoch
        self.vdigests = None
        #: per-world sha256 digest chain over the verified epochs
        self.vchain: Optional[List[str]] = None
        self.attempts = 0
        #: multi-host mode (serve/lease.py, docs/serving.md): the
        #: bucket lease this runner executes under — renewed at every
        #: chunk entry (the heartbeat) and CHECKED before every
        #: journal commit, so a host whose lease was reclaimed (it
        #: stalled past the TTL and a peer stole the bucket) abandons
        #: via LeaseLost instead of double-journaling. None in
        #: single-host mode: zero behavior change.
        self.lease = None
        self.lease_dir = None
        #: attempt generation (module docstring): bumped by
        #: begin_attempt and by abandon, so a zombie thread's stamped
        #: epoch can never match again
        self.epoch = 0
        self._lock = threading.Lock()
        self.engine = None
        self.state = None
        self.digests: Optional[List[str]] = None
        self.supersteps: Optional[List[int]] = None
        self.emitted: Optional[Set[str]] = None
        #: wall seconds this process has spent running the bucket's
        #: chunks (stamped onto world_done records — observability
        #: metadata OUTSIDE the result dict, so the sweep survival law
        #: and resume's replay-equality check never see it)
        self.wall_s = 0.0
        #: hardware-utilization accumulators (journaled as a
        #: `bucket_util` record when the bucket completes): how much
        #: of the batched executable's width and pow2-padded scan
        #: length did real (unmasked, unpadded) supersteps use
        self.util = {"chunks": 0, "world_supersteps": 0,
                     "scan_supersteps": 0, "pad_supersteps": 0,
                     "active_world_chunks": 0,
                     "engine_builds": 0, "compiles": 0}

    # -- attempt lifecycle (called from the event-loop thread) -----------

    def begin_attempt(self) -> int:
        """Start a new attempt generation; returns its epoch (stamped
        onto every blocking call of this attempt)."""
        with self._lock:
            self.epoch += 1
            return self.epoch

    def abandon(self, epoch: int) -> None:
        """Watchdog: invalidate ``epoch`` if it is still current —
        the abandoned thread's writes all fail their epoch check."""
        with self._lock:
            if self.epoch == epoch:
                self.epoch += 1

    def _check(self, epoch: Optional[int]) -> None:
        if epoch is not None and epoch != self.epoch:
            raise StaleAttempt(
                f"bucket {self.bucket.bucket_id!r}: attempt epoch "
                f"{epoch} was abandoned (current {self.epoch})")

    def _lease_renew(self) -> None:
        """Chunk-entry heartbeat (multi-host mode): raises LeaseLost
        when the bucket was reclaimed by a peer."""
        if self.lease is not None:
            self.lease_dir.renew(self.lease)
            self.journal.maybe_heartbeat()

    def _lease_check(self) -> None:
        """Pre-commit guard (multi-host mode): never journal for a
        bucket we no longer hold."""
        if self.lease is not None:
            self.lease_dir.check(self.lease)

    # -- blocking entry points (run on an executor thread) ---------------

    def prepare(self, epoch: Optional[int] = None) -> None:
        """Build the engine (once) and (re)load the bucket state from
        its checkpoint — every retry restarts exactly here, so a
        transient crash costs at most one chunk of progress."""
        self._check(epoch)
        engine = self.engine
        ctrl = self.ctrl
        if engine is None:
            if self.bucket.controller:
                from ..dispatch import DispatchController
                # the operator's --chunk stays the CEILING: it bounds
                # memory per executable and checkpoint granularity (a
                # crash loses at most one chunk) — the controller
                # adapts downward within it, never past it
                ctrl = DispatchController(
                    mode="auto", chunk=self.chunk,
                    chunk_min=min(8, self.chunk),
                    chunk_max=self.chunk,
                    replay=self.prior_decisions)
            elif self._spec:
                from ..speculate import parse_speculate
                from ..speculate.policy import SpeculationPolicy
                mode, w = parse_speculate(self.bucket.speculate)
                # the journaled chain replays as a PREFIX (mode stays
                # auto/fixed): committed chunks re-apply verbatim,
                # the in-flight chunk re-decides — identically, the
                # policy being a pure function of that chain
                ctrl = SpeculationPolicy(
                    mode=mode, fixed_w=w, chunk=self.chunk,
                    replay=self.prior_decisions or None)
            engine = build_bucket_engine(
                self.bucket, lint=self.lint, telemetry=self.telemetry,
                # a SpeculationPolicy is the runner's host-side
                # decision source, never an engine binding — the
                # engine's own speculate= knob (bucket.speculate,
                # build_bucket_engine) licenses the dynamic window
                controller=ctrl if self.bucket.controller else None,
                record=self.record,
                # digest mode includes the guard rung of the ladder
                # (the in-scan invariants); the digest itself is this
                # runner's chunk-boundary business
                verify="off" if self.verify == "off" else "guard")
            engine.metrics = self.metrics
        path = self.journal.checkpoint_path(self.bucket.bucket_id)
        B = self.bucket.B
        if os.path.exists(path):
            from ..utils.checkpoint import load_state
            st, meta = load_state(
                path, engine.init_state(),
                expect_meta={"bucket": self.bucket.bucket_id,
                             "run_ids": list(self.bucket.run_ids)})
            digests = list(meta["digests"])
            supersteps = [int(s) for s in meta["supersteps"]]
            chunks = int(meta.get("chunks", 0))
            trails = [list(t) for t in meta["trail"]] \
                if "trail" in meta else [[] for _ in range(B)]
        else:
            st = engine.init_state()
            meta = None
            digests = [DIGEST_ZERO] * B
            supersteps = [0] * B
            chunks = 0
            trails = [[] for _ in range(B)]
        vdigests = vchain = None
        if self.verify == "digest":
            # a restored checkpoint must match the digests its meta
            # recorded (the verified-epoch contract): the per-leaf
            # sha in utils/checkpoint.py caught at-rest disk
            # corruption; this catches a chain that was broken before
            # the checkpoint was even written (and seeds the chain
            # the coming chunks extend). The recompute runs every
            # retry, so resuming onto corrupt state is impossible.
            from ..integrity.checks import IntegrityViolation
            from ..integrity.digest import (VERIFY_CHAIN_ZERO,
                                            first_digest_mismatch,
                                            host_digests)
            vdigests = host_digests(st, engine.batch)
            if meta is not None and "state_digests" in meta:
                hit = first_digest_mismatch(vdigests,
                                            meta["state_digests"])
                if hit is not None:
                    bad, got_h, want_h = hit
                    raise IntegrityViolation(
                        f"bucket {self.bucket.bucket_id!r} checkpoint "
                        f"{path!r} world {bad}: restored state digest "
                        f"{got_h} != recorded {want_h} "
                        "— the checkpoint is not the verified epoch "
                        "its meta claims (docs/integrity.md)")
                vchain = list(meta["verify_chain"])
            else:
                vchain = [VERIFY_CHAIN_ZERO] * B
        with self._lock:
            self._check(epoch)
            if self.engine is None:
                self.engine = engine
                self.util["engine_builds"] += 1
                self.ctrl = ctrl
                if ctrl is not None:
                    ctrl.begin(engine)
            self.state = st
            self.digests = digests
            self.supersteps = supersteps
            self.chunks = chunks
            self.trails = trails
            self.vdigests = vdigests
            self.vchain = vchain
            self.emitted = set(self.done)
            # a retry restarts from the checkpoint: the telemetry the
            # in-flight chunk produced is gone, which is exactly why
            # its journaled decision (if any) is REUSED, not re-made
            if self.engine is not None:
                self.engine.last_run_telemetry = None

    def fault_pad(self):
        """The engine's realized fault-table pad shape — what split
        children must pad to so the sliced ``restart_done`` state
        keeps its shape (bucket.py)."""
        from ..faults.schedule import FaultFleet
        if self.engine is None or not isinstance(self.engine.faults,
                                                 FaultFleet):
            return None
        return self.engine.faults._pad_shape()

    def step(self, epoch: Optional[int] = None) -> str:
        """One chunk (module docstring). Returns ``"running"`` or
        ``"done"`` (every world's result is journaled)."""
        self._check(epoch)
        self._lease_renew()
        if self.inject is not None:
            self.inject()
            # the flip: form corrupts the in-memory state between
            # chunks (integrity/inject.py) — exactly the window the
            # entry digest check below covers
            hook = getattr(self.inject, "flip_hook", None)
            if hook is not None:
                hook(self)
        eng = self.engine
        if self.verify == "digest" and self.vdigests is not None:
            # chunk-entry verification: the state arrays did not
            # legitimately change since the last verified epoch, so
            # any digest movement is corruption at rest — detected
            # BEFORE the corrupt state runs a superstep. The raise
            # unwinds to the service, which journals the
            # integrity_violation and retries from the last verified
            # checkpoint (deterministic rollback of this bucket only)
            from ..integrity.checks import IntegrityViolation
            from ..integrity.digest import (first_digest_mismatch,
                                            host_digests)
            ver_cm = (self.metrics.span(
                "verify", bucket=self.bucket.bucket_id)
                if self.metrics is not None
                else contextlib.nullcontext())
            with ver_cm:
                hit = first_digest_mismatch(
                    host_digests(self.state, eng.batch),
                    self.vdigests)
            if hit is not None:
                bad, got_h, want_h = hit
                raise IntegrityViolation(
                    f"bucket {self.bucket.bucket_id!r} chunk "
                    f"{self.chunks} world {bad}: state digest "
                    f"{got_h} != last verified {want_h} — state "
                    "corrupted between chunks; rolling back to the "
                    "last verified checkpoint (docs/integrity.md)")
        # snapshot the attempt's view; commits re-check the epoch
        st, digests = self.state, list(self.digests)
        supersteps = list(self.supersteps)
        trails = [list(t) for t in self.trails]
        B = self.bucket.B
        _, remaining, active = eng.fleet_progress(st,
                                                  self.bucket.budgets)
        for b in np.nonzero(~active)[0]:
            cfg = self.bucket.configs[int(b)]
            if cfg.run_id in self.emitted:
                continue
            res = world_result(cfg, st, int(b), digests[int(b)],
                               supersteps[int(b)])
            with self._lock:
                self._check(epoch)
                self._lease_check()
                # wall_s / attempts are observability metadata on the
                # RECORD, deliberately outside "result": the sweep
                # survival law (and resume's replayed-record equality)
                # compare results, which must stay bit-deterministic
                # "chain" (the per-chunk digest trail) rides OUTSIDE
                # "result" like wall_s/attempts: --verify's
                # auto-bisect reads it, the survival law's compare
                # surface never sees it
                self.journal.append({"ev": "world_done",
                                     "bucket": self.bucket.bucket_id,
                                     "wall_s": round(self.wall_s, 6),
                                     "attempts": self.attempts,
                                     "chain": trails[int(b)],
                                     "result": res})
                self.done[cfg.run_id] = res
                self.emitted.add(cfg.run_id)
        if not active.any():
            self._finish_util(epoch)
            return "done"
        run_kw = {}
        chunk_len = self.chunk
        ci = self.chunks
        if self.ctrl is not None:
            # decide + journal atomically under the epoch lock: a
            # zombie attempt must neither mint a decision nor journal
            # one, and a FRESH decision is durable BEFORE its chunk
            # runs — so a kill mid-chunk resumes by replaying it,
            # never re-deciding from telemetry the crash destroyed
            t_now = int(np.min(np.asarray(st.time)))
            with self._lock:
                self._check(epoch)
                self._lease_check()
                dec, fresh = self.ctrl.decide(
                    ci, eng.last_run_telemetry, t_now)
                if fresh and not self._spec:
                    # speculate buckets journal at COMMIT instead
                    # (below): a speculative decision may be replaced
                    # by its rollback's floor decision before it ever
                    # commits, and the policy re-derives an in-flight
                    # decision bit-identically from the journaled
                    # chain — so journaling early would only plant
                    # double-journal conflicts
                    self.journal.append(
                        {"ev": "dispatch_decision",
                         "bucket": self.bucket.bucket_id,
                         "decision": dec.to_json()})
                    if self.metrics is not None:
                        # the decision also streams as a metrics line
                        # (obs/metrics.py `decision` kind), same as
                        # run_controlled — the journal stays the
                        # replay truth, metrics the observability
                        self.metrics.emit(
                            "decision",
                            label=f"bucket:{self.bucket.bucket_id}",
                            chunk=dec.chunk,
                            window_us=dec.window_us,
                            rung_pin=dec.rung_pin,
                            chunk_len=dec.chunk_len)
            chunk_len = dec.chunk_len
            dyn = eng.dyn_values(dec)
            if dyn is not None:
                run_kw["_dyn"] = dyn
        vec = np.where(active, np.minimum(remaining, chunk_len), 0)
        import time as _time
        from ..interp.jax_engine.common import scan_pad
        from ..obs.profiler import annotate
        _t0 = _time.perf_counter()
        # speculate buckets shield the metrics stream while the chunk
        # runs (the run_verified/run_speculative discipline): the
        # chunk is uncommitted until its causality plane decodes
        # clean, and eng.run flushes its `supersteps` lines BEFORE
        # the decode raises — a violating chunk would leave tainted
        # (then, after the floor re-run, duplicated) lines behind.
        # The committed chunk's lines flush below, at commit.
        if self._spec:
            eng.metrics = None
        try:
            with annotate(f"sweep bucket {self.bucket.bucket_id}"):
                new_state, traces = eng.run(vec, state=st, **run_kw)
        except Exception as e:  # noqa: BLE001 — re-raised unless spec
            from ..speculate import SpeculationViolation
            if not (self._spec
                    and isinstance(e, SpeculationViolation)):
                raise
            # optimistic rollback (speculate/, docs/speculation.md):
            # the chunk's causality plane flagged a straggler — the
            # chunk is DISCARDED (state/digests/trails untouched: the
            # in-memory view still holds the last committed chunk,
            # exactly what the checkpoint holds), its decision is
            # replaced with the conservative floor, and the next
            # step() call re-runs it. Journaled for observability
            # (resume needs nothing: the policy re-derives the floor
            # decision from the committed chain).
            hit = getattr(e, "hit", None) or {}
            if dec.window_us <= self.ctrl.floor:
                # the conservative floor itself violated: the link
                # model's declared min_delay_us lies about its
                # samples — surface to the retry machinery (terminal
                # failure, loud) instead of rolling back forever
                raise SpeculationViolation(
                    f"bucket {self.bucket.bucket_id!r} chunk {ci} "
                    f"violated causality at the conservative floor "
                    f"{self.ctrl.floor} µs — the link model's "
                    "declared min_delay_us is not a true lower bound "
                    "of its samples (docs/speculation.md)", hit) \
                    from e
            with self._lock:
                self._check(epoch)
                self.ctrl.rollback(ci, hit)
                eng.last_run_telemetry = None
                from ..speculate import hit_scalars
                self.journal.append({
                    "ev": "spec_rollback",
                    "bucket": self.bucket.bucket_id, "chunk": ci,
                    "window_us": dec.window_us, **hit_scalars(hit)})
                if self.metrics is not None:
                    self.metrics.emit(
                        "speculation",
                        label=f"bucket:{self.bucket.bucket_id}",
                        chunk=ci, window_us=dec.window_us,
                        outcome="rollback", **hit_scalars(hit))
            self.wall_s += _time.perf_counter() - _t0
            return "running"
        finally:
            if self._spec:
                eng.metrics = self.metrics
        chunk_wall = _time.perf_counter() - _t0
        if self._spec and self.metrics is not None \
                and eng.last_run_telemetry is not None:
            # the committed chunk's telemetry lines — exactly what
            # eng.run would have flushed had the stream not been
            # shielded above
            self.metrics.superstep_chunk(eng.metrics_label,
                                         eng.last_run_telemetry)
        for b in range(B):
            digests[b] = chain_digest(digests[b], traces[b])
            supersteps[b] += len(traces[b])
            if len(traces[b]):
                trails[b].append([supersteps[b], digests[b]])
        if self.record != "off" and self.flight is not None \
                and eng.last_run_flight is not None:
            # drain this chunk's per-world events into the shared
            # journal-dir event log, tagged by run_id (superstep
            # indices are run-global — the engine state's step count)
            for b, lg in enumerate(eng.last_run_flight):
                if len(lg) == 0 and lg.dropped == 0:
                    continue
                rid = self.bucket.configs[b].run_id
                self.flight.write(lg, world=b, run_id=rid)
                self.flight_counts[rid] = \
                    self.flight_counts.get(rid, 0) + len(lg)
        vdig2 = vchain2 = None
        if self.verify == "digest":
            # the new verified epoch: digest the post-chunk state and
            # extend the per-world sha256 chain — recorded in the
            # checkpoint meta below, so the checkpoint IS the epoch
            from ..integrity.digest import (chain_state_digest,
                                            host_digests)
            vdig2 = host_digests(new_state, eng.batch)
            vchain2 = [chain_state_digest(self.vchain[b], vdig2[b])
                       for b in range(B)]
        top = int(vec.max())
        with self._lock:
            self._check(epoch)
            self._lease_check()
            if self._spec and ci not in self._journaled:
                # the commit-time half of the speculation journaling
                # discipline (ctor comment): the decision that
                # actually committed — floor decisions a rollback
                # settled on included — becomes durable with its
                # chunk, so the solo twin's replay chain is exactly
                # the committed window sequence
                self.journal.append(
                    {"ev": "dispatch_decision",
                     "bucket": self.bucket.bucket_id,
                     "decision": dec.to_json()})
                self._journaled.add(ci)
                if self.metrics is not None:
                    self.metrics.emit(
                        "speculation",
                        label=f"bucket:{self.bucket.bucket_id}",
                        chunk=ci, window_us=dec.window_us,
                        outcome="committed")
            self.state = new_state
            self.digests = digests
            self.supersteps = supersteps
            self.trails = trails
            self.chunks = ci + 1
            self.wall_s += chunk_wall
            if vdig2 is not None:
                self.vdigests = vdig2
                self.vchain = vchain2
            # utilization bookkeeping: the fleet executed B ×
            # scan_pad(top) superstep bodies for Σ len(traces[b]) real
            # (unmasked) ones — the gap is pad waste + budget masking
            u = self.util
            u["chunks"] += 1
            u["world_supersteps"] += sum(len(traces[b])
                                         for b in range(B))
            u["scan_supersteps"] += scan_pad(top)
            u["pad_supersteps"] += scan_pad(top) - top
            u["active_world_chunks"] += int(active.sum())
            u["compiles"] += int((eng.last_run_stats or {}
                                  ).get("compiles", 0))
            from ..utils.checkpoint import save_state
            ckpt_cm = (self.metrics.span(
                "checkpoint", bucket=self.bucket.bucket_id)
                if self.metrics is not None
                else contextlib.nullcontext())
            meta = {"bucket": self.bucket.bucket_id,
                    "run_ids": list(self.bucket.run_ids),
                    "digests": list(digests),
                    "supersteps": [int(s) for s in supersteps],
                    "trail": [list(t) for t in trails],
                    "chunks": ci + 1}
            if vdig2 is not None:
                # the verified-epoch extension of the existing sha256
                # digest chain (docs/integrity.md): resume recomputes
                # state_digests from the restored arrays and refuses
                # a checkpoint that no longer matches its own record
                meta["state_digests"] = [int(d) for d in vdig2]
                meta["verify_chain"] = list(vchain2)
            with ckpt_cm:
                save_state(
                    self.journal.checkpoint_path(self.bucket.bucket_id),
                    new_state, meta=meta)
        return "running"

    def utilization(self) -> dict:
        """The bucket's hardware-utilization record (module docstring
        step 4's ledger): budget-mask efficiency = real supersteps /
        (B × scan supersteps executed), pow2 pad waste, and mean
        worlds-active occupancy per chunk. A resumed bucket reports
        only the resumed process's chunks (wall-clock facts are not
        replayable — the *results* are what the survival law pins)."""
        u = self.util
        B = self.bucket.B
        scan_total = u["scan_supersteps"]
        return {
            "bucket": self.bucket.bucket_id,
            "worlds": B,
            "chunks": u["chunks"],
            "world_supersteps": u["world_supersteps"],
            "scan_supersteps": scan_total,
            "budget_efficiency": round(
                u["world_supersteps"] / (B * scan_total), 4)
            if scan_total else 1.0,
            "pad_waste_frac": round(
                u["pad_supersteps"] / scan_total, 4)
            if scan_total else 0.0,
            "worlds_active_mean": round(
                u["active_world_chunks"] / (u["chunks"] * B), 4)
            if u["chunks"] else 0.0,
            "engine_builds": u["engine_builds"],
            "compiles": u["compiles"],
            "wall_s": round(self.wall_s, 6),
        }

    def _finish_util(self, epoch: Optional[int]) -> None:
        """Journal the bucket's utilization record once, when every
        world's result has streamed — alongside (not inside) the
        results, so `sweep status` can report hardware efficiency per
        bucket without touching the survival law's compare surface."""
        if self.util.get("_journaled"):
            return
        rec = self.utilization()
        with self._lock:
            self._check(epoch)
            self._lease_check()
            self.journal.append({"ev": "bucket_util", **rec})
            if self.record != "off":
                # per-world flight-event counts (this process's) —
                # `sweep status` surfaces them next to utilization
                self.journal.append({
                    "ev": "flight_counts",
                    "bucket": self.bucket.bucket_id,
                    "record": self.record,
                    "counts": dict(self.flight_counts)})
            self.util["_journaled"] = True
        if self.metrics is not None:
            self.metrics.emit("utilization", **rec)

    def split_children(self) -> List["BucketRunner"]:
        """The OOM degradation path: halve the bucket, slice the last
        good checkpointed state per child (world slices are exact —
        the batch exactness law), persist each child's checkpoint, and
        hand back child runners. The caller journals the split event
        AFTER this returns, so a crash mid-split leaves the parent
        authoritative."""
        import dataclasses

        import jax

        pad = self.fault_pad()
        kids = self.bucket.split()
        if pad is not None:
            kids = tuple(dataclasses.replace(k, fault_pad=pad)
                         for k in kids)
        mid = kids[0].B
        parts = [(kids[0], list(range(mid))),
                 (kids[1], list(range(mid, self.bucket.B)))]
        # controller buckets: children continue the parent's chunk
        # numbering from its checkpoint and REPLAY the parent's
        # decision chain (prior + this process's) — the solo twin's
        # decision_chain (journal.py) reassembles the same sequence
        kid_decisions = [d.to_json() for d in self.ctrl.decisions] \
            if self.ctrl is not None else list(self.prior_decisions)
        if self._spec and self.ctrl is not None:
            # speculation decisions journal at commit: an in-flight
            # (unjournaled) decision must not ride to the children as
            # replay truth — they re-derive it bit-identically from
            # the committed chain (policy.py module docstring)
            kid_decisions = [d for d in kid_decisions
                             if d["chunk"] in self._journaled]
        runners = []
        for child, idxs in parts:
            r = BucketRunner(child, self.journal, self.done,
                             lint=self.lint, chunk=self.chunk,
                             inject=self.inject,
                             telemetry=self.telemetry,
                             metrics=self.metrics,
                             prior_decisions=kid_decisions,
                             verify=self.verify, record=self.record,
                             flight=self.flight)
            if self.state is not None:
                idx = np.asarray(idxs)
                child_state = jax.tree.map(lambda x: x[idx], self.state)
                from ..utils.checkpoint import save_state
                meta = {"bucket": child.bucket_id,
                        "run_ids": list(child.run_ids),
                        "digests": [self.digests[i] for i in idxs],
                        "supersteps": [self.supersteps[i]
                                       for i in idxs],
                        "trail": [list(self.trails[i])
                                  for i in idxs]
                        if self.trails is not None
                        else [[] for _ in idxs],
                        "chunks": self.chunks}
                if self.vdigests is not None:
                    # world slices are exact (batch exactness law), so
                    # the per-world verified-epoch chain slices with
                    # them — the child checkpoint stays a verified
                    # epoch
                    meta["state_digests"] = [int(self.vdigests[i])
                                             for i in idxs]
                    meta["verify_chain"] = [self.vchain[i]
                                            for i in idxs]
                save_state(
                    self.journal.checkpoint_path(child.bucket_id),
                    child_state, meta=meta)
            runners.append(r)
        return runners
