"""Shape-bucketing: pack heterogeneous configs into batched executables.

A bucket is the largest set of pack configs one batched engine can
serve (engine.py ``batch=BatchSpec``): same scenario family and
builder params (one ``Scenario``, one compiled superstep), same link
*structure* (:func:`~timewarp_tpu.sweep.spec.link_signature`), and the
same solo-resolved window. The key is pure **shape** (plus the
per-bucket decision-source modes, ``_bucket_key``): everything that
picks *which executable* compiles. Per-world **identity** — seed
words, sweepable link values, fault tables — rides that executable
as traced operands (``WorldIdentity``, interp/jax_engine/batched.py)
and never splits a bucket; swapping identity re-invokes the SAME
compiled function with new device arrays
(``JaxEngine.rebind_identity``, the serving layer's zero-recompile
admission, serve/worker.py). Inside a bucket, worlds differ by:

- **seed** — ``BatchSpec.seeds``;
- **sweepable link values** — delay bounds / medians / sigmas /
  quanta as ``BatchSpec.link_params`` dotted-path vectors;
- **fault schedule** — a :class:`~timewarp_tpu.faults.schedule.
  FaultFleet` (schedules of different lengths pad with inert rows;
  worlds without faults run an empty schedule — result-identical to
  no schedule at all, which is what keeps the sweep survival law's
  solo twin honest);
- **step budget** — a per-world budget vector through the pow2-padded
  ``_scan_pad`` drivers (common.py ``padded_scan``), so every budget
  in a pow2 bucket shares one executable.

Under ``pack_mode="first-fit"`` (the default) the plan is a *pure
function of the pack* (dict-insertion order over the pack's config
order, chunked at ``max_bucket``), so a resumed sweep re-derives
bucket membership exactly from the journaled pack — no plan state
needs journaling beyond splits. Under ``pack_mode="predicted"``
(timewarp_tpu/pack/, docs/sweeps.md "Predictive packing") each shape
group is reordered best-fit-decreasing by forecast supersteps before
chunking — the plan is then a pure function of ``(pack, artifact)``,
and the service journals one ``pack_decision`` record per bucket
BEFORE any bucket starts, so resume replays the identical plan
without needing the artifact at all.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from .spec import (RunConfig, build_scenario, link_signature,
                   link_sweep_params, resolve_window)

__all__ = ["Bucket", "plan_buckets", "build_bucket_engine",
           "tile_world_state"]


@dataclass(frozen=True)
class Bucket:
    # NOTE: `controller` property below reports whether this bucket's
    # worlds run under online adaptive dispatch (all members agree —
    # it is part of the bucket key).
    """One schedulable unit: an ordered world list sharing a batched
    executable. ``bucket_id`` is stable across resume (derived from
    the deterministic plan; split children append ``.0``/``.1``).

    ``fault_pad`` pins the fault-table row counts (crash, partition,
    link-window) the bucket's FaultFleet must pad to. Split children
    of a bucket that already ran carry the parent's realized pad so
    the sliced ``restart_done`` state keeps its column count — pad
    rows are inert, so results are identical at any pad
    (faults/schedule.py FaultTables docstring)."""
    bucket_id: str
    configs: Tuple[RunConfig, ...]
    window: int
    fault_pad: Optional[Tuple[int, int, int]] = None

    @property
    def B(self) -> int:
        return len(self.configs)

    @property
    def run_ids(self) -> Tuple[str, ...]:
        return tuple(c.run_id for c in self.configs)

    @property
    def budgets(self) -> np.ndarray:
        return np.asarray([c.budget for c in self.configs], np.int64)

    @property
    def controller(self) -> bool:
        return self.configs[0].controller == "auto"

    @property
    def speculate(self) -> str:
        """The bucket's optimistic-execution mode (all members agree
        — part of the bucket key): the whole fleet speculates one
        window sequence, and ANY world's violation rolls the chunk
        back for every world (speculate/, docs/speculation.md)."""
        return self.configs[0].speculate

    def split(self) -> Tuple["Bucket", "Bucket"]:
        """Halve the bucket (the OOM degradation path, service.py):
        two children over the same window, ids suffixed so resume can
        replay the split from the journal. A solo bucket cannot
        split — the caller turns that OOM into a terminal failure."""
        if self.B < 2:
            raise ValueError(
                f"bucket {self.bucket_id!r} holds one world; OOM on a "
                "solo run cannot be split away")
        mid = self.B // 2
        return (Bucket(f"{self.bucket_id}.0", self.configs[:mid],
                       self.window, self.fault_pad),
                Bucket(f"{self.bucket_id}.1", self.configs[mid:],
                       self.window, self.fault_pad))


def _bucket_key(cfg: RunConfig):
    # the bucket key is the executable's SHAPE — scenario family +
    # params, link structure, resolved window — plus the per-bucket
    # decision-source modes. Seed / link values / fault schedules are
    # per-world IDENTITY: traced operands of the shared executable
    # (module docstring), deliberately absent from the key.
    # controller is part of the key: the dispatch controller makes
    # ONE decision sequence per bucket (journaled; replayed by every
    # member's solo twin), so controller-on and controller-off worlds
    # can never share an executable's chunking. speculate likewise:
    # the speculation policy is a per-bucket decision source with
    # per-bucket rollbacks (speculate/); the serving frontend's
    # bucket_key_sha mirrors this key (minus controller, refused at
    # admission there).
    return (cfg.family, cfg.params, link_signature(cfg.parse_link()),
            resolve_window(cfg), cfg.controller, cfg.speculate)


def plan_buckets(configs, max_bucket: int = 64, *,
                 pack_mode: str = "first-fit",
                 predict=None) -> List[Bucket]:
    """Deterministic shape-bucketing of a pack (module docstring).
    ``max_bucket`` caps worlds per bucket. ``pack_mode="first-fit"``
    chunks oversize groups in pack order (byte-identical to the
    historical planner); ``"predicted"`` reorders each group
    best-fit-decreasing by ``predict(cfg)`` forecast supersteps
    (``pack/allocate.predicted_order`` — budget fallback when no
    predictor is given), equalizing per-bucket quiescence horizons."""
    from ..pack.allocate import predicted_order, validate_pack_mode
    validate_pack_mode(pack_mode, "plan_buckets pack_mode")
    if max_bucket < 1:
        raise ValueError(f"max_bucket must be >= 1, got {max_bucket}")
    groups: Dict[tuple, List[RunConfig]] = {}
    for cfg in configs:
        groups.setdefault(_bucket_key(cfg), []).append(cfg)
    buckets: List[Bucket] = []
    for key, cfgs in groups.items():
        if pack_mode == "predicted":
            cfgs = predicted_order(
                cfgs, predict if predict is not None
                else (lambda c: c.budget))
        for i in range(0, len(cfgs), max_bucket):
            part = tuple(cfgs[i:i + max_bucket])
            buckets.append(Bucket(f"b{len(buckets)}", part, key[3]))
    return buckets


def build_bucket_engine(bucket: Bucket, *, lint: str = "warn",
                        telemetry: str = "off", controller=None,
                        verify: str = "off", record: str = "off",
                        record_cap=None):
    """One batched :class:`~timewarp_tpu.interp.jax_engine.engine.
    JaxEngine` serving every world of the bucket. World b's seed,
    sweepable link values, and (padded) fault schedule are exactly
    the solo run's — the batch exactness law then carries the sweep
    survival law (telemetry included: the counter planes feed nothing
    back, so the streamed results are mode-independent, obs/)."""
    from ..faults.schedule import FaultFleet, FaultSchedule
    from ..interp.jax_engine.batched import BatchSpec
    from ..interp.jax_engine.engine import JaxEngine

    cfgs = bucket.configs
    sc = build_scenario(cfgs[0].family, cfgs[0].params)
    links = [c.parse_link() for c in cfgs]
    rows = [link_sweep_params(lk) for lk in links]
    link_params = {path: np.asarray([r[path] for r in rows])
                   for path in rows[0]} if rows[0] else None
    spec = BatchSpec(seeds=tuple(c.seed for c in cfgs),
                     link_params=link_params)
    scheds = [c.parse_faults() or FaultSchedule(()) for c in cfgs]
    pad = bucket.fault_pad
    if pad is not None and tuple(pad) != (0, 0, 0):
        # grow world 0's tables to (at least) the pinned shape; the
        # fleet pads every other world up to the max, so the whole
        # fleet lands on the parent's realized row counts
        s0 = scheds[0]
        scheds[0] = s0.padded(
            max(pad[0], len(s0.crashes) + s0.pad[0]),
            max(pad[1], len(s0.partitions) + s0.pad[1]),
            max(pad[2], len(s0.link_windows) + s0.pad[2]))
    empty = all(not s.events for s in scheds)
    fleet = None if empty and (pad is None or tuple(pad) == (0, 0, 0)) \
        else FaultFleet(tuple(scheds))
    if bucket.controller and telemetry == "off":
        # an auto controller reads last_run_telemetry between chunks
        # — a controller bucket without the sensor layer cannot
        # decide; force the cheap counters mode (bit-exact by the
        # telemetry law, so streamed results are unchanged)
        telemetry = "counters"
    # verify is bit-exact like telemetry (the guard plane feeds
    # nothing back), so streamed results stay mode-independent and
    # the sweep survival law's solo twin needs no knob of its own
    # record is bit-exact like telemetry/verify (the event plane
    # feeds nothing back), so streamed results stay mode-independent
    eng = JaxEngine(sc, links[0], window=bucket.window, batch=spec,
                    faults=fleet, lint=lint, telemetry=telemetry,
                    controller=controller, verify=verify,
                    record=record, record_cap=record_cap,
                    speculate=bucket.speculate)
    eng.metrics_label = f"bucket:{bucket.bucket_id}"
    return eng


def tile_world_state(engine, solo_state):
    """Fork-from-snapshot bucket admission (timewarp_tpu/search/fork,
    docs/search.md): broadcast ONE world's solo-shaped state slice
    (``utils.checkpoint.load_world_state``) across every world of
    ``engine``'s batch — the initial state of a counterfactual fork
    fleet, where K continuation worlds share a snapshot prefix and
    diverge only through their fault-schedule suffixes. Worlds are
    independent and the copies are bit-identical, so world b of the
    fork fleet ≡ a solo continuation of the snapshot under schedule b
    by the batch exactness law (padding rows inert, identical seeds
    ⇒ identical entropy streams)."""
    import jax
    if engine.batch is None:
        raise ValueError(
            "tile_world_state targets a batched engine (the fork "
            "fleet); a solo continuation just resumes load_state's "
            "result directly")
    B = engine.batch.B

    def tile(x):
        arr = np.asarray(jax.device_get(x))   # one host transfer
        return np.broadcast_to(arr, (B,) + arr.shape).copy()
    return jax.tree.map(tile, solo_state)
