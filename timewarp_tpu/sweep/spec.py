"""Sweep run configs: what one emulated world of a pack looks like.

A :class:`RunConfig` is one world of a heterogeneous sweep — scenario
family + builder params, a ``--link``-grammar link spec, a seed, a
window, a superstep budget, and an optional ``--faults``-grammar fault
schedule. Configs are plain JSON (the pack file the CLI takes), so a
pack can be generated, diffed, and journaled; every config has a
stable ``run_id`` that the journal keys results by.

The module also owns the *identity* questions the bucketer
(:mod:`timewarp_tpu.sweep.bucket`) asks:

- :func:`link_signature` — the structural identity of a link model
  (nested types plus every non-sweepable field). Two configs whose
  links share a signature can run in one batched executable, with the
  **sweepable** numeric fields (delay bounds, medians, sigmas, quanta
  — the fields ``LinkModel.sample`` uses arithmetically, batched.py)
  carried as per-world ``BatchSpec.link_params`` vectors.
- :func:`resolve_window` — the window a *solo* run of the config
  would resolve ("auto" derives from the link's declared minimum
  delay, degraded by the config's own fault schedule) — part of the
  bucket key, so every world of a bucket runs the exact window its
  solo twin would.

And the law's right-hand side: :func:`solo_engine` /
:func:`solo_result` build and run the config standalone, producing
the same result record (chained trace digest + never-silent counters)
the sweep journal streams — the **sweep survival law** says the two
are equal byte-for-byte, regardless of bucketing, retries, splits, or
resume boundaries (docs/sweeps.md).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import struct
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "RunConfig", "SweepPack", "SweepConfigError",
    "build_scenario", "link_signature", "link_sweep_params",
    "resolve_window", "solo_engine", "solo_result",
    "chain_digest", "DIGEST_ZERO", "world_result",
]

#: scenario families a pack may name, and the params their builders
#: accept (a loud whitelist: a typo'd param must not silently build a
#: different scenario than the solo twin)
FAMILIES = {
    "token-ring": ("nodes", "n_tokens", "think_us", "bootstrap_us",
                   "end_us", "with_observer", "mailbox_cap"),
    "gossip": ("nodes", "fanout", "think_us", "gossip_interval",
               "end_us", "steady", "burst", "mailbox_cap"),
    "praos": ("nodes", "n_slots", "leader_prob", "fanout", "burst",
              "mailbox_cap"),
    "ping-pong": ("rounds",),
}


class SweepConfigError(ValueError):
    """A pack config is malformed — raised naming the ``run_id``."""


#: the pack-entry grammar, quoted by every malformed-field refusal —
#: the LINK_GRAMMAR/FAULT_GRAMMAR discipline (net/links.py,
#: faults/schedule.py): a typo dies naming the field, never a raw
#: KeyError/TypeError from deeper in the machinery
PACK_GRAMMAR = (
    'a pack entry is {"scenario": FAMILY, "id": str?, '
    '"params": {name: value}?, "link": LINK_SPEC?, "seed": int?, '
    '"window": int_us|"auto"?, "budget": int?, "faults": FAULT_SPEC?, '
    '"controller": "off"|"auto"?, '
    '"speculate": "off"|"auto"|"fixed:W"?} (docs/sweeps.md)')


@dataclass(frozen=True)
class RunConfig:
    """One world of a sweep pack (module docstring). ``params`` is
    held as a sorted item tuple so configs hash (bucket keys, dedup)."""
    run_id: str
    family: str
    params: Tuple[Tuple[str, Any], ...] = ()
    link: str = "uniform:1000:5000"
    seed: int = 0
    window: Any = 1            # int µs or "auto"
    budget: int = 1000
    faults: Optional[str] = None
    #: online adaptive dispatch (dispatch/, docs/dispatch.md):
    #: "auto" runs the world's bucket under a telemetry-driven
    #: controller whose per-chunk decisions are journaled, and the
    #: survival law's solo twin REPLAYS those decisions (the replay
    #: law carries the survival law)
    controller: str = "off"
    #: optimistic time-warp execution (speculate/,
    #: docs/speculation.md): "auto" | "fixed:W" runs the world's
    #: bucket with a speculative window wider than the provable link
    #: floor, rolling back on causality violations; the committed
    #: per-chunk window choices are journaled as dispatch_decision
    #: events and the survival law's solo twin replays them — exactly
    #: the controller's journaled-decision contract
    speculate: str = "off"

    def __post_init__(self):
        if self.family not in FAMILIES:
            raise SweepConfigError(
                f"config {self.run_id!r}: unknown scenario family "
                f"{self.family!r}; choose from {sorted(FAMILIES)}")
        allowed = FAMILIES[self.family]
        params = tuple(sorted(dict(self.params).items()))
        for k, _ in params:
            if k not in allowed:
                raise SweepConfigError(
                    f"config {self.run_id!r}: {self.family} takes no "
                    f"param {k!r}; allowed: {sorted(allowed)}")
        object.__setattr__(self, "params", params)
        if not isinstance(self.budget, int) or self.budget < 1:
            raise SweepConfigError(
                f"config {self.run_id!r}: budget must be an int >= 1, "
                f"got {self.budget!r}")
        if not isinstance(self.seed, int):
            raise SweepConfigError(
                f"config {self.run_id!r}: seed must be an int, "
                f"got {self.seed!r}")
        if self.window != "auto" and (
                isinstance(self.window, bool)
                or not isinstance(self.window, int)
                or self.window < 1):
            raise SweepConfigError(
                f"config {self.run_id!r}: window must be an int µs "
                f">= 1 or 'auto', got {self.window!r}")
        if self.controller not in ("off", "auto"):
            raise SweepConfigError(
                f"config {self.run_id!r}: controller must be 'off' or "
                f"'auto', got {self.controller!r} (replay is the "
                "verify path's business, not a pack knob)")
        if self.speculate != "off":
            from ..speculate import parse_speculate
            try:
                parse_speculate(self.speculate)
            except ValueError as e:
                raise SweepConfigError(
                    f"config {self.run_id!r}: {e}") from None
            if self.controller == "auto":
                raise SweepConfigError(
                    f"config {self.run_id!r}: speculate and "
                    "controller are both per-chunk window decision "
                    "sources — a bucket runs under exactly one "
                    "(docs/speculation.md)")

    # -- JSON (the pack file / journal form) ------------------------------

    @classmethod
    def from_json(cls, d: Dict[str, Any], index: int) -> "RunConfig":
        if not isinstance(d, dict):
            raise SweepConfigError(
                f"pack entry {index} must be a JSON object, got {d!r}")
        known = {"id", "scenario", "params", "link", "seed", "window",
                 "budget", "faults", "controller", "speculate"}
        extra = set(d) - known
        if extra:
            raise SweepConfigError(
                f"pack entry {index}: unknown keys {sorted(extra)}; "
                f"allowed: {sorted(known)} — {PACK_GRAMMAR}")
        if "scenario" not in d:
            raise SweepConfigError(
                f"pack entry {index}: missing \"scenario\" — every "
                f"entry names its family; {PACK_GRAMMAR}")

        def intf(key, default):
            # validate, don't coerce: int("abc") would be a raw
            # traceback and int(50.9) a silent truncation — both
            # violate the loud-config contract
            v = d.get(key, default)
            if isinstance(v, bool) or not isinstance(v, int):
                raise SweepConfigError(
                    f"pack entry {index}: {key} must be an integer, "
                    f"got {v!r} — {PACK_GRAMMAR}")
            return v

        def strf(key, default):
            v = d.get(key, default)
            if v is not default and not isinstance(v, str):
                raise SweepConfigError(
                    f"pack entry {index}: {key} must be a string "
                    f"spec, got {v!r} — {PACK_GRAMMAR}")
            return v
        params = d.get("params") or {}
        if not isinstance(params, dict):
            raise SweepConfigError(
                f"pack entry {index}: params must be a JSON object "
                f"of builder params, got {params!r} — {PACK_GRAMMAR}")
        window = d.get("window", 1)
        if isinstance(window, bool):
            # bool ⊂ int would silently read true as window=1 µs
            raise SweepConfigError(
                f"pack entry {index}: window must be an int µs or "
                f"'auto', got {window!r} — {PACK_GRAMMAR}")
        return cls(
            run_id=str(d.get("id", f"w{index}")),
            family=strf("scenario", ""),
            params=tuple(sorted(params.items())),
            link=strf("link", "uniform:1000:5000"),
            seed=intf("seed", 0),
            window=window,
            budget=intf("budget", 1000),
            faults=strf("faults", None),
            controller=strf("controller", "off"),
            speculate=strf("speculate", "off"),
        )

    def to_json(self) -> Dict[str, Any]:
        out = {"id": self.run_id, "scenario": self.family,
               "params": dict(self.params), "link": self.link,
               "seed": self.seed, "window": self.window,
               "budget": self.budget}
        if self.faults is not None:
            out["faults"] = self.faults
        if self.controller != "off":
            out["controller"] = self.controller
        if self.speculate != "off":
            out["speculate"] = self.speculate
        return out

    # -- parsed views ------------------------------------------------------

    def parse_link(self):
        """The config's link model; a malformed spec raises
        :class:`SweepConfigError` naming the run_id (the CLI grammar
        error is a SystemExit — wrong species for a library path).
        One grammar serves the CLI and the pack loader (net/links.py),
        so a pack world and its ``--link`` solo twin cannot drift."""
        from ..net.links import parse_link
        try:
            return parse_link(self.link)
        except SystemExit as e:
            raise SweepConfigError(
                f"config {self.run_id!r}: {e}") from None

    def parse_faults(self):
        """The config's fault schedule (or None)."""
        if self.faults is None:
            return None
        from ..faults.schedule import parse_faults
        try:
            return parse_faults(self.faults)
        except SystemExit as e:
            raise SweepConfigError(
                f"config {self.run_id!r}: {e}") from None


@dataclass(frozen=True)
class SweepPack:
    """An ordered pack of configs with unique run_ids. Order is part
    of the pack's identity: the bucket plan is derived from it, and
    resume re-derives the same plan from the journaled pack."""
    configs: Tuple[RunConfig, ...]

    def __post_init__(self):
        seen = set()
        for c in self.configs:
            if c.run_id in seen:
                raise SweepConfigError(
                    f"duplicate run_id {c.run_id!r} in pack — results "
                    "are journaled per run_id, so ids must be unique")
            seen.add(c.run_id)
        if not self.configs:
            raise SweepConfigError("a sweep pack needs at least one "
                                   "config")

    @classmethod
    def from_json(cls, data: Any,
                  speculate_default: Optional[str] = None
                  ) -> "SweepPack":
        default_ctrl = None
        default_spec = speculate_default
        if isinstance(data, dict):
            # pack-level controller/speculate defaults:
            # {"controller": "auto", "worlds": [...]} turns the knob
            # on for every config that does not say otherwise
            # (explicit per-config wins)
            default_ctrl = data.get("controller")
            # the operator's explicit flag beats the pack-file-level
            # default (CLI-beats-file, the usual convention); explicit
            # PER-CONFIG values beat both, below
            if default_spec is None:
                default_spec = data.get("speculate")
            data = data.get("worlds", data)
        if not isinstance(data, list):
            raise SweepConfigError(
                "a pack file is a JSON list of config objects (or "
                "{'worlds': [...]})")
        if default_ctrl is not None:
            data = [({**d, "controller": default_ctrl}
                     if isinstance(d, dict) and "controller" not in d
                     else d) for d in data]
        if default_spec is not None:
            data = [({**d, "speculate": default_spec}
                     if isinstance(d, dict) and "speculate" not in d
                     else d) for d in data]
        return cls(tuple(RunConfig.from_json(d, i)
                         for i, d in enumerate(data)))

    @classmethod
    def load(cls, path: str,
             speculate_default: Optional[str] = None) -> "SweepPack":
        """Load a pack file. ``speculate_default`` (the CLI's
        ``sweep run --speculate``) applies at the JSON layer — only
        to entries with NO ``"speculate"`` key, so a config that
        explicitly says ``"off"`` keeps its opt-out (an explicit off
        is indistinguishable from the dataclass default after
        parsing, which is why this cannot live post-parse)."""
        with open(path) as f:
            text = f.read()
        try:
            data = json.loads(text)
        except json.JSONDecodeError:
            # JSONL form: one config object per line
            try:
                data = [json.loads(line) for line in text.splitlines()
                        if line.strip()]
            except json.JSONDecodeError as e:
                raise SweepConfigError(
                    f"pack file {path!r} is neither a JSON list nor "
                    f"JSONL ({e})") from None
        return cls.from_json(data, speculate_default=speculate_default)

    def to_json(self) -> List[Dict[str, Any]]:
        return [c.to_json() for c in self.configs]

    def sha(self) -> str:
        """Content identity — resume refuses a journal written for a
        different pack."""
        blob = json.dumps(self.to_json(), sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()

    def by_id(self, run_id: str) -> RunConfig:
        for c in self.configs:
            if c.run_id == run_id:
                return c
        raise KeyError(run_id)


# -- scenario construction -------------------------------------------------

def build_scenario(family: str, params):
    """Build the family's scenario from a config's param dict — the
    same builders the run CLI uses, so a pack world and a CLI solo run
    agree on what a family name means."""
    kw = dict(params)
    try:
        if family == "token-ring":
            from ..models.token_ring import token_ring
            return token_ring(kw.pop("nodes"), **kw)
        if family == "gossip":
            from ..models.gossip import gossip
            return gossip(kw.pop("nodes"), **kw)
        if family == "praos":
            from ..models.praos import praos
            return praos(kw.pop("nodes"), **kw)
        if family == "ping-pong":
            from ..models.ping_pong import ping_pong
            return ping_pong(**kw)
    except KeyError as e:
        raise SweepConfigError(
            f"{family} config is missing required param {e}") from None
    raise SweepConfigError(f"unknown scenario family {family!r}")


# -- link identity ---------------------------------------------------------

#: per-link-class fields BatchSpec.link_params may sweep per world:
#: the values ``sample`` uses *arithmetically* (batched.py module
#: docstring). Everything else — WithDrop.drop_prob (trace-time
#: threshold), SeededHashUniform.salt (host-expanded) — is structural
#: and lands in the signature verbatim.
_SWEEPABLE = {
    "FixedDelay": ("delay",),
    "UniformDelay": ("lo", "hi"),
    "LogNormalDelay": ("median_us", "sigma", "cap_us", "floor_us"),
    "ParetoDelay": ("xm_us", "alpha", "cap_us", "floor_us"),
    "Quantize": ("quantum_us",),
}


def link_signature(link) -> tuple:
    """Structural identity of a link model: the nested dataclass types
    plus every non-sweepable field value, with sweepable fields as
    holes. Configs whose links share a signature can share one batched
    executable (the sweepable values ride in per-world vectors)."""
    from ..net.delays import LinkModel
    name = type(link).__name__
    sweep = _SWEEPABLE.get(name, ())
    sig: list = [name]
    for f in dataclasses.fields(link):
        v = getattr(link, f.name)
        if isinstance(v, LinkModel):
            sig.append((f.name, link_signature(v)))
        elif f.name in sweep:
            sig.append((f.name, None))
        else:
            sig.append((f.name, v))
    return tuple(sig)


def link_sweep_params(link, prefix: str = "") -> Dict[str, Any]:
    """The dotted-path -> value map of a link's sweepable fields —
    one world's row of the bucket's ``BatchSpec.link_params``."""
    from ..net.delays import LinkModel
    out: Dict[str, Any] = {}
    sweep = _SWEEPABLE.get(type(link).__name__, ())
    for f in dataclasses.fields(link):
        v = getattr(link, f.name)
        if isinstance(v, LinkModel):
            out.update(link_sweep_params(v, prefix + f.name + "."))
        elif f.name in sweep:
            out[prefix + f.name] = v
    return out


def resolve_window(cfg: RunConfig) -> int:
    """The window a solo run of ``cfg`` resolves (JaxEngine.__init__
    order: the link floor, degraded by the config's own fault
    schedule, then "auto" -> max(1, floor), int32-clamped). Buckets
    key on this so the batched engine runs exactly the window every
    member's solo twin would. Controller configs resolve the dynamic
    window's BOUND instead — the UNDEGRADED floor, exactly as the
    engine does (degradation clamps on-device per superstep,
    docs/dispatch.md). Speculate configs resolve their CONSERVATIVE
    floor the same undegraded way (the speculative bound is derived
    by the engine from the speculate spec; degradation clamps
    on-device — docs/speculation.md)."""
    from ..interp.jax_engine.common import I32MAX
    link = cfg.parse_link()
    floor = link.min_delay_us
    sched = cfg.parse_faults()
    if sched is not None and cfg.controller == "off" \
            and cfg.speculate == "off":
        floor = sched.min_delay_floor(floor)
    if cfg.window == "auto":
        return max(1, min(int(floor), I32MAX - 1))
    return int(cfg.window)


# -- the solo (law right-hand-side) run ------------------------------------

def solo_engine(cfg: RunConfig, *, lint: str = "warn",
                decisions=None):
    """The standalone engine for one config — what the sweep's
    streamed result must be bit-identical to. Controller configs take
    the bucket's journaled ``decisions`` (dispatch_decision records)
    and get a REPLAY controller: the replay law (dispatch/) then
    carries the survival law — the solo twin re-applies exactly the
    chunking/window/rung sequence the bucket decided."""
    from ..interp.jax_engine.engine import JaxEngine
    sc = build_scenario(cfg.family, cfg.params)
    controller = None
    if cfg.controller == "auto":
        if decisions is None:
            raise SweepConfigError(
                f"config {cfg.run_id!r} runs under a dispatch "
                "controller; its solo twin needs the journaled "
                "decision records (sweep journal dispatch_decision "
                "events) — an auto solo run would decide its own "
                "chunking and legitimately diverge")
        from ..dispatch import DispatchController
        controller = DispatchController(mode="replay",
                                        replay=decisions)
    if cfg.speculate != "off" and decisions is None:
        # a fresh speculative solo run would roll back on its OWN
        # violations, not the bucket fleet's (any world's violation
        # rolls the whole bucket chunk back), so its committed window
        # sequence — and therefore its superstep granularity — would
        # legitimately diverge from the streamed result
        raise SweepConfigError(
            f"config {cfg.run_id!r} runs under optimistic "
            "speculation; its solo twin needs the bucket's journaled "
            "decision records (sweep journal dispatch_decision "
            "events) to replay the committed window sequence "
            "(docs/speculation.md)")
    return JaxEngine(sc, cfg.parse_link(), seed=cfg.seed,
                     window=resolve_window(cfg),
                     faults=cfg.parse_faults(), lint=lint,
                     controller=controller, speculate=cfg.speculate)


#: the digest chain seed (hex of 32 zero bytes)
DIGEST_ZERO = "0" * 64

#: one trace row packed little-endian: t(int64), fired(int32),
#: fired_hash(uint32), recv, recv_hash, sent, sent_hash, overflow
_ROW = struct.Struct("<qiIiIiIi")


def chain_digest(h: str, trace) -> str:
    """Fold a :class:`SuperstepTrace`'s rows into a running sha256
    chain (hex in, hex out). Chaining — rather than one digest over a
    materialized trace — is what lets the sweep journal a world's
    digest incrementally across chunks, checkpoints, retries, and
    resume boundaries, and still land on the same value a single solo
    run computes."""
    cur = bytes.fromhex(h)
    for i in range(len(trace)):
        cur = hashlib.sha256(cur + _ROW.pack(*trace.row(i))).digest()
    return cur.hex()


#: never-silent counters every result record carries (per world)
_COUNTERS = ("overflow", "bad_dst", "bad_delay", "short_delay",
             "route_drop", "fault_dropped", "delivered")


def world_result(cfg: RunConfig, state, b: Optional[int],
                 digest: str, supersteps: int) -> Dict[str, Any]:
    """The result record streamed to the journal for one world:
    chained trace digest, superstep/virtual-time totals, and every
    never-silent counter. ``b`` indexes a batched state's world axis
    (None for a solo state)."""
    import jax
    import numpy as np

    def leaf(name):
        v = np.asarray(jax.device_get(getattr(state, name)))
        return int(v if b is None else v[b])

    out = {"run_id": cfg.run_id, "supersteps": int(supersteps),
           "trace_digest": digest,
           "steps": leaf("steps"),
           "virtual_time_us": leaf("time")}
    for c in _COUNTERS:
        out[c] = leaf(c)
    return out


def solo_result(cfg: RunConfig, *, lint: str = "warn",
                decisions=None, with_trace: bool = False):
    """Run ``cfg`` standalone and produce the exact record the sweep
    journal would stream for it — the right-hand side of the sweep
    survival law (tests/test_zsweep.py; the bench and CI smoke gates).
    Controller configs replay the bucket's journaled ``decisions``
    (see :func:`solo_engine`). ``with_trace=True`` returns
    ``(result, trace)`` so a ``--verify`` mismatch can auto-bisect
    against the rows this run already computed instead of re-running
    the whole solo twin."""
    eng = solo_engine(cfg, lint=lint, decisions=decisions)
    if cfg.controller == "auto":
        final, trace = eng.run_controlled(cfg.budget)
    elif cfg.speculate != "off":
        # replay the bucket's committed window sequence — committed
        # chunks are violation-free by construction, so the replay
        # never rolls back and is bit-identical to the streamed run
        # (the speculation replay law, docs/speculation.md)
        final, trace = eng.run_speculative(cfg.budget,
                                           replay=decisions)
    else:
        final, trace = eng.run(cfg.budget)
    res = world_result(cfg, final, None,
                       chain_digest(DIGEST_ZERO, trace), len(trace))
    return (res, trace) if with_trace else res
