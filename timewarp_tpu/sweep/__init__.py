"""Fault-tolerant sweep service for heterogeneous world packs.

The production face of the emulator (ROADMAP "emulation-as-a-service";
Revati's frame in PAPERS.md — the time-warp emulator as the
high-traffic system): accept a pack of heterogeneous run configs
(differing n_nodes, budgets, link sweeps, fault schedules, scenario
families), shape-bucket them into batched executables (bucket.py,
reusing the pow2-padded compile cache and BatchSpec/FaultFleet
machinery), and execute under a JobCurator supervision loop
(service.py) with watchdog timeouts, bounded retry + backoff,
OOM-degradation bucket splitting, and a crash-safe journal
(journal.py) that streams per-world results as worlds quiesce and
resumes a killed sweep exactly.

The contract that makes it trustworthy — the **sweep survival law**:
every world's streamed result record is bit-identical to the solo run
of that config, regardless of bucketing, per-world budgets, retries,
splits, or resume boundaries (docs/sweeps.md; tests/test_zsweep.py).
"""

from .bucket import Bucket, build_bucket_engine, plan_buckets
from .journal import SweepJournal, SweepJournalError
from .runner import BucketRunner
from .service import (InjectPlan, SimulatedOOM, SimulatedTransient,
                      SweepKilled, SweepReport, SweepService)
from .spec import (RunConfig, SweepConfigError, SweepPack, chain_digest,
                   solo_engine, solo_result)

__all__ = [
    "RunConfig", "SweepPack", "SweepConfigError",
    "Bucket", "plan_buckets", "build_bucket_engine",
    "SweepJournal", "SweepJournalError", "BucketRunner",
    "SweepService", "SweepReport", "SweepKilled",
    "SimulatedTransient", "SimulatedOOM", "InjectPlan",
    "chain_digest", "solo_engine", "solo_result",
]
