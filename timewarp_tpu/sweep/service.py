"""The fault-tolerant sweep service: supervision, retry, split, resume.

The scheduler the ROADMAP's "emulation-as-a-service" item asks for:
accept a heterogeneous pack, shape-bucket it (bucket.py), and execute
buckets under a supervision loop built on the manage/ layer's
:class:`~timewarp_tpu.manage.jobs.JobCurator` running on the real
asyncio interpreter (interp/aio/timed.py) — each bucket attempt is a
curator thread job whose blocking chunk calls are offloaded through
``AwaitIO`` to an executor thread, so the supervisor (and its
watchdogs) stay live while XLA runs.

Failure policy, per bucket attempt:

- **watchdog timeout** (``bucket_timeout_us``): a per-attempt
  watchdog interrupts the attempt's child curator with
  ``WithTimeout(grace_us)`` — Plain-kill now, Force-clear any
  straggler at the grace deadline — and the attempt counts as a
  transient failure. The abandoned executor thread's attempt *epoch*
  is invalidated (runner.py), so it can never again commit state,
  journal a world, or overwrite a checkpoint — even if it races the
  retry. (A chunk wedged in a native call that never returns cannot
  be killed from Python at all: the service itself still terminates
  — chunks run on a dedicated executor shut down without joining —
  but process exit then waits on the wedged thread. That residue is
  a CPython limit, not a supervision gap.)
- **transient errors** retry with exponential backoff
  (``backoff_us * 2^(attempt-1)``) from the bucket's last checkpoint,
  at most ``max_retries`` times; exhaustion is a **loud terminal
  failure** — every unfinished world journals ``world_failed``, lands
  in the report's ``failed`` map, and the CLI exits nonzero. Other
  buckets still complete.
- **device OOM** (RESOURCE_EXHAUSTED / out-of-memory, or the
  injected simulation) degrades gracefully: the bucket splits in half
  from its last checkpoint (exact — world slices, batch exactness
  law), down to solo buckets; a solo OOM is terminal for that world.
- :class:`SweepKilled` (the test/CI injection ``die:K``) aborts the
  whole process mid-sweep — the crash the journal's resume contract
  is tested against.

Everything observable streams to the journal as it happens
(journal.py), so ``SweepService.run`` on an existing journal dir IS
resume: completed worlds are never re-run, in-flight buckets restart
from their last checkpoint, and the per-world digest chains continue
to the same value an uninterrupted run produces (the sweep survival
law, docs/sweeps.md).
"""

from __future__ import annotations

import dataclasses
import logging
import time as _time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..core.effects import AwaitIO, Fork, Program, Wait
from ..core.errors import ThreadKilled
from ..manage.jobs import JobCurator, Plain, WithTimeout
from ..manage.sync import Flag
from .bucket import Bucket, plan_buckets
from .journal import SweepJournal, SweepJournalError
from .runner import BucketRunner
from .spec import SweepPack, resolve_window

__all__ = ["SweepService", "SweepReport", "SweepKilled",
           "SimulatedTransient", "SimulatedOOM", "InjectPlan"]

_log = logging.getLogger("timewarp.sweep")


class SimulatedTransient(RuntimeError):
    """Injected transient failure (retried like a real one)."""


class SimulatedOOM(RuntimeError):
    """Injected device OOM (split like a real one)."""


class SweepKilled(RuntimeError):
    """Injected hard kill: aborts the sweep process mid-bucket —
    what `sweep resume` is tested against. Never retried."""


def _is_oom(e: BaseException) -> bool:
    if isinstance(e, SimulatedOOM):
        return True
    s = f"{type(e).__name__}: {e}"
    return "RESOURCE_EXHAUSTED" in s or "out of memory" in s.lower()


class InjectPlan:
    """Deterministic chaos for the service itself (the emulator's
    chaos is faults/; this injects failures into the *sweep
    machinery*). Grammar: ``fail:K | oom:K | die:K | hang:K:MS |
    flip:SEED[:K[:PLANE]]``, ';'-joined — trigger at the K-th
    chunk-executor call (1-based, counted across the whole sweep),
    once each. ``flip:`` (integrity/inject.py, round 14) is the
    state-corruption form the detection law is tested against: a
    seeded bit-flip written into the bucket's in-memory state between
    chunks — what the ``verify`` knob must catch and roll back."""

    GRAMMAR = ("fail:K | oom:K | die:K | hang:K:MS | "
               "flip:SEED[:K[:PLANE]]  "
               "(';'-joined; K = 1-based chunk call, fires once; "
               "flip = seeded bit-flip into a state plane — "
               "docs/integrity.md)")

    def __init__(self, spec: str) -> None:
        self.fail, self.oom, self.die = set(), set(), set()
        self.hang: Dict[int, int] = {}
        self.flip: Dict[int, object] = {}
        self.calls = 0
        self.fired: List[str] = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            try:
                if bits[0] == "flip":
                    # full grammar (incl. INJECT_GRAMMAR naming on
                    # malformation) lives in integrity/inject.py
                    from ..integrity.inject import parse_flip
                    fs = parse_flip(part)
                    if fs.chunk in self.flip:
                        # two flips on one chunk call would silently
                        # overwrite each other — refuse like any
                        # other malformation
                        raise ValueError(
                            f"duplicate flip at chunk call "
                            f"{fs.chunk}")
                    self.flip[fs.chunk] = fs
                    continue
                kind, k = bits[0], int(bits[1])
                if kind == "fail" and len(bits) == 2:
                    self.fail.add(k)
                elif kind == "oom" and len(bits) == 2:
                    self.oom.add(k)
                elif kind == "die" and len(bits) == 2:
                    self.die.add(k)
                elif kind == "hang" and len(bits) == 3:
                    self.hang[k] = int(bits[2])
                else:
                    raise ValueError(part)
            except (IndexError, ValueError) as e:
                # a library-raised, catchable error (the CLI converts
                # it to a grammar-named exit; an embedding caller —
                # bench, notebook — must not have its process killed).
                # A flip malformation's own message (naming the
                # INJECT_GRAMMAR flip form) rides along verbatim.
                from .spec import SweepConfigError
                detail = f": {e}" if bits and bits[0] == "flip" else ""
                raise SweepConfigError(
                    f"malformed inject spec {part!r}; grammar: "
                    f"{self.GRAMMAR}{detail}") from None

    def __call__(self) -> None:
        self.calls += 1
        n = self.calls
        if n in self.hang:
            self.fired.append(f"hang:{n}")
            _time.sleep(self.hang[n] / 1000.0)
            raise SimulatedTransient(
                f"injected hang ({self.hang[n]} ms) at chunk call {n}")
        if n in self.fail:
            self.fired.append(f"fail:{n}")
            raise SimulatedTransient(f"injected transient failure at "
                                     f"chunk call {n}")
        if n in self.oom:
            self.fired.append(f"oom:{n}")
            raise SimulatedOOM(f"injected RESOURCE_EXHAUSTED at chunk "
                               f"call {n}")
        if n in self.die:
            self.fired.append(f"die:{n}")
            raise SweepKilled(f"injected sweep kill at chunk call {n}")

    def flip_hook(self, runner) -> None:
        """Corrupt the runner's in-memory state if a ``flip:`` spec
        is due at the current chunk call (the runner calls this right
        after ``__call__`` counted the call). Fires once — rollback
        re-runs the same chunk, and re-corrupting the recovered state
        would make recovery unfalsifiable."""
        n = self.calls
        fs = self.flip.get(n)
        tag = f"flip:{n}"
        if fs is None or tag in self.fired or runner.state is None:
            return
        from ..integrity.inject import apply_flip
        self.fired.append(tag)
        runner.state, desc = apply_flip(runner.state, fs.seed,
                                        fs.plane)
        _log.warning("sweep: injected state corruption at chunk call "
                     "%d — %s", n, desc)


@dataclass
class SweepReport:
    total: int
    done: Dict[str, dict]
    failed: Dict[str, dict]
    retries: int = 0
    splits: int = 0
    buckets: int = 0

    @property
    def ok(self) -> bool:
        return not self.failed and len(self.done) == self.total

    def to_json(self) -> dict:
        return {"worlds": self.total, "completed": len(self.done),
                "failed": sorted(self.failed), "retries": self.retries,
                "splits": self.splits, "buckets": self.buckets,
                "ok": self.ok}


@dataclass
class _Attempt:
    """Outcome box one bucket attempt fills in."""
    ok: bool = False
    error: Optional[BaseException] = None
    timed_out: bool = False
    box: dict = field(default_factory=dict)


class SweepService:
    def __init__(self, pack: SweepPack, journal_dir: str, *,
                 chunk: int = 64, max_retries: int = 2,
                 backoff_us: int = 50_000,
                 bucket_timeout_us: Optional[int] = None,
                 grace_us: int = 500_000, max_bucket: int = 64,
                 lint: str = "warn", inject=None,
                 telemetry: str = "off",
                 trace_out: Optional[str] = None,
                 verify: str = "off",
                 record: str = "off",
                 post_verify: bool = False,
                 host: Optional[str] = None,
                 lease_ttl_s: float = 30.0,
                 peer_poll_us: int = 500_000,
                 pack_mode: str = "first-fit",
                 pack_artifact: Optional[str] = None) -> None:
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        # online state-integrity checking per bucket (integrity/,
        # docs/integrity.md): "guard" threads the on-device invariant
        # plane through every bucket engine's scans; "digest" adds
        # the per-chunk rolling state digest — verified at every
        # chunk entry and chained through the checkpoints, so each
        # checkpoint marks a verified epoch. Detection journals an
        # `integrity_violation` event and ROLLS BACK just the
        # affected bucket: the existing retry machinery restores the
        # last verified checkpoint and replays the journaled
        # dispatch-decision chain — bit-identical recovery by the
        # replay law. "shadow" (sampled re-execution) is the solo
        # driver's mode (run_verified); refused here rather than
        # silently downgraded.
        from ..integrity.checks import validate_verify
        self.verify = validate_verify(verify, type(self).__name__)
        if self.verify == "shadow":
            raise ValueError(
                "the sweep service verifies buckets with "
                "verify='guard'|'digest'; shadow re-execution is the "
                "solo chunked driver's mode "
                "(engine.run_verified, docs/integrity.md)")
        self.pack = pack
        # multi-host mode (--hosts, docs/serving.md "Multi-host
        # sweeps"): N cooperating SweepService processes share one
        # journal dir — each appends to its own per-host file, claims
        # buckets through per-bucket leases, and STEALS a dead peer's
        # buckets after the lease TTL. host=None is the unchanged
        # single-host service, byte-identical to r10's.
        self.host = host
        self.journal = SweepJournal(journal_dir, host=host)
        self.leases = None
        if host is not None:
            from ..serve.lease import LeaseDir
            self.leases = LeaseDir(journal_dir, host,
                                   ttl_s=lease_ttl_s)
        self.peer_poll_us = int(peer_poll_us)
        self.chunk = chunk
        self.max_retries = max_retries
        self.backoff_us = int(backoff_us)
        self.bucket_timeout_us = bucket_timeout_us
        self.grace_us = int(grace_us)
        self.max_bucket = max_bucket
        # predictive packing (timewarp_tpu/pack/, docs/sweeps.md
        # "Predictive packing"): "predicted" reorders each shape
        # group best-fit-decreasing by forecast supersteps before
        # chunking, and journals one pack_decision per bucket BEFORE
        # any bucket starts — resume replays the journaled plan
        # bit-identically, artifact or not. "first-fit" is the
        # historical plan, a pure function of the pack (no journaling
        # needed). The artifact is the sha-stamped fitted predictor
        # (`timewarp-tpu pack fit`); without one, forecasts fall back
        # to each config's budget — honest, never fabricated.
        from ..pack.allocate import validate_pack_mode
        self.pack_mode = validate_pack_mode(pack_mode)
        self.pack_artifact = None
        if pack_artifact is not None:
            from ..pack.predict import load_artifact
            self.pack_artifact = load_artifact(pack_artifact)
        self.lint = lint
        # fleet-scale pre-flight verification (analysis/plan_lint.py,
        # docs/sweeps.md "Pre-flight verification"): the whole pack is
        # linted BEFORE any bucket engine is built — every refusal the
        # runtime would raise mid-bucket (TW6xx window/speculation
        # mirrors), the per-world scenario sanitizer, and the
        # fault-aware capacity proofs. "error" refuses the pack with
        # the findings (LintError), "warn" logs them, "off" skips;
        # per-engine construction lint below keeps the same knob.
        if lint != "off":
            from ..analysis import LINT_MODES, LintError, lint_pack
            if lint not in LINT_MODES:
                raise ValueError(
                    f"lint must be one of {LINT_MODES}, got {lint!r}")
            _rep = lint_pack(pack, max_bucket=max_bucket)
            if lint == "error" and not _rep.ok:
                raise LintError(_rep, who="sweep pack")
            for _f in _rep.errors:
                _log.warning("pack lint: %s", _f.render())
            for _f in _rep.warnings:
                _log.info("pack lint: %s", _f.render())
        self.inject = (InjectPlan(inject) if isinstance(inject, str)
                       else inject)
        if getattr(self.inject, "flip", None) \
                and self.verify != "digest" and not post_verify:
            # mirror of the solo CLI's guard: a flip without the
            # digest entry check would corrupt streamed results
            # SILENTLY (guard misses most planes by design) — the
            # detection-law test would test nothing. A promised
            # post-sweep --verify is the other legal arming: the
            # survival-law check catches the corrupted stream and
            # auto-bisects to the first diverging chunk
            # (obs/bisect.py, docs/observability.md)
            raise ValueError(
                "--inject flip: corrupts bucket state between "
                "chunks; it needs --state-verify digest (online "
                "detection + rollback) or --verify (post-sweep "
                "survival-law check, which auto-bisects the "
                "mismatch to its first diverging chunk) — "
                "anything less goes undetected into the journaled "
                "results (docs/integrity.md)")
        # observability (obs/, docs/observability.md): when telemetry
        # is on, the bucket engines thread counter planes through
        # their scans (bit-exact — the streamed results are
        # mode-independent), a MetricsRegistry streams
        # `<journal>/metrics.jsonl`, and a TraceBuilder records the
        # service's wall-clock spans (attempts, retries, backoffs,
        # checkpoints, journal fsyncs) for Perfetto
        import os as _os
        from ..obs.telemetry import validate_mode
        self.telemetry = validate_mode(telemetry, type(self).__name__)
        self.trace_out = trace_out
        self.trace_path = None
        self.metrics = None
        self.tracer = None
        if self.telemetry != "off":
            from ..obs.metrics import MetricsRegistry
            from ..obs.perfetto import TraceBuilder
            self.journal.ensure_dir()
            self.tracer = TraceBuilder(process="timewarp-tpu sweep")
            self.metrics = MetricsRegistry(
                path=_os.path.join(journal_dir, "metrics.jsonl"),
                run=f"sweep:{pack.sha()[:12]}", tracer=self.tracer)
            self.journal.on_append = (
                lambda ev, dt: self.tracer.complete(
                    f"journal fsync: {ev}", dur_us=dt * 1e6,
                    cat="journal"))
        # causal flight recorder per bucket (obs/flight.py,
        # docs/observability.md): bucket engines thread the event
        # plane (bit-exact — streamed results are mode-independent),
        # and every chunk's per-world events drain into
        # <journal>/events.jsonl tagged by run_id, queryable with
        # `timewarp-tpu explain EVENTS --run-id ID`
        from ..obs.flight import validate_record
        self.record = validate_record(record, type(self).__name__)
        self.flight = None
        if self.record != "off":
            from ..obs.flight import FlightWriter
            self.journal.ensure_dir()
            self.flight = FlightWriter(
                _os.path.join(journal_dir, "events.jsonl"),
                run=f"sweep:{pack.sha()[:12]}")
        self.done: Dict[str, dict] = {}
        self.failed: Dict[str, dict] = {}
        self._retries = 0
        self._splits = 0
        self._executor = None

    @classmethod
    def resume(cls, journal_dir: str, **kw) -> "SweepService":
        """Open an existing journal dir; the pack comes from the
        journaled copy."""
        j = SweepJournal(journal_dir)
        import os
        if not os.path.exists(j.pack_path):
            raise SweepJournalError(
                f"{journal_dir!r} holds no pack.json — nothing to "
                "resume (run `sweep run PACK --journal DIR` first)")
        return cls(SweepPack.load(j.pack_path), journal_dir, **kw)

    # -- planning ----------------------------------------------------------

    def _build_queue(self) -> deque:
        scan = self.journal.scan()
        if scan.pack_sha is not None and scan.pack_sha != self.pack.sha():
            raise SweepJournalError(
                f"journal {self.journal.path!r} was written for a "
                "different pack (sha mismatch) — one journal dir per "
                "pack; use a fresh --journal or the journaled pack")
        self.journal.write_pack(self.pack)
        if scan.pack_sha is None:
            self.journal.append({"ev": "pack", "sha": self.pack.sha(),
                                 "worlds": len(self.pack.configs)})
        self.done = dict(scan.done)
        self.failed = dict(scan.failed)
        self._retries = scan.retries

        def expand(bucket: Bucket) -> List[Bucket]:
            if bucket.bucket_id not in scan.splits:
                return [bucket]
            rec = next(e for e in scan.events
                       if e.get("ev") == "bucket_split"
                       and e["bucket"] == bucket.bucket_id)
            pad = rec.get("fault_pad")
            kids = bucket.split()
            if pad is not None:
                kids = tuple(dataclasses.replace(k, fault_pad=tuple(pad))
                             for k in kids)
            self._splits += 1
            return [g for k in kids for g in expand(k)]

        queue: deque = deque()
        settled = set(self.done) | set(self.failed)
        for base in self._base_plan(scan):
            for bucket in expand(base):
                if bucket.bucket_id in scan.bucket_done:
                    continue
                if all(r in settled for r in bucket.run_ids):
                    continue
                queue.append(BucketRunner(
                    bucket, self.journal, self.done, lint=self.lint,
                    chunk=self.chunk, inject=self.inject,
                    telemetry=self.telemetry, metrics=self.metrics,
                    verify=self.verify, record=self.record,
                    flight=self.flight,
                    # resume replays the journaled dispatch-decision
                    # chain (split-ancestor prefixes included) so a
                    # pre-kill decision is never re-made differently
                    prior_decisions=scan.decision_chain(
                        bucket.bucket_id)))
        self._planned = len(queue)
        return queue

    def _base_plan(self, scan) -> List[Bucket]:
        """The base bucket plan, BEFORE split expansion. Three-way:

        1. the journal already holds ``pack_decision`` plan records —
           replay them verbatim (membership and order), no artifact
           needed: the plan is journal state, so resume/steal rebuild
           the identical buckets even on a host without the predictor
           file;
        2. ``pack_mode="predicted"`` on a fresh journal — plan
           best-fit-decreasing by forecast supersteps
           (pack/allocate.py) and journal one ``pack_decision`` per
           bucket before ANY bucket starts;
        3. first-fit (the default) — the plan is a pure function of
           the pack (bucket.py docstring); nothing to journal.
        """
        if scan.pack_plan:
            by_id = {c.run_id: c for c in self.pack.configs}
            covered: set = set()
            planned: List[Bucket] = []
            for bid, d in scan.pack_plan.items():
                missing = [r for r in d["members"] if r not in by_id]
                if missing:
                    raise SweepJournalError(
                        f"journaled pack_decision for bucket {bid!r} "
                        f"names worlds absent from the pack "
                        f"({missing}) — the journal belongs to a "
                        "different pack")
                cfgs = tuple(by_id[r] for r in d["members"])
                planned.append(
                    Bucket(bid, cfgs, resolve_window(cfgs[0])))
                covered.update(d["members"])
            if covered != set(by_id):
                raise SweepJournalError(
                    "journaled pack_decision records cover "
                    f"{len(covered)} of {len(by_id)} pack worlds — "
                    "the plan journal is truncated; refusing to "
                    "invent placement for the rest")
            return planned
        if self.pack_mode == "predicted":
            if any(e.get("ev") == "bucket_start" for e in scan.events):
                raise SweepJournalError(
                    "this journal was planned first-fit (buckets "
                    "already started, no pack_decision records) — "
                    "re-bucketing in-flight worlds would resume them "
                    "from checkpoints planned for other buckets; "
                    "resume with --pack first-fit")
            from ..pack.predict import predict_supersteps
            art = self.pack_artifact

            def predict(c):
                return predict_supersteps(c, art)

            plan = plan_buckets(self.pack.configs, self.max_bucket,
                                pack_mode="predicted", predict=predict)
            for b in plan:
                self.journal.append({
                    "ev": "pack_decision", "bucket": b.bucket_id,
                    "members": list(b.run_ids), "mode": "predicted",
                    "artifact_sha": (art or {}).get("sha"),
                    "predicted": [predict(c) for c in b.configs]})
            return plan
        return plan_buckets(self.pack.configs, self.max_bucket)

    def decisions_for_world(self, run_id: str, scan=None):
        """The journaled dispatch-decision chain governing
        ``run_id``'s bucket (split ancestry included) — what the
        ``--verify`` solo twin replays for a controller config, and
        None for controller-off worlds. Pass a pre-computed
        ``journal.scan()`` when calling in a loop (the verify path
        does — re-scanning the whole append-only log per world would
        be O(worlds × journal)); without one the journal is read
        fresh, so it works after :meth:`run` returned (or was
        killed)."""
        if scan is None:
            scan = self.journal.scan()
        bid = scan.world_bucket.get(run_id)
        if not bid:
            return None
        chain = scan.decision_chain(bid)
        return chain or None

    # -- the supervision loop (runs under the asyncio interpreter) -------

    def _io(self, fn) -> Program:
        """Offload a blocking call to the sweep's own executor,
        awaited through AwaitIO so watchdogs stay live (and a
        ThreadKilled from one lands here, abandoning — not blocking
        on — the thread). A dedicated executor, NOT the loop default:
        asyncio.run joins the default executor at teardown, which
        would block the service's exit on a wedged abandoned chunk."""
        import asyncio
        if self._executor is None:
            from concurrent.futures import ThreadPoolExecutor
            self._executor = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="tw-sweep")
        loop = asyncio.get_running_loop()
        return (yield AwaitIO(loop.run_in_executor(self._executor, fn)))

    def _bucket_body(self, runner: BucketRunner, epoch: int) -> Program:
        from functools import partial
        yield from self._io(partial(runner.prepare, epoch))
        while True:
            status = yield from self._io(partial(runner.step, epoch))
            if status == "done":
                return

    def _attempt(self, jc: JobCurator, runner: BucketRunner) -> Program:
        """One supervised attempt: the bucket body as a thread job in
        a per-attempt child curator (nested under the service curator,
        so the end-of-sweep stop reaches every straggler), with an
        optional watchdog that escalates through ``WithTimeout`` at
        the deadline."""
        out = _Attempt()
        flag = Flag()
        child = JobCurator()
        yield from jc.add_manager_as_job(child, Plain)
        epoch = runner.begin_attempt()
        runner.attempts += 1

        def body() -> Program:
            try:
                yield from self._bucket_body(runner, epoch)
                out.ok = True
            except ThreadKilled:
                raise
            except Exception as e:  # noqa: BLE001 — classified below
                out.error = e
            finally:
                yield from flag.set()

        yield from child.add_thread_job(body)

        if self.bucket_timeout_us is not None:
            deadline = int(self.bucket_timeout_us)

            def watchdog() -> Program:
                yield Wait(deadline)
                if not flag.is_set:
                    out.timed_out = True
                    # invalidate the attempt's epoch FIRST: the
                    # zombie thread loses every write path before we
                    # even deliver the kill (runner.py)
                    runner.abandon(epoch)
                    # Plain-kill the attempt now; Force-clear any
                    # straggler at the grace deadline (the
                    # manage/jobs.py WithTimeout watchdog)
                    yield from child.stop_all_jobs(
                        WithTimeout(self.grace_us, None))

            yield Fork(watchdog)

        yield from flag.wait()
        if not child.is_closed:
            # close the (now job-free) curator so nothing dangles
            yield from child.interrupt_all_jobs(Plain)
        return out

    def _terminal_failure(self, runner: BucketRunner, reason: str) -> None:
        """Loud terminal failure: journal + report + ERROR log for
        every world the bucket never finished. Never silent, never
        blocking the rest of the sweep."""
        for cfg in runner.bucket.configs:
            if cfg.run_id in self.done or cfg.run_id in self.failed:
                continue
            rec = {"ev": "world_failed", "run_id": cfg.run_id,
                   "bucket": runner.bucket.bucket_id,
                   "attempts": runner.attempts, "error": reason}
            self.journal.append(rec)
            self.failed[cfg.run_id] = rec
            if self.metrics is not None:
                self.metrics.event("world_failed", run_id=cfg.run_id,
                                   bucket=runner.bucket.bucket_id)
            _log.error("sweep: world %r TERMINALLY FAILED after %d "
                       "attempt(s): %s", cfg.run_id, runner.attempts,
                       reason)

    def _refresh_settled(self) -> None:
        """Merged-journal re-scan (multi-host mode): fold in results
        and failures peers streamed — the steal path's dedup source
        (a thief's runner seeds ``emitted`` from ``done``, so worlds
        the dead holder already journaled are never re-journaled)."""
        scan = SweepJournal(self.journal.root).scan()
        self.done.update(scan.done)
        self.failed.update(scan.failed)

    def _settled(self, runner: BucketRunner) -> bool:
        return all(c.run_id in self.done or c.run_id in self.failed
                   for c in runner.bucket.configs)

    def _release_lease(self, runner: BucketRunner) -> None:
        if runner.lease is None:
            return
        try:
            self.journal.append({"ev": "lease_release",
                                 "bucket": runner.bucket.bucket_id,
                                 "host": self.host})
        finally:
            self.leases.release(runner.lease)
            runner.lease = None

    def _supervise(self, queue: deque) -> Program:
        from functools import partial

        from ..serve.lease import LeaseLost
        jc = JobCurator()
        #: buckets currently leased by a live peer — re-checked each
        #: poll round (a dead peer's lease goes stale and is stolen)
        deferred: deque = deque()
        while queue or deferred:
            if not queue:
                yield Wait(self.peer_poll_us)
                yield from self._io(self._refresh_settled)
                self.journal.maybe_heartbeat()
                queue.extend(deferred)
                deferred.clear()
                continue
            runner: BucketRunner = queue.popleft()
            if self.leases is not None:
                if self._settled(runner):
                    continue        # a peer finished it while we waited
                if runner.lease is not None:
                    # a retrying runner keeps its lease; just renew
                    try:
                        yield from self._io(partial(
                            self.leases.renew, runner.lease))
                    except LeaseLost:
                        runner.lease = None
                if runner.lease is None:
                    lease = yield from self._io(partial(
                        self.leases.try_acquire,
                        runner.bucket.bucket_id))
                    if lease is None:
                        deferred.append(runner)
                        continue
                    self.journal.append(
                        {"ev": "lease_acquire",
                         "bucket": runner.bucket.bucket_id,
                         "host": self.host, "gen": lease.gen,
                         "stolen_from": lease.stolen_from})
                    if lease.stolen_from:
                        _log.warning(
                            "sweep[%s]: STOLE bucket %s from dead "
                            "host %s (stale lease reclaimed)",
                            self.host, runner.bucket.bucket_id,
                            lease.stolen_from)
                    runner.lease = lease
                    runner.lease_dir = self.leases
                    # fold in whatever the previous holder (or any
                    # peer) streamed before we run a single chunk
                    yield from self._io(self._refresh_settled)
                    if self._settled(runner):
                        self._release_lease(runner)
                        continue
            self.journal.append({"ev": "bucket_start",
                                 "bucket": runner.bucket.bucket_id,
                                 "attempt": runner.attempts + 1})
            _t0 = _time.perf_counter()
            _ts = None if self.tracer is None else self.tracer.now_us()
            out = yield from self._attempt(jc, runner)
            if self.tracer is not None:
                self.tracer.complete(
                    f"attempt: bucket {runner.bucket.bucket_id}",
                    dur_us=(_time.perf_counter() - _t0) * 1e6,
                    ts_us=_ts, cat="attempt",
                    args={"attempt": runner.attempts,
                          "ok": out.ok,
                          "timed_out": out.timed_out})
            if out.ok:
                self.journal.append({"ev": "bucket_done",
                                     "bucket": runner.bucket.bucket_id})
                self._release_lease(runner)
                continue
            err = out.error
            if isinstance(err, SweepKilled):
                # the injected hard kill aborts the process WITHOUT
                # releasing the lease — exactly what a real host death
                # leaves behind; a peer steals after the TTL
                raise err
            if isinstance(err, LeaseLost):
                # the bucket was reclaimed by a peer (we stalled past
                # the TTL): not a failure, not a retry — the thief
                # continues from the shared checkpoint; re-check the
                # worlds as settled on a later poll round
                _log.warning("sweep[%s]: %s", self.host, err)
                runner.lease = None
                deferred.append(runner)
                continue
            from ..integrity.checks import IntegrityViolation
            if isinstance(err, IntegrityViolation):
                # detected state corruption (or a real bug surfacing
                # through the exactness laws): journal it — never
                # silent — then fall through to the retry path, which
                # IS the deterministic rollback: the attempt restarts
                # from the bucket's last verified checkpoint and
                # replays the journaled dispatch-decision chain, so
                # the recovered bucket is bit-identical to an
                # uncorrupted run (docs/integrity.md; the detection
                # law, tests/test_zzzzintegrity.py)
                self.journal.append({
                    "ev": "integrity_violation",
                    "bucket": runner.bucket.bucket_id,
                    "attempt": runner.attempts,
                    "detail": str(err)[:500]})
                if self.metrics is not None:
                    self.metrics.event("integrity_violation",
                                       bucket=runner.bucket.bucket_id)
                _log.warning("sweep: bucket %s INTEGRITY VIOLATION "
                             "(%s) — rolling back to its last "
                             "verified checkpoint",
                             runner.bucket.bucket_id, err)
            if err is not None and _is_oom(err):
                if runner.bucket.B > 1:
                    if self.metrics is not None:
                        self.metrics.event(
                            "oom_split",
                            bucket=runner.bucket.bucket_id)
                    kids = yield from self._io(runner.split_children)
                    self.journal.append({
                        "ev": "bucket_split",
                        "bucket": runner.bucket.bucket_id,
                        "into": [k.bucket.bucket_id for k in kids],
                        "fault_pad": runner.fault_pad(),
                        "reason": str(err)})
                    self._splits += 1
                    _log.warning("sweep: bucket %s OOM (%s) — split "
                                 "into %s", runner.bucket.bucket_id, err,
                                 [k.bucket.bucket_id for k in kids])
                    queue.extendleft(reversed(kids))
                else:
                    self._terminal_failure(runner, f"device OOM on a "
                                           f"solo bucket: {err}")
                # split children claim their own leases; the parent's
                # is done either way
                self._release_lease(runner)
                continue
            reason = ("bucket watchdog timeout "
                      f"({self.bucket_timeout_us} µs)" if out.timed_out
                      else f"{type(err).__name__}: {err}" if err
                      else "attempt ended without result")
            if runner.attempts <= self.max_retries:
                backoff = self.backoff_us * (
                    2 ** (runner.attempts - 1))
                self.journal.append({
                    "ev": "retry", "bucket": runner.bucket.bucket_id,
                    "attempt": runner.attempts, "backoff_us": backoff,
                    "reason": reason})
                self._retries += 1
                _log.warning("sweep: bucket %s attempt %d failed (%s) "
                             "— retrying after %d µs",
                             runner.bucket.bucket_id, runner.attempts,
                             reason, backoff)
                _bt = None if self.tracer is None \
                    else self.tracer.now_us()
                yield Wait(int(backoff))
                if self.tracer is not None:
                    self.tracer.complete(
                        f"backoff: bucket {runner.bucket.bucket_id}",
                        dur_us=self.tracer.now_us() - _bt, ts_us=_bt,
                        cat="retry",
                        args={"attempt": runner.attempts,
                              "reason": reason})
                queue.appendleft(runner)
            else:
                self._terminal_failure(
                    runner, f"{reason} (retries exhausted)")
                self._release_lease(runner)
        # end of sweep: Force-clear anything still straggling at the
        # grace deadline (a wedged executor thread's job) — the
        # service must terminate even when a chunk never returns
        yield from jc.stop_all_jobs(WithTimeout(self.grace_us, None))

    # -- entry point -------------------------------------------------------

    def run(self) -> SweepReport:
        """Run (or resume — same call) the sweep to completion.
        Raises :class:`SweepKilled` if an injected kill fires;
        otherwise always returns a report (terminal failures are in
        ``report.failed``, never raised)."""
        from ..interp.aio.timed import run_real_time
        queue = self._build_queue()
        try:
            if queue:
                run_real_time(lambda: self._supervise(queue))
            report = SweepReport(
                total=len(self.pack.configs), done=self.done,
                failed=self.failed, retries=self._retries,
                splits=self._splits, buckets=self._planned)
            self.journal.append({"ev": "sweep_done",
                                 **report.to_json()})
            return report
        finally:
            self.journal.close()
            if self.tracer is not None:
                # the Perfetto timeline survives kills too: written in
                # the finally, so a die:K abort still leaves the spans
                # up to the kill on disk. Best-effort: the sweep's
                # outcome (report, --verify, the killed path) must
                # never be masked by its own instrumentation failing
                # to write (a bad --trace-out dir, a full disk)
                import os as _os
                path = self.trace_out or _os.path.join(
                    self.journal.root, "trace.json")
                try:
                    self.tracer.save(path)
                    self.trace_path = path
                except OSError as e:
                    _log.warning("sweep: could not write Perfetto "
                                 "trace %r (%s) — results are "
                                 "unaffected", path, e)
            if self.metrics is not None:
                try:
                    self.metrics.close()
                except OSError as e:
                    _log.warning("sweep: metrics close failed: %s", e)
            if self.flight is not None:
                try:
                    self.flight.close()
                except OSError as e:
                    _log.warning("sweep: flight-event log close "
                                 "failed: %s", e)
            if self._executor is not None:
                # never join: an abandoned wedged chunk must not keep
                # a finished (or killed) sweep from returning
                self._executor.shutdown(wait=False)
                self._executor = None
