"""``timewarp-tpu sweep run|resume|status|watch`` — the sweep CLI.

::

    timewarp-tpu sweep run pack.json --journal DIR [--chunk N]
        [--retries K] [--backoff-us U] [--timeout-us T] [--inject S]
        [--max-bucket B] [--verify]
    timewarp-tpu sweep resume --journal DIR [...same knobs] [--verify]
    timewarp-tpu sweep status --journal DIR
    timewarp-tpu sweep watch --journal DIR [--interval S] [--once]

``run`` on a fresh dir starts the sweep; on an existing dir it
resumes (same pack only — a different pack is refused loudly).
``resume`` needs no pack argument: the journaled copy is the truth.
``status`` prints one JSON line of progress without running anything;
its ``events`` block (dispatch decisions, speculation rollbacks,
integrity violations) comes from the same journal fold the live
``watch`` renders, so the two surfaces always agree. ``watch``
attaches a READ-ONLY refreshing tail to a running (or finished)
sweep — obs/watch.py, docs/observability.md "Fleet observability".
``--verify`` re-runs every completed world solo after the sweep and
asserts the streamed result is bit-identical — the sweep survival law
as an executable gate (CI runs it).

Exit codes: 0 = every world completed (and verified, if asked);
1 = terminal world failures or a verification mismatch; an injected
``die:K`` kill exits 1 with the kill message (resume then finishes
the pack).
"""

from __future__ import annotations

import argparse
import json
import sys

from .journal import SweepJournal
from .service import SweepKilled, SweepService
from .spec import SweepConfigError, SweepPack, solo_result

__all__ = ["sweep_main"]


def _loud(fn):
    """Library config errors (SweepConfigError, the service's
    construction-time ValueError guards — bad chunk/retries, an
    unarmed flip injection — and a ``--lint error`` pack refusal)
    become clean CLI exits, keeping the guard-named message without a
    traceback."""
    from ..analysis import LintError
    try:
        return fn()
    except LintError as e:
        # the pre-flight verifier refused the pack (plan_lint.py):
        # exit with the pinned findings, one per line — no engine was
        # built, nothing was journaled
        raise SystemExit(str(e)) from None
    except (SweepConfigError, ValueError) as e:
        raise SystemExit(str(e)) from None


def _service_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--journal", required=True,
                   help="journal directory (JSONL log + checkpoints)")
    p.add_argument("--chunk", type=int, default=64,
                   help="supersteps per chunk between checkpoints")
    p.add_argument("--retries", type=int, default=2,
                   help="max retries per bucket before loud terminal "
                        "failure")
    p.add_argument("--backoff-us", type=int, default=50_000,
                   help="retry backoff base (doubles per attempt)")
    p.add_argument("--timeout-us", type=int, default=None,
                   help="per-bucket-attempt watchdog deadline")
    p.add_argument("--grace-us", type=int, default=500_000,
                   help="Force-clear grace after a watchdog interrupt")
    p.add_argument("--max-bucket", type=int, default=64,
                   help="max worlds per batched bucket")
    p.add_argument("--lint", default="warn",
                   choices=["error", "warn", "off"],
                   help="pre-flight verification of the whole pack "
                        "before any bucket builds (plan lint + "
                        "per-world sanitizer + fault-aware capacity "
                        "proofs) AND per-engine construction lint: "
                        "'error' refuses with the findings, 'warn' "
                        "(default) logs them, 'off' skips "
                        "(docs/sweeps.md 'Pre-flight verification')")
    p.add_argument("--inject", default=None,
                   help="deterministic failure injection: fail:K | "
                        "oom:K | die:K | hang:K:MS | "
                        "flip:SEED[:K[:PLANE]] (';'-joined, K = "
                        "1-based chunk call) — CI/test chaos for the "
                        "sweep machinery itself; flip writes a seeded "
                        "bit-flip into a bucket's state between "
                        "chunks (docs/integrity.md)")
    p.add_argument("--state-verify", default="off",
                   choices=["off", "guard", "digest"],
                   help="online state-integrity checking per bucket "
                        "(integrity/, docs/integrity.md): guard = "
                        "on-device invariant checks in every chunk; "
                        "digest = + rolling per-world state digest "
                        "verified at each chunk entry and chained "
                        "through the checkpoints (verified epochs). "
                        "Detection journals integrity_violation and "
                        "rolls the bucket back to its last verified "
                        "checkpoint")
    p.add_argument("--verify", action="store_true",
                   help="after the sweep, re-run every completed "
                        "world solo and assert the streamed result is "
                        "bit-identical (the sweep survival law)")
    p.add_argument("--hosts", default=None,
                   help="multi-host mode (serve/, docs/serving.md): "
                        "NAME[,PEER...] — first entry is THIS "
                        "process's identity (HOST_GRAMMAR). N "
                        "processes sharing one --journal cooperate "
                        "through per-bucket leases; a dead host's "
                        "buckets are stolen after --lease-ttl-s and "
                        "continue from their shared checkpoints")
    p.add_argument("--lease-ttl-s", type=float, default=30.0,
                   help="lease staleness TTL for --hosts mode")
    p.add_argument("--telemetry", default="off",
                   choices=["off", "counters", "full"],
                   help="engine telemetry mode (obs/, "
                        "docs/observability.md): bucket engines "
                        "thread per-superstep counter planes (bit-"
                        "exact; results are mode-independent), the "
                        "journal dir gains metrics.jsonl + a Perfetto "
                        "trace.json of service spans")
    p.add_argument("--trace-out", default=None,
                   help="write the Perfetto trace here instead of "
                        "<journal>/trace.json (needs --telemetry)")
    p.add_argument("--record", default="off",
                   choices=["off", "deliveries", "full"],
                   help="causal flight recorder per bucket "
                        "(obs/flight.py, docs/observability.md): "
                        "bucket engines thread the bounded event "
                        "plane (bit-exact; results are mode-"
                        "independent) and every chunk's per-world "
                        "events drain into <journal>/events.jsonl "
                        "tagged by run_id — query with `timewarp-tpu "
                        "explain <journal>/events.jsonl --run-id ID`; "
                        "`sweep status` surfaces per-world event "
                        "counts")
    p.add_argument("--pack", default="first-fit", dest="pack_mode",
                   help="bucket packing mode (first-fit | predicted; "
                        "docs/sweeps.md 'Predictive packing'): "
                        "predicted reorders each shape group best-fit-"
                        "decreasing by forecast supersteps and "
                        "journals the plan as pack_decision records "
                        "(streamed results are bit-identical either "
                        "way — the survival law holds per world)")
    p.add_argument("--pack-artifact", default=None,
                   help="sha-stamped predictor artifact from "
                        "`timewarp-tpu pack fit` (--pack predicted "
                        "falls back to each world's declared budget "
                        "without one)")


def _kw(args) -> dict:
    if args.trace_out and args.telemetry == "off":
        raise SystemExit("--trace-out needs --telemetry "
                         "counters|full (off records nothing)")
    host = None
    if args.hosts is not None:
        from ..serve.hosts import parse_hosts
        host = parse_hosts(args.hosts)[0].name
    return dict(chunk=args.chunk, max_retries=args.retries,
                backoff_us=args.backoff_us,
                bucket_timeout_us=args.timeout_us,
                grace_us=args.grace_us, max_bucket=args.max_bucket,
                lint=args.lint, inject=args.inject,
                telemetry=args.telemetry, trace_out=args.trace_out,
                verify=args.state_verify, record=args.record,
                host=host, lease_ttl_s=args.lease_ttl_s,
                pack_mode=args.pack_mode,
                pack_artifact=args.pack_artifact,
                # a promised post-sweep --verify arms the flip guard's
                # other legal detection path (service.py)
                post_verify=args.verify)


def _auto_bisect(trail, trace) -> dict:
    """Localize one ``--verify`` mismatch (obs/bisect.py): fold the
    solo twin's trace rows (already computed by the verify run
    itself) chunk-for-chunk against the world's journaled digest
    trail (the ``world_done`` record's ``chain``) — the result names
    the first diverging chunk and its superstep span, or reports that
    every journaled chunk agrees (the divergence then lies outside
    the digested rows)."""
    if not trail:
        return {"first_divergence": None}
    from ..obs.bisect import first_trail_divergence
    return {"first_divergence": first_trail_divergence(trail, trace)}


def _finish(svc: SweepService, verify: bool) -> int:
    try:
        report = svc.run()
    except SweepKilled as e:
        print(json.dumps({"sweep": "killed", "error": str(e)}))
        return 1
    out = report.to_json()
    if svc.trace_path is not None:
        out["trace"] = svc.trace_path
        out["metrics"] = svc.metrics.path
    if svc.flight is not None:
        out["events"] = svc.flight.path
        out["flight_events"] = svc.flight.events
    if verify:
        mismatches = []
        scan = svc.journal.scan()
        for rid, res in sorted(report.done.items()):
            cfg = svc.pack.by_id(rid)
            # controller AND speculate worlds: the solo twin replays
            # the bucket's journaled decision chain (the replay law
            # carries the survival law — docs/dispatch.md; for
            # speculation the chain is the committed window sequence,
            # rollbacks already resolved to their floor decisions —
            # docs/speculation.md)
            decs = svc.decisions_for_world(rid, scan) \
                if cfg.controller == "auto" or cfg.speculate != "off" \
                else None
            want, solo_tr = solo_result(cfg, lint="off",
                                        decisions=decs,
                                        with_trace=True)
            if want != res:
                mm = {"run_id": rid, "solo": want, "streamed": res}
                # auto-bisect the mismatch (obs/bisect.py): replay
                # the world's journaled per-chunk digest trail
                # against the solo twin's trace and name the first
                # diverging chunk — "which chunk broke", not just
                # "a digest differs"
                mm.update(_auto_bisect(scan.chains.get(rid, []),
                                       solo_tr))
                mismatches.append(mm)
        out["verified"] = len(report.done) - len(mismatches)
        if mismatches:
            out["verify_mismatches"] = mismatches
            print(json.dumps(out))
            for mm in mismatches:
                d = mm.get("first_divergence")
                sys.stderr.write(
                    f"sweep --verify: {mm['run_id']}: "
                    + (f"first diverging chunk {d['chunk']} "
                       f"(supersteps {d['supersteps'][0]}.."
                       f"{d['supersteps'][1]}): streamed "
                       f"{d['streamed'][:12]}.. != solo "
                       f"{str(d['solo'])[:12]}.."
                       if d else
                       "journaled chunk trail matches the solo "
                       "trace — the divergence is outside the "
                       "digested rows (counters/final state)")
                    + "\n")
            sys.stderr.write(
                "sweep survival law VIOLATED: streamed results "
                "diverge from solo runs\n")
            return 1
    print(json.dumps(out))
    return 0 if report.ok else 1


def _run(argv) -> int:
    p = argparse.ArgumentParser(
        prog="timewarp-tpu sweep run",
        description="Run (or resume, on an existing journal) a pack.")
    p.add_argument("pack", help="pack file: JSON list (or JSONL) of "
                   "run configs — see docs/sweeps.md")
    p.add_argument("--speculate", default=None,
                   help="optimistic time-warp execution per bucket "
                        "(speculate/, docs/speculation.md): "
                        "auto | fixed:W — applied as the pack-level "
                        "default to every config that does not set "
                        "its own \"speculate\" (explicit per-config "
                        "values — including \"off\" — win; this flag "
                        "beats a pack-file-level \"speculate\" key; "
                        "the journaled pack carries the result, so "
                        "resume needs no flag). Committed window "
                        "choices journal as dispatch_decision events "
                        "and --verify replays them; rollbacks "
                        "surface in `sweep status` spec_rollbacks")
    _service_args(p)
    args = p.parse_args(argv)

    def build():
        if args.speculate:
            from ..speculate import parse_speculate
            parse_speculate(args.speculate, who="--speculate")
        # the default applies at the JSON layer (explicit per-config
        # values — including an explicit "off" opt-out — win) and
        # BEFORE the pack is journaled, so pack.sha / resume / bucket
        # planning all see the speculated configs exactly as if the
        # pack file said it
        pack = SweepPack.load(args.pack,
                              speculate_default=args.speculate)
        return SweepService(pack, args.journal, **_kw(args))
    svc = _loud(build)
    return _finish(svc, args.verify)


def _resume(argv) -> int:
    p = argparse.ArgumentParser(
        prog="timewarp-tpu sweep resume",
        description="Resume a killed sweep from its journal dir.")
    _service_args(p)
    args = p.parse_args(argv)
    svc = _loud(lambda: SweepService.resume(args.journal, **_kw(args)))
    return _finish(svc, args.verify)


def _status(argv) -> int:
    p = argparse.ArgumentParser(
        prog="timewarp-tpu sweep status",
        description="One JSON progress line from a sweep journal.")
    p.add_argument("--journal", required=True)
    args = p.parse_args(argv)
    j = SweepJournal(args.journal)
    import os

    from .journal import status_fields
    if os.path.exists(j.pack_path):
        total = len(SweepPack.load(j.pack_path).configs)
        scan = j.scan()
    elif j.exists():
        # a serve journal dir (docs/serving.md) has no pack — the
        # world count is the admission ledger's
        scan = j.scan()
        total = len(scan.admits)
    else:
        raise SystemExit(
            f"{args.journal!r} holds no sweep (no pack.json and no "
            "journal files)")
    # ONE shared fold + assembly (journal.py status_fields) behind
    # both this line and `sweep watch`'s aggregates — the two
    # surfaces report identical numbers from the same journal by
    # construction (docs/observability.md "Fleet observability")
    print(json.dumps(status_fields(scan, total)))
    return 0


def _watch(argv) -> int:
    p = argparse.ArgumentParser(
        prog="timewarp-tpu sweep watch",
        description="READ-ONLY live tail of a sweep journal dir "
                    "(obs/watch.py): refreshing aggregates — worlds "
                    "done, buckets in flight, retries, event counts, "
                    "utilization. Plain append-only output (no "
                    "escape codes); one line per refresh in which "
                    "anything changed.")
    p.add_argument("--journal", required=True,
                   help="the sweep's journal directory")
    p.add_argument("--interval", type=float, default=2.0,
                   help="poll interval in seconds (default 2.0)")
    p.add_argument("--once", action="store_true",
                   help="render one snapshot and exit 0 — the CI "
                        "form against a finished journal")
    p.add_argument("--json", action="store_true",
                   help="one JSON object per refresh instead of the "
                        "text line (final snapshot's shared fields "
                        "equal `sweep status --json`)")
    p.add_argument("--max-seconds", type=float, default=None,
                   help="stop watching after this long even if the "
                        "sweep is still running (default: watch "
                        "until sweep_done or Ctrl-C)")
    args = p.parse_args(argv)
    if args.interval <= 0:
        raise SystemExit(
            f"--interval must be > 0, got {args.interval}")
    import os
    import time as _time

    from ..obs.watch import SweepWatch
    if args.once and not SweepJournal(args.journal).exists():
        raise SystemExit(
            f"{args.journal!r} holds no sweep journal to snapshot "
            "(no journal*.jsonl)")
    w = SweepWatch(args.journal)
    deadline = None if args.max_seconds is None \
        else _time.monotonic() + args.max_seconds
    last = None
    try:
        while True:
            snap = w.poll()
            out = json.dumps(snap) if args.json else w.render(snap)
            if out != last:
                print(out, flush=True)
                last = out
            if args.once or w.finished:
                return 0
            if deadline is not None and _time.monotonic() >= deadline:
                return 0
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0        # watching is observational; detach quietly


def sweep_main(argv) -> int:
    if not argv or argv[0] not in ("run", "resume", "status",
                                   "watch"):
        raise SystemExit(
            "usage: timewarp-tpu sweep run PACK --journal DIR | "
            "sweep resume --journal DIR | sweep status --journal DIR"
            " | sweep watch --journal DIR")
    cmd, rest = argv[0], argv[1:]
    if cmd == "run":
        return _run(rest)
    if cmd == "resume":
        return _resume(rest)
    if cmd == "watch":
        return _watch(rest)
    return _status(rest)
