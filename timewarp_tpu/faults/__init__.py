"""Deterministic chaos: scheduled fault injection inside the superstep.

The reference promised "manually controlled network nastiness"
(``Delays`` / ``ConnectionOutcome``, examples/token-ring/Main.hs:73-77);
:mod:`timewarp_tpu.net.delays` revives its *stationary* half — per-
message laws that never change over emulated time. This package adds
the **time-varying** half: crash/restart a node with state loss,
partition the network for a window, degrade a set of links for a
burst, skew a node's clock — all as a static, declarative
:class:`FaultSchedule` applied as pure jittable masks inside every
superstep, so the same schedule runs bit-for-bit under the host
oracle, the XLA engines, and a ``vmap``-ed multi-world fleet
(:class:`FaultFleet`: B worlds, B schedules, one chip — the
Monte-Carlo chaos study the ROADMAP's north star asks for).

Semantics are defined once (docs/faults.md) and pinned by the same
law every other feature answers to: oracle ≡ engine trace parity, and
chaos-fleet world-slice exactness (tests/test_zfault_parity.py).
"""

from .properties import (TraceRow, converged, eventually_delivered,
                         no_fire_while_down)
from .schedule import (FAULT_GRAMMAR, ClockSkew, FaultFleet,
                       FaultSchedule, FaultTables, LinkWindow, NodeCrash,
                       Partition, as_fleet, parse_faults)

__all__ = [
    "NodeCrash", "Partition", "LinkWindow", "ClockSkew",
    "FaultSchedule", "FaultFleet", "FaultTables",
    "parse_faults", "FAULT_GRAMMAR", "as_fleet",
    "eventually_delivered", "converged", "no_fire_while_down",
    "TraceRow",
]
