"""Robustness properties over traces and event streams.

The small assertion vocabulary chaos tests and bench gates speak:
does the protocol keep making progress after the faults clear? did
anything fire while it was supposed to be down? Properties are
deliberately simple host-side checks over the observables the
framework already emits — :class:`~timewarp_tpu.trace.events.
SuperstepTrace` rows (aggregate, always available) and per-event
streams (``SuperstepOracle(record_events=True).events`` or the
engine's device ring) when per-node resolution is needed.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, NamedTuple, Optional

import numpy as np

from ..trace.events import SuperstepTrace

__all__ = ["TraceRow", "eventually_delivered", "converged",
           "no_fire_while_down",
           "WorldProp", "WorldCheckFailure", "FleetCheck",
           "prop_eventually_delivered", "prop_converged",
           "check_worlds"]


class TraceRow(NamedTuple):
    """One superstep's aggregates, as handed to ``converged``
    predicates."""
    t: int
    fired_count: int
    fired_hash: int
    recv_count: int
    recv_hash: int
    sent_count: int
    sent_hash: int
    overflow: int


def _rows(trace: SuperstepTrace):
    return (TraceRow(*trace.row(i)) for i in range(len(trace)))


def eventually_delivered(trace: SuperstepTrace, after_t: int) -> bool:
    """True iff some superstep at virtual time >= ``after_t`` delivers
    at least one message — "traffic still flows after the faults
    clear" (e.g. after a partition heals)."""
    return any(r.t >= after_t and r.recv_count > 0 for r in _rows(trace))


def converged(trace: SuperstepTrace,
              pred: Callable[[TraceRow], bool]) -> bool:
    """Eventually-always: there is a superstep from which ``pred``
    holds for every remaining row (vacuously False on an empty
    trace — a run that never fired converged to nothing)."""
    rows = list(_rows(trace))
    if not rows:
        return False
    ok_from = len(rows)
    for i in range(len(rows) - 1, -1, -1):
        if not pred(rows[i]):
            break
        ok_from = i
    return ok_from < len(rows)


# -- batched (world-sliced) evaluation -------------------------------------
#
# The solo functions above take one trace; fleet consumers — the
# adversarial chaos search (timewarp_tpu/search/), sweep-level chaos
# gates — evaluate a whole world axis at once. A WorldProp is one
# named per-world predicate over (trace, that world's FaultSchedule);
# check_worlds folds a list of them over every world of a fleet and
# reports both the bool[B] verdict vector and per-world failure
# detail, so a violating world is named, never a bare False.


class WorldProp(NamedTuple):
    """One named per-world property. ``fn(trace, schedule)`` returns
    a bool, or ``(bool, detail_str)`` when it can say *why* it
    failed."""
    name: str
    fn: Callable


class WorldCheckFailure(NamedTuple):
    world: int
    run_id: Optional[str]
    prop: str
    detail: str


class FleetCheck(NamedTuple):
    """``check_worlds``'s verdict: ``ok[b]`` iff every property held
    in world ``b``; ``failures`` carries one record per (world,
    property) violation, in world-major order."""
    ok: np.ndarray            # bool[B]
    failures: List[WorldCheckFailure]

    @property
    def all_ok(self) -> bool:
        return bool(self.ok.all())


def prop_eventually_delivered(after_t: int) -> WorldProp:
    """The solo :func:`eventually_delivered` as a WorldProp."""
    t = int(after_t)

    def fn(trace, schedule):
        if eventually_delivered(trace, t):
            return True
        return (False, f"no delivery at or after t={t}")
    return WorldProp(f"eventually-delivered:{t}", fn)


def prop_converged(pred: Callable[[TraceRow], bool],
                   name: str = "converged") -> WorldProp:
    """The solo :func:`converged` as a WorldProp."""
    def fn(trace, schedule):
        if converged(trace, pred):
            return True
        return (False, "predicate never holds to the end of the "
                       "trace")
    return WorldProp(name, fn)


def _world_schedules(fleet, B: int):
    from .schedule import FaultFleet, FaultSchedule
    if fleet is None:
        return [FaultSchedule(())] * B
    if isinstance(fleet, FaultFleet):
        scheds = list(fleet.schedules)
    else:
        scheds = list(fleet)
    if len(scheds) != B:
        raise ValueError(
            f"fleet carries {len(scheds)} world schedules but "
            f"{B} traces were handed in")
    return scheds


def check_worlds(traces, fleet, props,
                 run_ids=None) -> FleetCheck:
    """Evaluate ``props`` (WorldProps) against every world of a
    fleet: ``traces`` is the per-world trace list a batched engine
    returns, ``fleet`` a :class:`~timewarp_tpu.faults.schedule.
    FaultFleet` (or a plain sequence of FaultSchedules, or None for
    a fault-free fleet). Returns ``ok: bool[B]`` plus per-world
    failure detail; ``run_ids`` (optional, length B) names worlds in
    the failure records the way the sweep journal would."""
    B = len(traces)
    scheds = _world_schedules(fleet, B)
    if run_ids is not None and len(run_ids) != B:
        raise ValueError(
            f"run_ids names {len(run_ids)} worlds for {B} traces")
    ok = np.ones(B, bool)
    failures: List[WorldCheckFailure] = []
    for b in range(B):
        for prop in props:
            res = prop.fn(traces[b], scheds[b])
            detail = f"property {prop.name} failed"
            if isinstance(res, tuple):
                res, detail = res[0], f"{prop.name}: {res[1]}"
            if not res:
                ok[b] = False
                failures.append(WorldCheckFailure(
                    b, None if run_ids is None else run_ids[b],
                    prop.name, detail))
    return FleetCheck(ok, failures)


def no_fire_while_down(events: Iterable[tuple], schedule) -> bool:
    """True iff no ``("fire", t, node)`` event lands inside one of the
    ``schedule``'s crash windows — the firing-suppression contract,
    checked at per-node resolution over an event stream
    (``SuperstepOracle(record_events=True).events`` or the engine
    ring's decode)."""
    windows = [(c.node, c.t_down, c.t_up) for c in schedule.crashes
               if c.t_up > c.t_down]
    if not windows:
        return True
    for ev in events:
        if ev[0] != "fire":
            continue
        _, t, node = ev[0], ev[1], ev[2]
        for k, d, u in windows:
            if node == k and d <= t < u:
                return False
    return True
