"""Robustness properties over traces and event streams.

The small assertion vocabulary chaos tests and bench gates speak:
does the protocol keep making progress after the faults clear? did
anything fire while it was supposed to be down? Properties are
deliberately simple host-side checks over the observables the
framework already emits — :class:`~timewarp_tpu.trace.events.
SuperstepTrace` rows (aggregate, always available) and per-event
streams (``SuperstepOracle(record_events=True).events`` or the
engine's device ring) when per-node resolution is needed.
"""

from __future__ import annotations

from typing import Callable, Iterable, NamedTuple

from ..trace.events import SuperstepTrace

__all__ = ["TraceRow", "eventually_delivered", "converged",
           "no_fire_while_down"]


class TraceRow(NamedTuple):
    """One superstep's aggregates, as handed to ``converged``
    predicates."""
    t: int
    fired_count: int
    fired_hash: int
    recv_count: int
    recv_hash: int
    sent_count: int
    sent_hash: int
    overflow: int


def _rows(trace: SuperstepTrace):
    return (TraceRow(*trace.row(i)) for i in range(len(trace)))


def eventually_delivered(trace: SuperstepTrace, after_t: int) -> bool:
    """True iff some superstep at virtual time >= ``after_t`` delivers
    at least one message — "traffic still flows after the faults
    clear" (e.g. after a partition heals)."""
    return any(r.t >= after_t and r.recv_count > 0 for r in _rows(trace))


def converged(trace: SuperstepTrace,
              pred: Callable[[TraceRow], bool]) -> bool:
    """Eventually-always: there is a superstep from which ``pred``
    holds for every remaining row (vacuously False on an empty
    trace — a run that never fired converged to nothing)."""
    rows = list(_rows(trace))
    if not rows:
        return False
    ok_from = len(rows)
    for i in range(len(rows) - 1, -1, -1):
        if not pred(rows[i]):
            break
        ok_from = i
    return ok_from < len(rows)


def no_fire_while_down(events: Iterable[tuple], schedule) -> bool:
    """True iff no ``("fire", t, node)`` event lands inside one of the
    ``schedule``'s crash windows — the firing-suppression contract,
    checked at per-node resolution over an event stream
    (``SuperstepOracle(record_events=True).events`` or the engine
    ring's decode)."""
    windows = [(c.node, c.t_down, c.t_up) for c in schedule.crashes
               if c.t_up > c.t_down]
    if not windows:
        return True
    for ev in events:
        if ev[0] != "fire":
            continue
        _, t, node = ev[0], ev[1], ev[2]
        for k, d, u in windows:
            if node == k and d <= t < u:
                return False
    return True
