"""Pure jittable fault masks: ``(FaultTables, virtual time) -> masks``.

Every function here is elementwise/broadcast jax.numpy over the
fixed-shape tables of :mod:`timewarp_tpu.faults.schedule` — no host
control flow on traced values, so the same code runs inside the solo
superstep, under ``vmap`` for a :class:`~timewarp_tpu.faults.schedule.
FaultFleet` (tables carry a leading world axis), and under
``shard_map`` (masks are per-node elementwise; node ids are global).
Zero-row tables short-circuit at trace time (shapes are static), so an
engine built without a given fault kind compiles the exact pre-fault
program for that stage.

The one piece of *state* faults need is ``restart_done: bool[C]`` —
whether each crash row's injected restart firing has been consumed.
Everything else is a pure function of the schedule and the clock
(injecting restarts statelessly would re-fire a rebooted node whose
window start the epoch has not yet crossed — windowed supersteps run
per-node instants ahead of the epoch).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.scenario import NEVER

__all__ = [
    "defer_next", "restart_fire", "consume_restarts",
    "cut_mask", "down_mask", "degrade", "skewed_step",
    "window_floor",
]


def _crash_active(ft):
    return ft.crash_up > ft.crash_down            # [C] (inert rows off)


def defer_next(ft, node_ids, node_next, restart_done):
    """Crash-adjusted next-event times: an event inside the node's
    down window slides to ``t_up`` (single pass — overlapping windows
    per node are a TW502 lint error), and every unconsumed
    ``reset_state`` row injects a restart firing at exactly ``t_up``
    (the reboot event the reset anchors to)."""
    if ft.crash_node.shape[0] == 0:
        return node_next
    m = (ft.crash_node[:, None] == node_ids[None, :]) \
        & _crash_active(ft)[:, None]                        # [C, N]
    x = node_next[None, :]
    inwin = m & (ft.crash_down[:, None] <= x) & (x < ft.crash_up[:, None])
    deferred = jnp.max(jnp.where(inwin, ft.crash_up[:, None], x),
                       axis=0)
    pend = m & ft.crash_reset[:, None] & ~restart_done[:, None]
    inject = jnp.min(jnp.where(pend, ft.crash_up[:, None],
                               jnp.int64(NEVER)), axis=0)
    return jnp.minimum(deferred, inject)


def restart_fire(ft, fire, now_vec, node_ids, restart_done):
    """The restart firings happening *this* superstep: a fired node
    whose instant equals an unconsumed reset row's ``t_up``. Returns
    ``(reset_now bool[N], purge_before int64[N])`` — reset the node's
    state before its step runs, and purge mailbox entries with deliver
    time < ``purge_before`` (memory the reboot lost; 0 = none)."""
    n = node_ids.shape[0]
    if ft.crash_node.shape[0] == 0:
        return (jnp.zeros((n,), bool), jnp.zeros((n,), jnp.int64))
    m = (ft.crash_node[:, None] == node_ids[None, :]) \
        & _crash_active(ft)[:, None]
    hit = m & ft.crash_reset[:, None] & ~restart_done[:, None] \
        & fire[None, :] & (now_vec[None, :] == ft.crash_up[:, None])
    reset_now = jnp.any(hit, axis=0)
    purge_before = jnp.max(
        jnp.where(hit, ft.crash_down[:, None], jnp.int64(0)), axis=0)
    return reset_now, purge_before


def consume_restarts(ft, fire, now_vec, node_ids, restart_done):
    """``restart_done`` after this superstep: a row is consumed when
    its node fires at exactly its ``t_up`` (the injected restart — or
    a coincident legitimate event; either way the reboot happened)."""
    if ft.crash_node.shape[0] == 0:
        return restart_done
    m = (ft.crash_node[:, None] == node_ids[None, :]) \
        & _crash_active(ft)[:, None]
    hit = m & ft.crash_reset[:, None] & fire[None, :] \
        & (now_vec[None, :] == ft.crash_up[:, None])
    return restart_done | jnp.any(hit, axis=1)


def _flat(*xs):
    """Broadcast operands to a common shape and flatten — the mask
    bodies below work on 1-D lanes, callers pass any (mutually
    broadcastable) rank: [S] message lanes, [M, N] outbox planes,
    scalar times against [N] node vectors."""
    bs = jnp.broadcast_arrays(*(jnp.asarray(x) for x in xs))
    return bs[0].shape, tuple(b.reshape(-1) for b in bs)


def cut_mask(ft, src, dst, t_send):
    """True where a message crosses a live partition cut: some
    partition row active at the *send instant* puts src and dst in
    different (non-absent) groups. ``src``/``dst`` are global node
    ids; out-of-range values must be pre-masked by the caller (indices
    are clipped here only for gather safety)."""
    shape, (src, dst, t) = _flat(src, dst, t_send)
    if ft.part_group.shape[0] == 0:
        return jnp.zeros(shape, bool)
    n = ft.part_group.shape[-1]
    gs = ft.part_group[:, jnp.clip(src, 0, n - 1)]         # [Pn, S]
    gd = ft.part_group[:, jnp.clip(dst, 0, n - 1)]
    act = (ft.part_start[:, None] <= t[None, :]) \
        & (t[None, :] < ft.part_end[:, None])
    cut = act & (gs != gd) & (gs >= 0) & (gd >= 0)
    return jnp.any(cut, axis=0).reshape(shape)


def down_mask(ft, node, t):
    """True where ``node`` is inside a crash window at time ``t`` —
    the routing stage drops messages whose *deliver* time lands in the
    destination's down window (the NIC is off)."""
    shape, (node, t) = _flat(node, t)
    if ft.crash_node.shape[0] == 0:
        return jnp.zeros(shape, bool)
    m = (ft.crash_node[:, None] == node[None, :]) \
        & _crash_active(ft)[:, None]
    win = (ft.crash_down[:, None] <= t[None, :]) \
        & (t[None, :] < ft.crash_up[:, None])
    return jnp.any(m & win, axis=0).reshape(shape)


def degrade(ft, delay, src, dst, t_send):
    """Apply every live link-degradation window to the sampled delays:
    ``delay' = (delay * num) // den + extra`` for affected messages.
    Rows compose in table order (a static Python loop — L is a shape).
    Integer arithmetic throughout: bit-exact on every backend."""
    L = ft.link_start.shape[0]
    if L == 0:
        return delay
    shape, (delay, src, dst, t) = _flat(delay, src, dst, t_send)
    n = ft.link_src.shape[-1]
    sc = jnp.clip(src, 0, n - 1)
    dc = jnp.clip(dst, 0, n - 1)
    for i in range(L):
        aff = (ft.link_start[i] <= t) & (t < ft.link_end[i]) \
            & ft.link_src[i][sc] & ft.link_dst[i][dc]
        delay = jnp.where(
            aff, (delay * ft.link_num[i]) // ft.link_den[i]
            + ft.link_add[i], delay)
    return delay.reshape(shape)


def window_floor(ft, t, w_req, base_floor: int):
    """Effective exact superstep window at instant ``t`` for a
    *requested* width ``w_req`` (traced int64 scalar): the degraded
    delay floor over sends in ``[t, t + w_req)``, clamped to
    ``[1, w_req]``. The device-side half of the dynamic-window
    contract (engine.py): a degradation window that undercuts the
    link's declared floor mid-run narrows the superstep window for
    exactly the supersteps it overlaps, instead of forcing the whole
    run onto the conservative schedule-wide floor
    (``FaultSchedule.min_delay_floor``).

    ``base_floor`` is a *host int* lower bound on every world's
    undegraded delay (the engine's controller window bound) — a
    per-world traced floor would not lower under the link-param sweep
    (``min_delay_us`` of a rebound link may do host arithmetic).
    Same greedy fold as the host ``min_delay_floor`` (transforms are
    monotone, so ``x <- min(x, T_i(x))`` in declaration order realizes
    the minimum over every row subset), restricted to rows whose
    window overlaps ``[t, t + w_req)`` — restricting to the *requested*
    (not effective) span only admits extra rows, so the clamp is
    conservative-safe. Inert pad rows (``t_end <= t_start``) never
    match. Deterministic given ``(tables, t, w_req)``, which is what
    keeps controller replay bit-exact."""
    f = jnp.int64(base_floor)
    L = ft.link_start.shape[0]
    if L == 0:
        return jnp.clip(jnp.asarray(w_req, jnp.int64), jnp.int64(1), f)
    for i in range(L):
        live = (ft.link_end[i] > ft.link_start[i]) \
            & (ft.link_start[i] < t + w_req) & (ft.link_end[i] > t)
        fi = jnp.maximum(
            jnp.int64(1),
            (f * ft.link_num[i]) // ft.link_den[i] + ft.link_add[i])
        f = jnp.where(live, jnp.minimum(f, fi), f)
    return jnp.clip(jnp.asarray(w_req, jnp.int64), jnp.int64(1),
                    jnp.maximum(f, jnp.int64(1)))


def skewed_step(step, skew):
    """Wrap a scenario step so the node observes skewed time: ``now``
    and (valid) inbox deliver times shift by ``skew[node]``; the
    returned wake shifts back to true time (NEVER stays NEVER).
    Engine internals — entropy keys, digests, fault windows, the
    contract-#5 clamp — all stay on true time. The *same* wrapped
    function runs under the oracle's vmap and the engines', so skewed
    behavior cannot diverge between interpreters."""
    def wrapped(state, inbox, now, node_id, key):
        off = skew[node_id]
        ib = inbox._replace(
            time=jnp.where(inbox.valid, inbox.time + off, inbox.time))
        st, out, wake = step(state, ib, now + off, node_id, key)
        wake = jnp.where(wake >= NEVER, wake, wake - off)
        return st, out, wake
    return wrapped
