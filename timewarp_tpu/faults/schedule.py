"""Fault schedules: declarative, fixed-shape, validated at construction.

A :class:`FaultSchedule` is a static list of fault events — known from
t=0, like the reference's seeded ``Delays`` function was — lowered to
a :class:`FaultTables` pytree of fixed-shape int64-µs event tables the
engines can close over (solo) or ``vmap`` a leading world axis through
(:class:`FaultFleet`). Nothing here is sampled at run time: the same
schedule produces the same masks in every interpreter, which is what
keeps chaos runs inside the oracle ≡ engine parity law.

Event semantics (normative statement in docs/faults.md):

- :class:`NodeCrash` ``(node, t_down, t_up, reset_state)`` — the node
  cannot fire at any instant in ``[t_down, t_up)``; its pending events
  inside the window slide to ``t_up``; messages that would be
  *delivered* inside the window are dropped at routing time (the NIC
  is off) and counted in ``fault_dropped``. With ``reset_state`` the
  node also reboots: a restart firing is injected at exactly ``t_up``,
  the node's state re-initializes to ``Scenario.init``'s state, and
  mailbox entries older than ``t_down`` are purged (memory loss) —
  in-flight messages due at or after ``t_up`` survive (they were in
  the network, not the node).
- :class:`Partition` ``(groups, t_start, t_end)`` — while live at a
  message's *send instant*, a message whose source and destination sit
  in different groups is dropped (and counted). Nodes in no group are
  unaffected.
- :class:`LinkWindow` ``(src, dst, t_start, t_end, scale, extra_us)`` —
  degradation: messages sent inside the window from a ``src`` node to
  a ``dst`` node have their sampled delay transformed
  ``delay' = (delay * num) // den + extra_us`` (``scale`` is held as
  the exact integer rational ``num/den``, so the transform is
  bit-exact on every backend). Rows compose in declaration order.
- :class:`ClockSkew` ``(node, offset_us)`` — the node's *view* of time
  (the ``now`` and inbox times its step function sees) is shifted by
  ``offset_us``; returned wake times are shifted back. Engine
  internals (entropy keys, digests, fault windows) stay on true time.

All times are int64 µs and validated eagerly; scenario-dependent
checks (node ranges, overlapping crash windows, …) are the TW5xx lint
rules (:mod:`timewarp_tpu.analysis.fault_lint`), run by every
fault-capable engine at construction under its ``lint`` knob.
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction
from typing import Any, List, NamedTuple, Optional, Tuple

import numpy as np

from ..core.time import FOREVER

__all__ = [
    "NodeCrash", "Partition", "LinkWindow", "ClockSkew",
    "FaultSchedule", "FaultFleet", "FaultTables",
    "parse_faults", "format_faults", "FAULT_GRAMMAR",
]

#: ceiling every schedule time must stay under (NEVER arithmetic
#: headroom: a deferred event at t_up must still be < FOREVER)
_T_MAX = FOREVER // 2


def _t(us, what: str) -> int:
    if isinstance(us, bool) or not isinstance(us, (int, np.integer)):
        raise ValueError(f"{what} must be an int µs count, got {us!r}")
    v = int(us)
    if not -_T_MAX < v < _T_MAX:
        raise ValueError(f"{what}={v} outside the int64-µs schedule "
                         f"range (|t| < 2^61)")
    return v


def _node(i, what: str) -> int:
    if isinstance(i, bool) or not isinstance(i, (int, np.integer)) or i < 0:
        raise ValueError(f"{what} must be a node id >= 0, got {i!r}")
    return int(i)


@dataclass(frozen=True)
class NodeCrash:
    """Crash ``node`` for ``[t_down, t_up)``; ``reset_state`` reboots
    it (state loss + injected restart firing at ``t_up``)."""
    node: int
    t_down: int
    t_up: int
    reset_state: bool = False

    def __post_init__(self):
        object.__setattr__(self, "node", _node(self.node, "crash node"))
        object.__setattr__(self, "t_down", _t(self.t_down, "t_down"))
        object.__setattr__(self, "t_up", _t(self.t_up, "t_up"))
        if self.t_down < 0:
            raise ValueError(f"t_down={self.t_down} must be >= 0")
        object.__setattr__(self, "reset_state", bool(self.reset_state))


@dataclass(frozen=True)
class Partition:
    """Cut the network into ``groups`` (sequences of node ids) for
    ``[t_start, t_end)``. Cross-group messages *sent* while the cut is
    live are dropped; nodes in no group keep full connectivity."""
    groups: Tuple[Tuple[int, ...], ...]
    t_start: int
    t_end: int

    def __post_init__(self):
        gs = tuple(tuple(_node(i, "partition member") for i in g)
                   for g in self.groups)
        if len(gs) < 2:
            raise ValueError(
                f"a partition needs at least two groups, got {len(gs)} "
                "(one group cuts nothing)")
        for gi, g in enumerate(gs):
            if not g:
                raise ValueError(
                    f"partition group {gi} is empty — an empty side "
                    "cuts nothing (drop it, or name its members)")
        seen = set()
        for g in gs:
            for i in g:
                if i in seen:
                    raise ValueError(
                        f"node {i} appears in two partition groups")
                seen.add(i)
        object.__setattr__(self, "groups", gs)
        object.__setattr__(self, "t_start", _t(self.t_start, "t_start"))
        object.__setattr__(self, "t_end", _t(self.t_end, "t_end"))


@dataclass(frozen=True)
class LinkWindow:
    """Degrade messages from ``src`` nodes to ``dst`` nodes sent in
    ``[t_start, t_end)``: sampled delay becomes
    ``(delay * num) // den + extra_us``. ``src``/``dst`` are node-id
    sequences, or ``None`` for "all nodes"."""
    src: Optional[Tuple[int, ...]]
    dst: Optional[Tuple[int, ...]]
    t_start: int
    t_end: int
    scale: float = 1.0
    extra_us: int = 0

    def __post_init__(self):
        for name in ("src", "dst"):
            v = getattr(self, name)
            if v is not None:
                object.__setattr__(
                    self, name,
                    tuple(_node(i, f"link-window {name}") for i in v))
        object.__setattr__(self, "t_start", _t(self.t_start, "t_start"))
        object.__setattr__(self, "t_end", _t(self.t_end, "t_end"))
        object.__setattr__(self, "extra_us",
                           _t(self.extra_us, "extra_us"))
        if self.extra_us < 0:
            raise ValueError("extra_us must be >= 0 (a negative offset "
                             "could time-travel a message; shrink "
                             "delays with scale < 1 instead)")
        if not (isinstance(self.scale, (int, float))
                and not isinstance(self.scale, bool)) or self.scale <= 0:
            raise ValueError(f"scale must be a number > 0, "
                             f"got {self.scale!r}")
        # normalize to a plain float: np.float64 IS a float subclass,
        # but its repr ('np.float64(2.0)') would make format_faults
        # emit an unparseable grammar string — and numpy is exactly
        # where programmatic scales come from (link-param vectors)
        object.__setattr__(self, "scale", float(self.scale))
        # exact rational form: the engines transform integer delays as
        # (d * num) // den, identical on every backend
        fr = Fraction(self.scale).limit_denominator(1 << 20)
        object.__setattr__(self, "_num", fr.numerator)
        object.__setattr__(self, "_den", fr.denominator)


@dataclass(frozen=True)
class ClockSkew:
    """Shift ``node``'s view of time by ``offset_us`` (may be
    negative). Multiple skews on one node sum."""
    node: int
    offset_us: int

    def __post_init__(self):
        object.__setattr__(self, "node", _node(self.node, "skew node"))
        object.__setattr__(self, "offset_us",
                           _t(self.offset_us, "offset_us"))


class FaultTables(NamedTuple):
    """The lowered schedule: fixed-shape arrays the superstep masks
    are derived from (:mod:`timewarp_tpu.faults.apply`). A plain
    pytree, so a leading world axis stacks/``vmap``s through it.

    Inert (padding) rows are windows with ``t_up <= t_down`` /
    ``t_end <= t_start`` — every mask guards on window non-emptiness,
    so padded and unpadded schedules are result-identical.
    """
    crash_node: Any    # int32[C]
    crash_down: Any    # int64[C]
    crash_up: Any      # int64[C]
    crash_reset: Any   # bool[C]
    part_group: Any    # int32[Pn, N]  (-1 = not in any group)
    part_start: Any    # int64[Pn]
    part_end: Any      # int64[Pn]
    link_src: Any      # bool[L, N]
    link_dst: Any      # bool[L, N]
    link_start: Any    # int64[L]
    link_end: Any      # int64[L]
    link_num: Any      # int64[L]
    link_den: Any      # int64[L]
    link_add: Any      # int64[L]
    skew: Any          # int64[N]


@dataclass(frozen=True)
class FaultSchedule:
    """An ordered collection of fault events (module docstring), plus
    the pad counts :class:`FaultFleet` uses to equalize table shapes
    across worlds (padding rows are inert — see
    :class:`FaultTables`)."""
    events: Tuple[Any, ...] = ()
    pad: Tuple[int, int, int] = (0, 0, 0)   # extra (crash, part, link) rows

    def __post_init__(self):
        evs = tuple(self.events)
        kinds = (NodeCrash, Partition, LinkWindow, ClockSkew)
        for e in evs:
            if not isinstance(e, kinds):
                raise ValueError(
                    f"fault events must be NodeCrash / Partition / "
                    f"LinkWindow / ClockSkew, got {e!r}")
        object.__setattr__(self, "events", evs)
        object.__setattr__(self, "pad", tuple(int(p) for p in self.pad))

    # -- views -----------------------------------------------------------

    def _of(self, kind):
        return [e for e in self.events if isinstance(e, kind)]

    @property
    def crashes(self) -> List[NodeCrash]:
        return self._of(NodeCrash)

    @property
    def partitions(self) -> List[Partition]:
        return self._of(Partition)

    @property
    def link_windows(self) -> List[LinkWindow]:
        return self._of(LinkWindow)

    @property
    def skews(self) -> List[ClockSkew]:
        return self._of(ClockSkew)

    @property
    def has_skew(self) -> bool:
        return any(s.offset_us for s in self.skews)

    @property
    def has_reset(self) -> bool:
        return any(c.reset_state for c in self.crashes)

    @property
    def n_restarts(self) -> int:
        """Rows of the restart-consumption state vector
        (``restart_done``), padding included — one slot per crash row
        (only active reset rows ever flip theirs)."""
        return len(self.crashes) + self.pad[0]

    def min_delay_floor(self, link_floor: int) -> int:
        """Conservative lower bound on any *degraded* delay given the
        link model's declared ``min_delay_us`` — what windowed
        execution must validate against (a shrink window can undercut
        the link's floor; never silently). Degradation rows compose in
        declaration order (apply.degrade), so the bound is the minimum
        over every *subset* of rows a message could match: each
        transform is monotone in its input, so the greedy fold
        ``x <- min(x, T_i(x))`` realizes that minimum exactly —
        overlapping shrink windows compound and the floor reflects it."""
        floor = int(link_floor)
        for lw in self.link_windows:
            if lw.t_end > lw.t_start:
                floor = min(floor, max(
                    1, (floor * lw._num) // lw._den + lw.extra_us))
        return max(1, floor)

    def min_delay_floor_in(self, link_floor: int, t_lo: int,
                           t_hi: int) -> int:
        """:meth:`min_delay_floor` restricted to degradation rows whose
        window overlaps ``[t_lo, t_hi)`` — the *per-window* link floor
        the online dispatch controller consumes (dispatch/): outside
        every degradation window the bound is the link's own floor, so
        a shrink window that undercuts the declared floor only narrows
        the supersteps it actually covers. Host mirror of the device
        clamp ``faults.apply.window_floor`` (same greedy fold, same
        overlap rule), used for *policy* only — exactness never
        depends on this query."""
        floor = int(link_floor)
        for lw in self.link_windows:
            if lw.t_end > lw.t_start and lw.t_start < t_hi \
                    and lw.t_end > t_lo:
                floor = min(floor, max(
                    1, (floor * lw._num) // lw._den + lw.extra_us))
        return max(1, floor)

    def padded(self, crashes: int, parts: int, links: int
               ) -> "FaultSchedule":
        """This schedule with table shapes grown to the given row
        counts (inert rows appended) — what :class:`FaultFleet` hands
        out as ``world_schedule(b)`` so every world's state shapes
        match."""
        c, p, li = len(self.crashes), len(self.partitions), \
            len(self.link_windows)
        if crashes < c or parts < p or links < li:
            raise ValueError("padded() cannot shrink a schedule")
        return FaultSchedule(self.events,
                             pad=(crashes - c, parts - p, links - li))

    # -- lowering ----------------------------------------------------------

    def tables(self, n_nodes: int) -> FaultTables:
        """Lower to fixed-shape numpy tables for ``n_nodes`` nodes.
        Events naming out-of-range nodes lower to inert/ignored rows
        (they can never match a live node id) — TW501 surfaces them."""
        n = int(n_nodes)
        cr = self.crashes
        C = len(cr) + self.pad[0]
        crash_node = np.zeros(C, np.int32)
        crash_down = np.zeros(C, np.int64)
        crash_up = np.zeros(C, np.int64)
        crash_reset = np.zeros(C, bool)
        for i, c in enumerate(cr):
            crash_node[i] = c.node
            crash_down[i] = c.t_down
            crash_up[i] = c.t_up
            crash_reset[i] = c.reset_state

        ps = self.partitions
        Pn = len(ps) + self.pad[1]
        part_group = np.full((Pn, n), -1, np.int32)
        part_start = np.zeros(Pn, np.int64)
        part_end = np.zeros(Pn, np.int64)
        for i, p in enumerate(ps):
            part_start[i] = p.t_start
            part_end[i] = p.t_end
            for g, members in enumerate(p.groups):
                for m in members:
                    if m < n:
                        part_group[i, m] = g

        lws = self.link_windows
        L = len(lws) + self.pad[2]
        link_src = np.zeros((L, n), bool)
        link_dst = np.zeros((L, n), bool)
        link_start = np.zeros(L, np.int64)
        link_end = np.zeros(L, np.int64)
        link_num = np.ones(L, np.int64)
        link_den = np.ones(L, np.int64)
        link_add = np.zeros(L, np.int64)
        for i, lw in enumerate(lws):
            link_start[i] = lw.t_start
            link_end[i] = lw.t_end
            link_num[i] = lw._num
            link_den[i] = lw._den
            link_add[i] = lw.extra_us
            for name, row in (("src", link_src[i]), ("dst", link_dst[i])):
                side = getattr(lw, name)
                if side is None:
                    row[:] = True
                else:
                    for m in side:
                        if m < n:
                            row[m] = True

        skew = np.zeros(n, np.int64)
        for s in self.skews:
            if s.node < n:
                skew[s.node] += s.offset_us
        return FaultTables(
            crash_node, crash_down, crash_up, crash_reset,
            part_group, part_start, part_end,
            link_src, link_dst, link_start, link_end,
            link_num, link_den, link_add, skew)


@dataclass(frozen=True)
class FaultFleet:
    """Per-world fault schedules for a batched engine: world b of a
    ``BatchSpec`` fleet runs ``schedules[b]``. Tables are stacked on a
    leading B axis with shorter worlds padded by inert rows, so the
    ``vmap``-ed superstep maps one fixed-shape pytree — and
    ``world_schedule(b)`` returns world b's schedule *at the padded
    shape*, which is what a solo run must use to reproduce world b's
    state bit-for-bit (padding is inert, so traces and every non-shape
    observable also equal the unpadded solo run —
    tests/test_zfault_parity.py pins both)."""
    schedules: Tuple[FaultSchedule, ...]

    def __post_init__(self):
        scheds = tuple(self.schedules)
        if not scheds:
            raise ValueError("a FaultFleet needs at least one world "
                             "schedule")
        for s in scheds:
            if not isinstance(s, FaultSchedule):
                raise ValueError(
                    f"FaultFleet takes FaultSchedules, got {s!r}")
        object.__setattr__(self, "schedules", scheds)

    @property
    def B(self) -> int:
        return len(self.schedules)

    def _pad_shape(self) -> Tuple[int, int, int]:
        return (max(len(s.crashes) + s.pad[0] for s in self.schedules),
                max(len(s.partitions) + s.pad[1] for s in self.schedules),
                max(len(s.link_windows) + s.pad[2]
                    for s in self.schedules))

    def world_schedule(self, b: int) -> FaultSchedule:
        """World ``b``'s schedule at the fleet's padded table shape —
        the right-hand side of the chaos-fleet exactness law."""
        return self.schedules[b].padded(*self._pad_shape())

    @property
    def has_skew(self) -> bool:
        return any(s.has_skew for s in self.schedules)

    @property
    def has_reset(self) -> bool:
        return any(s.has_reset for s in self.schedules)

    @property
    def n_restarts(self) -> int:
        return self._pad_shape()[0]

    def min_delay_floor(self, link_floor: int) -> int:
        return min(s.min_delay_floor(link_floor)
                   for s in self.schedules)

    def min_delay_floor_in(self, link_floor: int, t_lo: int,
                           t_hi: int) -> int:
        """Fleet-wide per-window floor: the min over every world's
        (the controller makes one fleet decision per chunk, so the
        bound must hold in every world — the recorded ``min`` slack
        aggregation's twin for the floor side)."""
        return min(s.min_delay_floor_in(link_floor, t_lo, t_hi)
                   for s in self.schedules)

    def tables(self, n_nodes: int) -> FaultTables:
        """Stacked ``[B, ...]`` tables (every leaf gains a leading
        world axis)."""
        C, Pn, L = self._pad_shape()
        ts = [s.padded(C, Pn, L).tables(n_nodes) for s in self.schedules]
        return FaultTables(*(np.stack([getattr(t, f) for t in ts])
                             for f in FaultTables._fields))


# -- the CLI grammar -------------------------------------------------------

#: the --faults grammar, named in every parse error (mirrors
#: cli.LINK_GRAMMAR). Events are ';'-separated; node sets are
#: '+'-joined ids/ranges (e.g. 0-3+7); times are µs ints or
#: suffixed (10ms, 5s); 'all' = every node.
FAULT_GRAMMAR = (
    "crash:NODE:DOWN:UP[:reset] | partition:G0|G1[|G2...]:START:END | "
    "degrade:SRC:DST:START:END:SCALE[:EXTRA] | skew:NODE:OFFSET  "
    "(events ';'-separated; times µs ints or 10ms/5s; node sets "
    "'+'-joined ids/ranges like 0-3+7, or 'all')")


def _parse_time(s: str, what: str) -> int:
    s = s.strip()
    for suffix, mult in (("us", 1), ("ms", 1_000), ("s", 1_000_000)):
        if s.endswith(suffix):
            body = s[:-len(suffix)]
            try:
                return int(round(float(body) * mult))
            except ValueError:
                raise ValueError(
                    f"{what}: bad time {s!r} (number before "
                    f"'{suffix}')") from None
    try:
        return int(s)
    except ValueError:
        raise ValueError(
            f"{what}: bad time {s!r} (µs int or 10ms/5s)") from None


def _parse_nodes(s: str, what: str) -> Optional[Tuple[int, ...]]:
    if s == "all":
        return None
    out: List[int] = []
    for part in s.split("+"):
        if "-" in part:
            a, _, b = part.partition("-")
            try:
                lo, hi = int(a), int(b)
            except ValueError:
                raise ValueError(
                    f"{what}: bad node range {part!r}") from None
            if hi < lo:
                raise ValueError(f"{what}: empty node range {part!r}")
            out.extend(range(lo, hi + 1))
        else:
            try:
                out.append(int(part))
            except ValueError:
                raise ValueError(
                    f"{what}: bad node id {part!r}") from None
    return tuple(out)


def _parse_event(spec: str):
    parts = spec.split(":")
    kind = parts[0]
    if kind == "crash":
        if len(parts) not in (4, 5) or (
                len(parts) == 5 and parts[4] != "reset"):
            raise ValueError("crash takes NODE:DOWN:UP[:reset]")
        return NodeCrash(int(parts[1]),
                         _parse_time(parts[2], "crash DOWN"),
                         _parse_time(parts[3], "crash UP"),
                         reset_state=len(parts) == 5)
    if kind == "partition":
        if len(parts) != 4:
            raise ValueError("partition takes G0|G1[|...]:START:END")
        groups = tuple(_parse_nodes(g, "partition group")
                       for g in parts[1].split("|"))
        if any(g is None for g in groups):
            raise ValueError("partition groups must be explicit node "
                             "sets ('all' in one group cuts nothing)")
        return Partition(groups,
                         _parse_time(parts[2], "partition START"),
                         _parse_time(parts[3], "partition END"))
    if kind == "degrade":
        if len(parts) not in (6, 7):
            raise ValueError(
                "degrade takes SRC:DST:START:END:SCALE[:EXTRA]")
        return LinkWindow(_parse_nodes(parts[1], "degrade SRC"),
                          _parse_nodes(parts[2], "degrade DST"),
                          _parse_time(parts[3], "degrade START"),
                          _parse_time(parts[4], "degrade END"),
                          scale=float(parts[5]),
                          extra_us=_parse_time(parts[6], "degrade EXTRA")
                          if len(parts) == 7 else 0)
    if kind == "skew":
        if len(parts) != 3:
            raise ValueError("skew takes NODE:OFFSET")
        return ClockSkew(int(parts[1]),
                         _parse_time(parts[2], "skew OFFSET"))
    raise ValueError(f"unknown fault kind {kind!r}")


def parse_faults(spec: str) -> FaultSchedule:
    """Parse a ``;``-separated fault-event string (the CLI's
    ``--faults``) into a :class:`FaultSchedule`. Malformed specs die
    naming :data:`FAULT_GRAMMAR`, never with a raw
    IndexError/ValueError (the ``parse_link`` convention)."""
    events = []
    for ev in spec.split(";"):
        ev = ev.strip()
        if not ev:
            continue
        try:
            events.append(_parse_event(ev))
        except (IndexError, ValueError) as e:
            raise SystemExit(
                f"malformed fault spec {ev!r} ({e}); "
                f"grammar: {FAULT_GRAMMAR}") from None
    if not events:
        raise SystemExit(
            f"empty fault spec {spec!r}; grammar: {FAULT_GRAMMAR}")
    return FaultSchedule(tuple(events))


def _fmt_nodes(nodes: Optional[Tuple[int, ...]]) -> str:
    """One node set in the grammar's '+'-joined ids/ranges form,
    preserving the stored order (consecutive ascending runs compress
    to ranges; re-parsing yields the identical tuple)."""
    if nodes is None:
        return "all"
    parts: List[str] = []
    i, n = 0, len(nodes)
    while i < n:
        j = i
        while j + 1 < n and nodes[j + 1] == nodes[j] + 1:
            j += 1
        if j - i >= 1:
            parts.append(f"{nodes[i]}-{nodes[j]}")
        else:
            parts.append(str(nodes[i]))
        i = j + 1
    return "+".join(parts)


def _fmt_event(e) -> str:
    if isinstance(e, NodeCrash):
        s = f"crash:{e.node}:{e.t_down}:{e.t_up}"
        return s + ":reset" if e.reset_state else s
    if isinstance(e, Partition):
        gs = "|".join(_fmt_nodes(g) for g in e.groups)
        return f"partition:{gs}:{e.t_start}:{e.t_end}"
    if isinstance(e, LinkWindow):
        s = (f"degrade:{_fmt_nodes(e.src)}:{_fmt_nodes(e.dst)}:"
             f"{e.t_start}:{e.t_end}:{e.scale!r}")
        return s + f":{e.extra_us}" if e.extra_us else s
    if isinstance(e, ClockSkew):
        return f"skew:{e.node}:{e.offset_us}"
    raise ValueError(f"unknown fault event {e!r}")


def format_faults(schedule: FaultSchedule) -> str:
    """The grammar round-trip inverse of :func:`parse_faults`: a
    ``;``-separated :data:`FAULT_GRAMMAR` string whose re-parse is
    field-equal to ``schedule`` (tests/test_zgrammar.py pins the
    law). Times print as raw µs ints — exact, no suffix rounding.
    ``pad`` is a fleet-shape artifact with no grammar form and is
    deliberately not represented (a re-parsed schedule carries pad
    ``(0, 0, 0)``; padding is inert, so the two are result-identical
    — :class:`FaultTables`). This is what lets the chaos search
    (timewarp_tpu/search/) emit every minimized counterexample as a
    paste-able ``--faults`` repro string. An empty schedule has no
    grammar form (``parse_faults`` refuses empty specs) and is
    refused here symmetrically."""
    if not schedule.events:
        raise ValueError(
            "an empty FaultSchedule has no --faults grammar form "
            "(parse_faults refuses empty specs); represent 'no "
            "faults' as None, the RunConfig convention")
    return "; ".join(_fmt_event(e) for e in schedule.events)


def as_fleet(faults, B: int) -> FaultFleet:
    """Normalize a solo schedule onto a ``B``-world fleet (every world
    runs the same schedule) — the CLI's ``--faults`` + ``--batch``
    path. A real per-world study builds the :class:`FaultFleet`
    directly."""
    if isinstance(faults, FaultFleet):
        if faults.B != B:
            raise ValueError(
                f"FaultFleet has {faults.B} world schedules but the "
                f"batch runs {B} worlds")
        return faults
    return FaultFleet((faults,) * B)
